"""Bounded PG-log recovery + backfill (VERDICT r3 next-round #1).

The reference's core scaling idea (osd/PGLog.h): peering exchanges
LOG BOUNDS, never object maps; a rejoining peer recovers from the log
DELTA (O(ops missed)); a peer behind the trimmed tail — or wiped —
enters BACKFILL, a reservation-throttled ranged scan whose messages
are O(batch), not O(objects).

Covered here:
  * delta recovery: N >> log-bound objects written, an OSD restarts
    mid-stream, and recovery pushes only the delta;
  * backfill: a wiped OSD is restored by ranged scans; deletions that
    happened while it was away are applied; peering info payloads
    carry no object maps regardless of N.
"""

import os
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster

CONF = {
    "osd_pg_log_max_entries": 32,
    "osd_backfill_scan_batch": 16,
    "osd_heartbeat_interval": 0.5,
    "osd_heartbeat_grace": 5.0,
    "mon_osd_min_down_reporters": 2,
}


def _settle(io, timeout=60.0):
    end = time.time() + timeout
    while True:
        try:
            io.write_full("settle", b"s")
            return
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)


def _write_n(io, prefix, n, start=0, retries=15):
    for i in range(start, start + n):
        data = f"{prefix}-{i}-".encode() * 20
        for _ in range(retries):
            try:
                io.write_full(f"{prefix}{i}", data)
                break
            except RadosError:
                time.sleep(0.4)


def _wait_all(io, names, timeout=60.0):
    end = time.time() + timeout
    missing = list(names)
    while missing and time.time() < end:
        still = []
        for n in missing:
            try:
                io.read(n)
            except RadosError:
                still.append(n)
        missing = still
        if missing:
            time.sleep(0.5)
    assert not missing, f"never became readable: {missing[:5]}"


class TestDeltaRecovery:
    """Persistent stores: a restarted OSD keeps its pre-kill log, so
    rejoin recovers from the log delta only."""

    @pytest.fixture()
    def cluster(self, tmp_path):
        c = MiniCluster(num_mons=1, num_osds=3, conf=Config(dict(CONF)),
                        store_kind="kstore",
                        store_dir=str(tmp_path)).start()
        yield c
        c.stop()

    def test_rejoin_transfers_only_the_delta(self, cluster):
        rados = cluster.client()
        rados.create_pool("delta", pg_num=1)
        io = rados.open_ioctx("delta")
        _settle(io)
        # N >> log bound (32): 120 objects before the outage
        _write_n(io, "pre", 120)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "pre0")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        victim = acting[-1]
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim, timeout=40)
        # a SMALL delta while the victim is away (stays within the
        # 32-entry log bound)
        _write_n(io, "delta", 10)
        _write_n(io, "pre", 5)          # overwrite pre0..pre4
        # count recovery pushes to the victim from now on
        import ceph_tpu.osd.daemon as D
        pushes = []
        orig = D.OSDDaemon.pg_push_object
        orig_inline = D.OSDDaemon._push_object_inline

        def counting(self, pgid_, target, oid, version, shard):
            pushes.append((self.whoami, target, oid))
            return orig(self, pgid_, target, oid, version, shard)

        def counting_inline(self, pg_, target, oid, version):
            pushes.append((self.whoami, target, oid))
            return orig_inline(self, pg_, target, oid, version)

        D.OSDDaemon.pg_push_object = counting
        D.OSDDaemon._push_object_inline = counting_inline
        try:
            cluster.start_osd(victim)
            cluster.wait_for_osds(3, timeout=40)
            vic = cluster.osds[victim]
            want = [f"delta{i}" for i in range(10)] + \
                   [f"pre{i}" for i in range(5)]
            end = time.time() + 60
            while time.time() < end:
                try:
                    ok = all(
                        vic.store.read(f"pg_{pgid}", f"delta{i}")
                        for i in range(10))
                    if ok and all(
                            vic.store.read(f"pg_{pgid}", f"pre{i}") ==
                            f"pre-{i}-".encode() * 20
                            for i in range(5)):
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            for i in range(10):
                assert vic.store.read(f"pg_{pgid}", f"delta{i}")
        finally:
            D.OSDDaemon.pg_push_object = orig
            D.OSDDaemon._push_object_inline = orig_inline
        to_victim = [p for p in pushes if p[1] == victim]
        # the delta is 15 ops; a full resync would be 130+.  Allow
        # slack for duplicate pushes from racing peering rounds.
        assert 1 <= len(to_victim) <= 45, \
            f"expected delta-sized recovery, got {len(to_victim)} pushes"

    def test_peering_info_carries_no_object_map(self, cluster):
        """The round-3 design shipped dict(pglog.objects) in every
        info exchange — O(objects) peering.  The bounded protocol
        must stay O(1): log bounds only."""
        rados = cluster.client()
        rados.create_pool("bounds", pg_num=1)
        io = rados.open_ioctx("bounds")
        _settle(io)
        _write_n(io, "b", 80)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "b0")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        pg = cluster.osds[acting[0]].get_pg(pgid)
        info = pg.get_info()
        assert "objects" not in info and "deleted" not in info
        assert "entries" not in info
        assert tuple(info["last_update"]) > (0, 0)
        # the log itself is bounded
        assert len(pg.pglog.entries) <= 32
        assert pg.pglog.tail > (0, 0)   # trimmed: tail advanced


class TestBackfill:
    """A wiped OSD (memstore: restart = empty) predates any log tail
    and must be restored by ranged-scan backfill."""

    @pytest.fixture()
    def cluster(self):
        c = MiniCluster(num_mons=1, num_osds=3,
                        conf=Config(dict(CONF))).start()
        yield c
        c.stop()

    def test_wiped_osd_backfills_fully(self, cluster):
        rados = cluster.client()
        rados.create_pool("bf", pg_num=1)
        io = rados.open_ioctx("bf")
        _settle(io)
        _write_n(io, "o", 60)            # 60 objects >> log bound 32
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "o0")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        victim = acting[-1]
        # delete a few AFTER the victim holds them, then wipe it
        vic_before = cluster.osds[victim]
        for i in range(5):
            assert vic_before.store.read(f"pg_{pgid}", f"o{i}")
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim, timeout=40)
        for i in range(5):
            io.remove_object(f"o{i}")
        _write_n(io, "late", 10)
        # count scan rounds: messages must be O(batch), not O(objects)
        import ceph_tpu.osd.daemon as D
        scans = []
        orig_call = D.OSDDaemon._call

        def counting_call(self, osd_id, msg, timeout=10.0):
            if getattr(msg, "op", None) == "scan_range":
                scans.append((self.whoami, osd_id))
            return orig_call(self, osd_id, msg, timeout)

        D.OSDDaemon._call = counting_call
        try:
            cluster.start_osd(victim)   # memstore: comes back EMPTY
            cluster.wait_for_osds(3, timeout=40)
            vic = cluster.osds[victim]
            end = time.time() + 90
            want = [f"o{i}" for i in range(5, 60)] + \
                   [f"late{i}" for i in range(10)]
            while time.time() < end:
                have = 0
                for n in want:
                    try:
                        if vic.store.read(f"pg_{pgid}", n):
                            have += 1
                    except Exception:
                        pass
                if have == len(want):
                    break
                time.sleep(0.5)
            assert have == len(want), \
                f"backfill incomplete: {have}/{len(want)}"
            # deletions that happened while it was away are applied
            end = time.time() + 30
            while time.time() < end:
                gone = sum(1 for i in range(5)
                           if not vic.store.exists(f"pg_{pgid}",
                                                   f"o{i}"))
                if gone == 5:
                    break
                time.sleep(0.5)
            assert gone == 5, "stale objects survived backfill"
        finally:
            D.OSDDaemon._call = orig_call
        # ~70 objects at batch 16 -> a handful of scan rounds, each
        # O(batch); a whole-map exchange would be a single O(N) blob
        assert scans, "backfill never ranged-scanned the peer"
        assert len(scans) <= 30

    def test_interrupted_backfill_resumes_from_watermark(self,
                                                         cluster):
        """A peer that died mid-backfill persists its last_backfill
        watermark; the next session resumes the scan FROM it instead
        of re-walking the namespace (cursor starts at the watermark,
        counter-asserted), and still converges."""
        rados = cluster.client()
        rados.create_pool("wm", pg_num=1)
        io = rados.open_ioctx("wm")
        _settle(io)
        _write_n(io, "w", 60)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "w0")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        victim = acting[-1]
        vic = cluster.osds[victim]
        vpg = vic.get_pg(pgid)
        # construct the mid-backfill state: watermark at "w29", every
        # object above it missing (names sort w0,w1,w10..: take the
        # sorted midpoint so the split is real)
        with vpg.lock:
            names = sorted(f"w{i}" for i in range(60))
            watermark = names[29]
            from ceph_tpu.store.objectstore import Transaction as Txn
            txn = Txn()
            for n in names[30:]:
                txn.try_remove(vpg.cid, n)
                vpg.pglog.objects.pop(n, None)
            vic.store.apply_transaction(txn)
            vpg.set_backfill_state(False, watermark)
        assert vpg.last_backfill == watermark
        # the watermark survives the advertised bounds
        info = vpg.get_info()
        assert info["backfilling"] and \
            info["last_backfill"] == watermark
        import ceph_tpu.osd.daemon as D
        scans = []
        orig_call = D.OSDDaemon._call

        def counting_call(self, osd_id, msg, timeout=10.0):
            if getattr(msg, "op", None) == "scan_range" and \
                    osd_id == victim:
                scans.append(getattr(msg, "after", ""))
            return orig_call(self, osd_id, msg, timeout)

        D.OSDDaemon._call = counting_call
        try:
            primary = acting[0]
            posd = cluster.osds[primary]
            r0 = posd._perf_dump()["osd"]["backfill_resumes"]
            posd.get_pg(pgid).start_peering()
            end = time.time() + 90
            while time.time() < end:
                have = sum(1 for n in names[30:]
                           if vic.store.exists(f"pg_{pgid}", n))
                if have == 30 and vpg.backfill_complete:
                    break
                time.sleep(0.5)
            assert have == 30, f"resume incomplete: {have}/30"
            assert vpg.backfill_complete
            assert posd._perf_dump()["osd"]["backfill_resumes"] > r0
        finally:
            D.OSDDaemon._call = orig_call
        # the scan started AT the watermark: no cursor below it ever
        # went to the peer — the namespace below was not re-walked
        assert scans, "no ranged scan ran"
        assert all(c >= watermark for c in scans), scans

    def test_last_backfill_routes_live_ops(self, cluster):
        """Primary-side op routing: a backfill peer receives live
        sub-ops only for objects at or below its watermark; beyond it
        they are backfill-deferred (should_send_op)."""
        rados = cluster.client()
        rados.create_pool("route", pg_num=1)
        io = rados.open_ioctx("route")
        _settle(io)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "settle")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary, peer = acting[0], acting[1]
        pg = cluster.osds[primary].get_pg(pgid)
        with pg.lock:
            assert pg.should_send_op(peer, "anything")   # not backfilling
            pg.peer_last_backfill[peer] = "m"
            assert pg.should_send_op(peer, "a")          # <= watermark
            assert pg.should_send_op(peer, "m")
            assert not pg.should_send_op(peer, "z")      # deferred
            pg.peer_last_backfill.pop(peer)
        # functional: with the peer watermarked below the object, a
        # live write completes WITHOUT that peer in the gather and the
        # peer never applies it
        with pg.lock:
            pg.peer_last_backfill[peer] = ""     # nothing restored yet
        try:
            io.write_full("zz-beyond", b"deferred" * 10)
            ppg = cluster.osds[peer].get_pg(pgid)
            deadline = time.time() + 3
            while time.time() < deadline:
                assert "zz-beyond" not in ppg.pglog.objects
                time.sleep(0.1)
            assert not cluster.osds[peer].store.exists(
                f"pg_{pgid}", "zz-beyond")
        finally:
            with pg.lock:
                pg.peer_last_backfill.pop(peer, None)
        # after the routing view clears, a rewrite reaches the peer
        io.write_full("zz-beyond", b"now-normal")
        deadline = time.time() + 15
        while time.time() < deadline:
            if cluster.osds[peer].store.exists(f"pg_{pgid}",
                                               "zz-beyond"):
                break
            time.sleep(0.2)
        assert cluster.osds[peer].store.exists(f"pg_{pgid}",
                                               "zz-beyond")

    def test_wiped_ec_member_rebuilt_by_backfill(self, cluster):
        rados = cluster.client()
        rados.create_ec_pool("bfec", "k2m1bf",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "technique": "reed_sol_van"}, pg_num=1)
        io = rados.open_ioctx("bfec")
        _settle(io)
        _write_n(io, "e", 50)            # > log bound
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "e0")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        victim = acting[-1]
        shard = acting.index(victim)
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim, timeout=40)
        cluster.start_osd(victim)
        cluster.wait_for_osds(3, timeout=40)
        vic = cluster.osds[victim]
        end = time.time() + 120
        while time.time() < end:
            have = sum(
                1 for i in range(50)
                if vic.store.exists(f"pg_{pgid}", f"e{i}.s{shard}"))
            if have == 50:
                break
            time.sleep(0.5)
        assert have == 50, f"EC backfill incomplete: {have}/50"
        # and the pool still reads everything through the rebuilt shard
        for i in (0, 17, 49):
            assert io.read(f"e{i}") == f"e-{i}-".encode() * 20
