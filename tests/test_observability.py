"""Observability: perf counters move during I/O, op tracking, admin
socket (in-process + unix domain), slow-op surfacing.

The VERDICT item: PerfCounters existed but nothing instantiated them —
these tests pin that the messenger/OSD/mon sets are WIRED.
"""

import time

import pytest

from ceph_tpu.utils.admin_socket import admin_command
from ceph_tpu.utils.clock import ManualClock
from ceph_tpu.utils.optracker import OpTracker
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    sock_dir = str(tmp_path_factory.mktemp("asok"))
    from ceph_tpu.utils.config import Config
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "mon_osd_down_out_interval": 5.0,
        "admin_socket_dir": sock_dir,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    c.sock_dir = sock_dir
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("obs", pg_num=4)
    ctx = rados.open_ioctx("obs")
    from ceph_tpu.client import RadosError
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warm", b"x")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


class TestCounterSchema:
    """The COMPLETE perf-counter schema per subsystem, asserted
    name-by-name: tools/counter_audit.py (tier-1) requires every
    counter declared or incremented anywhere in ceph_tpu/ to appear
    here — a counter cannot ship undocumented/untested."""

    OSD = {"op", "op_r", "op_w", "op_in_bytes", "op_out_bytes",
           "subop_w", "op_latency",
           "peering_auth_catchups", "peering_getlog_merges",
           "peering_divergent_rewinds", "peering_divergent_entries",
           "recovery_pushes", "recovery_bytes", "backfill_resumes",
           # serve-during-repair: ops parked on a missing object's
           # recovery pull, their resumes, and front-of-queue pull
           # promotions (blocked == unblocked at quiesce)
           "recovery_blocked_ops", "recovery_unblocked_ops",
           "recovery_prio_promotions"}
    MSGR = {"msg_send", "msg_recv", "bytes_send", "bytes_recv",
            "reconnects", "auth_failures", "auth_ticket_accepts",
            "auth_secret_accepts",
            # event-loop plane (shared schema across both stacks):
            # worker-model gauge, live connection gauge, cross-thread
            # loop handoffs, gather-writes resumed by EPOLLOUT, and
            # accepted-socket handshakes
            "event_workers", "open_connections", "event_wakeups",
            "partial_write_resumes", "accepts"}
    MON = {"elections_won", "elections_lost", "commands"}
    PAXOS = {"collect", "begin", "commit", "lease"}
    # multisite replication agent: rounds attempted, per-bucket/round
    # failures, in-round retries after a backoff expired, buckets
    # benched behind a per-bucket backoff, applied copies/deletes, and
    # total seconds of scheduled backoff (backoff-not-wedge evidence)
    RGW_SYNC = {"sync_rounds", "sync_errors", "sync_retries",
                "sync_quarantines", "sync_objects_copied",
                "sync_deletes_applied", "sync_backoff_secs"}

    def test_osd_schema_complete(self, cluster):
        osd = next(iter(cluster.osds.values()))
        assert set(osd.perf._schema) == self.OSD
        assert set(osd.msgr.perf._schema) == self.MSGR

    def test_mon_schema_complete(self, cluster):
        mon = cluster.leader()
        assert set(mon.perf._schema) == self.MON
        assert set(mon.paxos.perf._schema) == self.PAXOS

    def test_rgw_sync_schema_complete(self, cluster):
        """The sync agent's `perf dump rgw_sync` block: schema pinned,
        and one healthy self-pointed round moves the round counter
        without manufacturing errors/backoff."""
        from ceph_tpu.rgw.sync import RGWSyncAgent
        gw = cluster.start_rgw()
        try:
            agent = RGWSyncAgent(gw, f"http://127.0.0.1:{gw.port}")
            assert set(agent.perf._schema) == self.RGW_SYNC
            agent.sync_once()       # self-sync: trivially healthy
            dump = agent.perf_dump()["rgw_sync"]
            assert set(dump) == self.RGW_SYNC | {"quarantined_buckets"}
            assert dump["sync_rounds"] == 1
            assert dump["sync_errors"] == 0
            assert dump["sync_backoff_secs"] == 0
            assert dump["quarantined_buckets"] == []
        finally:
            gw.shutdown()
            cluster.rgws.remove(gw)

    def test_counter_audit_clean(self):
        """Tier-1 gate: a counter incremented in ceph_tpu/ but absent
        from the sets above fails here until it is added."""
        from ceph_tpu.tools import counter_audit
        violations = counter_audit.audit()
        assert violations == [], "\n".join(violations)


class TestPerfCounters:
    def test_osd_counters_move_during_io(self, cluster, io):
        before = {o.whoami: o.perf.value("op") for o in
                  cluster.osds.values()}
        for i in range(5):
            io.write_full(f"c{i}", b"data" * 50)
            io.read(f"c{i}")
        after = {o.whoami: o.perf.value("op") for o in
                 cluster.osds.values()}
        assert sum(after.values()) >= sum(before.values()) + 10
        osd = max(cluster.osds.values(),
                  key=lambda o: o.perf.value("op_w"))
        assert osd.perf.value("op_w") >= 1
        assert osd.perf.value("op_in_bytes") >= 200
        assert osd.perf.avg("op_latency") >= 0.0

    def test_messenger_counters(self, cluster, io):
        osd = next(iter(cluster.osds.values()))
        dump = osd.msgr.perf.dump()
        assert dump["msg_send"] > 0
        assert dump["msg_recv"] > 0
        assert dump["bytes_send"] > 0

    def test_mon_paxos_counters(self, cluster, io):
        mon = cluster.leader()
        dump = mon.perf_collection.dump()
        assert dump["paxos"]["commit"] > 0
        assert dump["paxos"]["lease"] >= 0
        assert dump["mon"]["elections_won"] >= 1
        assert dump["mon"]["commands"] >= 1

    def test_perf_dump_includes_ec_codecs(self, cluster, io):
        cluster.client().create_ec_pool(
            "obsec", "k2m1", {"plugin": "tpu", "k": 2, "m": 1})
        ioe = cluster.client().open_ioctx("obsec")
        from ceph_tpu.client import RadosError
        end = time.time() + 20
        while True:
            try:
                ioe.write_full("e", b"ec" * 3000)
                break
            except RadosError:
                if time.time() > end:
                    raise
                time.sleep(0.3)
        dumps = [o.asok.execute("perf dump") for o in
                 cluster.osds.values()]
        assert any(d.get("ec_codecs") for d in dumps)

    def test_ec_pipeline_counters(self, cluster, io):
        """The shared EC dispatch pipeline surfaces its counters in
        perf dump: dispatch count, mean batch size, queue depth."""
        rados = cluster.client()
        rados.create_ec_pool(
            "obsecp", "k2m1p", {"plugin": "tpu", "k": 2, "m": 1})
        ioe = rados.open_ioctx("obsecp")
        from ceph_tpu.client import RadosError
        end = time.time() + 20
        while True:
            try:
                ioe.write_full("p0", b"pipe" * 2000)
                break
            except RadosError:
                if time.time() > end:
                    raise
                time.sleep(0.3)
        for i in range(1, 6):
            ioe.write_full(f"p{i}", bytes([i]) * 6000)
        dump = next(iter(cluster.osds.values())).asok.execute(
            "perf dump")
        stats = dump["ec_pipeline"]
        # the pipeline is process-wide, so every OSD reports the same
        # counters — the EC writes above must have moved them
        assert stats["dispatches"] >= 1
        assert stats["ops"] >= 6
        assert stats["stripes"] >= stats["dispatches"]
        assert stats["mean_batch_size"] >= 1.0
        assert stats["queue_depth"] >= 0
        assert stats["max_queue_depth"] >= 1
        for key in ("dev_dispatches", "host_dispatches",
                    "coalesce_waits", "device_errors",
                    "drained_to_host", "inflight", "depth",
                    # multichip surface: per-device lanes + placement
                    "active_devices", "devices", "quarantines",
                    "split_dispatches", "redrained",
                    "qos_scrub_yields", "scrub_weight",
                    "device_shards",
                    # pod-scale mesh surface: dispatch/degrade/arena
                    # counters + the per-axis device table + the
                    # placement knobs + the bytes-weighted QoS unit
                    "mesh_dispatches", "mesh_degrades",
                    "arena_donations", "mesh", "mesh_min_bytes",
                    "device_mesh", "qos_cost_unit",
                    "qos_cost_picks"):
            assert key in stats, key
        # the mesh table is None until a mesh plane is built, else a
        # per-axis device map
        if stats["mesh"] is not None:
            for key in ("dp", "ls", "lanes", "devices"):
                assert key in stats["mesh"], key
        # per-device lane counters carry the full schema once the
        # device set is built (host-only runs may leave it lazy)
        for dev in stats["devices"].values():
            for key in ("device", "dispatches", "stripes", "bytes",
                        "errors", "inflight", "quarantined"):
                assert key in dev, key

    def test_data_path_copy_counters(self, cluster, io):
        """The zero-copy plane's audit block: perf dump reports where
        payload bytes still materialize, amortized per write AND per
        read op (the PR 9 read-side floor)."""
        io.write_full("dp0", b"copyaudit" * 400)
        io.read("dp0")
        dump = next(iter(cluster.osds.values())).asok.execute(
            "perf dump")
        dp = dump["data_path"]
        for key in ("host_copies", "ec_host_copy_bytes", "sites",
                    "host_copies_per_write",
                    "host_copy_bytes_per_write",
                    "reads", "read_copies", "read_copy_bytes",
                    "host_copies_per_read",
                    "host_copy_bytes_per_read"):
            assert key in dp, key
        assert dp["host_copies_per_write"] >= 0
        assert dp["reads"] >= 1
        # replicated/intact reads are view-served: no read-site copies
        assert dp["host_copies_per_read"] >= 0

    def test_qos_block_schema(self, cluster, io):
        """Per-pool QoS surfaces in perf dump: the op-queue dmClock
        state (grants/misses/stalls per client) plus the EC pipeline's
        dispatch-lane half — and installing a pool class at runtime
        (injectargs, dynamic option) makes it appear."""
        osd = next(iter(cluster.osds.values()))
        dump = osd.asok.execute("perf dump")
        qos = dump["qos"]
        for key in ("enabled", "throttle_stalls", "clients",
                    "pipeline", "recovery"):
            assert key in qos, key
        # the @recovery class surfaces its own grants/stalls even when
        # unconfigured (operators tune osd_qos_recovery against it)
        for key in ("configured", "res_grants", "prop_grants",
                    "deadline_misses", "throttle_stalls"):
            assert key in qos["recovery"], key
        assert qos["recovery"]["configured"] == ""
        assert qos["enabled"] is False        # nothing configured yet
        for key in ("enabled", "throttle_stalls", "clients"):
            assert key in qos["pipeline"], key
        # dynamic per-pool conf: a runtime injectargs registers the
        # class and the next I/O is scheduled (and counted) under it
        osd.conf.injectargs("--osd-pool-qos-obs 100:2:0")
        try:
            io.write_full("qos0", b"q" * 512)
            io.read("qos0")
            dump = osd.asok.execute("perf dump")
            qos = dump["qos"]
            assert qos["enabled"] is True
            # every osd sharing the conf reconfigures on its next map/
            # observer tick; the one serving qos0's pg granted it
            grants = 0
            for o in cluster.osds.values():
                ent = o._qos.stats()["clients"].get("obs")
                if ent:
                    assert ent["spec"] == "100:2:0"
                    grants += ent["res_grants"] + ent["prop_grants"]
            assert grants >= 1
        finally:
            osd.conf.injectargs("--osd-pool-qos-obs ''")

    def test_peering_and_recovery_counters(self, cluster, io):
        """The log-authoritative peering plane surfaces in perf dump:
        authority catch-ups, GetLog merges, divergent rewinds (and
        their entry counts), recovery push/byte accounting, and
        backfill watermark resumes."""
        dump = next(iter(cluster.osds.values())).asok.execute(
            "perf dump")
        for key in ("peering_auth_catchups", "peering_getlog_merges",
                    "peering_divergent_rewinds",
                    "peering_divergent_entries", "recovery_pushes",
                    "recovery_bytes", "backfill_resumes"):
            assert key in dump["osd"], key
            assert dump["osd"][key] >= 0

    def test_journal_and_crash_counters(self, cluster, io, tmp_path):
        """The crash-consistency plane surfaces in perf dump: every
        daemon reports a `crash` block (state + installed rules) and a
        `journal` block (recovery counters; empty for non-journaled
        backends like this cluster's memstore)."""
        from ceph_tpu.utils import faults
        osd = next(iter(cluster.osds.values()))
        dump = osd.asok.execute("perf dump")
        assert dump["journal"] == {}        # memstore: no journal
        assert dump["crash"] == {
            "crashed": 0, "site": "", "crash_rules": 0,
            "sites": ["store.pre_apply", "store.post_apply",
                      "pglog.append"],
            "wal_torn_extent_repairs": 0,
            "fsync_reorder_windows": 0}
        # an installed (unfired) crash rule is visible cluster-wide
        rid = faults.get().crash("journal.*", 0.0, "osd.none")
        try:
            dump = osd.asok.execute("perf dump")
            assert dump["crash"]["crash_rules"] == 1
        finally:
            faults.get().clear(rid)
        # the MON tier reports its own crash block: the paxos crash
        # sites plus the torn-commit repair counters
        mdump = cluster.mons[0].asok.execute("perf dump")
        assert mdump["crash"]["crashed"] == 0
        assert mdump["crash"]["sites"] == [
            "paxos.pre_commit", "paxos.mid_commit",
            "paxos.post_accept_pre_ack"]
        assert mdump["crash"]["paxos_torn_commit_repairs"] == 0
        assert mdump["crash"]["fsync_reorder_windows"] == 0
        # the journal block's schema on a journaled backend — the
        # same dict JournalFileStore feeds perf dump (the chaos
        # kill-restart drill asserts it end-to-end via asok)
        from ceph_tpu.store import JournalFileStore, Transaction
        s = JournalFileStore(str(tmp_path / "fs"), commit_interval=3600)
        s.mkfs()
        s.mount()
        s.apply_transaction(
            Transaction().create_collection("c").write("c", "o", 0,
                                                       b"x"))
        s._checkpoint()
        stats = s.journal_stats()
        for key in ("journal_records_replayed",
                    "journal_torn_tail_discards",
                    "journal_bad_record_halts",
                    "journal_tail_bytes_discarded",
                    "snapshot_corrupt_fallbacks",
                    "journal_checkpoint_errors",
                    "journal_checkpoints",
                    "fsync_reorder_windows"):
            assert key in stats, key
        assert stats["journal_checkpoints"] == 1
        assert set(s.crash_sites()) >= {
            "journal.pre_fsync", "journal.post_fsync",
            "journal.mid_apply", "snapshot.mid_write",
            "snapshot.pre_rename"}
        s.umount()
        # the blockstore's WAL/extent counters + site names
        from ceph_tpu.store.blockstore import BlockStore
        bs = BlockStore(str(tmp_path / "bs"))
        bs.mkfs()
        bstats = bs.journal_stats()
        for key in ("wal_records_replayed", "wal_torn_extent_repairs",
                    "freelist_repairs", "fsync_reorder_windows"):
            assert key in bstats, key
        assert set(bs.crash_sites()) >= {
            "wal.pre_kv_commit", "wal.post_kv_commit",
            "wal.mid_apply", "wal.pre_trim", "alloc.mid_cow"}
        bs.umount()
        assert s.health_warning() is None
        s.umount()


class TestAdminSocket:
    def test_in_process_hooks(self, cluster, io):
        osd = next(iter(cluster.osds.values()))
        assert "perf dump" in osd.asok.execute("help")
        st = osd.asok.execute("status")
        assert st["whoami"] == osd.whoami
        hist = osd.asok.execute("dump_historic_ops")
        assert isinstance(hist["num_ops"], int)
        assert osd.asok.execute({"prefix": "nope"})["error"]

    def test_unix_socket_roundtrip(self, cluster, io):
        osd = next(iter(cluster.osds.values()))
        path = f"{cluster.sock_dir}/{osd.entity}.asok"
        out = admin_command(path, "perf dump")
        assert "osd" in out and out["osd"]["op"] >= 0
        out = admin_command(path, {"prefix": "config show"})
        assert out["osd_op_num_shards"] == 5

    def test_config_set_via_asok(self, cluster, io):
        osd = next(iter(cluster.osds.values()))
        osd.asok.execute({"prefix": "config set",
                          "key": "osd_scrub_sleep", "value": "0.5"})
        assert osd.conf.osd_scrub_sleep == 0.5
        osd.asok.execute({"prefix": "config set",
                          "key": "osd_scrub_sleep", "value": "0.0"})

    def test_mon_quorum_status(self, cluster, io):
        mon = cluster.leader()
        qs = mon.asok.execute("quorum_status")
        assert qs["leader"] == mon.entity


class TestOpTracking:
    def test_historic_ops_recorded(self, cluster, io):
        io.write_full("tracked", b"watch me")
        osd_dumps = [o.asok.execute("dump_historic_ops")
                     for o in cluster.osds.values()]
        all_ops = [op for d in osd_dumps for op in d["ops"]]
        assert any("tracked" in op["description"] for op in all_ops)
        done = [op for op in all_ops if "tracked" in op["description"]]
        events = [e["event"] for e in done[0]["events"]]
        assert events[0] == "initiated"
        assert "reached_pg" in events
        assert events[-1] == "done"

    def test_slow_op_detection(self):
        clock = ManualClock()
        warned = []

        class Log:
            def warn(self, fmt, *a):
                warned.append(fmt % a)

        trk = OpTracker(clock, complaint_age=5.0, logger=Log())
        op = trk.create("osd_op(test slow)")
        clock.advance(10.0)
        slow = trk.check_slow_ops()
        assert len(slow) == 1
        assert slow[0]["age"] >= 10.0
        assert warned and "test slow" in warned[0]
        # complained once only
        assert trk.check_slow_ops() == []
        op.finish()
        assert trk.dump_ops_in_flight()["num_ops"] == 0
        assert trk.dump_historic_ops()["num_ops"] == 1
