"""ObjectStore conformance suite, run against every backend.

The reference pattern (test/objectstore/store_test.cc): one suite,
parameterized over memstore/filestore; plus journal-replay crash tests
for the journaled backend.
"""

import os
import struct
import threading

import pytest

from ceph_tpu.store import (ENOENT, JournalFileStore, MemStore, StoreError,
                            Transaction, create)


@pytest.fixture(params=["memstore", "filestore", "kstore",
                        "kstore-disk", "blockstore", "blockstore-disk"])
def store(request, tmp_path):
    if request.param == "memstore":
        s = MemStore()
        yield s
    elif request.param == "blockstore":
        from ceph_tpu.store.blockstore import BlockStore
        s = BlockStore()
        s.mkfs()
        yield s
        s.umount()
    elif request.param == "blockstore-disk":
        from ceph_tpu.store.blockstore import BlockStore
        s = BlockStore(str(tmp_path / "bs"))
        s.mkfs()
        s.mount()
        yield s
        s.umount()
    elif request.param == "kstore":
        from ceph_tpu.store.kstore import KStore
        s = KStore()
        s.mkfs()
        yield s
        s.umount()
    elif request.param == "kstore-disk":
        from ceph_tpu.store.kstore import KStore
        s = KStore(str(tmp_path / "ks"))
        s.mkfs()
        s.mount()
        yield s
        s.umount()
    else:
        s = JournalFileStore(str(tmp_path / "fs"), commit_interval=60)
        s.mkfs()
        s.mount()
        yield s
        s.umount()


def T():
    return Transaction()


class TestConformance:
    def test_create_collection_and_write_read(self, store):
        store.apply_transaction(T().create_collection("c1")
                                .write("c1", "o1", 0, b"hello"))
        assert store.read("c1", "o1") == b"hello"
        assert store.stat("c1", "o1")["size"] == 5
        assert store.exists("c1", "o1")
        assert not store.exists("c1", "o2")

    def test_write_offset_extends_with_zeros(self, store):
        store.apply_transaction(T().create_collection("c")
                                .write("c", "o", 10, b"xy"))
        assert store.read("c", "o") == b"\x00" * 10 + b"xy"

    def test_overwrite_middle(self, store):
        store.apply_transaction(T().create_collection("c")
                                .write("c", "o", 0, b"aaaaaaaa")
                                .write("c", "o", 2, b"BB"))
        assert store.read("c", "o") == b"aaBBaaaa"

    def test_read_range(self, store):
        store.apply_transaction(T().create_collection("c")
                                .write("c", "o", 0, b"0123456789"))
        assert store.read("c", "o", 2, 3) == b"234"
        assert store.read("c", "o", 8, 100) == b"89"

    def test_write_accepts_views_and_ropes(self, store):
        """The zero-copy contract: every backend lands memoryview,
        numpy-backed-view and BufferList payloads bit-exactly (the EC
        fan-out hands stores shard VIEWS over the encode output)."""
        import numpy as np
        from ceph_tpu.utils.bufferlist import BufferList
        blob = bytes(range(256)) * 40
        arr = np.frombuffer(blob, dtype=np.uint8)
        rope = BufferList(blob[:100])
        rope.append(blob[100:])
        store.apply_transaction(
            T().create_collection("v")
            .write("v", "mv", 0, memoryview(blob))
            .write("v", "np", 0, memoryview(arr))
            .write("v", "rope", 0, rope)
            .write("v", "mid", 3, memoryview(blob)[5:50]))
        assert store.read("v", "mv") == blob
        assert store.read("v", "np") == blob
        assert store.read("v", "rope") == blob
        assert store.read("v", "mid") == b"\x00" * 3 + blob[5:50]
        # unaligned overwrite with a view (block rmw paths)
        store.apply_transaction(
            T().write("v", "mv", 7, memoryview(b"PATCH")))
        assert store.read("v", "mv") == blob[:7] + b"PATCH" + blob[12:]

    def test_zero_and_truncate(self, store):
        store.apply_transaction(T().create_collection("c")
                                .write("c", "o", 0, b"abcdefgh")
                                .zero("c", "o", 2, 3))
        assert store.read("c", "o") == b"ab\x00\x00\x00fgh"
        store.apply_transaction(T().truncate("c", "o", 4))
        assert store.read("c", "o") == b"ab\x00\x00"
        store.apply_transaction(T().truncate("c", "o", 6))
        assert store.read("c", "o") == b"ab\x00\x00\x00\x00"

    def test_remove_and_enoent(self, store):
        store.apply_transaction(T().create_collection("c").touch("c", "o"))
        store.apply_transaction(T().remove("c", "o"))
        with pytest.raises(StoreError) as ei:
            store.read("c", "o")
        assert ei.value.errno == ENOENT

    def test_clone(self, store):
        store.apply_transaction(T().create_collection("c")
                                .write("c", "src", 0, b"payload")
                                .setattr("c", "src", "a1", b"v1")
                                .omap_setkeys("c", "src", {"k": b"v"}))
        store.apply_transaction(T().clone("c", "src", "dst"))
        store.apply_transaction(T().write("c", "src", 0, b"CHANGED"))
        assert store.read("c", "dst") == b"payload"
        assert store.getattr("c", "dst", "a1") == b"v1"
        assert store.omap_get("c", "dst") == {"k": b"v"}

    def test_xattrs(self, store):
        store.apply_transaction(T().create_collection("c")
                                .setattr("c", "o", "n1", b"v1")
                                .setattr("c", "o", "n2", b"v2"))
        assert store.getattrs("c", "o") == {"n1": b"v1", "n2": b"v2"}
        store.apply_transaction(T().rmattr("c", "o", "n1"))
        with pytest.raises(StoreError):
            store.getattr("c", "o", "n1")

    def test_omap(self, store):
        store.apply_transaction(
            T().create_collection("c")
            .omap_setkeys("c", "o", {"a": b"1", "b": b"2", "c": b"3"}))
        assert store.omap_get_values("c", "o", ["a", "c", "zz"]) == {
            "a": b"1", "c": b"3"}
        store.apply_transaction(T().omap_rmkeys("c", "o", ["b"]))
        assert store.omap_get("c", "o") == {"a": b"1", "c": b"3"}
        store.apply_transaction(T().omap_clear("c", "o"))
        assert store.omap_get("c", "o") == {}

    def test_collection_list_sorted_after(self, store):
        t = T().create_collection("c")
        for name in ["obj3", "obj1", "obj5", "obj2"]:
            t.touch("c", name)
        store.apply_transaction(t)
        assert store.collection_list("c") == ["obj1", "obj2", "obj3", "obj5"]
        assert store.collection_list("c", start="obj2") == ["obj3", "obj5"]
        assert store.collection_list("c", start="obj1", max_count=2) == [
            "obj2", "obj3"]

    def test_collection_move_rename(self, store):
        store.apply_transaction(T().create_collection("c1")
                                .create_collection("c2")
                                .write("c1", "o", 0, b"data"))
        store.apply_transaction(
            T().collection_move_rename("c1", "o", "c2", "o2"))
        assert not store.exists("c1", "o")
        assert store.read("c2", "o2") == b"data"

    def test_commit_callbacks(self, store):
        fired = []
        t = T().create_collection("cb").write("cb", "o", 0, b"x")
        t.register_on_applied(lambda: fired.append("applied"))
        t.register_on_commit(lambda: fired.append("commit"))
        done = threading.Event()
        store.queue_transactions([t], on_commit=done.set)
        assert done.wait(5)
        assert "applied" in fired and "commit" in fired

    def test_list_collections(self, store):
        store.apply_transaction(T().create_collection("x")
                                .create_collection("y"))
        assert set(store.list_collections()) >= {"x", "y"}


class TestJournalReplay:
    def test_remount_preserves_state(self, tmp_path):
        path = str(tmp_path / "fs")
        s = JournalFileStore(path, commit_interval=60)
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c")
                            .write("c", "o", 0, b"persisted")
                            .omap_setkeys("c", "o", {"k": b"v"}))
        s.umount()
        s2 = JournalFileStore(path)
        s2.mount()
        assert s2.read("c", "o") == b"persisted"
        assert s2.omap_get("c", "o") == {"k": b"v"}
        s2.umount()

    def test_crash_without_checkpoint_replays_journal(self, tmp_path):
        path = str(tmp_path / "fs")
        s = JournalFileStore(path, commit_interval=3600)
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c")
                            .write("c", "o", 0, b"journal-only"))
        # simulate crash: no umount/checkpoint, just drop the handle
        s._jf.close()
        s2 = JournalFileStore(path)
        s2.mount()
        assert s2.read("c", "o") == b"journal-only"
        s2.umount()

    def test_torn_tail_write_is_discarded(self, tmp_path):
        path = str(tmp_path / "fs")
        s = JournalFileStore(path, commit_interval=3600)
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c")
                            .write("c", "o", 0, b"good"))
        seq = s._next_seq
        s._jf.close()
        # append a torn entry: a valid header promising more payload
        # bytes than the crash let reach the disk
        with open(os.path.join(path, "journal"), "ab") as f:
            from ceph_tpu.ops.crc32c import crc32c
            from ceph_tpu.utils import denc
            blob = denc.dumps([[("write", "c", "o", 0, b"torn")]])
            f.write(struct.pack("<QQI", len(blob), seq, crc32c(0, blob)))
            f.write(blob[: len(blob) // 2])
        s2 = JournalFileStore(path)
        s2.mount()
        assert s2.read("c", "o") == b"good"
        assert s2.journal_stats()["journal_torn_tail_discards"] == 1
        # the unparseable tail was discarded ON DISK: appends resume a
        # clean record stream and a further remount halts nowhere
        s2.apply_transaction(T().write("c", "o2", 0, b"after"))
        s2._jf.close()
        s3 = JournalFileStore(path)
        s3.mount()
        assert s3.read("c", "o") == b"good"
        assert s3.read("c", "o2") == b"after"
        assert s3.journal_stats()["journal_torn_tail_discards"] == 0
        s3.umount()

    def test_bitflipped_record_halts_replay_cleanly(self, tmp_path):
        """A crc-failing record must stop replay at the last valid
        record — not crash, not apply garbage."""
        path = str(tmp_path / "fs")
        s = JournalFileStore(path, commit_interval=3600)
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c")
                            .write("c", "keep", 0, b"kept"))
        start = s._journal_len
        s.apply_transaction(T().write("c", "lost", 0, b"lost"))
        s._jf.close()
        with open(os.path.join(path, "journal"), "r+b") as f:
            f.seek(start + 24)              # a payload byte of rec 2
            b = f.read(1)
            f.seek(start + 24)
            f.write(bytes([b[0] ^ 0x40]))
        s2 = JournalFileStore(path)
        s2.mount()
        assert s2.read("c", "keep") == b"kept"
        assert not s2.exists("c", "lost")
        assert s2.journal_stats()["journal_bad_record_halts"] == 1
        s2.umount()

    def test_replay_tolerates_failed_live_ops(self, tmp_path):
        """The journal is a WAL: an op that failed at LIVE apply time
        (e.g. a client remove of a never-created object, NACKed with
        ENOENT) was still journaled first.  Replay must reach the
        same end state the live run did — not refuse to mount
        (the filestore crash-restart soak caught this)."""
        path = str(tmp_path / "fs")
        s = JournalFileStore(path, commit_interval=3600)
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c")
                            .write("c", "o", 0, b"keep"))
        with pytest.raises(StoreError):
            s.apply_transaction(T().remove("c", "ghost"))
        s.apply_transaction(T().write("c", "p", 0, b"after"))
        s._jf.close()                      # crash: no checkpoint
        s2 = JournalFileStore(path)
        s2.mount()
        assert s2.read("c", "o") == b"keep"
        assert s2.read("c", "p") == b"after"
        assert s2.journal_stats()["journal_records_replayed"] == 3
        s2.umount()

    def test_corrupt_snapshot_falls_back_to_full_replay(self, tmp_path):
        path = str(tmp_path / "fs")
        s = JournalFileStore(path, commit_interval=3600)
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c")
                            .write("c", "o", 0, b"snapshotted"))
        s._checkpoint()
        s.apply_transaction(T().write("c", "p", 0, b"journal-tail"))
        s.umount()
        with open(os.path.join(path, "snapshot"), "r+b") as f:
            f.seek(16)
            f.write(b"\xde\xad\xbe\xef")    # body corruption: crc fails
        s2 = JournalFileStore(path)
        s2.mount()
        assert s2.read("c", "o") == b"snapshotted"
        assert s2.read("c", "p") == b"journal-tail"
        assert s2.journal_stats()["snapshot_corrupt_fallbacks"] == 1
        s2.umount()

    def test_checkpoint_then_more_journal(self, tmp_path):
        path = str(tmp_path / "fs")
        s = JournalFileStore(path, commit_interval=3600)
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c")
                            .write("c", "o1", 0, b"one"))
        s._checkpoint()
        s.apply_transaction(T().write("c", "o2", 0, b"two"))
        s._jf.close()  # crash after checkpoint + extra journal
        s2 = JournalFileStore(path)
        s2.mount()
        assert s2.read("c", "o1") == b"one"
        assert s2.read("c", "o2") == b"two"
        s2.umount()


class TestKV:
    def test_memdb_and_sqlite(self, tmp_path):
        from ceph_tpu.kv import MemDB, SqliteDB
        for db in (MemDB(), SqliteDB(str(tmp_path / "kv.db"))):
            db.open()
            t = db.transaction()
            t.set("p", "k1", b"v1")
            t.set("p", "k2", b"v2")
            t.set("q", "k1", b"other")
            db.submit_transaction(t)
            assert db.get("p", "k1") == b"v1"
            assert db.get("p", "nope") is None
            assert list(db.iterate("p")) == [("k1", b"v1"), ("k2", b"v2")]
            assert list(db.iterate("p", start="k2")) == [("k2", b"v2")]
            t2 = db.transaction()
            t2.rmkey("p", "k1")
            db.submit_transaction(t2)
            assert db.get("p", "k1") is None
            db.close()

    def test_sqlite_durability(self, tmp_path):
        from ceph_tpu.kv import SqliteDB
        path = str(tmp_path / "kv.db")
        db = SqliteDB(path)
        db.open()
        t = db.transaction()
        t.set("p", "k", b"v")
        db.submit_transaction(t, sync=True)
        db.close()
        db2 = SqliteDB(path)
        db2.open()
        assert db2.get("p", "k") == b"v"
        db2.close()

    def test_rm_prefix(self, tmp_path):
        from ceph_tpu.kv import MemDB
        db = MemDB()
        db.open()
        t = db.transaction()
        t.set("a", "k", b"1")
        t.set("b", "k", b"2")
        db.submit_transaction(t)
        t2 = db.transaction()
        t2.rmkeys_by_prefix("a")
        db.submit_transaction(t2)
        assert db.get("a", "k") is None
        assert db.get("b", "k") == b"2"


class TestKStoreDurability:
    def test_remount_preserves_everything(self, tmp_path):
        from ceph_tpu.store.kstore import KStore
        path = str(tmp_path / "kd")
        s = KStore(path)
        s.mkfs()
        s.mount()
        s.apply_transaction(
            T().create_collection("c").write("c", "o", 0, b"d" * 100000)
            .setattr("c", "o", "k", b"v").omap_setkeys("c", "o",
                                                       {"m": b"1"}))
        s.umount()
        s2 = KStore(path)
        s2.mount()
        assert s2.read("c", "o") == b"d" * 100000
        assert s2.getattr("c", "o", "k") == b"v"
        assert s2.omap_get("c", "o") == {"m": b"1"}
        s2.umount()

    def test_cluster_on_kstore(self, tmp_path):
        """OSDs run on the KV-backed store end to end."""
        import time
        from ceph_tpu.client import RadosError
        from ceph_tpu.vstart import MiniCluster
        c = MiniCluster(num_mons=1, num_osds=3, store_kind="kstore",
                        store_dir=str(tmp_path)).start()
        try:
            r = c.client()
            r.create_pool("kv", pg_num=4)
            io = r.open_ioctx("kv")
            end = time.time() + 20
            while True:
                try:
                    io.write_full("o", b"kv-backed!")
                    break
                except RadosError:
                    if time.time() > end:
                        raise
                    time.sleep(0.3)
            assert io.read("o") == b"kv-backed!"
        finally:
            c.stop()

    def test_omap_then_remove_in_one_txn(self, tmp_path):
        """Staged omap writes must be visible to later ops in the SAME
        transaction (regression: kstore wrote them past the staging)."""
        from ceph_tpu.store.kstore import KStore
        s = KStore()
        s.mkfs()
        s.apply_transaction(T().create_collection("c"))
        s.apply_transaction(
            T().omap_setkeys("c", "o", {"k": b"v"}).remove("c", "o"))
        s.apply_transaction(T().touch("c", "o"))
        assert s.omap_get("c", "o") == {}
        s.apply_transaction(
            T().omap_setkeys("c", "p", {"x": b"1"}).clone("c", "p", "p2"))
        assert s.omap_get("c", "p2") == {"x": b"1"}
        s.umount()

    def test_rmcoll_purges_omap(self, tmp_path):
        from ceph_tpu.store.kstore import KStore
        s = KStore()
        s.mkfs()
        s.apply_transaction(T().create_collection("d"))
        s.apply_transaction(T().omap_setkeys("d", "q", {"z": b"9"}))
        s.apply_transaction(T().remove_collection("d"))
        s.apply_transaction(T().create_collection("d").touch("d", "q"))
        assert s.omap_get("d", "q") == {}
        s.umount()

    def test_rmcoll_cancels_staged_ops_same_txn(self, tmp_path):
        from ceph_tpu.store.kstore import KStore
        s = KStore()
        s.mkfs()
        s.apply_transaction(
            T().create_collection("c").touch("c", "o")
            .write("c", "o", 0, b"x").remove_collection("c"))
        assert not s.collection_exists("c")
        assert not s.exists("c", "o")
        s.umount()


class TestBlockStore:
    """BlueStore-analog specifics: allocator, COW, deferred WAL,
    checksums (tests mirror store_test.cc's bluestore sections)."""

    def _mk(self, tmp_path, **kw):
        from ceph_tpu.store.blockstore import BlockStore
        s = BlockStore(str(tmp_path / "bs"), **kw)
        s.mkfs()
        s.mount()
        return s

    def test_allocator_coalesce_and_reuse(self):
        from ceph_tpu.store.blockstore import MIN_ALLOC, ExtentAllocator
        a = ExtentAllocator([[0, 8 * MIN_ALLOC]])
        e1 = a.allocate(2 * MIN_ALLOC)
        e2 = a.allocate(MIN_ALLOC)
        assert a.total_free() == 5 * MIN_ALLOC
        a.release(e1)
        a.release(e2)
        assert a.total_free() == 8 * MIN_ALLOC
        assert a.dump() == [[0, 8 * MIN_ALLOC]]   # coalesced back
        # splits across runs when no single run fits
        a2 = ExtentAllocator([[0, MIN_ALLOC], [10 * MIN_ALLOC, MIN_ALLOC]])
        got = a2.allocate(2 * MIN_ALLOC)
        assert sum(l for _, l in got) == 2 * MIN_ALLOC
        assert a2.total_free() == 0

    def test_overwrites_do_not_leak_space(self, tmp_path):
        import os
        from ceph_tpu.store.blockstore import GROW
        s = self._mk(tmp_path)
        s.apply_transaction(T().create_collection("c"))
        for i in range(200):
            s.apply_transaction(
                T().write("c", "o", 0, bytes([i % 251]) * 4096))
        s.umount()
        # 200 COW overwrites of one block must recycle freed blocks,
        # not grow the device past the first growth increment
        assert os.path.getsize(str(tmp_path / "bs" / "block")) <= GROW

    def test_remount_preserves_everything(self, tmp_path):
        from ceph_tpu.store.blockstore import BlockStore
        s = self._mk(tmp_path)
        payload = bytes(range(256)) * 2000          # multi-block
        s.apply_transaction(
            T().create_collection("c").write("c", "o", 0, payload)
            .setattr("c", "o", "k", b"v")
            .omap_setkeys("c", "o", {"m": b"1"}))
        s.umount()
        s2 = BlockStore(str(tmp_path / "bs"))
        s2.mount()
        assert s2.read("c", "o") == payload
        assert s2.getattr("c", "o", "k") == b"v"
        assert s2.omap_get("c", "o") == {"m": b"1"}
        s2.umount()

    def test_deferred_wal_replay_on_mount(self, tmp_path):
        """A small write whose device apply was lost (real one-shot
        crash at wal.post_kv_commit — KV committed, deferred applies
        never ran) must be recovered from the WAL at mount."""
        from ceph_tpu.store import CrashPoint
        from ceph_tpu.store.blockstore import BlockStore
        from ceph_tpu.utils import faults
        s = self._mk(tmp_path)
        s.owner = "osd.9"
        s.apply_transaction(T().create_collection("c"))
        faults.get().reset(seed=1)
        faults.get().crash("wal.post_kv_commit", 1.0, "osd.9")
        try:
            with pytest.raises(CrashPoint):
                s.apply_transaction(T().write("c", "o", 0, b"deferred!"))
            assert s.frozen
            s.dev.close()
            s.db.close()
            s2 = BlockStore(str(tmp_path / "bs"))
            s2.mount()
            assert s2.read("c", "o") == b"deferred!"
            assert s2.counters["wal_torn_extent_repairs"] >= 1
            assert s2.counters["wal_records_replayed"] == 1
            s2.umount()
        finally:
            faults.get().reset(seed=0)

    def test_csum_mismatch_surfaces_eio(self, tmp_path):
        from ceph_tpu.store import StoreError
        from ceph_tpu.store.blockstore import BlockStore
        s = self._mk(tmp_path)
        pattern = b"\xabPATTERN\xcd" * 500
        s.apply_transaction(
            T().create_collection("c").write("c", "o", 0, pattern))
        s.umount()
        block = str(tmp_path / "bs" / "block")
        with open(block, "r+b") as f:
            raw = f.read()
            at = raw.index(b"\xabPATTERN\xcd")
            f.seek(at)
            f.write(b"\xee")                        # silent corruption
        s2 = BlockStore(str(tmp_path / "bs"))
        s2.mount()
        with pytest.raises(StoreError) as ei:
            s2.read("c", "o")
        assert ei.value.errno == 5                  # EIO
        s2.umount()

    def test_zero_punches_holes(self, tmp_path):
        s = self._mk(tmp_path)
        s.apply_transaction(
            T().create_collection("c").write("c", "o", 0, b"x" * 16384))
        free_before = s.alloc.total_free()
        s.apply_transaction(T().zero("c", "o", 0, 8192))
        assert s.read("c", "o", 0, 8192) == b"\x00" * 8192
        assert s.read("c", "o", 8192, 8192) == b"x" * 8192
        # the two fully-zeroed blocks were deallocated
        assert s.alloc.total_free() >= free_before + 8192
        s.umount()

    def test_cluster_on_blockstore(self, tmp_path):
        """OSDs run on the raw-block store end to end."""
        import time
        from ceph_tpu.client import RadosError
        from ceph_tpu.vstart import MiniCluster
        c = MiniCluster(num_mons=1, num_osds=3, store_kind="blockstore",
                        store_dir=str(tmp_path)).start()
        try:
            r = c.client()
            r.create_pool("bp", pg_num=4)
            io = r.open_ioctx("bp")
            end = time.time() + 20
            while True:
                try:
                    io.write_full("o", b"block-backed!")
                    break
                except RadosError:
                    if time.time() > end:
                        raise
                    time.sleep(0.3)
            assert io.read("o") == b"block-backed!"
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# BlockStore WAL / extent crash-point matrix (the durability-frontier
# sites, mirroring TestCrashPointMatrix in test_journal.py): every
# site proves its promise — acked writes bit-exact after remount,
# torn extent windows either old or new, never interleaved.
# ---------------------------------------------------------------------------


class TestBlockStoreCrashMatrix:
    OWNER = "osd.7"

    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        from ceph_tpu.utils import faults
        faults.get().reset(seed=0)
        yield
        faults.get().reset(seed=0)

    def _mk(self, tmp_path, **kw):
        from ceph_tpu.store.blockstore import BlockStore
        s = BlockStore(str(tmp_path / "bs"), **kw)
        s.owner = self.OWNER
        s.mkfs()
        s.mount()
        return s

    def _remount(self, tmp_path):
        from ceph_tpu.store.blockstore import BlockStore
        s = BlockStore(str(tmp_path / "bs"))
        s.mount()
        return s

    def _arm(self, site, seed=0x5EED, reorder=False):
        from ceph_tpu.utils import faults
        faults.get().reset(seed=seed)
        faults.get().crash(site, 1.0, self.OWNER)
        if reorder:
            faults.get().fsync_reorder(1.0, self.OWNER)

    def _crash_write(self, s, oid, payload):
        from ceph_tpu.store import CrashPoint
        acked = []
        t = T().write("c", oid, 0, payload)
        t.register_on_commit(lambda: acked.append(oid))
        with pytest.raises(CrashPoint):
            s.queue_transactions([t])
        assert not acked, "a crashed write must never ack"
        assert s.frozen
        s.umount()

    @pytest.mark.parametrize("site", ["wal.pre_kv_commit",
                                      "wal.post_kv_commit",
                                      "wal.mid_apply"])
    @pytest.mark.parametrize("seed", [0x5EED, 0xA11CE])
    def test_wal_sites_old_or_new_never_interleaved(self, tmp_path,
                                                    site, seed):
        """Deferred (WAL-riding) overwrites through every WAL site:
        the base object and the prior payload stay bit-exact, the
        victim reads whole-old or whole-new — a mix of generations is
        the one forbidden outcome."""
        old = b"OLD." * 1024                      # 4 KiB: deferred
        new = b"NEWER..." * 512
        s = self._mk(tmp_path)
        s.apply_transaction(T().create_collection("c")
                            .write("c", "base", 0, b"base-bytes")
                            .write("c", "victim", 0, old))
        self._arm(site, seed=seed)
        self._crash_write(s, "victim", new)
        from ceph_tpu.utils import faults
        assert not faults.get().rules(), "crash rules are one-shot"
        s2 = self._remount(tmp_path)
        assert s2.read("c", "base") == b"base-bytes"
        got = s2.read("c", "victim")
        if site == "wal.pre_kv_commit":
            # the KV commit tore: whichever onode generation landed,
            # its payload must be WHOLE
            assert got in (old, new), "interleaved generations"
        else:
            # past the KV commit point: the write is durable even
            # though it never acked — replay must finish the job
            assert got == new
            assert s2.counters["wal_records_replayed"] >= 1
        s2.umount()

    def test_mid_cow_torn_extent_reads_old(self, tmp_path):
        """A direct (big, COW) overwrite torn mid-extent-copy: the
        committed onode still points at the old blocks, so every read
        after remount returns the OLD payload whole — the torn bytes
        sit in never-referenced blocks."""
        from ceph_tpu.store.blockstore import MIN_ALLOC
        old = bytes(range(256)) * (MIN_ALLOC // 8)    # many blocks
        new = b"\xeeNEW" * (len(old) // 4)
        s = self._mk(tmp_path, deferred_max=1024)     # force direct
        s.apply_transaction(T().create_collection("c")
                            .write("c", "victim", 0, old))
        self._arm("alloc.mid_cow")
        self._crash_write(s, "victim", new)
        s2 = self._remount(tmp_path)
        assert s2.read("c", "victim") == old
        # and the store keeps working: the allocator was repaired or
        # consistent, so new writes never corrupt surviving objects
        s2.apply_transaction(T().write("c", "fresh", 0, b"x" * 8192))
        assert s2.read("c", "victim") == old
        s2.umount()

    def test_pre_trim_crash_is_idempotent(self, tmp_path):
        """Crash between the deferred-apply fsync and the WAL trim:
        every record replays idempotently over already-applied state."""
        from ceph_tpu.store.blockstore import WAL_FLUSH_EVERY
        s = self._mk(tmp_path)
        s.apply_transaction(T().create_collection("c"))
        payloads = {}
        self._arm("wal.pre_trim")
        from ceph_tpu.store import CrashPoint
        try:
            for i in range(WAL_FLUSH_EVERY + 1):
                payloads[f"o{i}"] = f"payload-{i}-".encode() * 100
                s.apply_transaction(
                    T().write("c", f"o{i}", 0, payloads[f"o{i}"]))
        except CrashPoint:
            pass
        assert s.frozen, "the trim-site crash must have fired"
        s.umount()
        s2 = self._remount(tmp_path)
        for oid, data in payloads.items():
            if s2.exists("c", oid):
                assert s2.read("c", oid) == data
        # every write whose commit ACKED before the crash must be there
        assert s2.counters["wal_records_replayed"] >= 1
        s2.umount()

    def test_torn_kv_commit_keeps_other_objects_safe(self, tmp_path):
        """The torn-KV window's worst case is allocator damage (a
        block both referenced and free).  After remount the freelist
        verification must have made reuse safe: hammering new writes
        never corrupts the surviving objects."""
        s = self._mk(tmp_path)
        keep = {f"k{i}": f"keep-{i}-".encode() * 200 for i in range(4)}
        t = T().create_collection("c")
        for oid, data in keep.items():
            t.write("c", oid, 0, data)
        s.apply_transaction(t)
        self._arm("wal.pre_kv_commit", seed=0xBAD)
        self._crash_write(s, "victim", b"V" * 3000)
        s2 = self._remount(tmp_path)
        for i in range(50):
            s2.apply_transaction(
                T().write("c", f"churn{i % 7}", 0,
                          bytes([i % 251]) * 4096))
        for oid, data in keep.items():
            assert s2.read("c", oid) == data, f"{oid} corrupted"
        s2.umount()

    def test_torn_kv_commit_is_seed_deterministic(self, tmp_path):
        outcomes = []
        for run in range(2):
            sub = tmp_path / f"run{run}"
            sub.mkdir()
            s = self._mk(sub)
            s.apply_transaction(T().create_collection("c")
                                .write("c", "v", 0, b"OLD" * 700))
            self._arm("wal.pre_kv_commit", seed=0xABCD)
            self._crash_write(s, "v", b"NEW" * 700)
            s2 = self._remount(sub)
            outcomes.append((s2.read("c", "v"),
                             s2.counters["freelist_repairs"]))
            s2.umount()
        assert outcomes[0] == outcomes[1]

    def test_fsync_reorder_window_wal_applies(self, tmp_path):
        """The reordering model: deferred device applies buffered
        between fsync barriers survive as a SUBSET (durable B, lost
        earlier A).  Replay must still leave every committed write
        bit-exact — the WAL records outlive the lost device bytes."""
        from ceph_tpu.utils import faults
        s = self._mk(tmp_path)
        s.apply_transaction(T().create_collection("c"))
        # the reorder rule is armed BEFORE the buffered writes so
        # their pre-images are tracked; the crash rule comes last
        faults.get().reset(seed=0x5EED)
        faults.get().fsync_reorder(1.0, self.OWNER)
        payloads = {}
        for i in range(6):                   # buffered, un-fsync'd
            payloads[f"r{i}"] = f"reorder-{i}-".encode() * 150
            s.apply_transaction(
                T().write("c", f"r{i}", 0, payloads[f"r{i}"]))
        faults.get().crash("wal.post_kv_commit", 1.0, self.OWNER)
        self._crash_write(s, "r6", b"last-one" * 100)
        assert s.counters["fsync_reorder_windows"] == 1
        s2 = self._remount(tmp_path)
        for oid, data in payloads.items():
            assert s2.read("c", oid) == data, \
                f"{oid} lost to the reorder window"
        # r6's KV commit landed (post_kv_commit), so replay makes it
        # durable too
        assert s2.read("c", "r6") == b"last-one" * 100
        assert s2.counters["wal_torn_extent_repairs"] >= 1
        s2.umount()
