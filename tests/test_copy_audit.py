"""Copy-audit plane: the static hot-path scan stays clean, the scanner
itself catches regressions, and the runtime counters flow into perf
dump semantics."""

import numpy as np

from ceph_tpu.tools import copy_audit
from ceph_tpu.utils import copyaudit


class TestStaticPass:
    def test_hot_path_within_budget(self):
        """Tier-1 gate: a new bytes()/tobytes()/join in the zero-copy
        path fails here until its budget is consciously raised."""
        violations = copy_audit.audit()
        assert violations == [], "\n".join(violations)

    def test_scanner_catches_regressions(self):
        src = (
            "def send(payload):\n"
            "    flat = bytes(payload)      # the regression\n"
            "    arr = payload.tobytes()\n"
            "    joined = b''.join([flat, arr])\n"
            "    return joined\n")
        hits = copy_audit.scan_source(src)
        assert hits["bytes()"] == [2]
        assert hits[".tobytes()"] == [3]
        assert hits["b''.join()"] == [4]

    def test_scanner_ignores_prose_and_types(self):
        src = (
            '"""docstring mentioning bytes( and .tobytes( freely"""\n'
            "# comment: bytes( .tobytes( b''.join(\n"
            "def f(data: bytes) -> bytes:\n"
            "    s = 'literal with bytes( inside'\n"
            "    return data\n")
        assert copy_audit.scan_source(src) == {}

    def test_allowlist_files_exist(self):
        assert copy_audit.audit() == []      # includes missing-file check


class TestRuntimeCounters:
    def test_note_and_snapshot(self):
        copyaudit.note("test.site", 100)
        copyaudit.note("test.site", 50)
        snap = copyaudit.snapshot()
        assert snap["host_copies"] >= 2
        assert snap["ec_host_copy_bytes"] >= 150
        assert snap["sites"]["test.site"]["copies"] >= 2
        assert snap["sites"]["test.site"]["bytes"] >= 150

    def test_flatten_sites_fire(self):
        from ceph_tpu.utils.bufferlist import BufferList
        before = copyaudit.snapshot()
        bl = BufferList(b"a" * 64)
        bl.append(b"b" * 64)
        bl.to_bytes()
        after = copyaudit.snapshot()
        site = after["sites"]["bufferlist.flatten"]
        assert site["bytes"] >= \
            before["sites"].get("bufferlist.flatten",
                                {"bytes": 0})["bytes"] + 128

    def test_encode_staging_is_the_only_write_copy(self):
        """A whole-object EC encode through ecutil costs exactly one
        payload staging copy + one shard-major relayout — shard files
        come back as views, never per-shard bytes."""
        from ceph_tpu.erasure.registry import registry
        from ceph_tpu.osd import ecutil
        from ceph_tpu.utils.bufferlist import BufferList
        codec = registry.factory("jerasure", {"k": "2", "m": "1",
                                              "technique":
                                              "reed_sol_van"})
        sinfo = ecutil.StripeInfo(2, 256)
        payload = BufferList(b"x" * 1000)
        payload.append(b"y" * 500)
        before = copyaudit.snapshot()["sites"]
        shards, crcs = ecutil.encode_object_ex(codec, sinfo, payload)
        after = copyaudit.snapshot()["sites"]

        def delta(site):
            b = before.get(site, {"copies": 0})["copies"]
            return after.get(site, {"copies": 0})["copies"] - b

        assert delta("ec.stage") == 1
        assert delta("ec.shard_layout") == 1
        assert delta("bufferlist.flatten") == 0
        assert all(isinstance(s, memoryview) for s in shards)
        # the views are correct shard bytes (vs the bytes-payload run)
        shards2, _ = ecutil.encode_object_ex(codec, sinfo,
                                             payload.to_bytes())
        for a, b in zip(shards, shards2):
            assert bytes(a) == bytes(b)


class TestDecodeNoCopy:
    def test_decode_channel_key_is_cheap(self):
        """plugin_tpu regression: the decode-channel memo key must not
        serialize the decode matrix (rows.tobytes() copied it on every
        decode) — the key is the semantic (want, present, L) pattern
        and contains no bytes blob."""
        from ceph_tpu.erasure.registry import registry
        codec = registry.factory("tpu", {"k": "4", "m": "2",
                                         "technique": "reed_sol_van"})
        rows = codec._decode_rows([0], [1, 2, 3, 4])
        chan = codec._decode_channel([0], [1, 2, 3, 4], rows, 128)
        again = codec._decode_channel([0], [1, 2, 3, 4], rows, 128)
        assert chan is again                      # memoized
        flat = []

        def walk(x):
            if isinstance(x, tuple):
                for v in x:
                    walk(v)
            else:
                flat.append(x)

        walk(chan.key)
        assert not any(isinstance(v, (bytes, bytearray)) for v in flat)

    def test_decode_does_not_copy_input(self, monkeypatch):
        """The chunks array handed to decode_batch_async reaches the
        pipeline as the same memory (ascontiguousarray of a contiguous
        uint8 array is a no-op)."""
        from ceph_tpu.erasure.registry import registry
        from ceph_tpu.ops import pipeline as ec_pipeline
        codec = registry.factory("tpu", {"k": "4", "m": "2",
                                         "technique": "reed_sol_van"})
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, size=(2, 4, 128), dtype=np.uint8)
        parity = np.asarray(codec.encode_batch(data))
        present = [1, 2, 3, 4]
        stack = np.ascontiguousarray(
            np.stack([data[:, 1], data[:, 2], data[:, 3],
                      parity[:, 0]], axis=1))
        seen = {}
        real_submit = ec_pipeline.EcDevicePipeline.submit

        def spy(self, chan, arr, cache=None, qos=None):
            seen["arr"] = arr
            return real_submit(self, chan, arr, cache=cache, qos=qos)

        monkeypatch.setattr(ec_pipeline.EcDevicePipeline, "submit", spy)
        out = np.asarray(
            codec.decode_batch_async([0], present, stack).result())
        assert np.array_equal(out[:, 0], data[:, 0])
        assert "arr" in seen
        assert np.shares_memory(seen["arr"], stack), \
            "decode copied its input before submit"
