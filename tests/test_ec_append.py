"""EC partial-stripe append: appends touch only the tail stripe(s).

The reference's EC transactions are append-oriented and land at stripe
boundaries without rewriting existing stripes
(osd/ECTransaction.h:201 generate_transactions, osd/ECUtil.h:35
stripe_info_t).  Round 2 re-read and re-encoded the WHOLE object per
append; these tests pin the O(tail) behavior: per-shard bytes written
by an append ≈ append/k + one chunk, not object/k — and that the
chained HashInfo CRCs stay bit-exact (deep scrub agrees).
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.store import memstore
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_ec_pool("apnd", "ap_k2m1",
                         {"plugin": "tpu", "k": 2, "m": 1})
    ctx = rados.open_ioctx("apnd")
    end = time.time() + 60
    while True:
        try:
            ctx.write_full("settle", b"s")
            return ctx
        except RadosError:
            if time.time() > end:
                raise
            cluster.tick(0.3)


class _WriteMeter:
    """Counts bytes landed via Transaction write ops across every
    OSD's store, keyed by substring of the object name."""

    def __init__(self, cluster, match: str):
        self.cluster = cluster
        self.match = match
        self.bytes = 0
        self.orig = None

    def __enter__(self):
        meter = self
        self.orig = memstore.MemStore.apply_transaction

        def counting(store, txn):
            for op in txn.ops:
                if op[0] == "write" and meter.match in op[2]:
                    meter.bytes += len(op[4])
            return meter.orig(store, txn)

        memstore.MemStore.apply_transaction = counting
        return self

    def __exit__(self, *exc):
        memstore.MemStore.apply_transaction = self.orig


class TestPartialStripeAppend:
    def test_append_writes_only_the_tail(self, cluster, io):
        """8 MiB object + 64 KiB append: bytes written per append must
        scale with the append (64K/k + chunk), not the object."""
        base = bytes(range(256)) * (8 * 1024 * 1024 // 256)
        io.write_full("big", base)
        delta = b"D" * (64 * 1024)
        with _WriteMeter(cluster, "big") as m:
            io.append("big", delta)
        # k=2: data ~32 KiB/shard * 3 shards (m=1 parity carries the
        # same tail region) + a chunk of slack each + stash tails.
        # The round-2 whole-object path would have written ~12 MiB.
        assert m.bytes < 1024 * 1024, \
            f"append rewrote {m.bytes} bytes (O(object) path?)"
        assert m.bytes >= len(delta) * 3 // 2, "suspiciously few bytes"
        assert io.read("big") == base + delta

    def test_append_content_and_crcs_stay_consistent(self, cluster, io):
        """Unaligned appends chain CRCs; deep scrub must agree with
        the stored HashInfo on every shard afterwards."""
        acc = b""
        io.write_full("chain", acc)
        for i, n in enumerate([5, 4091, 4096, 9000, 1, 123457]):
            piece = bytes([i + 65]) * n
            io.append("chain", piece)
            acc += piece
            assert io.read("chain") == acc
        # deep scrub across the EC pool: zero inconsistencies means
        # every shard's bytes match its chained HashInfo crc
        pool_id = cluster.osds[0].osdmap.pool_by_name("apnd").id
        bad = []
        for osd in cluster.osds.values():
            for pgid, pg in osd.pgs.items():
                if pgid.pool == pool_id and pg.is_primary:
                    res = pg.scrub(deep=True)
                    bad.extend(res["inconsistent"])
        assert bad == [], bad

    def test_append_to_missing_object_creates_it(self, cluster, io):
        io.append("fresh", b"first-bytes")
        assert io.read("fresh") == b"first-bytes"

    def test_interleaved_appends_and_rewrites(self, cluster, io):
        io.write_full("mix", b"A" * 10)
        io.append("mix", b"B" * 5000)
        io.write_full("mix", b"C" * 100)     # back to whole-object
        io.append("mix", b"D" * 77)
        assert io.read("mix") == b"C" * 100 + b"D" * 77

    def test_append_tail_rides_the_shared_pipeline(self, cluster, io):
        """The O(tail) append path submits its tail-stripe encode
        through the async pipeline API (overlap window), and
        concurrent appends to different objects all stay bit-exact
        under that coalescing."""
        import threading

        from ceph_tpu.ops import pipeline as ec_pipeline

        for i in range(4):
            io.write_full(f"par{i}", bytes([i]) * 6000)
        ops_before = ec_pipeline.stats()["ops"]
        errs: list = []

        def appender(i):
            try:
                for j in range(3):
                    io.append(f"par{i}", bytes([64 + i + j]) * 3000)
            except Exception as e:            # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=appender, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs[0]
        for i in range(4):
            expect = bytes([i]) * 6000 + b"".join(
                bytes([64 + i + j]) * 3000 for j in range(3))
            assert io.read(f"par{i}") == expect
        # every tail encode rode the pipeline (one submission per
        # append at minimum; the whole-object writes above add more)
        assert ec_pipeline.stats()["ops"] >= ops_before + 12
