"""DurabilityLedger front doors: the acked-write oracle on CephFS and
RGW, not just RADOS.

The PR 5 ledger proved acked RADOS writes survive crash-restart
cycles; this drill proves the SAME machinery (write/delete/verify,
candidate digests, no-torn-state) holds at every front door — CephFS
metadata mutations (file create + data write + size flush, unlink)
and RGW object puts/deletes over real HTTP — across one abrupt OSD
crash + remount shared by both doors.  (The torn-journal MID-write
cases are pinned by the RADOS-path drills in test_chaos.py; the doors
prove the oracle's coverage of the front doors themselves.)
"""

import time

import pytest

from ceph_tpu.client import (CephFSDoor, DurabilityLedger, RGWDoor,
                             RadosError, SwiftDoor)
from ceph_tpu.utils import faults
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster

CONF = {
    "mon_tick_interval": 0.5,
    "osd_heartbeat_interval": 0.5,
    "osd_heartbeat_grace": 8.0,
    "mon_osd_min_down_reporters": 2,
    "mon_osd_down_out_interval": 5.0,
    # fail blocked ops fast: the MDS journals metadata under its big
    # lock, and a 30-virtual-second objecter stall there starves every
    # client request for minutes of real time after an OSD kill
    "objecter_op_timeout": 5.0,
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    # flight recorder armed for the whole module: the known ~1-in-6
    # "deg: ACKED write lost" flake (ROADMAP known-flakes) now
    # auto-captures every daemon's in-flight/historic ops + pg log
    # summaries the moment verify raises — the dump directory is
    # printed so a flaked CI run hands over the timeline instead of
    # a rerun-and-hope
    from ceph_tpu.utils import optracker
    fr_dir = str(tmp_path_factory.mktemp("flightrec"))
    optracker.recorder().arm(fr_dir)
    print(f"[ledger-doors] flight recorder armed: {fr_dir}")
    c = MiniCluster(num_mons=1, num_osds=3, conf=Config(dict(CONF)),
                    store_kind="filestore",
                    store_dir=str(tmp_path_factory.mktemp("doors"))
                    ).start()
    # settle the data plane before the gateways build their pools
    r = c.client()
    r.create_pool("warmup", pg_num=4)
    io = r.open_ioctx("warmup")
    end = time.time() + 40
    while True:
        try:
            io.write_full("w", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            c.tick(0.3)
    yield c
    c.stop()
    if optracker.recorder().records:
        print("[ledger-doors] flight recorder captured: "
              + ", ".join(optracker.recorder().records))
    optracker.recorder().disarm()


@pytest.fixture(scope="module")
def fs_door(cluster):
    from ceph_tpu.fs import CephFS, FsError
    cluster.start_mds("a")
    fs = CephFS(cluster.client("client.fsdoor"))
    end = time.time() + 60
    while True:
        try:
            fs.mount(timeout=10.0)
            break
        except FsError:
            if time.time() > end:
                raise
            cluster.tick(0.5)
    return CephFSDoor(fs, root="/ledger")


@pytest.fixture(scope="module")
def rgw(cluster):
    return cluster.start_rgw()


@pytest.fixture(scope="module")
def rgw_door(rgw):
    return RGWDoor(f"http://127.0.0.1:{rgw.port}", bucket="ldoor")


@pytest.fixture(scope="module")
def swift_door(rgw):
    # the SAME gateway spoken as TempAuth'd Swift v1: one namespace,
    # two dialects — the crash drill must hold for both
    return SwiftDoor(f"http://127.0.0.1:{rgw.port}", container="sdoor")


class TestFrontDoorLedgers:
    def test_acked_mutations_survive_osd_crash_on_every_door(
            self, cluster, fs_door, rgw_door, swift_door):
        """Acked CephFS file creates/writes/unlinks, RGW S3 HTTP
        puts/deletes AND TempAuth'd Swift puts/deletes are
        crash-verified through one abrupt OSD kill + remount (journal
        replay runs on the reborn daemon): every ack any front door
        handed out must read back bit-exact, and an acked
        unlink/DELETE stays gone."""
        retry = lambda: cluster.tick(0.3)        # noqa: E731
        fsl, rgwl = DurabilityLedger(), DurabilityLedger()
        swl = DurabilityLedger()
        for i in range(4):
            assert fsl.write(fs_door, f"f{i}",
                             f"fsdoor-{i}-".encode() * 50,
                             retry_window=120, on_retry=retry)
            assert rgwl.write(rgw_door, f"k{i}",
                              f"rgw-{i}-".encode() * 60,
                              retry_window=120, on_retry=retry)
            assert swl.write(swift_door, f"s{i}",
                             f"swift-{i}-".encode() * 55,
                             retry_window=120, on_retry=retry)
        assert fsl.delete(fs_door, "f3", retry_window=120,
                          on_retry=retry)
        assert rgwl.delete(rgw_door, "k3", retry_window=120,
                           on_retry=retry)
        assert swl.delete(swift_door, "s3", retry_window=120,
                          on_retry=retry)
        cluster.kill_osd(1)               # abrupt: store frozen as-is
        # degraded mutations keep acking and stay covered
        assert fsl.write(fs_door, "f0", b"degraded-rewrite" * 40,
                         retry_window=180, on_retry=retry)
        assert rgwl.write(rgw_door, "deg", b"degraded-put" * 40,
                          retry_window=180, on_retry=retry)
        assert swl.write(swift_door, "sdeg", b"degraded-swift" * 40,
                         retry_window=180, on_retry=retry)
        cluster.restart_osd(1, timeout=240)
        freport = fsl.verify(fs_door, retry_window=180, on_retry=retry)
        assert freport["checked"] == 4, freport
        assert freport["acked_deletes"] == 1, freport
        rreport = rgwl.verify(rgw_door, retry_window=180,
                              on_retry=retry)
        assert rreport["checked"] == 5, rreport
        assert rreport["acked_deletes"] == 1, rreport
        sreport = swl.verify(swift_door, retry_window=180,
                             on_retry=retry)
        assert sreport["checked"] == 5, sreport
        assert sreport["acked_deletes"] == 1, sreport
        # acked deletes stay deleted through the crash cycle, with the
        # door-native errno semantics
        with pytest.raises(RadosError):
            fs_door.read("f3")
        with pytest.raises(RadosError) as ei:
            rgw_door.read("k3")
        assert ei.value.errno == 2
        with pytest.raises(RadosError) as ei:
            swift_door.read("s3")
        assert ei.value.errno == 2
