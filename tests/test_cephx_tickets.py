"""cephx ticket protocol + rotating service keys (VERDICT r3 #6).

TGS indirection (auth/cephx/CephxProtocol.h:143): clients fetch
service tickets from the mon — sealed under the service class's
ROTATING secret — and present the blob on connect; service daemons
redeem it with rotating secrets fetched over their own mon channel.
Rotating the service key under live traffic must not fail I/O:
sessions renew via the client's ticket-refresh loop, and a ticket
sealed under a fully rotated-out secret is refused.
"""

import time

import pytest

from ceph_tpu.auth import generate_key
from ceph_tpu.client import RadosError
from ceph_tpu.utils import denc
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "auth_cluster_required": "cephx",
        "auth_service_ticket_ttl": 30.0,
        "key": generate_key(),
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rados(cluster):
    r = cluster.client()
    r.create_pool("tkt", pg_num=4)
    io = r.open_ioctx("tkt")
    end = time.time() + 40
    while True:
        try:
            io.write_full("settle", b"s")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return r


class TestTickets:
    def test_client_io_uses_ticket_auth(self, cluster, rados):
        io = rados.open_ioctx("tkt")
        # wait for the client's refresh loop to land an osd ticket,
        # then force fresh connections so the ticket path is used
        end = time.time() + 30
        while time.time() < end and \
                rados.monc._tickets.get("osd") is None:
            time.sleep(0.3)
        assert rados.monc._tickets.get("osd") is not None
        before = sum(
            o.msgr.perf.dump()["auth_ticket_accepts"]
            for o in cluster.osds.values())
        r2 = cluster.client("client.ticketed")
        io2 = r2.open_ioctx("tkt")
        end = time.time() + 30
        while time.time() < end and \
                r2.monc._tickets.get("osd") is None:
            time.sleep(0.3)
        io2.write_full("via-ticket", b"ticket-authed bytes")
        assert io2.read("via-ticket") == b"ticket-authed bytes"
        after = sum(
            o.msgr.perf.dump()["auth_ticket_accepts"]
            for o in cluster.osds.values())
        assert after > before, "no OSD accepted a ticket handshake"

    def test_rotation_under_live_traffic(self, cluster, rados):
        io = rados.open_ioctx("tkt")
        rv, out, _ = rados.mon_command(
            {"prefix": "auth rotate", "service": "osd"})
        assert rv == 0, out
        # live I/O keeps working across repeated rotations: existing
        # sessions are untouched, new sessions renew tickets
        for i in range(3):
            io.write_full(f"rot{i}", f"alive-{i}".encode())
            assert io.read(f"rot{i}") == f"alive-{i}".encode()
            rv, out, _ = rados.mon_command(
                {"prefix": "auth rotate", "service": "osd"})
            assert rv == 0, out
            time.sleep(0.3)
        # a FRESH client after all those rotations still connects
        # (its ticket is sealed under the current secret)
        r3 = cluster.client("client.postrot")
        io3 = r3.open_ioctx("tkt")
        end = time.time() + 30
        while time.time() < end:
            try:
                io3.write_full("post-rotate", b"still fine")
                break
            except RadosError:
                time.sleep(0.3)
        assert io3.read("post-rotate") == b"still fine"

    def test_fully_rotated_out_ticket_refused(self, cluster, rados):
        """A ticket sealed under a secret that has been rotated out of
        BOTH slots (current + previous) must be refused — the 'old
        tickets expire' half of the rotation contract."""
        rv, _out, data = rados.mon_command(
            {"prefix": "auth get-ticket", "service": "osd"})
        assert rv == 0
        stale = denc.loads(data)
        rados.mon_command({"prefix": "auth rotate", "service": "osd"})
        rados.mon_command({"prefix": "auth rotate", "service": "osd"})
        # give the OSDs time to pick up the rotated secrets
        deadline = time.time() + 40
        refused = False
        while time.time() < deadline and not refused:
            r4 = cluster.client("client.stale")
            r4.monc._auth_stop = True           # no auto-renew
            r4.monc._tickets = {"osd": stale}   # pin the stale blob
            r4.msgr.ticket_provider = r4.monc._tickets.get
            io4 = r4.open_ioctx("tkt")
            try:
                # refusal surfaces as the op never acking: a short
                # per-op deadline keeps each probe cheap (the default
                # 30s objecter timeout would stall the whole attempt)
                io4._op("stale-tkt", [("writefull", b"x")], timeout=3.0)
            except RadosError:
                refused = True
                break
            # the write went through: OSDs may still hold the old
            # secret in their previous slot; wait for the refresh
            r4.shutdown()
            time.sleep(2.0)
        assert refused, "stale ticket was still accepted"

    def test_rotating_keys_gated_to_service_daemons(self, rados):
        rv, out, _ = rados.mon_command(
            {"prefix": "auth get-rotating", "service": "osd"})
        assert rv == -13, f"client read rotating keys: {out}"
