"""BufferList rope + CTM2 data-segment wire path.

Property suite: every rope operation (append of mixed source types,
zero-copy slice, concat, iov reassembly, chained crc32c) is checked
against a plain-bytes oracle, including zero-length and unaligned
slices.  Wire suite: large payloads ride out-of-band data segments
bit-exact — through plain sockets, through cephx-signed sockets, and
through the FaultSet socket-kill/reconnect resend path — and CTM1
frames still decode (magic-gated back-compat).
"""

import queue
import time

import numpy as np
import pytest

from ceph_tpu.msg import Dispatcher, Message, Messenger, register_message
from ceph_tpu.msg.message import MAGIC, MAGIC2, SEG_THRESHOLD, _HDR
from ceph_tpu.ops import crc32c as crc_mod
from ceph_tpu.utils.bufferlist import (BufferList, as_buffer, concat,
                                       iov_of, wrap_payload)
from ceph_tpu.utils.config import Config


class TestRopeProperties:
    def _mixed_sources(self, rng):
        """(piece-as-exotic-type, piece-as-bytes) pairs."""
        out = []
        for _ in range(rng.integers(1, 9)):
            n = int(rng.choice([0, 1, 7, 128, 4096, 10000]))
            raw = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
            kind = rng.integers(0, 4)
            if kind == 0:
                out.append((raw, raw))
            elif kind == 1:
                out.append((memoryview(raw), raw))
            elif kind == 2:
                out.append((np.frombuffer(raw, dtype=np.uint8), raw))
            else:
                out.append((BufferList(raw), raw))
        return out

    def test_append_vs_oracle(self):
        rng = np.random.default_rng(11)
        for _ in range(30):
            bl = BufferList()
            oracle = b""
            for piece, raw in self._mixed_sources(rng):
                bl.append(piece)
                oracle += raw
            assert len(bl) == len(oracle)
            assert bl.to_bytes() == oracle
            assert bl == oracle
            assert bytes(bl) == oracle

    def test_slice_vs_oracle_unaligned(self):
        rng = np.random.default_rng(13)
        bl = BufferList()
        oracle = b""
        for piece, raw in self._mixed_sources(rng):
            bl.append(piece)
            oracle += raw
        n = len(oracle)
        cases = [(0, 0), (0, n), (n, 0), (n, 5)]
        for _ in range(60):
            off = int(rng.integers(0, n + 1))
            length = int(rng.integers(0, n - off + 2))
            cases.append((off, length))
        for off, length in cases:
            got = bl.slice(off, length)
            want = oracle[off: off + length]
            assert got.to_bytes() == want, (off, length)
            assert len(got) == len(want)
        # python slice syntax, and slices share memory (zero-copy)
        assert bl[3: n - 7].to_bytes() == oracle[3: n - 7]
        if bl.num_segments:
            seg0 = bl.iov()[0]
            sl = bl.slice(0, len(seg0))
            assert np.shares_memory(np.frombuffer(sl.iov()[0],
                                                  dtype=np.uint8),
                                    np.frombuffer(seg0, dtype=np.uint8))

    def test_iov_reassembly_and_concat(self):
        rng = np.random.default_rng(17)
        parts = self._mixed_sources(rng)
        bl = concat(p for p, _raw in parts)
        oracle = b"".join(raw for _p, raw in parts)
        assert b"".join(bytes(s) for s in bl.iov()) == oracle
        assert sum(len(s) for s in iov_of(bl)) == len(oracle)
        # appending a rope shares segments
        bl2 = BufferList(bl)
        assert bl2.num_segments == bl.num_segments
        assert bl2 == bl

    def test_crc32c_chained_vs_oracle(self):
        rng = np.random.default_rng(19)
        for seed in (0, 1, 0xDEADBEEF):
            bl = BufferList()
            oracle = b""
            for piece, raw in self._mixed_sources(rng):
                bl.append(piece)
                oracle += raw
            assert bl.crc32c(seed) == crc_mod.crc32c(seed, oracle)
        assert BufferList().crc32c(7) == 7          # empty rope: seed

    def test_indexing(self):
        bl = BufferList(b"abc")
        bl.append(b"defg")
        assert bl[0] == ord("a") and bl[4] == ord("e")
        assert bl[-1] == ord("g")
        with pytest.raises(IndexError):
            bl[7]

    def test_wrap_payload_contract(self):
        raw = b"imm"
        assert wrap_payload(raw) is raw              # immutable: shared
        mv = memoryview(raw)
        assert wrap_payload(mv) is mv
        ba = bytearray(b"mut")
        out = wrap_payload(ba)
        assert isinstance(out, bytes)                # snapshot
        ba[0] = 0
        assert out == b"mut"
        bl = BufferList(b"x" * 10)
        assert wrap_payload(bl) is bl

    def test_as_buffer(self):
        one = BufferList(b"single-seg")
        v = as_buffer(one)
        assert isinstance(v, memoryview) and bytes(v) == b"single-seg"
        two = BufferList(b"a" * 4)
        two.append(b"b" * 4)
        assert as_buffer(two) == b"aaaabbbb"         # flatten (audited)
        assert as_buffer(b"plain") == b"plain"


class QueueDispatcher(Dispatcher):
    def __init__(self):
        self.q: queue.Queue = queue.Queue()

    def ms_dispatch(self, conn, msg):
        self.q.put((conn, msg))
        return True

    def get(self, timeout=10):
        return self.q.get(timeout=timeout)


@register_message
class MSeg(Message):
    TYPE = 9100


def make_msgr(name, conf=None):
    m = Messenger(name, conf=conf)
    m.bind(("127.0.0.1", 0))
    disp = QueueDispatcher()
    m.add_dispatcher_tail(disp)
    m.start()
    return m, disp


class TestDataSegments:
    def test_large_fields_ride_segments(self):
        """Fields over the threshold leave the denc payload and ride
        as iovec segments — sharing the sender's buffer, not copying."""
        blob = bytes(range(256)) * 64          # 16 KiB
        rope = BufferList(b"ab" * 4000)
        rope.append(blob)
        msg = MSeg(a=blob, ops=[("writefull", rope)], small=b"s")
        iov = msg.encode_iov(seq=3)
        assert bytes(iov[0][:4]) == MAGIC2
        assert any(b is blob for b in iov), "payload must ride uncopied"
        out = Message.decode_frame(msg.encode(seq=3))
        assert out.a == blob
        assert bytes(out.ops[0][1]) == rope.to_bytes()
        assert out.small == b"s"

    def test_small_frames_stay_ctm1(self):
        msg = MSeg(x=1, blob=b"tiny" * 10)
        iov = msg.encode_iov(seq=1)
        assert bytes(iov[0][:4]) == MAGIC
        # CTM1 back-compat: the v1 parse path still decodes it
        frame = msg.encode(seq=1)
        type_id, plen, seq = Message.parse_header(
            frame[:Message.header_size()])
        out = Message.decode(type_id, seq, frame[Message.header_size():])
        assert out.blob == b"tiny" * 10 and out.seq == 1

    def test_hostile_segment_refs_rejected(self):
        """A _SegRef is a registered denc type, so any peer can encode
        one: out-of-range / negative indices and refs in segment-free
        frames must raise the corrupt-frame ValueError (which the
        messenger skips cleanly) — never IndexError, and never silent
        wrong-segment substitution."""
        from ceph_tpu.msg.message import _SegRef
        from ceph_tpu.utils import denc

        def frame_with(fields, segs):
            payload = denc.dumps(fields)
            return payload, segs

        broken = _SegRef(0)
        del broken.__dict__["i"]                      # denc-encodable
        for fields, segs in (
                ({"x": _SegRef(5)}, [b"only-one"]),   # out of range
                ({"x": _SegRef(-1)}, [b"a", b"b"]),   # negative alias
                ({"x": [1, (_SegRef(0),)]}, []),      # ref, no segments
                ({"x": broken}, [b"seg"]),            # no index at all
                ({"x": _SegRef("0")}, [b"seg"]),      # non-int index
        ):
            payload, segs = frame_with(fields, segs)
            with pytest.raises(ValueError):
                Message.decode(MSeg.TYPE, 1, payload, segs)

    def test_socket_roundtrip_bit_exact(self):
        a, _ = make_msgr("a")
        b, bd = make_msgr("b")
        try:
            rng = np.random.default_rng(5)
            blobs = [rng.integers(0, 256, size=n, dtype=np.uint8
                                  ).tobytes()
                     for n in (SEG_THRESHOLD, 1 << 16, (1 << 20) + 13)]
            for i, blob in enumerate(blobs):
                rope = BufferList(blob[: 1000])
                rope.append(blob[1000:])
                a.send_message(
                    MSeg(i=i, payload=blob, rope=rope), "b", b.addr)
            for i, blob in enumerate(blobs):
                _, msg = bd.get()
                assert msg.i == i
                assert msg.payload == blob
                assert bytes(msg.rope) == blob
        finally:
            a.shutdown()
            b.shutdown()

    def test_signed_segments_roundtrip(self):
        """cephx signing covers header + table + payload + segments as
        an iovec fold; a signed large-payload frame verifies and a
        tampered segment would fail (same-digest-as-joined contract)."""
        from ceph_tpu.auth import cephx, generate_key
        key = generate_key()

        def mk(name):
            conf = Config({"ms_connect_timeout": 2.0,
                           "ms_max_backoff": 0.5})
            conf.set_val("auth_cluster_required", "cephx")
            conf.set_val("key", key)
            conf.apply_changes()
            m = Messenger(name, conf=conf)
            m.bind(("127.0.0.1", 0))
            d = QueueDispatcher()
            m.add_dispatcher_tail(d)
            m.start()
            return m, d

        a, _ = mk("client.a")
        b, bd = mk("osd.0")
        try:
            blob = bytes(range(256)) * 256     # 64 KiB, segmented
            a.send_message(MSeg(payload=blob), "osd.0", b.addr)
            _, msg = bd.get()
            assert msg.payload == blob
        finally:
            a.shutdown()
            b.shutdown()
        # the iov signature equals the joined-frame signature
        skey = b"k" * 32
        parts = [b"C", b"hdr", b"payload", b"seg0", b"seg1"]
        assert cephx.sign_iov(skey, parts) == cephx.sign(
            skey, b"".join(parts))

    def test_segments_survive_socket_kill_resend(self):
        """FaultSet-style socket kills mid-stream: the lossless resend
        path replays iovec frames (segments included) bit-exact and in
        order."""
        conf = Config({"ms_inject_socket_failures": 4})
        a, _ = make_msgr("a", conf)
        b, bd = make_msgr("b")
        try:
            rng = np.random.default_rng(23)
            n = 25
            blobs = [rng.integers(0, 256, size=8192, dtype=np.uint8
                                  ).tobytes() for _ in range(n)]
            for i, blob in enumerate(blobs):
                a.send_message(MSeg(i=i, payload=blob), "b", b.addr)
            got = {}
            deadline = time.time() + 30
            while len(got) < n and time.time() < deadline:
                _, msg = bd.get(timeout=30)
                got[msg.i] = msg.payload
            assert sorted(got) == list(range(n))
            for i, blob in enumerate(blobs):
                assert got[i] == blob, f"payload {i} corrupted by resend"
        finally:
            a.shutdown()
            b.shutdown()
