"""Load harness: seeded determinism, open-loop semantics, report
schema — plus the cache-served read coherence drill (bit-exactness vs
the store oracle under overwrites, appends and quarantine drops)."""

import time

import pytest

from ceph_tpu.tools.loadgen import (LoadGen, TenantSpec, _payload_bytes,
                                    _zipf_cdf)


class TestSchedule:
    def test_seed_deterministic(self):
        spec = TenantSpec("p", rate=200, duration=2.0, obj_count=32)
        a = LoadGen([spec], seed=7).schedule
        b = LoadGen([spec], seed=7).schedule
        assert [(o.t, o.pool, o.kind, o.oid, o.body_seed)
                for o in a] == \
            [(o.t, o.pool, o.kind, o.oid, o.body_seed) for o in b]
        c = LoadGen([spec], seed=8).schedule
        assert [(o.t, o.oid) for o in a] != [(o.t, o.oid) for o in c]

    def test_rate_and_duration_respected(self):
        spec = TenantSpec("p", rate=500, duration=4.0)
        sched = LoadGen([spec], seed=3).schedule
        assert all(0 <= o.t < 4.0 for o in sched)
        # Poisson(500/s * 4s): well within 5 sigma
        assert 1700 <= len(sched) <= 2300

    def test_zipf_head_is_hot(self):
        spec = TenantSpec("p", rate=2000, duration=2.0,
                          obj_count=64, zipf_s=1.2, read_frac=1.0)
        sched = LoadGen([spec], seed=5).schedule
        counts: dict[str, int] = {}
        for op in sched:
            counts[op.oid] = counts.get(op.oid, 0) + 1
        ranked = sorted(counts.values(), reverse=True)
        # the hot head dominates the tail
        assert ranked[0] > 5 * (ranked[-1] if ranked[-1] else 1)

    def test_op_mix(self):
        spec = TenantSpec("p", rate=1000, duration=2.0,
                          read_frac=0.5, append_frac=0.5)
        sched = LoadGen([spec], seed=9).schedule
        kinds = {k: sum(1 for o in sched if o.kind == k)
                 for k in ("read", "write_full", "append")}
        total = len(sched)
        assert 0.4 < kinds["read"] / total < 0.6
        assert kinds["append"] > 0 and kinds["write_full"] > 0

    def test_zipf_cdf_monotone(self):
        cdf = _zipf_cdf(16, 1.1)
        assert cdf == sorted(cdf) and abs(cdf[-1] - 1.0) < 1e-9
        flat = _zipf_cdf(4, 0.0)
        assert flat == [0.25, 0.5, 0.75, 1.0]

    def test_payloads_distinct_and_deterministic(self):
        assert _payload_bytes(1, 100) == _payload_bytes(1, 100)
        assert _payload_bytes(1, 100) != _payload_bytes(2, 100)
        assert len(_payload_bytes(3, 12345)) == 12345
        assert _payload_bytes(1, 0) == b""


class _StubIoCtx:
    """In-memory IoCtx stub with a configurable service delay."""

    def __init__(self, delay: float = 0.0):
        self.objs: dict[str, bytes] = {}
        self.delay = delay

    def _d(self):
        if self.delay:
            time.sleep(self.delay)

    def write_full(self, oid, data):
        self._d()
        self.objs[oid] = bytes(data)

    def append(self, oid, data):
        self._d()
        self.objs[oid] = self.objs.get(oid, b"") + bytes(data)

    def read(self, oid):
        self._d()
        return self.objs[oid]


class TestRun:
    def test_report_schema_and_goodput(self):
        spec = TenantSpec("p", rate=300, duration=1.0, obj_count=8,
                          read_frac=0.5, payload=1024)
        rep = LoadGen([spec], seed=11).run({"p": _StubIoCtx()})
        assert rep["completed"] == sum(rep["offered"].values())
        st = rep["pools"]["p"]
        for key in ("ops", "errors", "timeouts", "reads", "writes",
                    "p50_ms", "p99_ms", "p999_ms", "mean_ms",
                    "goodput_gbs", "queue_depth_max",
                    "queue_depth_mean"):
            assert key in st, key
        assert st["errors"] == 0
        assert st["p50_ms"] <= st["p99_ms"] <= st["p999_ms"]
        assert rep["goodput_gbs"] > 0

    def test_open_loop_latency_includes_queueing(self):
        """A slow backend must SHOW its backlog: arrivals outpace a
        25 ms service time, so the open-loop p99 (measured from the
        scheduled arrival) grows far beyond one service time."""
        spec = TenantSpec("p", rate=150, duration=1.0, obj_count=4,
                          read_frac=0.0, payload=64, max_workers=1)
        rep = LoadGen([spec], seed=13).run(
            {"p": _StubIoCtx(delay=0.025)}, warm=False)
        st = rep["pools"]["p"]
        assert st["p99_ms"] > 300.0            # backlog, not service
        assert st["queue_depth_max"] > 5


# ---------------------------------------------------------------------------
# Cache-served read coherence: bit-exact vs the store oracle through
# overwrites, appends (write-through) and quarantine drops.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    from ceph_tpu.utils.config import Config
    from ceph_tpu.vstart import MiniCluster
    c = MiniCluster(num_mons=1, num_osds=3, conf=Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "mon_osd_down_out_interval": 5.0,
    })).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def ec_io(cluster):
    rados = cluster.client()
    # host_cutover=1: encodes ride the (CPU-mesh) device lanes so the
    # HBM stripe cache populates exactly as on a real chip
    rados.create_ec_pool("cread", "creadp",
                         {"plugin": "tpu", "k": 2, "m": 1,
                          "host_cutover": 1}, pg_num=4)
    io = rados.open_ioctx("cread")
    end = time.time() + 60
    while True:
        try:
            io.write_full("settle", b"s")
            return io
        except Exception:
            if time.time() > end:
                raise
            time.sleep(0.3)


def _write_until_cached(io, cache, oid: str, body: bytes,
                        window: float = 60.0) -> None:
    """Overwrite until a probe read serves from the cache (lanes warm
    their fused fns in the background; cold-lane writes host-serve)."""
    end = time.time() + window
    while time.time() < end:
        io.write_full(oid, body)
        s0 = cache.stats()["read_bytes_served"]
        got = io.read(oid)
        assert bytes(got) == body          # correct either way
        if cache.stats()["read_bytes_served"] > s0:
            return
        time.sleep(0.2)
    raise AssertionError(f"{oid} never became cache-served")


class TestCacheServedReads:
    def test_bit_exact_through_overwrites_appends_and_drops(
            self, cluster, ec_io):
        from ceph_tpu.ops import hbm_cache
        from ceph_tpu.ops import pipeline as ec_pipeline
        from ceph_tpu.utils import faults
        cache = hbm_cache.get()
        payload = 3 * 8192 + 517           # unaligned: padding paths
        v1 = _payload_bytes(0xA1, payload)
        _write_until_cached(ec_io, cache, "cobj", v1)
        # 1) cache-served == store oracle for the SAME read: disable
        # the cache (clears it), read again, compare byte-for-byte
        cached_read = bytes(ec_io.read("cobj"))
        hbm_cache.configure(0)
        try:
            oracle = bytes(ec_io.read("cobj"))
        finally:
            hbm_cache.configure(64 << 20)
        assert cached_read == oracle == v1
        # 2) overwrite coherence: the stale entry must never serve
        v2 = _payload_bytes(0xA2, payload - 2048)
        _write_until_cached(ec_io, cache, "cobj", v2)
        assert bytes(ec_io.read("cobj")) == v2
        # 3) append write-through: the appended object stays
        # cache-served (no re-upload of the prefix) and bit-exact
        delta = _payload_bytes(0xA3, 4321)
        s = cache.stats()
        ec_io.append("cobj", delta)
        got = bytes(ec_io.read("cobj"))
        assert got == v2 + delta
        s2 = cache.stats()
        if s2["append_throughs"] > s["append_throughs"]:
            # the write-through engaged: that read came off the chip
            assert s2["read_bytes_served"] > s["read_bytes_served"]
        # 4) quarantine drop: kill the lane(s), entries must drop and
        # the store path keeps serving the same bytes
        faults.get().tpu_error(1.0)        # every lane
        try:
            assert bytes(ec_io.read("cobj")) == v2 + delta
        finally:
            faults.get().reset()
        ec_pipeline.get().reset_devices()

    def test_concurrent_overwrites_never_serve_stale(
            self, cluster, ec_io):
        """Interleave overwrites and reads: every read must return
        the value of SOME completed write (monotone versions — a
        cache serving a stale entry would resurrect an old payload
        after a newer read observed the overwrite)."""
        import threading
        payload = 16384
        versions = [_payload_bytes(0xB0 + i, payload)
                    for i in range(6)]
        ec_io.write_full("race", versions[0])
        errors = []

        def reader():
            # sequential reads from one client: versions are monotone
            # at the primary, so observing v_i and THEN v_j (j < i)
            # means a stale cache entry served after its overwrite
            high = 0
            for _ in range(40):
                try:
                    got = bytes(ec_io.read("race"))
                except Exception:
                    continue
                try:
                    idx = versions.index(got)
                except ValueError:
                    errors.append("read returned bytes matching NO "
                                  "written version")
                    return
                if idx < high:
                    errors.append(
                        f"stale read: v{idx} after v{high}")
                    return
                high = idx

        th = threading.Thread(target=reader)
        th.start()
        for body in versions[1:]:
            ec_io.write_full("race", body)
            time.sleep(0.02)
        th.join(timeout=60)
        assert not th.is_alive()
        assert not errors, errors
