"""End-to-end cluster tests: the minimum system slice (SURVEY.md §7).

1 quorum of mons + 3 osds (MemStore) + librados client on localhost:
rados put/get on a replicated pool, then an EC pool k=2,m=1 exercising
the TPU encode path, degraded reads after osd kill, scrub.
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=3, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rados(cluster):
    return cluster.client()


class TestReplicatedPool:
    def test_put_get(self, cluster, rados):
        rados.create_pool("rep", pg_num=8)
        io = rados.open_ioctx("rep")
        io.write_full("obj1", b"hello world")
        assert io.read("obj1") == b"hello world"

    def test_partial_write_and_read(self, cluster, rados):
        io = rados.open_ioctx("rep")
        io.write_full("obj2", b"0123456789")
        io.write("obj2", b"AB", offset=3)
        assert io.read("obj2") == b"012AB56789"
        assert io.read("obj2", length=4, offset=2) == b"2AB5"

    def test_append_stat_remove(self, cluster, rados):
        io = rados.open_ioctx("rep")
        io.write_full("obj3", b"aaa")
        io.append("obj3", b"bbb")
        st = io.stat("obj3")
        assert st["size"] == 6
        io.remove_object("obj3")
        with pytest.raises(RadosError) as ei:
            io.read("obj3")
        assert ei.value.errno == 2

    def test_xattr_omap(self, cluster, rados):
        io = rados.open_ioctx("rep")
        io.write_full("obj4", b"x")
        io.set_xattr("obj4", "k", b"v")
        assert io.get_xattr("obj4", "k") == b"v"
        io.set_omap("obj4", {"a": b"1", "b": b"2"})
        assert io.get_omap("obj4") == {"a": b"1", "b": b"2"}

    def test_replication_to_all_osds(self, cluster, rados):
        """The object must exist in the pg collection on every replica."""
        io = rados.open_ioctx("rep")
        io.write_full("replicated-obj", b"copies everywhere")
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "replicated-obj")
        up, acting = m.pg_to_up_acting_osds(pgid)
        assert len(acting) == 3
        time.sleep(0.5)   # replica acks already gathered; small settle
        for osd_id in acting:
            store = cluster.osds[osd_id].store
            assert store.read(f"pg_{pgid}", "replicated-obj") == \
                b"copies everywhere", f"osd.{osd_id}"

    def test_list_objects(self, cluster, rados):
        io = rados.open_ioctx("rep")
        names = io.list_objects()
        assert "obj1" in names and "replicated-obj" in names


class TestECPool:
    def test_ec_put_get(self, cluster, rados):
        rados.create_ec_pool("ecpool", "k2m1",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "technique": "reed_sol_van"})
        io = rados.open_ioctx("ecpool")
        payload = bytes(range(256)) * 40    # 10240 bytes
        io.write_full("ecobj", payload)
        assert io.read("ecobj") == payload

    def test_shards_spread_with_parity(self, cluster, rados):
        io = rados.open_ioctx("ecpool")
        payload = b"E" * 4096
        io.write_full("spread", payload)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "spread")
        up, acting = m.pg_to_up_acting_osds(pgid)
        live = [o for o in acting if o >= 0]
        assert len(live) == 3
        time.sleep(0.5)
        sizes = []
        for shard, osd_id in enumerate(acting):
            store = cluster.osds[osd_id].store
            data = store.read(f"pg_{pgid}", f"spread.s{shard}")
            sizes.append(len(data))
        # k=2 data shards + 1 parity, all chunk-size
        assert len(set(sizes)) == 1
        assert sizes[0] >= 4096 // 2

    def test_ec_append(self, cluster, rados):
        io = rados.open_ioctx("ecpool")
        io.write_full("appendobj", b"first-")
        # a loaded suite can push the append's sub-op gather past the
        # client op deadline; a timed-out op may still have landed, so
        # re-check before retrying (a blind retry would double-append)
        import time
        end = time.time() + 60
        while True:
            try:
                io.append("appendobj", b"second")
                break
            except RadosError:
                if io.read("appendobj") == b"first-second":
                    break
                if time.time() > end:
                    raise
                cluster.tick(0.3)
        assert io.read("appendobj") == b"first-second"

    def test_ec_write_uses_fused_device_pass(self, cluster, rados):
        """Repeated large EC writes must route through the fused
        device encode+CRC pass (VERDICT: assert via a counter)."""
        rados.create_ec_pool("ecfused", "k2m1dev",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "technique": "reed_sol_van",
                              "host_cutover": 1})
        io = rados.open_ioctx("ecfused")
        payload = bytes(range(256)) * 512        # 128 KiB

        def passes() -> int:
            # THIS profile's codecs only: other pools' codecs may have
            # engaged their own device passes already
            return sum(
                codec.stat_counters()["device_stripe_passes"]
                for osd in cluster.osds.values()
                for name, codec in osd._ec_codecs.items()
                if name == "k2m1dev")

        # device kernels warm in the background; keep writing until the
        # fused pass engages
        io.write_full("fusedobj", payload)
        deadline = time.time() + 60
        while time.time() < deadline and passes() == 0:
            io.write_full("fusedobj", payload)
            time.sleep(0.05)
        assert io.read("fusedobj") == payload
        assert passes() >= 1

    def test_ec_degraded_read_after_shard_loss(self, cluster, rados):
        """Lose one shard's OSD: reads must reconstruct from survivors."""
        io = rados.open_ioctx("ecpool")
        payload = bytes(range(256)) * 16
        io.write_full("degraded", payload)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "degraded")
        up, acting = m.pg_to_up_acting_osds(pgid)
        time.sleep(0.3)
        # corrupt one shard directly instead of killing the osd (keeps
        # the module-scoped cluster intact): shard read must fail crc
        # and the primary must reconstruct from the other two
        victim_shard = 1
        victim = acting[victim_shard]
        store = cluster.osds[victim].store
        from ceph_tpu.store import Transaction
        data = store.read(f"pg_{pgid}", f"degraded.s{victim_shard}")
        corrupted = bytearray(data)
        corrupted[0] ^= 0xFF
        store.apply_transaction(
            Transaction().write(f"pg_{pgid}", f"degraded.s{victim_shard}",
                                0, bytes(corrupted)))
        assert io.read("degraded") == payload

    def test_ec_scrub_detects_corruption(self, cluster, rados):
        io = rados.open_ioctx("ecpool")
        io.write_full("scrubme", b"S" * 2048)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "scrubme")
        up, acting = m.pg_to_up_acting_osds(pgid)
        time.sleep(0.3)
        primary = acting[0] if acting[0] >= 0 else acting[1]
        pg = cluster.osds[primary].get_pg(pgid)
        clean = pg.scrub(deep=True)
        assert clean["inconsistent"] == []
        # corrupt shard 0 on the primary
        store = cluster.osds[acting[0]].store
        from ceph_tpu.store import Transaction
        store.apply_transaction(
            Transaction().write(f"pg_{pgid}", "scrubme.s0", 10, b"\xde"))
        dirty = pg.scrub(deep=True)
        assert any("scrubme" in str(i) for i in dirty["inconsistent"])


class TestFailureHandling:
    def test_osd_kill_detected_and_marked_down(self, cluster, rados):
        osd = cluster.start_osd(3)
        cluster.wait_for_osds(4)
        cluster.kill_osd(3)
        cluster.wait_for_osd_down(3, timeout=30)

    def test_replicated_write_survives_minsize(self, cluster, rados):
        """With one of 3 replicas down, size-3 min_size-2 pool still
        serves writes once the map reflects the failure."""
        rados.create_pool("wounded", pg_num=4)
        io = rados.open_ioctx("wounded")
        io.write_full("before", b"pre-failure")
        # mark osd.2 down via command (map-level failure injection)
        cluster.mark_osd_down(2)
        cluster.wait_for_osd_down(2)
        deadline = time.time() + 20
        last_err = None
        while time.time() < deadline:
            try:
                io.write_full("after", b"post-failure")
                break
            except RadosError as e:
                last_err = e
                time.sleep(0.5)
        else:
            raise AssertionError(f"write never succeeded: {last_err}")
        assert io.read("after") == b"post-failure"
        # the daemon is still alive: its heartbeat re-asserts boot
        # ("map says i am down") — starting a SECOND osd.2 here would
        # race two daemons claiming the same id
        cluster.wait_for_osds(3)
