"""Async (epoll event-loop) messenger: cross-stack wire identity,
cephx over nonblocking sockets, lossless resend under socket kills,
partial-write resume, dispatch tracing, and connection-churn hygiene.

The async stack (`ms_type=async`) must be byte-identical on the wire
to the blocking stack — same banners, same CTM1/CTM2 frames, same
cephx signatures, same reconnect semantics.  These tests pin that:
corpus frames delivered over live sockets re-encode to the archived
bytes on BOTH stacks, the stacks interoperate directly, and a churn
storm of client sessions leaves zero residual threads or FDs.
"""

import json
import os
import random
import threading
import time

import pytest

from ceph_tpu.msg import Message, create_messenger
from ceph_tpu.msg.message import register_message
from ceph_tpu.utils.config import Config

from test_msg import MData, QueueDispatcher
from test_wire_corpus import CORPUS_PATH, build_samples


def make_msgr(name, ms_type, extra=None):
    conf = Config({"ms_type": ms_type, "ms_connect_timeout": 2.0,
                   "ms_max_backoff": 0.5, **(extra or {})})
    m = create_messenger(name, conf=conf)
    m.bind(("127.0.0.1", 0))
    disp = QueueDispatcher()
    m.add_dispatcher_tail(disp)
    m.start()
    return m, disp


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def _settle(probe, window: float = 0.3, timeout: float = 5.0):
    """Poll `probe()` until it returns the same value across a quiet
    window (teardown FDs/threads lag the API calls that retire them)."""
    deadline = time.monotonic() + timeout
    last, last_t = probe(), time.monotonic()
    while time.monotonic() < deadline:
        time.sleep(0.05)
        cur = probe()
        if cur != last:
            last, last_t = cur, time.monotonic()
        elif time.monotonic() - last_t >= window:
            break
    return last


class TestCrossStackWireIdentity:
    """The corpus pins the bytes; these tests pin that BOTH stacks put
    exactly those bytes on real sockets."""

    def _frames(self):
        return {name: blob for name, blob in build_samples().items()
                if blob[:4] in (b"CTM1", b"CTM2")}

    def test_corpus_frames_identical_on_both_stacks(self):
        """Every archived message frame, delivered over a live socket
        on each stack, decodes and re-encodes to the archived bytes —
        a stack that joined, reordered, or re-framed anything fails."""
        from ceph_tpu.ops import crc32c as crc_mod
        with open(CORPUS_PATH) as f:
            archived = json.load(f)
        frames = self._frames()
        assert frames, "corpus has no message frames?"
        received: dict[str, dict[str, bytes]] = {}
        for ms_type in ("blocking", "async"):
            a, _ = make_msgr("corpus-src", ms_type)
            b, bd = make_msgr("corpus-dst", ms_type)
            try:
                for name in sorted(frames):
                    a.send_message(Message.decode_frame(frames[name]),
                                   "corpus-dst", b.addr)
                got: dict[str, bytes] = {}
                for _ in frames:
                    _conn, msg = bd.get(timeout=20)
                    # the messenger stamps the sender entity; the
                    # corpus was encoded src-less — normalize back
                    msg.src = ""
                    got[type(msg).__name__] = msg.encode(seq=7)
                received[ms_type] = got
            finally:
                a.shutdown()
                b.shutdown()
        for name, blob in sorted(frames.items()):
            assert received["blocking"][name] == blob, \
                f"{name}: blocking stack re-encode drifted from corpus"
            assert received["async"][name] == blob, \
                f"{name}: async stack re-encode drifted from corpus"
            assert crc_mod.crc32c(0, received["async"][name]) == \
                archived[name]["crc"], f"{name}: crc vs archive"

    @pytest.mark.parametrize("src_type,dst_type",
                             [("blocking", "async"),
                              ("async", "blocking")])
    def test_stacks_interoperate(self, src_type, dst_type):
        """A blocking peer and an async peer speak the same protocol
        in both directions (rolling-restart compatibility)."""
        a, ad = make_msgr("a", src_type)
        b, bd = make_msgr("b", dst_type)
        try:
            for i in range(50):
                a.send_message(MData(i=i), "b", b.addr)
            got = [bd.get(timeout=10)[1].i for _ in range(50)]
            assert got == list(range(50))
            b.send_message(MData(i=99), "a", a.addr)
            _, reply = ad.get(timeout=10)
            assert reply.i == 99 and reply.src == "b"
        finally:
            a.shutdown()
            b.shutdown()


class TestAsyncStack:
    def test_cephx_signed_roundtrip(self):
        """sign_iov signatures computed over the gather-written iovec
        must verify on the acceptor — over real nonblocking sockets."""
        from ceph_tpu.auth import generate_key
        key = generate_key()
        extra = {"auth_cluster_required": "cephx", "key": key}
        a, _ = make_msgr("osd.90", "async", extra)
        b, bd = make_msgr("osd.91", "async", extra)
        try:
            for i in range(50):
                a.send_message(MData(i=i, pad=b"p" * (i * 17)),
                               "osd.91", b.addr)
            got = [bd.get(timeout=10)[1].i for _ in range(50)]
            assert got == list(range(50))
        finally:
            a.shutdown()
            b.shutdown()

    def test_socket_failure_injection_still_delivers(self):
        """Lossless resend on the async stack: kill the socket under
        the writer repeatedly, every message still arrives exactly
        once and in order (mirrors the blocking-stack test)."""
        a, _ = make_msgr("a", "async",
                         {"ms_inject_socket_failures": 10})
        b, bd = make_msgr("b", "async")
        try:
            n = 100
            for i in range(n):
                a.send_message(MData(i=i), "b", b.addr)
            got = sorted(bd.get(timeout=30)[1].i for _ in range(n))
            assert got == list(range(n))
        finally:
            a.shutdown()
            b.shutdown()

    def test_large_ctm2_partial_write_resume(self):
        """A multi-MB CTM2 frame cannot fit one sendmsg: the loop must
        park the remainder, re-arm EPOLLOUT and resume — counted."""
        a, _ = make_msgr("a", "async")
        b, bd = make_msgr("b", "async")
        try:
            blob = bytes(range(256)) * 40000    # ~10 MB
            a.send_message(MData(blob=blob), "b", b.addr)
            _, msg = bd.get(timeout=30)
            assert msg.blob == blob
            assert a.perf.value("partial_write_resumes") > 0
        finally:
            a.shutdown()
            b.shutdown()

    def test_event_stats_and_thread_floor(self):
        """N messengers share one fixed worker pool: thread cost is
        O(ms_async_op_threads), not O(messengers) — the whole point."""
        msgrs = []
        try:
            first, _ = make_msgr("floor-0", "async")
            msgrs.append(first)
            base = threading.active_count()
            for i in range(1, 6):
                msgrs.append(make_msgr(f"floor-{i}", "async")[0])
            st = first.event_stats()
            assert st["type"] == "async"
            assert st["workers"] == int(first.conf.ms_async_op_threads)
            # five more messengers, zero more event threads
            assert threading.active_count() == base
        finally:
            for m in msgrs:
                m.shutdown()


class TestDispatchTracing:
    def test_queue_span_survives_async_dispatch(self):
        """The tracer's queue span anchors at messenger receive; the
        async stack hands off from an event worker, and the span must
        still cover receive -> op-shard pickup."""
        from ceph_tpu.vstart import MiniCluster
        conf = Config({"ms_type": "async"})
        c = MiniCluster(num_mons=1, num_osds=2, conf=conf).start()
        try:
            r = c.client()
            r.create_pool("tr", pg_num=8)
            io = r.open_ioctx("tr")
            io.write_full("obj", b"traced")
            assert io.read("obj") == b"traced"
            spans = set()
            for osd in c.osds.values():
                for doc in osd.op_tracker.dump_historic_ops()["ops"]:
                    spans.update(s["name"] for s in doc["spans"])
            assert "queue" in spans, \
                f"no queue span in historic ops under async: {spans}"
        finally:
            c.stop()


class TestConnectionChurn:
    @pytest.mark.parametrize("ms_type", ["blocking", "async"])
    def test_churn_storm_leaves_no_fds_or_threads(self, ms_type):
        """Seeded open/close storm of client sessions against a live
        cluster: after quiesce the process is back to its post-warmup
        thread and FD baseline on BOTH stacks.  Warmup first — the
        async worker pool (and jit caches) are process-wide state that
        spins up on first use and persists by design."""
        from ceph_tpu.client.rados import Rados
        from ceph_tpu.vstart import MiniCluster
        conf = Config({"ms_type": ms_type})
        c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
        try:
            warm = Rados(c.monmap, "client.warm", conf=c.conf)
            warm.connect()
            warm.create_pool("churn", pg_num=8)
            io = warm.open_ioctx("churn")
            io.write_full("seed", b"x")
            warm.shutdown()
            base_threads = _settle(threading.active_count)
            base_fds = _settle(_fd_count)

            rng = random.Random(0xC109)
            for rnd in range(3):
                sessions = []
                for i in range(rng.randint(6, 10)):
                    cl = Rados(c.monmap, f"client.s{rnd}_{i}",
                               conf=c.conf)
                    cl.connect()
                    sessions.append(cl)
                rng.shuffle(sessions)
                for j, cl in enumerate(sessions):
                    if j % 2 == 0:     # half do IO, half just churn
                        cio = cl.open_ioctx("churn")
                        cio.write_full(
                            f"o{rnd}", b"y" * rng.randint(1, 4096))
                        assert cio.read(f"o{rnd}")
                    cl.shutdown()

            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if threading.active_count() <= base_threads and \
                        _fd_count() <= base_fds:
                    break
                time.sleep(0.1)
            threads, fds = threading.active_count(), _fd_count()
            assert threads <= base_threads, \
                f"{ms_type}: thread leak {threads} > {base_threads}: " \
                f"{sorted(t.name for t in threading.enumerate())}"
            assert fds <= base_fds, \
                f"{ms_type}: fd leak {fds} > {base_fds}"
        finally:
            c.stop()
