"""Counter-coverage lint: the static pass stays clean and the scanner
itself catches regressions (the copy_audit pattern for perf counters:
every counter incremented in ceph_tpu/ must be pinned by the
test_observability schema assertions)."""

from ceph_tpu.tools import counter_audit


class TestStaticPass:
    def test_every_counter_is_covered(self):
        """Tier-1 gate: a perf counter declared or incremented in
        ceph_tpu/ but absent from tests/test_observability.py fails
        here until the schema test names it."""
        violations = counter_audit.audit()
        assert violations == [], "\n".join(violations)


class TestScanner:
    def test_finds_declarations_and_increments(self):
        src = (
            "perf = (PerfCountersBuilder('x')\n"
            "        .add_u64_counter(\"push_total\")\n"
            "        .add_time_avg(\"push_latency\")\n"
            "        .create_perf_counters())\n"
            "perf.inc(\"push_total\")\n"
            "perf.tinc(\"push_latency\", 0.1)\n")
        hits = counter_audit.scan_counters(src)
        assert sorted(hits) == ["push_latency", "push_total"]
        assert hits["push_total"] == [2, 5]

    def test_ternary_counts_both_names(self):
        """perf.inc("op_w" if w else "op_r") increments either at
        runtime — BOTH must be covered."""
        hits = counter_audit.scan_counters(
            'perf.inc("op_w" if writes else "op_r")\n')
        assert set(hits) == {"op_w", "op_r"}

    def test_continuation_line_name_found(self):
        hits = counter_audit.scan_counters(
            "perf.inc(\n    \"late_name\", 5)\n")
        assert "late_name" in hits

    def test_prose_does_not_count(self):
        src = (
            '"""docstring naming .inc("ghost_counter") freely"""\n'
            "# comment: perf.inc(\"ghost_too\")\n"
            "x = 1\n")
        assert counter_audit.scan_counters(src) == {}
