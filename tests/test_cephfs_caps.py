"""CephFS capabilities (caps-lite): client-side dentry/attr caching
with MDS-driven grant/revoke.

Reduced mds/Locker.cc + client/Client.h cap cache: read caps let a
client serve stat/readdir locally with no MDS round trip; a
conflicting mutation (or a reader hitting a write-buffering holder)
revokes first, flushing buffered attr state in the ack.
"""

import time

import pytest

from ceph_tpu.fs import CephFS, FsError
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    c.start_mds("a")
    yield c
    c.stop()


def _mount(cluster, name):
    rados = cluster.client(name)
    f = CephFS(rados)
    end = time.time() + 40
    while True:
        try:
            return f.mount(timeout=10.0)
        except FsError:
            if time.time() > end:
                raise
            cluster.tick(0.5)


@pytest.fixture(scope="module")
def fs_a(cluster):
    return _mount(cluster, "client.caps-a")


@pytest.fixture(scope="module")
def fs_b(cluster):
    return _mount(cluster, "client.caps-b")


class TestCapCaching:
    def test_repeated_stat_hits_no_mds_rpc(self, fs_a):
        fs_a.mkdir("/cachedir")
        with fs_a.open("/cachedir/f", "w") as f:
            f.write(b"cached-bytes")
        st = fs_a.stat("/cachedir/f")      # may RPC (fills the cache)
        before = fs_a.rpcs
        for _ in range(10):
            assert fs_a.stat("/cachedir/f") == st
        assert fs_a.rpcs == before, "stat kept hitting the MDS"

    def test_repeated_readdir_hits_no_mds_rpc(self, fs_a):
        fs_a.mkdir("/lsdir")
        with fs_a.open("/lsdir/x", "w") as f:
            f.write(b"1")
        first = fs_a.listdir("/lsdir")
        before = fs_a.rpcs
        for _ in range(10):
            assert fs_a.listdir("/lsdir") == first
        assert fs_a.rpcs == before, "readdir kept hitting the MDS"

    def test_concurrent_writer_invalidates_stat(self, fs_a, fs_b):
        fs_a.mkdir("/shared")
        with fs_a.open("/shared/doc", "w") as f:
            f.write(b"version-1")
        assert fs_a.stat("/shared/doc")["size"] == 9
        before = fs_a.rpcs
        assert fs_a.stat("/shared/doc")["size"] == 9   # cached
        assert fs_a.rpcs == before
        # client B rewrites the file: the MDS revokes A's cap BEFORE
        # B's mutation lands, so A's next stat goes back to the MDS
        with fs_b.open("/shared/doc", "w") as f:
            f.write(b"version-two!")
        assert fs_a.stat("/shared/doc")["size"] == 12
        assert fs_a.rpcs > before

    def test_concurrent_create_invalidates_readdir(self, fs_a, fs_b):
        fs_a.mkdir("/watched")
        with fs_a.open("/watched/one", "w") as f:
            f.write(b"1")
        assert fs_a.listdir("/watched") == ["one"]
        before = fs_a.rpcs
        assert fs_a.listdir("/watched") == ["one"]     # cached
        assert fs_a.rpcs == before
        with fs_b.open("/watched/two", "w") as f:
            f.write(b"2")
        assert fs_a.listdir("/watched") == ["one", "two"]

    def test_rename_invalidates_subtree(self, fs_a, fs_b):
        fs_a.mkdirs("/mvdir/sub")
        with fs_a.open("/mvdir/sub/f", "w") as f:
            f.write(b"x")
        fs_a.stat("/mvdir/sub/f")          # cache below /mvdir
        fs_b.rename("/mvdir", "/mvdir2")
        with pytest.raises(FsError):
            fs_a.stat("/mvdir/sub/f")      # old path is gone
        assert fs_a.stat("/mvdir2/sub/f")["type"] == "file"


class TestWriteBuffering:
    def test_writes_buffer_size_updates(self, fs_a):
        fs_a.mkdir("/wb")
        f = fs_a.open("/wb/log", "w")
        f.write(b"first")
        before = fs_a.rpcs
        for i in range(20):
            f.write(b"-chunk")             # extends: size is buffered
        assert fs_a.rpcs == before, "every write did a setattr RPC"
        assert fs_a.stat("/wb/log")["size"] == 5 + 20 * 6
        f.close()                          # flush
        assert fs_a.stat("/wb/log")["size"] == 5 + 20 * 6

    def test_reader_forces_writer_flush(self, fs_a, fs_b):
        fs_a.mkdir("/wf")
        f = fs_a.open("/wf/live", "w")
        f.write(b"A" * 1000)               # buffered on A, not closed
        # B's stat must see the buffered size: the MDS revokes A's
        # write cap and A's ack carries the flush
        st = fs_b.stat("/wf/live")
        assert st["size"] == 1000
        f.close()

    def test_flush_survives_close_path(self, fs_a, cluster):
        fs_a.mkdir("/wc")
        with fs_a.open("/wc/data", "w") as f:
            f.write(b"Z" * 4321)
        # a FRESH mount (no caches) sees the flushed size
        fresh = _mount(cluster, "client.caps-fresh")
        assert fresh.stat("/wc/data")["size"] == 4321
        assert fresh.open("/wc/data").read() == b"Z" * 4321
