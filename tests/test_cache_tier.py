"""Cache tiering end-to-end (MiniCluster): writeback promote / flush /
whiteout / evict semantics plus the mon-side tiering guards.

Models the reference's agent + promote behavior
(osd/ReplicatedPG.cc: agent_work :12031, agent_maybe_flush :12250,
agent_maybe_evict :12313, maybe_handle_cache/promote_object) and the
OSDMonitor _check_become_tier validation.
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster

from ceph_tpu.osd.pg import DIRTY_KEY, WHITEOUT_KEY
from ceph_tpu.store.objectstore import StoreError


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,   # agent tick cadence
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rados(cluster):
    return cluster.client()


def _pool_id(cluster, name: str) -> int:
    return cluster.osds[0].osdmap.pool_by_name(name).id


def _pool_objects(cluster, pool_id: int) -> dict:
    """{oid: (data, attrs)} for live (non-whiteout) objects of a
    replicated pool, inspected directly in the primaries' stores."""
    out = {}
    for osd in cluster.osds.values():
        for pgid, pg in list(osd.pgs.items()):
            if pgid.pool != pool_id or not pg.is_primary:
                continue
            try:
                names = osd.store.collection_list(pg.cid)
            except StoreError:
                continue
            for n in names:
                if n.startswith("_pgmeta") or "@" in n:
                    continue
                try:
                    attrs = osd.store.getattrs(pg.cid, n)
                    data = osd.store.read(pg.cid, n)
                except StoreError:
                    continue
                if WHITEOUT_KEY in attrs:
                    continue
                out[n] = (data, attrs)
    return out


def _ec_pool_objects(cluster, pool_id: int) -> set:
    """Base-object names present (as shards) in an EC pool."""
    out = set()
    for osd in cluster.osds.values():
        for pgid, pg in list(osd.pgs.items()):
            if pgid.pool != pool_id:
                continue
            try:
                names = osd.store.collection_list(pg.cid)
            except StoreError:
                continue
            out |= {n.rsplit(".s", 1)[0] for n in names
                    if ".s" in n and "@" not in n
                    and not n.startswith("_pgmeta")}
    return out


def _wait_for(cluster, pred, what: str, timeout: float = 30.0):
    end = time.time() + timeout
    while time.time() < end:
        if pred():
            return
        cluster.tick(0.5)
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _settle(rados, cluster, pool: str, **kw):
    ctx = rados.open_ioctx(pool)
    end = time.time() + 60
    while True:
        try:
            ctx.write_full("settle", b"s")
            return ctx
        except RadosError:
            if time.time() > end:
                raise
            cluster.tick(0.3)


def _mon(rados, cmd: dict, expect: int = 0):
    rv, out, _ = rados.mon_command(cmd)
    assert rv == expect, f"{cmd}: rv={rv} out={out}"
    return out


def _setup_tier(rados, cluster, base: str, cache: str,
                base_ec: bool = False, mode: str = "writeback"):
    if base_ec:
        rados.create_ec_pool(base, f"p_{base}",
                             {"plugin": "tpu", "k": 2, "m": 1})
    else:
        rados.create_pool(base, pg_num=4)
    rados.create_pool(cache, pg_num=4)
    # both pools must serve I/O before tiering links them
    _settle(rados, cluster, base)
    _settle(rados, cluster, cache)
    _mon(rados, {"prefix": "osd tier add", "pool": base,
                 "tierpool": cache})
    _mon(rados, {"prefix": "osd tier cache-mode", "pool": cache,
                 "mode": mode})
    _mon(rados, {"prefix": "osd tier set-overlay", "pool": base,
                 "overlaypool": cache})


class TestWritebackTier:
    def test_write_lands_in_tier_then_flushes_to_base(self, cluster,
                                                      rados):
        _setup_tier(rados, cluster, "wb-base", "wb-cache")
        base_id = _pool_id(cluster, "wb-base")
        cache_id = _pool_id(cluster, "wb-cache")
        io = rados.open_ioctx("wb-base")      # overlay redirects
        io.write_full("hot", b"cached-bytes")
        # the write must be in the TIER, dirty, before any flush
        tier_objs = _pool_objects(cluster, cache_id)
        assert "hot" in tier_objs
        data, attrs = tier_objs["hot"]
        assert data == b"cached-bytes"
        assert DIRTY_KEY in attrs
        assert io.read("hot") == b"cached-bytes"
        # agent flushes to the base and clears DIRTY
        _wait_for(cluster,
                  lambda: "hot" in _pool_objects(cluster, base_id),
                  "flush to base")
        assert _pool_objects(cluster, base_id)["hot"][0] == \
            b"cached-bytes"
        _wait_for(cluster,
                  lambda: DIRTY_KEY not in _pool_objects(
                      cluster, cache_id).get("hot", (b"", {}))[1],
                  "dirty cleared after flush")

    def test_promote_on_read_miss(self, cluster, rados):
        rados.create_pool("pr-base", pg_num=4)
        rados.create_pool("pr-cache", pg_num=4)
        base_io = _settle(rados, cluster, "pr-base")
        _settle(rados, cluster, "pr-cache")
        base_io.write_full("cold", b"only-in-base")
        _mon(rados, {"prefix": "osd tier add", "pool": "pr-base",
                     "tierpool": "pr-cache"})
        _mon(rados, {"prefix": "osd tier cache-mode",
                     "pool": "pr-cache", "mode": "writeback"})
        _mon(rados, {"prefix": "osd tier set-overlay",
                     "pool": "pr-base", "overlaypool": "pr-cache"})
        cache_id = _pool_id(cluster, "pr-cache")
        io = rados.open_ioctx("pr-base")
        # read through the overlay: miss -> promote -> served
        assert io.read("cold") == b"only-in-base"
        assert "cold" in _pool_objects(cluster, cache_id)
        # promoted copy is CLEAN (no re-flush of unchanged data)
        assert DIRTY_KEY not in _pool_objects(
            cluster, cache_id)["cold"][1]

    def test_partial_write_promotes_then_applies(self, cluster, rados):
        rados.create_pool("pw-base", pg_num=4)
        rados.create_pool("pw-cache", pg_num=4)
        base_io = _settle(rados, cluster, "pw-base")
        _settle(rados, cluster, "pw-cache")
        base_io.write_full("doc", b"0123456789")
        _mon(rados, {"prefix": "osd tier add", "pool": "pw-base",
                     "tierpool": "pw-cache"})
        _mon(rados, {"prefix": "osd tier cache-mode",
                     "pool": "pw-cache", "mode": "writeback"})
        _mon(rados, {"prefix": "osd tier set-overlay",
                     "pool": "pw-base", "overlaypool": "pw-cache"})
        io = rados.open_ioctx("pw-base")
        io.write("doc", b"AB", offset=2)      # needs the base bytes
        assert io.read("doc") == b"01AB456789"

    def test_delete_whiteout_propagates_to_base(self, cluster, rados):
        _setup_tier(rados, cluster, "del-base", "del-cache")
        base_id = _pool_id(cluster, "del-base")
        cache_id = _pool_id(cluster, "del-cache")
        io = rados.open_ioctx("del-base")
        io.write_full("gone", b"soon")
        _wait_for(cluster,
                  lambda: "gone" in _pool_objects(cluster, base_id),
                  "flush before delete")
        io.remove_object("gone")
        # logically deleted NOW, even though the base still has it
        with pytest.raises(RadosError):
            io.read("gone")
        # the whiteout flush deletes the base copy, then retires itself
        _wait_for(cluster,
                  lambda: "gone" not in _pool_objects(cluster, base_id),
                  "whiteout propagated to base")
        _wait_for(cluster,
                  lambda: "gone" not in _pool_objects(cluster, cache_id),
                  "whiteout retired from tier")
        with pytest.raises(RadosError):
            io.read("gone")

    def test_evict_cold_then_repromote(self, cluster, rados):
        _setup_tier(rados, cluster, "ev-base", "ev-cache")
        _mon(rados, {"prefix": "osd pool set", "pool": "ev-cache",
                     "var": "target_max_objects", "val": "2"})
        base_id = _pool_id(cluster, "ev-base")
        cache_id = _pool_id(cluster, "ev-cache")
        io = rados.open_ioctx("ev-base")
        for i in range(6):
            io.write_full(f"e{i}", bytes([65 + i]) * 64)
        _wait_for(cluster,
                  lambda: all(f"e{i}" in _pool_objects(cluster, base_id)
                              for i in range(6)),
                  "all flushed to base")
        _wait_for(cluster,
                  lambda: len([o for o in _pool_objects(
                      cluster, cache_id) if o.startswith("e")]) <= 2,
                  "evicted down to target")
        # evicted objects re-promote transparently
        for i in range(6):
            assert io.read(f"e{i}") == bytes([65 + i]) * 64

    def test_ec_base_pool_with_replicated_cache(self, cluster, rados):
        """The headline tiering shape: EC cold pool fronted by a
        replicated cache (EC pools can't take partial overwrites, the
        tier absorbs them)."""
        _setup_tier(rados, cluster, "ecb-base", "ecb-cache",
                    base_ec=True)
        base_id = _pool_id(cluster, "ecb-base")
        io = rados.open_ioctx("ecb-base")
        io.write_full("bulk", b"Z" * 8192)
        io.write("bulk", b"yy", offset=1)   # partial: tier absorbs it
        assert io.read("bulk") == b"Z" + b"yy" + b"Z" * 8189
        _wait_for(cluster,
                  lambda: "bulk" in _ec_pool_objects(cluster, base_id),
                  "flush to EC base")
        # a base copy EXISTING can be the first version's flush racing
        # the partial overwrite (the agent flushes on its own tick):
        # wait until the cache copy is CLEAN — the latest version
        # flushed — before dropping the overlay, or the still-dirty
        # v2 is orphaned in the no-longer-consulted tier and the
        # direct base read below serves v1 forever
        cache_id = _pool_id(cluster, "ecb-cache")

        def _flushed_clean() -> bool:
            ent = _pool_objects(cluster, cache_id).get("bulk")
            return ent is None or DIRTY_KEY not in ent[1]

        _wait_for(cluster, _flushed_clean, "latest version flushed")
        # drop the overlay: reads now hit the EC base directly
        _mon(rados, {"prefix": "osd tier remove-overlay",
                     "pool": "ecb-base"})
        _wait_for(cluster,
                  lambda: rados.open_ioctx("ecb-base") is not None,
                  "map propagated")
        end = time.time() + 30
        while True:
            try:
                assert io.read("bulk") == b"Z" + b"yy" + b"Z" * 8189
                break
            except RadosError:
                if time.time() > end:
                    raise
                cluster.tick(0.3)

    def test_hit_sets_rotate_and_stay_bounded(self, cluster, rados):
        _setup_tier(rados, cluster, "hs-base", "hs-cache")
        _mon(rados, {"prefix": "osd pool set", "pool": "hs-cache",
                     "var": "hit_set_period", "val": "1.0"})
        _mon(rados, {"prefix": "osd pool set", "pool": "hs-cache",
                     "var": "hit_set_count", "val": "3"})
        cache_id = _pool_id(cluster, "hs-cache")
        io = rados.open_ioctx("hs-base")
        for i in range(10):
            io.write_full(f"h{i}", b"x")
            cluster.tick(0.6)
        sets = []
        for osd in cluster.osds.values():
            for pgid, pg in osd.pgs.items():
                if pgid.pool == cache_id and pg.hit_sets:
                    sets.append(pg.hit_sets)
        assert sets, "no hit sets recorded"
        assert all(len(hs) <= 3 for hs in sets)
        recorded = set()
        for hs in sets:
            for _ts, oids in hs:
                recorded |= oids
        assert any(o.startswith("h") for o in recorded)


class TestModeSwitch:
    def test_mode_switch_does_not_strand_dirty_data(self, cluster,
                                                    rados):
        """Leaving writeback (here: -> none) with dirty objects in the
        tier must still flush them — stranding acked updates in a
        de-activated cache would be silent data loss."""
        _setup_tier(rados, cluster, "ms-base", "ms-cache")
        base_id = _pool_id(cluster, "ms-base")
        io = rados.open_ioctx("ms-base")
        io.write_full("stranded", b"must-reach-base")
        # immediately de-activate the cache before the agent flushed
        _mon(rados, {"prefix": "osd tier cache-mode",
                     "pool": "ms-cache", "mode": "none"})
        _mon(rados, {"prefix": "osd tier remove-overlay",
                     "pool": "ms-base"})
        _wait_for(cluster,
                  lambda: "stranded" in _pool_objects(cluster, base_id),
                  "dirty flushed after mode switch")
        assert _pool_objects(cluster, base_id)["stranded"][0] == \
            b"must-reach-base"


class TestTierGuards:
    def test_tier_chain_rejected(self, cluster, rados):
        rados.create_pool("g-a", pg_num=4)
        rados.create_pool("g-b", pg_num=4)
        rados.create_pool("g-c", pg_num=4)
        _mon(rados, {"prefix": "osd tier add", "pool": "g-a",
                     "tierpool": "g-b"})
        # b is a tier of a: chaining c under b must fail
        rv, out, _ = rados.mon_command(
            {"prefix": "osd tier add", "pool": "g-b", "tierpool": "g-c"})
        assert rv == -22, out
        # and a pool cannot tier itself
        rv, out, _ = rados.mon_command(
            {"prefix": "osd tier add", "pool": "g-c", "tierpool": "g-c"})
        assert rv == -22, out

    def test_pool_set_min_size_validated(self, cluster, rados):
        rados.create_pool("g-sz", pg_num=4)
        rv, out, _ = rados.mon_command(
            {"prefix": "osd pool set", "pool": "g-sz",
             "var": "min_size", "val": "5"})
        assert rv == -22, out
        rv, out, _ = rados.mon_command(
            {"prefix": "osd pool set", "pool": "g-sz",
             "var": "size", "val": "0"})
        assert rv == -22, out
        rv, out, _ = rados.mon_command(
            {"prefix": "osd pool set", "pool": "g-sz",
             "var": "min_size", "val": "2"})
        assert rv == 0, out
