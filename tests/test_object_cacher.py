"""ObjectCacher (osdc/ObjectCacher.cc reduced): extent cache unit
tests + librbd integration (rbd_cache behavior under the exclusive-
writer contract)."""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.client.object_cacher import ObjectCacher
from ceph_tpu.rbd import RBD, Image, data_oid
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


class TestObjectCacherUnit:
    def test_read_caches_and_hits(self):
        fetches = []

        def fetch(off, ln):
            fetches.append((off, ln))
            return bytes(range(off, off + ln))

        c = ObjectCacher()
        assert c.read("o", 10, 5, fetch) == bytes(range(10, 15))
        assert c.read("o", 10, 5, fetch) == bytes(range(10, 15))
        assert c.read("o", 11, 3, fetch) == bytes(range(11, 14))
        assert fetches == [(10, 5)]
        assert c.hits == 2 and c.misses == 1

    def test_extent_merge_and_overlay(self):
        c = ObjectCacher(writer=lambda *a: None)
        c.write("o", 0, b"AAAA")
        c.write("o", 4, b"BBBB")        # adjacent: merges
        c.write("o", 2, b"XX")          # overlay
        got = c.read("o", 0, 8, lambda o, l: pytest.fail("miss"))
        assert got == b"AAXXBBBB"

    def test_writeback_flush_order_and_once(self):
        wrote = []
        c = ObjectCacher(writer=lambda oid, off, d:
                         wrote.append((oid, off, bytes(d))))
        c.write("o", 100, b"late")
        c.write("o", 0, b"early")
        assert wrote == []              # write-back: nothing yet
        c.flush()
        assert wrote == [("o", 0, b"early"), ("o", 100, b"late")]
        wrote.clear()
        c.flush()
        assert wrote == []              # clean now

    def test_dirty_budget_forces_flush(self):
        wrote = []
        c = ObjectCacher(max_dirty=1024,
                         writer=lambda oid, off, d:
                         wrote.append(len(d)))
        c.write("o", 0, b"x" * 2048)
        assert sum(wrote) == 2048       # budget exceeded -> flushed
        assert c.dirty_bytes() == 0

    def test_lru_evicts_clean_never_dirty(self):
        c = ObjectCacher(max_size=4096, writer=lambda *a: None)
        c.write("dirty", 0, b"d" * 2048)
        c.read("clean1", 0, 2048, lambda o, l: b"c" * l)
        c.read("clean2", 0, 2048, lambda o, l: b"e" * l)  # over budget
        # a clean object was evicted; the dirty one survives
        assert c.dirty_bytes() == 2048
        assert c.size() <= 4096
        got = c.read("dirty", 0, 4, lambda o, l: pytest.fail("lost"))
        assert got == b"dddd"

    def test_miss_with_partial_dirty_overlap_keeps_dirty_bytes(self):
        """A buffered write overlapping a missed read range must win
        over the fetched bytes — and still flush ITS data later."""
        wrote = []
        c = ObjectCacher(writer=lambda oid, off, d:
                         wrote.append((off, bytes(d))))
        c.write("o", 10, b"XX")                 # dirty [10,12)
        got = c.read("o", 0, 20, lambda o, l: b"Z" * l)
        assert got == b"Z" * 10 + b"XX" + b"Z" * 8
        c.flush()
        assert wrote == [(10, b"XX")]           # dirty bytes, not 'ZZ'

    def test_miss_short_fetch_with_dirty_overlap_no_crash(self):
        """Backing object absent (short fetch) + dirty overlay: the
        read pads with zeros and serves the dirty bytes."""
        c = ObjectCacher(writer=lambda *a: None)
        c.write("o", 0, b"AB")
        got = c.read("o", 0, 10, lambda o, l: b"")   # ENOENT analog
        assert got == b"AB" + b"\x00" * 8

    def test_flush_failure_keeps_data_dirty(self):
        calls = []

        def flaky(oid, off, d):
            calls.append(bytes(d))
            if len(calls) == 1:
                raise RadosError(110, "transient")

        c = ObjectCacher(writer=flaky)
        c.write("o", 0, b"must-not-launder")
        with pytest.raises(RadosError):
            c.flush()
        assert c.dirty_bytes() > 0              # still dirty
        c.flush()                               # retry succeeds
        assert calls == [b"must-not-launder"] * 2
        assert c.dirty_bytes() == 0

    def test_ranged_discard_trims_straddling_dirty_run(self):
        wrote = []
        c = ObjectCacher(writer=lambda oid, off, d:
                         wrote.append((off, bytes(d))))
        c.write("o", 0, b"x" * 100)
        c.discard("o", 50, 100)
        c.flush()
        assert wrote == [(0, b"x" * 50)]        # kept half flushes

    def test_discard_drops_dirty(self):
        wrote = []
        c = ObjectCacher(writer=lambda oid, off, d: wrote.append(oid))
        c.write("o", 0, b"gone")
        c.discard("o")
        c.flush()
        assert wrote == []


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("rbdc", pg_num=8)
    ctx = rados.open_ioctx("rbdc")
    end = time.time() + 60
    while True:
        try:
            ctx.write_full("settle", b"s")
            return ctx
        except RadosError:
            if time.time() > end:
                raise
            cluster.tick(0.3)


class TestRbdCache:
    def test_read_your_writes_before_flush(self, io):
        RBD(io).create("c1", 1 << 20, order=16)
        with Image(io, "c1", cache=True) as img:
            img.write(0, b"buffered-bytes")
            # backing object untouched (write-back)
            with pytest.raises(RadosError):
                io.stat(data_oid("c1", 0))
            assert img.read(0, 14) == b"buffered-bytes"
        # close flushed: a fresh uncached handle sees the bytes
        with Image(io, "c1") as img:
            assert img.read(0, 14) == b"buffered-bytes"

    def test_cached_reads_skip_the_cluster(self, io):
        RBD(io).create("c2", 1 << 20, order=16)
        with Image(io, "c2") as w:
            w.write(0, b"Z" * 1000)
        with Image(io, "c2", cache=True) as img:
            assert img.read(0, 1000) == b"Z" * 1000   # miss, warms
            h0 = img._cache.hits
            for _ in range(5):
                assert img.read(0, 1000) == b"Z" * 1000
            assert img._cache.hits == h0 + 5
            assert img._cache.misses == 1

    def test_snap_create_flushes_buffered_writes(self, io):
        RBD(io).create("c3", 1 << 20, order=16)
        with Image(io, "c3", cache=True) as img:
            img.write(0, b"pre-snap!")
            img.snap_create("s1")      # must flush first
            img.write(0, b"post-snap")
        with Image(io, "c3", snapshot="s1") as snap:
            assert snap.read(0, 9) == b"pre-snap!"
        with Image(io, "c3") as img:
            assert img.read(0, 9) == b"post-snap"

    def test_clone_with_cache_copyup(self, io):
        rbd = RBD(io)
        rbd.create("cp", 1 << 20, order=16)
        with Image(io, "cp") as p:
            p.write(0, b"P" * 65536)
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("cp", "v1", "cc")
        with Image(io, "cc", cache=True) as c:
            assert c.read(0, 8) == b"P" * 8       # through parent
            c.write(2, b"xx")                     # copyup + buffer
            assert c.read(0, 8) == b"PPxxPPPP"
        with Image(io, "cc") as c:                # uncached verify
            assert c.read(0, 8) == b"PPxxPPPP"
            assert c.read(65530, 6) == b"P" * 6   # copied-up tail

    def test_discard_with_cache(self, io):
        RBD(io).create("c4", 1 << 20, order=16)
        with Image(io, "c4", cache=True) as img:
            img.write(0, b"doomed-but-first-flushed")
            img.write(70_000, b"survivor")
            img.discard(0, 65536)
            assert img.read(0, 6) == b"\x00" * 6
            assert img.read(70_000, 8) == b"survivor"
        with Image(io, "c4") as img:
            assert img.read(0, 6) == b"\x00" * 6
            assert img.read(70_000, 8) == b"survivor"
