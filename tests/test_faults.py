"""FaultSet unit tests: rule scoping, seed determinism, the
injectargs/admin-socket surface, and the layer hooks' fast paths.

The cluster-level behavior the rules drive (partitions blocking real
traffic, EIO surviving via degraded EC reads, tpu_error degrading the
plugin) lives in tests/test_chaos.py; this module pins the registry
semantics those scenarios rely on.
"""

import pytest

from ceph_tpu.utils import faults
from ceph_tpu.utils.admin_socket import AdminSocket
from ceph_tpu.utils.config import Config
from ceph_tpu.utils.faults import FaultSet


@pytest.fixture(autouse=True)
def _clean_global():
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)


class TestRules:
    def test_partition_symmetric(self):
        fs = FaultSet()
        rid = fs.partition("osd.1", "osd.2")
        assert fs.partitioned("osd.1", "osd.2")
        assert fs.partitioned("osd.2", "osd.1")
        assert not fs.partitioned("osd.1", "osd.3")
        fs.clear(rid)
        assert not fs.partitioned("osd.1", "osd.2")

    def test_partition_oneway(self):
        fs = FaultSet()
        fs.partition("osd.1", "osd.2", symmetric=False)
        assert fs.partitioned("osd.1", "osd.2")
        assert not fs.partitioned("osd.2", "osd.1")

    def test_partition_glob_scopes(self):
        fs = FaultSet()
        fs.partition("client.*", "osd.*")
        assert fs.partitioned("client.c0", "osd.2")
        assert fs.partitioned("osd.2", "client.c0")   # symmetric
        assert not fs.partitioned("client.c0", "mon.a")
        assert not fs.partitioned("osd.1", "osd.2")

    def test_drop_probability_extremes(self):
        fs = FaultSet()
        fs.drop("osd.*", 0.0)
        assert not any(fs.should_drop("a", "osd.1") for _ in range(50))
        fs.reset()
        fs.drop("osd.*", 1.0)
        assert all(fs.should_drop("a", "osd.1") for _ in range(50))
        # non-matching dst never rolls the dice
        assert not fs.should_drop("a", "mon.a")

    def test_delay_accumulates_and_scopes(self):
        fs = FaultSet()
        fs.delay("osd.3", 0.25)
        assert fs.send_delay("client.x", "osd.3") == pytest.approx(0.25)
        assert fs.send_delay("client.x", "osd.4") == 0.0

    def test_socket_kill_rule_and_conf_knob(self):
        fs = FaultSet(seed=3)
        # conf knob only (no rules): still seeded through the registry
        hits = sum(fs.should_kill_socket("osd.0", "osd.1", 4)
                   for _ in range(400))
        assert 40 < hits < 180            # ~1 in 4
        fs.reset(seed=3)
        fs.socket_kill("osd.1", one_in=2)
        hits = sum(fs.should_kill_socket("osd.0", "osd.1", 0)
                   for _ in range(400))
        assert 120 < hits < 280           # ~1 in 2
        assert not fs.should_kill_socket("osd.0", "mon.a", 0)

    def test_store_eio_targets_owner_and_oid(self):
        fs = FaultSet()
        fs.store_eio("osd.1", "m*", prob=1.0)
        assert fs.should_store_eio("osd.1", "m7")
        assert not fs.should_store_eio("osd.2", "m7")
        assert not fs.should_store_eio("osd.1", "other")
        # legacy probability knob flows through the same decision point
        fs.reset()
        assert not fs.should_store_eio("osd.1", "m7", conf_prob=0.0)
        assert fs.should_store_eio("osd.1", "m7", conf_prob=1.0)

    def test_tpu_error(self):
        fs = FaultSet()
        assert not fs.tpu_error()
        rid = fs.tpu_device_error(1.0)
        assert fs.tpu_error()
        # an untargeted rule fires for every device query too
        assert fs.tpu_error(device=3)
        fs.clear(rid)
        assert not fs.tpu_error()

    def test_tpu_error_device_targeted(self):
        """A device-index-targeted rule fires ONLY for that chip's
        lane queries — never for the untargeted plugin degrade
        check."""
        fs = FaultSet()
        fs.tpu_device_error(1.0, device="3")
        assert not fs.tpu_error()            # untargeted: no degrade
        assert fs.tpu_error(device=3)
        assert fs.tpu_error(device="3")
        assert not fs.tpu_error(device=0)
        fs.reset()
        fs.tpu_device_error(1.0, device="[0-3]")
        assert fs.tpu_error(device=2)
        assert not fs.tpu_error(device=7)

    def test_tpu_error_device_spec(self):
        fs = FaultSet()
        fs.install_from_spec("tpu_error 1.0 5")
        assert not fs.tpu_error()
        assert fs.tpu_error(device=5)
        assert not fs.tpu_error(device=4)

    def test_clear_by_source(self):
        fs = FaultSet()
        fs.partition("a", "b", source="conf")
        fs.partition("c", "d", source="api")
        assert fs.clear(source="conf") == 1
        assert [r.params["a"] for r in fs.rules()] == ["c"]
        assert fs.clear() == 1
        assert not fs.rules()


class TestDeterminism:
    def test_same_seed_same_decisions(self):
        def run(seed):
            fs = FaultSet(seed=seed)
            fs.drop("osd.*", 0.5)
            fs.socket_kill("osd.*", one_in=3)
            out = []
            for i in range(200):
                out.append(fs.should_drop("osd.0", f"osd.{i % 3}"))
                out.append(fs.should_kill_socket("osd.0",
                                                 f"osd.{i % 3}", 0))
            return out
        a, b = run(11), run(11)
        assert a == b
        assert any(a) and not all(a)
        assert run(11) != run(12)

    def test_per_entity_streams_are_independent(self):
        """One entity's decision sequence must not shift when ANOTHER
        entity interleaves queries (thread-schedule immunity)."""
        fs1 = FaultSet(seed=5)
        fs1.drop("*", 0.5)
        solo = [fs1.should_drop("osd.0", "osd.1") for _ in range(100)]
        fs2 = FaultSet(seed=5)
        fs2.drop("*", 0.5)
        mixed = []
        for _ in range(100):
            fs2.should_drop("osd.9", "osd.1")     # interloper
            mixed.append(fs2.should_drop("osd.0", "osd.1"))
        assert solo == mixed

    def test_reseed_restarts_streams(self):
        fs = FaultSet(seed=7)
        fs.drop("*", 0.5)
        first = [fs.should_drop("x", "y") for _ in range(50)]
        fs.reseed(7)
        assert [fs.should_drop("x", "y") for _ in range(50)] == first

    def test_trace_records_fired_faults(self):
        fs = FaultSet()
        fs.drop("osd.1", 1.0)
        fs.should_drop("osd.0", "osd.1")
        assert ("drop", "osd.0", "osd.1") in fs.trace()


class TestSpecSurface:
    def test_spec_roundtrip(self):
        fs = FaultSet()
        ids = fs.install_from_spec(
            "partition osd.1 osd.2; drop client.* 0.25; "
            "delay osd.3 0.1 0.5; kill osd.* 10; "
            "eio osd.0 m* 0.75; tpu_error 1.0")
        assert len(ids) == 6
        kinds = sorted(r.kind for r in fs.rules())
        assert kinds == ["delay", "drop", "partition", "socket_kill",
                         "store_eio", "tpu_device_error"]
        assert fs.partitioned("osd.1", "osd.2")

    def test_spec_oneway_partition(self):
        fs = FaultSet()
        fs.install_from_spec("partition osd.1 osd.2 oneway")
        assert fs.partitioned("osd.1", "osd.2")
        assert not fs.partitioned("osd.2", "osd.1")

    def test_spec_replaces_same_source(self):
        fs = FaultSet()
        fs.install_from_spec("partition a b")
        fs.partition("keep", "me", source="api")
        fs.install_from_spec("drop osd.* 0.5")
        kinds = sorted((r.kind, r.source) for r in fs.rules())
        assert kinds == [("drop", "conf"), ("partition", "api")]
        fs.install_from_spec("")          # empty spec clears conf rules
        assert [r.kind for r in fs.rules()] == ["partition"]

    def test_spec_rejects_garbage(self):
        fs = FaultSet()
        with pytest.raises(ValueError):
            fs.install_from_spec("frobnicate x y")
        with pytest.raises(ValueError):
            fs.install_from_spec("partition onlyone")

    def test_config_observer_applies_injectargs(self):
        conf = Config()
        conf.add_observer(faults.conf_observer(),
                          ("faultset_rules", "faultset_seed"))
        conf.injectargs("--faultset-seed 99")
        assert faults.get().seed == 99
        conf.injectargs("--faultset-rules 'partition osd.1 osd.2'")
        assert faults.get().partitioned("osd.1", "osd.2")
        conf.injectargs("--faultset-rules ''")
        assert not faults.get().partitioned("osd.1", "osd.2")

    def test_admin_socket_surface(self):
        fs = FaultSet()
        asok = AdminSocket("test")
        fs.register_asok(asok)
        out = asok.execute({"prefix": "faults install",
                            "rules": "partition osd.1 osd.2"})
        assert len(out["installed"]) == 1
        assert fs.partitioned("osd.1", "osd.2")
        dump = asok.execute("faults dump")
        assert dump["rules"][0]["kind"] == "partition"
        out = asok.execute({"prefix": "faults clear"})
        assert out["removed"] == 1
        assert not fs.partitioned("osd.1", "osd.2")
        out = asok.execute({"prefix": "faults reseed", "seed": 42})
        assert out["seed"] == 42


class TestLayerHooks:
    def test_memstore_targeted_eio(self):
        from ceph_tpu.store.memstore import MemStore
        from ceph_tpu.store.objectstore import StoreError, Transaction
        store = MemStore()
        store.owner = "osd.1"
        txn = Transaction().create_collection("c")
        txn.write("c", "obj1", 0, b"data")
        txn.write("c", "other", 0, b"data")
        store.apply_transaction(txn)
        faults.get().store_eio("osd.1", "obj*", prob=1.0)
        with pytest.raises(StoreError) as ei:
            store.read("c", "obj1")
        assert ei.value.errno == 5
        assert store.read("c", "other") == b"data"   # glob miss
        store2 = MemStore()
        store2.owner = "osd.2"
        store2.apply_transaction(
            Transaction().create_collection("c").write(
                "c", "obj1", 0, b"x"))
        assert store2.read("c", "obj1") == b"x"      # owner miss

    def test_tpu_codec_degrades_not_errors(self):
        import numpy as np
        from ceph_tpu.erasure.matrix_codec import NumpyBackend
        from ceph_tpu.erasure.plugin_tpu import ErasureCodeTpu
        from ceph_tpu.erasure.registry import registry
        codec = ErasureCodeTpu()
        codec.init({"k": "2", "m": "1", "technique": "reed_sol_van"})
        # device-sized payload so the encode routes through the guarded
        # _apply path rather than the small-op host fast path
        L = 1 << 16
        data = np.frombuffer(b"ab" * L, dtype=np.uint8).reshape(2, L)
        before = codec.encode_chunks(data.copy())
        events = []
        registry.add_health_hook("test", lambda n, r: events.append(n))
        try:
            faults.get().tpu_device_error(1.0)
            after = codec.encode_chunks(data.copy())
            assert codec.degraded
            assert isinstance(codec.backend, NumpyBackend)
            # fallback produces the SAME parity bytes
            assert np.array_equal(before, after)
            assert events == ["tpu"]
            # degrade is sticky and silent: no further errors/events
            codec.encode_chunks(data.copy())
            assert events == ["tpu"]
        finally:
            registry.remove_health_hook("test")
            registry.degraded.pop("tpu", None)

    def test_objecter_timeout_errno_defined(self):
        from ceph_tpu.client.objecter import ETIMEDOUT
        assert ETIMEDOUT == 110
