"""Stripe math + batched object encode/decode (osd/ecutil.py).

Mirrors the reference's ECUtil tests: stripe_info_t offset algebra,
encode/decode roundtrips across stripes, HashInfo-style cumulative CRC
equality, and the fused-device-pass counter the OSD path asserts.
"""

import numpy as np
import pytest

from ceph_tpu.erasure.registry import registry
from ceph_tpu.ops import crc32c as crc_mod
from ceph_tpu.osd import ecutil


def tpu_codec(k=4, m=2, su=None):
    codec = registry.factory("tpu", {"k": str(k), "m": str(m),
                                     "technique": "reed_sol_van"})
    return codec


class TestStripeInfo:
    def test_offsets(self):
        si = ecutil.StripeInfo(4, 4096)
        assert si.stripe_width == 16384
        assert si.logical_to_prev_stripe_offset(20000) == 16384
        assert si.logical_to_next_stripe_offset(20000) == 32768
        assert si.aligned_logical_offset_to_chunk_offset(32768) == 8192
        assert si.aligned_chunk_offset_to_logical_offset(8192) == 32768
        assert si.offset_len_to_stripe_bounds(5000, 20000) == (0, 32768)

    def test_sizes(self):
        si = ecutil.StripeInfo(2, 4096)
        assert si.stripe_count(0) == 1
        assert si.stripe_count(1) == 1
        assert si.stripe_count(8192) == 1
        assert si.stripe_count(8193) == 2
        assert si.logical_size_to_shard_size(8193) == 8192

    def test_alignment_rounds_up(self):
        si = ecutil.StripeInfo(3, 100)     # not a multiple of 128
        assert si.chunk_size == 128


class TestEncodeDecodeObject:
    @pytest.mark.parametrize("size", [0, 1, 4095, 4096, 10000, 40000])
    def test_roundtrip_all_shards(self, size):
        codec = tpu_codec()
        si = ecutil.StripeInfo(codec.get_data_chunk_count(), 4096)
        payload = bytes(np.random.default_rng(size).integers(
            0, 256, size, dtype=np.uint8))
        shards, crcs = ecutil.encode_object(codec, si, payload)
        assert len(shards) == 6
        assert all(len(s) == si.logical_size_to_shard_size(size)
                   for s in shards)
        have = {i: shards[i] for i in range(6)}
        assert ecutil.decode_object(codec, si, have, size) == payload

    def test_roundtrip_with_erasures(self):
        codec = tpu_codec()
        si = ecutil.StripeInfo(4, 4096)
        payload = bytes(range(256)) * 150          # 38400 B, 3 stripes
        shards, _ = ecutil.encode_object(codec, si, payload)
        # lose two data shards: parity must rebuild them, batched
        have = {i: shards[i] for i in (0, 3, 4, 5)}
        assert ecutil.decode_object(codec, si, have, len(payload)) == payload
        # lose one data + one parity
        have = {i: shards[i] for i in (0, 1, 3, 4)}
        assert ecutil.decode_object(codec, si, have, len(payload)) == payload

    def test_too_few_shards_raises(self):
        codec = tpu_codec()
        si = ecutil.StripeInfo(4, 4096)
        shards, _ = ecutil.encode_object(codec, si, b"x" * 9999)
        from ceph_tpu.erasure.interface import ErasureCodeError
        with pytest.raises(ErasureCodeError):
            ecutil.decode_object(codec, si,
                                 {i: shards[i] for i in (0, 1, 2)}, 9999)

    def test_shard_crcs_match_direct_crc(self):
        """Cumulative combine == crc32c of the whole shard file —
        HashInfo::append equivalence across stripes."""
        codec = tpu_codec()
        si = ecutil.StripeInfo(4, 4096)
        payload = bytes(np.random.default_rng(7).integers(
            0, 256, 50000, dtype=np.uint8))
        shards, crcs = ecutil.encode_object(codec, si, payload)
        for s, crc in zip(shards, crcs):
            assert crc_mod.crc32c(0, s) == crc

    def test_packets_technique_roundtrip(self):
        """Bit-matrix (packets) techniques must batch across stripes
        too — regression: 3-D batches crashed the host packet kernel."""
        codec = registry.factory("tpu", {"k": "4", "m": "2",
                                         "technique": "cauchy_good",
                                         "packetsize": "128"})
        si = ecutil.StripeInfo(4, codec.get_alignment() // 4)
        payload = bytes(np.random.default_rng(11).integers(
            0, 256, 3 * si.stripe_width + 17, dtype=np.uint8))
        shards, crcs = ecutil.encode_object(codec, si, payload)
        for s, crc in zip(shards, crcs):
            assert crc_mod.crc32c(0, s) == crc
        have = {i: shards[i] for i in (1, 2, 3, 5)}
        assert ecutil.decode_object(codec, si, have,
                                    len(payload)) == payload

    def test_host_plugin_fallback(self):
        """Non-matrix codecs use the base per-stripe host path."""
        codec = registry.factory("shec", {"k": "4", "m": "3", "c": "2"})
        si = ecutil.StripeInfo(4, 512)
        payload = b"shingled" * 700
        shards, crcs = ecutil.encode_object(codec, si, payload)
        assert codec.stat_counters()["host_stripe_passes"] >= 1
        have = {i: s for i, s in enumerate(shards) if i not in (1, 5)}
        assert ecutil.decode_object(codec, si, have,
                                    len(payload)) == payload
        for s, crc in zip(shards, crcs):
            assert crc_mod.crc32c(0, s) == crc


class TestDevicePassCounter:
    def test_fused_device_pass_counts(self):
        """With routing pinned to the device, the fused pass must
        engage (after background warm) and be bit-identical to host."""
        codec = tpu_codec()
        codec.backend.HOST_CUTOVER_BYTES = 1   # pin: CPU CI would
        si = ecutil.StripeInfo(4, 4096)        # rightly prefer host
        payload = bytes(np.random.default_rng(3).integers(
            0, 256, 256 * 1024, dtype=np.uint8))
        ref_shards, ref_crcs = None, None
        # kernels warm on a background thread (an OSD op never blocks
        # on a jit compile), so poll until the device path engages
        import time
        deadline = time.time() + 60
        while time.time() < deadline:
            shards, crcs = ecutil.encode_object(codec, si, payload)
            if ref_shards is None:
                ref_shards, ref_crcs = shards, crcs
            assert shards == ref_shards
            assert list(crcs) == list(ref_crcs)
            if codec.stat_counters()["device_stripe_passes"] >= 1:
                break
            time.sleep(0.05)
        stats = codec.stat_counters()
        assert stats["device_stripe_passes"] >= 1, stats
        assert stats["host_stripe_passes"] >= 1, stats

    def test_adaptive_router_prefers_faster_path(self):
        """Unpinned, both paths get sampled and the steady-state choice
        is whichever measured faster (on CPU CI that is host)."""
        codec = tpu_codec()
        si = ecutil.StripeInfo(4, 4096)
        payload = b"r" * (128 * 1024)
        import time
        deadline = time.time() + 60
        b = codec.backend
        # the routed decision compares EMAs within ONE size bucket —
        # read the payload's own bucket (multichip splits record
        # per-chip part samples into smaller buckets too)
        bkt = b._bucket(128 * 1024)
        while time.time() < deadline:
            ecutil.encode_object(codec, si, payload)
            dev = b._perf.get(("dev", bkt))
            host = b._perf.get(("host", bkt))
            if dev and host and dev["n"] >= 2 and host["n"] >= 2:
                break
            time.sleep(0.02)
        dev = b._perf.get(("dev", bkt))
        host = b._perf.get(("host", bkt))
        assert dev and host and dev["n"] >= 2 and host["n"] >= 2
        faster = "dev" if dev["spb"] <= host["spb"] else "host"
        # routed calls must follow the winner (majority: one in
        # PROBE_EVERY calls deliberately re-probes the loser)
        choices = [b.use_device(128 * 1024) for _ in range(5)]
        assert (sum(choices) >= 3) == (faster == "dev")
