"""PGLog subsystem units: the find_best_info ordering, divergence
math, merge_log claims/missing set, and the shared rewind core —
the pure-log half of log-authoritative peering (osd/PGLog.{h,cc},
PG::find_best_info)."""

from ceph_tpu.osd.pglog import PGLog, ZERO_EV


def _log(entries, tail=ZERO_EV):
    log = PGLog()
    for e in entries:
        log.add(dict(e))
    log.tail = tail
    return log


def _e(ev, oid, op="modify", prior=None, **kw):
    return {"ev": ev, "oid": oid, "op": op, "prior": prior,
            "rollback": None, "shard": None, **kw}


class TestFindBestInfo:
    BASE = {"last_update": (2, 5), "log_tail": (1, 0),
            "last_epoch_started": 3, "in_up": True}

    def _best(self, **overrides):
        cands = {1: dict(self.BASE)}
        cands[2] = {**self.BASE, **overrides}
        return PGLog.find_best_info(cands)

    def test_les_dominates_last_update(self):
        # the pg_temp race killer: a stray higher version minted on a
        # partitioned branch loses to a copy that SERVED a later
        # interval — max(last_update) alone would elect the branch
        assert self._best(last_epoch_started=4,
                          last_update=(1, 9)) == 2

    def test_last_update_breaks_les_tie(self):
        assert self._best(last_update=(2, 6)) == 2
        assert self._best(last_update=(2, 4)) == 1

    def test_longer_tail_breaks_update_tie(self):
        # smaller tail ev == longer retained log == more peers can
        # delta-recover from the winner
        assert self._best(log_tail=(0, 0)) == 2
        assert self._best(log_tail=(1, 5)) == 1

    def test_up_preferred_over_acting_only(self):
        assert self._best(in_up=False) == 1
        cands = {1: {**self.BASE, "in_up": False},
                 2: dict(self.BASE)}
        assert PGLog.find_best_info(cands) == 2

    def test_deterministic_on_full_tie(self):
        cands = {7: dict(self.BASE), 3: dict(self.BASE)}
        assert PGLog.find_best_info(cands) == 3
        assert PGLog.find_best_info({}) is None


class TestContains:
    def test_contains_entry_tail_and_trimmed_history(self):
        log = _log([_e((1, 1), "a"), _e((1, 2), "b")], tail=(0, 5))
        assert log.contains((1, 1)) and log.contains((1, 2))
        assert log.contains((0, 5))     # the tail boundary
        assert log.contains((0, 3))     # below tail: trimmed history
        assert not log.contains((1, 3))
        assert not log.contains((2, 1))


class TestDivergence:
    def test_clean_prefix_has_no_divergence(self):
        auth = _log([_e((1, 1), "a"), _e((1, 2), "b"),
                     _e((2, 3), "c")])
        peer = [_e((1, 1), "a"), _e((1, 2), "b")]
        rewind_to, div = auth.find_divergence(peer)
        assert div == []
        assert rewind_to == (1, 2)

    def test_forked_suffix_is_divergent(self):
        # the partition shape: shared prefix, then the stale side
        # minted (1, 3..4) while the serving side minted (2, 3)
        auth = _log([_e((1, 1), "a"), _e((1, 2), "b"),
                     _e((2, 3), "c")])
        peer = [_e((1, 1), "a"), _e((1, 2), "b"),
                _e((1, 3), "x", prior=(1, 1)), _e((1, 4), "y")]
        rewind_to, div = auth.find_divergence(peer)
        assert rewind_to == (1, 2)
        assert [tuple(e["ev"]) for e in div] == [(1, 4), (1, 3)]

    def test_peer_below_auth_tail_is_trusted(self):
        auth = _log([_e((3, 7), "z")], tail=(3, 6))
        peer = [_e((2, 1), "old"), _e((3, 6), "w")]
        rewind_to, div = auth.find_divergence(peer)
        assert div == []
        assert rewind_to == (3, 6)


class TestMergeLog:
    def test_claims_enter_missing_until_recovered(self):
        log = _log([_e((1, 1), "a")])
        pulls = log.merge_log([_e((2, 2), "b"), _e((2, 3), "a")])
        assert pulls == {"b": (2, 2), "a": (2, 3)}
        assert log.missing == {"b": (2, 2), "a": (2, 3)}
        assert log.head == (2, 3)
        log.record_recovered((2, 2), "b")
        log.record_recovered((2, 3), "a")
        assert log.missing == {}

    def test_merge_is_idempotent_and_delete_wins(self):
        log = _log([_e((1, 1), "a")])
        entries = [_e((2, 2), "b"), _e((2, 3), "b", op="delete")]
        pulls = log.merge_log(entries)
        assert pulls == {}                  # delete superseded the pull
        assert log.missing == {}
        assert "b" in log.deleted
        again = log.merge_log(entries)
        assert again == {} and log.head == (2, 3)
        assert len(log.entries) == 3        # no double-merge

    def test_reqid_claims_survive_merge(self):
        log = _log([])
        log.merge_log([_e((1, 1), "a", reqid=("client.x", 42))])
        assert log.entries[0]["reqid"] == ("client.x", 42)


class TestRewind:
    def test_rewind_restores_index_and_registers_missing(self):
        log = _log([_e((1, 1), "a"), _e((1, 2), "b"),
                    _e((1, 3), "a", prior=(1, 1)), _e((1, 4), "c")])
        undone = []
        div = log.rewind((1, 2), on_divergent=lambda e: (
            undone.append(tuple(e["ev"])), False)[1])
        assert [tuple(e["ev"]) for e in div] == [(1, 4), (1, 3)]
        assert undone == [(1, 4), (1, 3)]
        assert log.head == (1, 2)
        # modified object: back to prior AND missing (no local bytes)
        assert log.objects["a"] == (1, 1)
        assert log.missing == {"a": (1, 1)}
        # divergent create: gone entirely
        assert "c" not in log.objects and "c" not in log.missing

    def test_rewind_with_local_restore_skips_missing(self):
        # the EC stash path: on_divergent restored bytes locally
        log = _log([_e((1, 1), "a"), _e((1, 2), "a", prior=(1, 1))])
        log.rewind((1, 1), on_divergent=lambda e: True)
        assert log.objects["a"] == (1, 1)
        assert log.missing == {}

    def test_rewind_divergent_delete_undeletes(self):
        log = _log([_e((1, 1), "a"),
                    _e((1, 2), "a", op="delete", prior=(1, 1))])
        log.rewind((1, 1), on_divergent=lambda e: False)
        assert "a" not in log.deleted
        assert log.objects["a"] == (1, 1)
        assert log.missing == {"a": (1, 1)}

    def test_oldest_divergent_prior_wins_chain(self):
        log = _log([_e((1, 1), "a"),
                    _e((1, 2), "a", prior=(1, 1)),
                    _e((1, 3), "a", prior=(1, 2))])
        log.rewind((1, 1), on_divergent=lambda e: False)
        assert log.objects["a"] == (1, 1)
        assert log.missing["a"] == (1, 1)


class TestEncodeDecode:
    def test_missing_round_trips_and_legacy_decodes(self):
        log = _log([_e((1, 1), "a")])
        log.merge_log([_e((2, 2), "b")])
        out = PGLog.decode(log.encode())
        assert out.missing == {"b": (2, 2)}
        assert out.head == (2, 2)
        # legacy 4-field blob (pre-missing) still decodes
        from ceph_tpu.utils import denc
        legacy = denc.dumps((log.entries, log.objects, log.deleted,
                             log.tail))
        out2 = PGLog.decode(legacy)
        assert out2.missing == {} and out2.head == (2, 2)
