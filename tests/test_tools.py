"""Admin tools: rados CLI (+bench), ceph CLI, crushtool, osdmaptool,
objectstore tool, and standalone daemon entry points.

The tier-3 pattern (qa/workunits style): tools drive a live cluster;
offline tools operate on dumped maps and stopped stores.
"""

import io as io_mod
import os
import subprocess
import sys
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.tools import (ceph_cli, crushtool, objectstore_tool,
                            osdmaptool, rados_cli)
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def conf_file(cluster, tmp_path_factory):
    path = tmp_path_factory.mktemp("conf") / "ceph.conf"
    mon_host = ",".join(f"{h}:{p}" for h, p in
                        (cluster.monmap.addr_of(n)
                         for n in cluster.monmap.ranks()))
    path.write_text(
        f"[global]\nfsid = {cluster.monmap.fsid}\n"
        f"mon_host = {mon_host}\n"
        f"osd_heartbeat_grace = 8.0\n")
    return str(path)


def run_tool(main, argv) -> tuple[int, str]:
    buf = io_mod.StringIO()
    rc = main(argv, out=buf)
    return rc, buf.getvalue()


class TestRadosCli:
    def test_pool_and_object_lifecycle(self, cluster, conf_file,
                                       tmp_path):
        rc, _ = run_tool(rados_cli.main,
                         ["-c", conf_file, "mkpool", "clipool"])
        assert rc == 0
        src = tmp_path / "in.bin"
        src.write_bytes(b"cli payload " * 100)
        rc, _ = run_tool(rados_cli.main,
                         ["-c", conf_file, "-p", "clipool", "put",
                          "obj1", str(src)])
        assert rc == 0
        dst = tmp_path / "out.bin"
        rc, _ = run_tool(rados_cli.main,
                         ["-c", conf_file, "-p", "clipool", "get",
                          "obj1", str(dst)])
        assert rc == 0
        assert dst.read_bytes() == src.read_bytes()
        rc, out = run_tool(rados_cli.main,
                           ["-c", conf_file, "-p", "clipool", "ls"])
        assert "obj1" in out
        rc, out = run_tool(rados_cli.main,
                           ["-c", conf_file, "-p", "clipool", "stat",
                            "obj1"])
        assert "size 1200" in out
        rc, out = run_tool(rados_cli.main, ["-c", conf_file, "lspools"])
        assert "clipool" in out

    def test_bench(self, cluster, conf_file):
        rc, out = run_tool(
            rados_cli.main,
            ["-c", conf_file, "-p", "clipool", "bench", "2", "write",
             "-b", "4096", "-t", "2"])
        assert rc == 0
        assert "Bandwidth (MB/sec):" in out
        assert "Average IOPS:" in out


class TestCephCli:
    def test_status_and_osd_cmds(self, cluster, conf_file):
        rc, out = run_tool(ceph_cli.main, ["-c", conf_file, "status"])
        assert rc == 0 and "osd:" in out
        rc, out = run_tool(ceph_cli.main, ["-c", conf_file, "osd",
                                           "tree"])
        assert rc == 0
        rc, out = run_tool(ceph_cli.main,
                           ["-c", conf_file, "osd", "pool", "ls"])
        assert "clipool" in out

    def test_ec_profile_roundtrip(self, cluster, conf_file):
        rc, _ = run_tool(ceph_cli.main,
                         ["-c", conf_file, "osd",
                          "erasure-code-profile", "set", "cliprof",
                          "k=2", "m=1", "plugin=tpu"])
        assert rc == 0
        rc, out = run_tool(ceph_cli.main,
                           ["-c", conf_file, "osd",
                            "erasure-code-profile", "get", "cliprof"])
        assert "k=2" in out

    def test_daemon_passthrough(self, cluster, conf_file, tmp_path):
        osd = next(iter(cluster.osds.values()))
        # daemon mode needs a socket; MiniCluster default has none, so
        # spin one up ad hoc
        from ceph_tpu.utils.admin_socket import AdminSocket
        path = str(tmp_path / "t.asok")
        sock = AdminSocket("t", path)
        sock.register("ping", lambda c: {"pong": True})
        sock.start()
        try:
            rc, out = run_tool(ceph_cli.main,
                               ["daemon", path, "ping"])
            assert rc == 0 and '"pong": true' in out
        finally:
            sock.shutdown()


class TestCrushtool:
    def test_build_and_test(self, tmp_path):
        mapfile = str(tmp_path / "crush.bin")
        rc, out = run_tool(crushtool.main,
                           ["--build", "--num-osds", "12",
                            "--num-hosts", "4", "-o", mapfile])
        assert rc == 0 and os.path.exists(mapfile)
        rc, out = run_tool(crushtool.main,
                           ["-i", mapfile, "--test", "--num-rep", "3",
                            "--max-x", "255", "--show-utilization"])
        assert rc == 0
        assert "checked 256 mappings, 0 bad" in out

    def test_distribution_is_reasonable(self, tmp_path):
        buf = io_mod.StringIO()
        from ceph_tpu.crush.map import CrushMap
        cmap = CrushMap.build_flat(8)
        res = crushtool.test_map(cmap, 0, 3, 0, 2047, False, False,
                                 out=buf)
        assert res["bad_mappings"] == 0
        util = res["device_util"]
        avg = sum(util.values()) / len(util)
        assert all(abs(v - avg) / avg < 0.25 for v in util.values())


class TestOsdmaptool:
    def test_print_and_pg_distribution(self, cluster, conf_file,
                                       tmp_path):
        r = cluster.client()
        rv, _out, data = r.mon_command({"prefix": "osd getmap"})
        assert rv == 0 and data
        mapfile = tmp_path / "osdmap.bin"
        mapfile.write_bytes(data)
        rc, out = run_tool(osdmaptool.main,
                           [str(mapfile), "--print"])
        assert rc == 0 and "pool" in out and "osd.0" in out
        rc, out = run_tool(osdmaptool.main,
                           [str(mapfile), "--test-map-pgs"])
        assert rc == 0 and "examined" in out


class TestObjectstoreTool:
    def test_export_import_roundtrip(self, tmp_path):
        from ceph_tpu.store import create as store_create
        from ceph_tpu.store.objectstore import Transaction
        path = str(tmp_path / "osd-data")
        store = store_create("filestore", path)
        store.mkfs()
        store.mount()
        txn = (Transaction().create_collection("pg_9.0")
               .touch("pg_9.0", "obj").write("pg_9.0", "obj", 0, b"data")
               .setattr("pg_9.0", "obj", "k", b"v"))
        store.apply_transaction(txn)
        store.umount()

        export = str(tmp_path / "pg.export")
        rc, out = run_tool(
            objectstore_tool.main,
            ["--data-path", path, "--op", "export", "--pgid", "9.0",
             "--file", export])
        assert rc == 0 and "exported" in out

        path2 = str(tmp_path / "osd-data2")
        store2 = store_create("filestore", path2)
        store2.mkfs()
        store2.umount()
        rc, out = run_tool(
            objectstore_tool.main,
            ["--data-path", path2, "--op", "import", "--file", export])
        assert rc == 0
        rc, out = run_tool(
            objectstore_tool.main,
            ["--data-path", path2, "--op", "list"])
        assert "obj" in out
        rc, out = run_tool(
            objectstore_tool.main,
            ["--data-path", path2, "--op", "dump", "--pgid", "9.0",
             "--oid", "obj"])
        assert '"size": 4' in out


class TestPglogDump:
    """pglog-dump: offline PG log bounds/divergence inspection (the
    log-authoritative peering debug surface for wedged soaks)."""

    @staticmethod
    def _mk(path, entries, watermark=None, les=0):
        from ceph_tpu.osd.pglog import PGLog
        from ceph_tpu.store import create as store_create
        from ceph_tpu.store.objectstore import Transaction
        s = store_create("filestore", str(path))
        s.mkfs()
        s.mount()
        log = PGLog()
        for e in entries:
            log.add(dict(e))
        txn = (Transaction().create_collection("pg_7.0")
               .touch("pg_7.0", "_pgmeta")
               .setattr("pg_7.0", "_pgmeta", "log", log.encode()))
        if watermark is not None:
            txn.setattr("pg_7.0", "_pgmeta", "backfilling",
                        b"@" + watermark.encode())
        if les:
            txn.setattr("pg_7.0", "_pgmeta", "les",
                        str(les).encode())
        s.apply_transaction(txn)
        s.umount()

    def test_dump_divergence_and_watermark(self, tmp_path):
        import json
        from ceph_tpu.tools import pglog_dump

        def e(ev, oid, op="modify"):
            return {"ev": ev, "oid": oid, "op": op, "prior": None,
                    "rollback": None, "shard": None}

        self._mk(tmp_path / "a",
                 [e((1, 1), "x"), e((1, 2), "y"), e((2, 3), "z")],
                 les=2)
        self._mk(tmp_path / "b",
                 [e((1, 1), "x"), e((1, 2), "y"), e((1, 3), "w")],
                 watermark="mmm", les=1)
        rc, out = run_tool(pglog_dump.main,
                           ["--data-path", str(tmp_path / "a"),
                            "--pgid", "7.0", "--entries"])
        assert rc == 0
        doc = json.loads(out)
        assert doc["last_update"] == [2, 3]
        assert doc["entries"] == 3 and len(doc["log"]) == 3
        assert doc["last_epoch_started"] == 2
        assert doc["backfill_complete"] is True
        # the mid-backfill peer reports its persisted watermark
        rc, out = run_tool(pglog_dump.main,
                           ["--data-path", str(tmp_path / "b"),
                            "--pgid", "7.0"])
        doc = json.loads(out)
        assert doc["last_backfill"] == "mmm"
        assert doc["backfill_complete"] is False
        # divergence report: b's (1,3) suffix forked off a's history
        rc, out = run_tool(pglog_dump.main,
                           ["--data-path", str(tmp_path / "a"),
                            "--pgid", "7.0",
                            "--peer-path", str(tmp_path / "b")])
        assert rc == 0
        div = json.loads(out)["divergence"]
        mine = div["mine_as_auth"]
        assert mine["rewind_to"] == [1, 2]
        assert [d["ev"] for d in mine["divergent_entries"]] == [[1, 3]]
        assert mine["peer_contained"] is False
        # listing mode + missing pg error path
        rc, out = run_tool(pglog_dump.main,
                           ["--data-path", str(tmp_path / "a")])
        assert rc == 0 and "7.0" in json.loads(out)["pgs"]
        rc, _out = run_tool(pglog_dump.main,
                            ["--data-path", str(tmp_path / "a"),
                             "--pgid", "9.9"])
        assert rc == 1


class TestTraceDump:
    def test_live_cluster_dump_to_chrome_trace(self, cluster,
                                               tmp_path):
        """Smoke: real traced ops off a live cluster's historic ring
        -> trace_dump CLI -> loadable Chrome-trace JSON with complete
        events, span slices and process/thread metadata."""
        import json
        from ceph_tpu.tools import trace_dump
        rados = cluster.client()
        rados.create_pool("tracetool", pg_num=2)
        io = rados.open_ioctx("tracetool")
        end = time.time() + 30
        while True:
            try:
                io.write_full("t0", b"trace me" * 64)
                break
            except RadosError:
                if time.time() > end:
                    raise
                cluster.tick(0.3)
        paths = []
        for osd in cluster.osds.values():
            p = tmp_path / f"{osd.entity}.json"
            p.write_text(json.dumps(
                osd.op_tracker.dump_historic_ops()))
            paths.append(str(p))
        rc, out = run_tool(trace_dump.main, ["--dump", *paths])
        assert rc == 0
        doc = json.loads(out)
        events = doc["traceEvents"]
        assert any(e["ph"] == "X" and "t0" in e["name"]
                   for e in events)
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in events)
        assert any(e["ph"] == "X" and e["cat"] == "span"
                   for e in events)
        # no inputs is a usage error, not a crash
        assert trace_dump.main([]) == 2


class TestStandaloneDaemons:
    def test_process_level_cluster(self, tmp_path):
        """Real processes: 1 mon + 1 osd booted via the entry points,
        driven by the rados CLI over the wire (vstart.sh tier-3, but
        with actual process isolation)."""
        import socket as socket_mod
        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        conf = tmp_path / "ceph.conf"
        conf.write_text(
            "[global]\n"
            "fsid = 424242aa-0000-0000-0000-000000000000\n"
            f"mon_host = 127.0.0.1:{port}\n"
            "osd_pool_default_size = 1\n"
            "osd_pool_default_min_size = 1\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH="/root/repo:" + os.environ.get(
                       "PYTHONPATH", ""))
        procs = []
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ceph_tpu.daemons", "mon",
                 "--name", "a", "-c", str(conf)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL))
            time.sleep(1.5)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ceph_tpu.daemons", "osd",
                 "--id", "0", "-c", str(conf)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL))
            # drive it with the CLI from THIS process
            end = time.time() + 60
            while True:
                try:
                    rc, _ = run_tool(rados_cli.main,
                                     ["-c", str(conf), "mkpool", "solo"])
                    assert rc == 0
                    break
                except (RadosError, AssertionError):
                    if time.time() > end:
                        raise
                    time.sleep(1.0)
            payload = tmp_path / "p.bin"
            payload.write_bytes(b"inter-process!" * 10)
            end = time.time() + 30
            while True:
                try:
                    rc, _ = run_tool(
                        rados_cli.main,
                        ["-c", str(conf), "-p", "solo", "put", "x",
                         str(payload)])
                    assert rc == 0
                    break
                except (RadosError, AssertionError):
                    if time.time() > end:
                        raise
                    time.sleep(1.0)
            back = tmp_path / "b.bin"
            rc, _ = run_tool(
                rados_cli.main,
                ["-c", str(conf), "-p", "solo", "get", "x", str(back)])
            assert rc == 0
            assert back.read_bytes() == payload.read_bytes()
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestMonmapTool:
    def test_create_edit_print(self, tmp_path):
        from ceph_tpu.mon.monmap import MonMap
        from ceph_tpu.tools import monmaptool
        path = str(tmp_path / "monmap.bin")
        rc, out = run_tool(monmaptool.main, [
            "--create", "--fsid", "f-1",
            "--add", "a", "127.0.0.1:6789",
            "--add", "b", "127.0.0.1:6790", "-o", path])
        assert rc == 0 and "2 mons" in out
        rc, out = run_tool(monmaptool.main, ["-i", path, "--print"])
        assert rc == 0
        assert "mon.a" in out and "6790" in out and "fsid f-1" in out
        # edit: rm + add bumps the epoch
        path2 = str(tmp_path / "monmap2.bin")
        rc, out = run_tool(monmaptool.main, [
            "-i", path, "--rm", "b", "--add", "c", "127.0.0.1:6791",
            "-o", path2])
        assert rc == 0
        with open(path2, "rb") as f:
            mm = MonMap.decode(f.read())
        assert mm.ranks() == ["a", "c"] and mm.epoch == 2
        # duplicate add refused
        rc, _ = run_tool(monmaptool.main, [
            "-i", path2, "--add", "a", "127.0.0.1:7000"])
        assert rc == 1

    def test_seeds_a_bootable_monitor(self, tmp_path):
        """The tool's output is a real seed: a Monitor boots from it."""
        import socket
        from ceph_tpu.mon import Monitor
        from ceph_tpu.mon.monmap import MonMap
        from ceph_tpu.tools import monmaptool
        s = socket.socket(); s.bind(("127.0.0.1", 0))
        addr = f"127.0.0.1:{s.getsockname()[1]}"; s.close()
        path = str(tmp_path / "seed.bin")
        rc, _ = run_tool(monmaptool.main, [
            "--create", "--fsid", "boot-1", "--add", "a", addr,
            "-o", path])
        assert rc == 0
        with open(path, "rb") as f:
            mm = MonMap.decode(f.read())
        mon = Monitor("a", mm)
        mon.start()
        try:
            deadline = time.time() + 10
            while not mon.is_leader() and time.time() < deadline:
                time.sleep(0.1)
            assert mon.is_leader()
        finally:
            mon.shutdown()


class TestAuthTool:
    def test_keyring_lifecycle(self, tmp_path):
        import base64
        from ceph_tpu.auth import KeyRing
        from ceph_tpu.tools import authtool
        path = str(tmp_path / "keyring")
        rc, out = run_tool(authtool.main, [
            "--create-keyring", path, "--gen-key",
            "--name", "client.admin"])
        assert rc == 0 and "creating" in out
        rc, _ = run_tool(authtool.main, [path, "--gen-key",
                                         "--name", "osd.0"])
        assert rc == 0
        rc, out = run_tool(authtool.main, [path, "--list"])
        assert rc == 0
        assert "[client.admin]" in out and "[osd.0]" in out
        rc, out = run_tool(authtool.main, [path, "--print-key",
                                           "--name", "client.admin"])
        assert rc == 0
        ring = KeyRing.from_file(path)
        assert base64.b64decode(out.strip()) == \
            ring.get("client.admin")
        # import an explicit key
        k = base64.b64encode(b"S" * 24).decode()
        rc, _ = run_tool(authtool.main, [path, "--add-key", k,
                                         "--name", "mds.a"])
        assert rc == 0
        assert KeyRing.from_file(path).get("mds.a") == b"S" * 24


class TestCephfsShell:
    def test_namespace_workflow(self, cluster, conf_file, tmp_path):
        from ceph_tpu.tools import cephfs_shell
        cluster.start_mds("shell-mds")
        src = tmp_path / "local.txt"
        src.write_bytes(b"shell payload\n")
        rc, _ = run_tool(cephfs_shell.main,
                         ["-c", conf_file, "mkdir", "/sh/deep"])
        assert rc == 0
        rc, _ = run_tool(cephfs_shell.main,
                         ["-c", conf_file, "put", str(src),
                          "/sh/deep/f"])
        assert rc == 0
        rc, out = run_tool(cephfs_shell.main,
                           ["-c", conf_file, "cat", "/sh/deep/f"])
        assert rc == 0 and out == "shell payload\n"
        rc, out = run_tool(cephfs_shell.main,
                           ["-c", conf_file, "stat", "/sh/deep/f"])
        assert rc == 0 and "size=14" in out
        rc, _ = run_tool(cephfs_shell.main,
                         ["-c", conf_file, "mv", "/sh/deep/f",
                          "/sh/deep/g"])
        assert rc == 0
        dst = tmp_path / "out.txt"
        rc, _ = run_tool(cephfs_shell.main,
                         ["-c", conf_file, "get", "/sh/deep/g",
                          str(dst)])
        assert rc == 0 and dst.read_bytes() == b"shell payload\n"
        rc, out = run_tool(cephfs_shell.main,
                           ["-c", conf_file, "tree", "/sh"])
        assert rc == 0 and "deep/" in out and "g [14]" in out
        rc, _ = run_tool(cephfs_shell.main,
                         ["-c", conf_file, "rm", "/sh/deep/g"])
        assert rc == 0
        rc, out = run_tool(cephfs_shell.main,
                           ["-c", conf_file, "ls", "/sh/deep"])
        assert rc == 0 and out.strip() == ""
        # errors surface as rc=1, not tracebacks
        rc, out = run_tool(cephfs_shell.main,
                           ["-c", conf_file, "cat", "/nope"])
        assert rc == 1 and "cephfs-shell:" in out
