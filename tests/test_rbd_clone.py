"""RBD layering (clone/copyup/flatten) + image journaling (mirror
replay).

References: librbd/CopyupRequest.cc (copy-on-first-write),
librbd/operation/FlattenRequest.cc, cls_rbd parent/children/
protection, librbd/Journal.cc + journal/ (rbd-mirror's replay path).
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.rbd import RBD, Image, RbdError, replay_journal
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("rbd", pg_num=8)
    ctx = rados.open_ioctx("rbd")
    end = time.time() + 60
    while True:
        try:
            ctx.write_full("settle", b"s")
            return ctx
        except RadosError:
            if time.time() > end:
                raise
            cluster.tick(0.3)


@pytest.fixture(scope="module")
def io2(cluster, io):
    rados = cluster.client()
    rados.create_pool("rbd2", pg_num=8)
    ctx = rados.open_ioctx("rbd2")
    end = time.time() + 60
    while True:
        try:
            ctx.write_full("settle", b"s")
            return ctx
        except RadosError:
            if time.time() > end:
                raise
            cluster.tick(0.3)


class TestCloneCopyup:
    def test_clone_reads_through_to_parent(self, io):
        rbd = RBD(io)
        rbd.create("golden", 1 << 22, order=16)   # 64 KiB objects
        with Image(io, "golden") as p:
            p.write(0, b"base-image-bytes")
            p.write(100_000, b"deep-data")
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("golden", "v1", "vm1")
        with Image(io, "vm1") as c:
            assert c.size() == 1 << 22
            assert c.read(0, 16) == b"base-image-bytes"
            assert c.read(100_000, 9) == b"deep-data"

    def test_clone_requires_protected_snap(self, io):
        rbd = RBD(io)
        rbd.create("unprot", 1 << 20, order=16)
        with Image(io, "unprot") as p:
            p.write(0, b"x")
            p.snap_create("s1")
        with pytest.raises(RbdError):
            rbd.clone("unprot", "s1", "nope")

    def test_copyup_preserves_parent_bytes_around_write(self, io):
        rbd = RBD(io)
        rbd.create("cow-p", 1 << 20, order=16)
        with Image(io, "cow-p") as p:
            p.write(0, b"A" * 65536)           # one full object
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("cow-p", "v1", "cow-c")
        with Image(io, "cow-c") as c:
            c.write(10, b"BBBB")               # partial: must copyup
            got = c.read(0, 20)
            assert got == b"A" * 10 + b"BBBB" + b"A" * 6
            # the child object now materialized with inherited bytes
            assert c.read(65530, 6) == b"A" * 6
        # the parent stays pristine
        with Image(io, "cow-p", snapshot="v1") as p:
            assert p.read(0, 20) == b"A" * 20

    def test_parent_writes_after_snap_do_not_leak(self, io):
        rbd = RBD(io)
        rbd.create("leak-p", 1 << 20, order=16)
        with Image(io, "leak-p") as p:
            p.write(0, b"OLD-")
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("leak-p", "v1", "leak-c")
        with Image(io, "leak-p") as p:
            p.write(0, b"NEW-")                # after the snap
        with Image(io, "leak-c") as c:
            assert c.read(0, 4) == b"OLD-"     # clone sees the snap

    def test_discard_on_clone_hides_parent(self, io):
        rbd = RBD(io)
        rbd.create("disc-p", 1 << 20, order=16)
        with Image(io, "disc-p") as p:
            p.write(0, b"P" * 65536)
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("disc-p", "v1", "disc-c")
        with Image(io, "disc-c") as c:
            c.discard(0, 65536)                # whole parent-backed obj
            assert c.read(0, 16) == b"\x00" * 16

    def test_flatten_detaches_and_keeps_content(self, io):
        rbd = RBD(io)
        rbd.create("flat-p", 1 << 20, order=16)
        with Image(io, "flat-p") as p:
            p.write(0, b"flatten-me")
            p.write(70_000, b"tail")
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("flat-p", "v1", "flat-c")
        with Image(io, "flat-c") as c:
            c.write(4, b"XX")
            c.flatten()
            assert c.parent_spec is None
            assert c.read(0, 10) == b"flatXXn-me"
            assert c.read(70_000, 4) == b"tail"
        # the parent snap can now be unprotected and removed
        assert RBD(io).children("flat-p", "v1") == []
        with Image(io, "flat-p") as p:
            p.snap_unprotect("v1")
            p.snap_remove("v1")

    def test_unprotect_refused_while_children_exist(self, io):
        rbd = RBD(io)
        rbd.create("busy-p", 1 << 20, order=16)
        with Image(io, "busy-p") as p:
            p.write(0, b"y")
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("busy-p", "v1", "busy-c")
        with Image(io, "busy-p") as p:
            with pytest.raises(RbdError):
                p.snap_unprotect("v1")
            with pytest.raises(RbdError):
                p.snap_remove("v1")   # protected
        rbd.remove("busy-c")          # removing the clone detaches it
        with Image(io, "busy-p") as p:
            p.snap_unprotect("v1")

    def test_cross_pool_clone(self, io, io2):
        rbd = RBD(io)
        rbd.create("xp-p", 1 << 20, order=16)
        with Image(io, "xp-p") as p:
            p.write(0, b"cross-pool-parent")
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("xp-p", "v1", "xp-c", child_ioctx=io2)
        with Image(io2, "xp-c") as c:
            assert c.read(0, 17) == b"cross-pool-parent"
            c.write(0, b"LOCAL")
            assert c.read(0, 17) == b"LOCAL-pool-parent"


class TestCloneEdgeCases:
    def test_shrink_then_regrow_exposes_zeros_not_parent(self, io):
        rbd = RBD(io)
        rbd.create("sz-p", 1 << 20, order=16)
        with Image(io, "sz-p") as p:
            p.write(200_000, b"parent-tail-data")
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("sz-p", "v1", "sz-c")
        with Image(io, "sz-c") as c:
            assert c.read(200_000, 16) == b"parent-tail-data"
            c.resize(100_000)            # below the parent region
            c.resize(1 << 20)            # regrow
            # the shrink permanently reduced the overlap: zeros, not
            # the parent's pre-shrink bytes
            assert c.read(200_000, 16) == b"\x00" * 16

    def test_clone_snapshot_survives_flatten(self, io):
        """Copyup writes beneath the clone's snapshots: a snap taken
        before flatten must still see inherited parent bytes after."""
        rbd = RBD(io)
        rbd.create("fs-p", 1 << 20, order=16)
        with Image(io, "fs-p") as p:
            p.write(0, b"inherited-bytes!")
            p.snap_create("v1")
            p.snap_protect("v1")
        rbd.clone("fs-p", "v1", "fs-c")
        with Image(io, "fs-c") as c:
            c.snap_create("before-flatten")
            c.flatten()
            assert c.read(0, 16) == b"inherited-bytes!"
        with Image(io, "fs-c", snapshot="before-flatten") as s:
            assert s.read(0, 16) == b"inherited-bytes!"


class TestImageJournal:
    def test_journal_replay_reproduces_image(self, io, io2):
        """The mirror demo: replay a journaled image's events into a
        second pool; contents converge bit-exactly."""
        rbd = RBD(io)
        rbd.create("jrn", 1 << 20, order=16, journaling=True)
        with Image(io, "jrn") as src:
            assert src.journaling
            src.write(0, b"hello-journal")
            src.write(65_530, b"span-objects!")   # crosses a boundary
            src.discard(3, 4)
            src.resize(1 << 21)
            src.write((1 << 20) + 5, b"beyond-old-end")
        RBD(io2).create("jrn-copy", 1 << 20, order=16)
        with Image(io2, "jrn-copy") as dst:
            n = replay_journal(io, "jrn", dst)
            assert n == 5
            with Image(io, "jrn") as src:
                assert dst.size() == src.size()
                for off in (0, 3, 65_530, (1 << 20) + 5):
                    assert dst.read(off, 16) == src.read(off, 16), off
        # incremental: new events only
        with Image(io, "jrn") as src:
            src.write(512, b"incremental")
        with Image(io2, "jrn-copy") as dst:
            assert replay_journal(io, "jrn", dst) == 1
            assert dst.read(512, 11) == b"incremental"
            assert replay_journal(io, "jrn", dst) == 0   # idempotent

    def test_snapshot_events_replay(self, io, io2):
        rbd = RBD(io)
        rbd.create("jsnap", 1 << 20, order=16, journaling=True)
        with Image(io, "jsnap") as src:
            src.write(0, b"before-snap")
            src.snap_create("s1")
            src.write(0, b"after-snapp")
        RBD(io2).create("jsnap-copy", 1 << 20, order=16)
        with Image(io2, "jsnap-copy") as dst:
            replay_journal(io, "jsnap", dst)
        with Image(io2, "jsnap-copy") as dst:
            assert "s1" in dst.hdr["snaps"]
            assert dst.read(0, 11) == b"after-snapp"
        with Image(io2, "jsnap-copy", snapshot="s1") as snap:
            assert snap.read(0, 11) == b"before-snap"
