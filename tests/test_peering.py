"""Divergent-log peering: kill the primary mid-EC-write and prove the
survivors converge without losing acked data.

The scenario the reference exercises via
test/osd/osd-scrub-repair.sh:243 (TEST_unfound_erasure_coded) and the
PGLog rewind machinery (osd/PGLog.h, osd/ECTransaction.h rollback):

  * a write acked to the client exists on ALL live shards (the EC
    gather requires every shard), so survivors can always decode it;
  * a write the primary died in the middle of exists on a SUBSET of
    shards.  If >= k shards carry it, the new primary may roll forward
    (decodable, no client was told either way); with < k shards it MUST
    roll back via the stashed rollback state — those shards alone can
    never decode stripe v2.
"""

import time

import pytest

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.pg import ZERO_EV, shard_oid, stash_oid
from ceph_tpu.store.objectstore import Transaction
from ceph_tpu.utils import denc
from ceph_tpu.vstart import MiniCluster


@pytest.fixture()
def cluster():
    c = MiniCluster(num_mons=3, num_osds=3).start()
    yield c
    c.stop()


def _ec_setup(cluster):
    rados = cluster.client()
    rados.create_ec_pool("ecdiv", "k2m1",
                         {"plugin": "tpu", "k": 2, "m": 1,
                          "technique": "reed_sol_van"})
    return rados, rados.open_ioctx("ecdiv")


def _partial_ec_write(cluster, io, oid: str, payload: bytes,
                      to_shards: list[int]):
    """Apply a v-next EC write to only SOME shards — exactly what the
    acting set looks like when the primary dies mid-fan-out."""
    m = cluster.leader().osdmon.osdmap
    pgid = m.object_to_pg(io.pool_id, oid)
    up, acting = m.pg_to_up_acting_osds(pgid)
    primary = next(o for o in acting if o >= 0)
    ppg = cluster.osds[primary].get_pg(pgid)
    codec = ppg._ec_codec()
    sinfo = ppg._ec_sinfo(codec)
    shards, crcs = ecutil.encode_object(codec, sinfo, payload)
    # strictly newer than EVERY replica's applied state: the mon-map
    # "primary" may not be the replica that executed the client write
    # (map propagation race), and a colliding eversion would make the
    # partial write an idempotent no-op instead of a divergent v-next
    replicas = [cluster.osds[o].get_pg(pgid) for o in acting if o >= 0]
    ev = (max(p.interval_epoch for p in replicas),
          max(p.version for p in replicas) + 1)
    # prior likewise from the most-advanced replica: a lagging copy
    # would yield prior=None, mislabeling the divergent write a CREATE
    # (rewind would then delete the object instead of restoring it)
    prior = max((p.pglog.objects.get(oid) for p in replicas
                 if p.pglog.objects.get(oid) is not None),
                default=None)
    entry = {"ev": ev, "oid": oid, "op": "modify", "prior": prior,
             "rollback": {"type": "stash"}, "shard": None}
    for shard in to_shards:
        osd_id = acting[shard]
        pg = cluster.osds[osd_id].get_pg(pgid)
        soid = shard_oid(oid, shard)
        txn = Transaction()
        if prior is not None:
            txn.try_clone(pg.cid, soid, stash_oid(soid, prior))
        hinfo = denc.dumps({"size": len(payload), "crc": crcs[shard],
                            "shard": shard,
                            "stripe_unit": sinfo.chunk_size})
        txn.truncate(pg.cid, soid, 0)
        txn.write(pg.cid, soid, 0, shards[shard])
        txn.setattr(pg.cid, soid, "_hinfo", hinfo)
        with pg.lock:
            pg._apply_ec_sub_write(txn, entry, shard)
    return pgid, acting, primary


def _wait_read(io, oid: str, timeout: float = 30.0) -> bytes:
    from ceph_tpu.client import RadosError
    end = time.time() + timeout
    last = None
    while time.time() < end:
        try:
            return io.read(oid)
        except RadosError as e:
            last = e
            time.sleep(0.3)
    raise AssertionError(f"read never succeeded: {last}")


class TestDivergentRewind:
    def test_rollback_when_under_k_shards(self, cluster):
        """v2 reached only 1 of 3 shards (k=2): after the primary dies
        the divergent shard must REWIND and reads must return v1."""
        rados, io = _ec_setup(cluster)
        v1 = b"acked-and-safe" * 300
        v2 = b"torn-unacked!!" * 300
        io.write_full("obj", v1)
        assert io.read("obj") == v1
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "obj")
        up, acting = m.pg_to_up_acting_osds(pgid)
        primary = next(o for o in acting if o >= 0)
        # partial v2: only the first non-primary shard gets it
        victim = [s for s, o in enumerate(acting) if o != primary][:1]
        _partial_ec_write(cluster, io, "obj", v2, to_shards=victim)
        cluster.kill_osd(primary)
        cluster.wait_for_osd_down(primary)
        assert _wait_read(io, "obj") == v1

    def test_rollforward_when_k_shards_have_it(self, cluster):
        """v2 reached 2 of 3 shards (k=2, both survivors): the new
        primary may keep it — v2 is decodable and was never nacked."""
        rados, io = _ec_setup(cluster)
        v1 = b"first-version!" * 300
        v2 = b"newer-version!" * 300
        io.write_full("obj2", v1)
        assert io.read("obj2") == v1
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "obj2")
        up, acting = m.pg_to_up_acting_osds(pgid)
        primary = next(o for o in acting if o >= 0)
        others = [s for s, o in enumerate(acting) if o != primary]
        _partial_ec_write(cluster, io, "obj2", v2, to_shards=others)
        cluster.kill_osd(primary)
        cluster.wait_for_osd_down(primary)
        assert _wait_read(io, "obj2") == v2

    def test_rewind_restores_stash_content(self, cluster):
        """Unit-ish: rewind_to restores the pre-write shard bytes and
        version index from the stash."""
        rados, io = _ec_setup(cluster)
        v1 = b"A" * 5000
        v2 = b"B" * 5000
        io.write_full("obj3", v1)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "obj3")
        up, acting = m.pg_to_up_acting_osds(pgid)
        shard = 1
        osd_id = acting[shard]
        pg = cluster.osds[osd_id].get_pg(pgid)
        before_ev = pg.pglog.objects["obj3"]
        before_bytes = cluster.osds[osd_id].store.read(
            pg.cid, shard_oid("obj3", shard))
        _partial_ec_write(cluster, io, "obj3", v2, to_shards=[shard])
        assert pg.pglog.objects["obj3"] > before_ev
        pg.rewind_to(before_ev)
        assert pg.pglog.objects["obj3"] == before_ev
        assert cluster.osds[osd_id].store.read(
            pg.cid, shard_oid("obj3", shard)) == before_bytes

    def test_duplicate_client_op_not_reexecuted(self, cluster):
        """A client retry (same src+tid) must re-reply, not re-execute
        — double execution mints a second version and races rewinds."""
        rados, io = _ec_setup(cluster)
        io.write_full("dup", b"once")
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "dup")
        up, acting = m.pg_to_up_acting_osds(pgid)
        primary = next(o for o in acting if o >= 0)
        pg = cluster.osds[primary].get_pg(pgid)
        v_before = pg.pglog.objects["dup"]

        from ceph_tpu.osd.messages import MOSDOp
        replies = []

        class FakeConn:
            peer_name = "client.dup"
            peer_addr = None

        # reply_to_client goes through the messenger; intercept instead
        orig = pg.osd.reply_to_client
        pg.osd.reply_to_client = lambda conn, msg: replies.append(msg)
        try:
            op = MOSDOp(tid=9999, pgid=str(pgid), oid="dup",
                        ops=[("writefull", b"twice")], epoch=m.epoch,
                        snapc=None, snapid=None)
            op.src = "client.dup"
            pg.do_op(FakeConn(), op)
            dup = MOSDOp(tid=9999, pgid=str(pgid), oid="dup",
                         ops=[("writefull", b"twice")], epoch=m.epoch,
                         snapc=None, snapid=None)
            dup.src = "client.dup"
            deadline = time.time() + 10
            while len(replies) < 1 and time.time() < deadline:
                time.sleep(0.05)
            pg.do_op(FakeConn(), dup)       # retry after completion
            deadline = time.time() + 10
            while len(replies) < 2 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            pg.osd.reply_to_client = orig
        assert len(replies) == 2
        assert replies[0].version == replies[1].version
        # exactly ONE new version was minted
        assert pg.pglog.objects["dup"][1] == v_before[1] + 1

    def test_stashes_trimmed_after_full_ack(self, cluster):
        """Rollback stashes are GC'd once later fully-acked writes
        carry roll_forward_to past them (ECSubWrite trim semantics)."""
        rados, io = _ec_setup(cluster)
        for i in range(4):
            io.write_full("obj4", bytes([i]) * 3000)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "obj4")
        up, acting = m.pg_to_up_acting_osds(pgid)
        deadline = time.time() + 20
        while time.time() < deadline:
            stashes = [n for o in acting if o >= 0
                       for n in cluster.osds[o].store.collection_list(
                           f"pg_{pgid}") if "obj4" in n and "@" in n]
            # the newest write may still be untrimmed; all older
            # generations must be gone (<= 1 stash per shard)
            if len(stashes) <= len([o for o in acting if o >= 0]):
                break
            time.sleep(0.2)
        assert len(stashes) <= len([o for o in acting if o >= 0]), stashes




class TestAuthorityProof:
    """The pg_temp race class, CONSTRUCTED (not lucked into): a
    pg_temp cut elects a primary whose log lags an acked write.  The
    GetLog authority proof must block serving until the auth log is
    merged, and a client retry of the acked write must RE-REPLY (from
    the reqid-carrying merged log entry), never re-execute — the
    deterministic re-arming of test_duplicate_client_op_not_reexecuted.
    """

    @pytest.fixture()
    def quiet_cluster(self):
        # long heartbeat: the heartbeat-driven pg_temp reconcile must
        # not release our injected pin mid-assertion
        from ceph_tpu.utils.config import Config
        c = MiniCluster(num_mons=1, num_osds=3,
                        conf=Config({"osd_heartbeat_interval": 30.0,
                                     "osd_heartbeat_grace": 120.0})
                        ).start()
        yield c
        c.stop()

    def test_pg_temp_cut_lagging_primary_blocked_until_merge(
            self, quiet_cluster):
        from ceph_tpu.osd.messages import MOSDOp
        from ceph_tpu.store.objectstore import Transaction as Txn
        cluster = quiet_cluster
        rados = cluster.client()
        rados.create_pool("authp", pg_num=4, size=3, min_size=2)
        io = rados.open_ioctx("authp")
        end = time.time() + 60
        while True:
            try:
                io.write_full("settle", b"s")
                break
            except Exception:
                if time.time() > end:
                    raise
                time.sleep(0.3)
        io.write_full("dup", b"v1")
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "dup")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary = acting[0]
        ppg = cluster.osds[primary].get_pg(pgid)
        replies = []

        class FakeConn:
            peer_name = "client.race"
            peer_addr = None

        def crafted_op(epoch):
            op = MOSDOp(tid=4242, pgid=str(pgid), oid="dup",
                        ops=[("writefull", b"acked-v2")], epoch=epoch,
                        snapc=None, snapid=None)
            op.src = "client.race"
            return op

        orig = cluster.osds[primary].reply_to_client
        cluster.osds[primary].reply_to_client = \
            lambda conn, msg: replies.append(msg)
        try:
            ppg.do_op(FakeConn(), crafted_op(m.epoch))
            end = time.time() + 15
            while not replies and time.time() < end:
                time.sleep(0.05)
        finally:
            cluster.osds[primary].reply_to_client = orig
        assert replies and replies[0].result == 0
        acked_ver = tuple(replies[0].version)
        # construct the LAGGING copy: one replica loses the acked
        # write (log entry + bytes back to v1) — exactly the copy the
        # old max(last_update) election could have let serve
        lag = acting[1]
        lpg = cluster.osds[lag].get_pg(pgid)
        with lpg.lock:
            prior = None
            for e in lpg.pglog.entries:
                if e["oid"] == "dup" and tuple(e["ev"]) == acked_ver:
                    prior = e.get("prior")
            assert prior is not None, "acked entry never reached lag"
            prior = tuple(prior)
            lpg.pglog.entries = [
                e for e in lpg.pglog.entries
                if not (e["oid"] == "dup"
                        and tuple(e["ev"]) == acked_ver)]
            lpg.pglog.objects["dup"] = prior
            from ceph_tpu.osd.pg import VER_KEY
            cluster.osds[lag].store.apply_transaction(
                Txn().truncate(lpg.cid, "dup", 0)
                .write(lpg.cid, "dup", 0, b"v1")
                .setattr(lpg.cid, "dup", VER_KEY,
                         repr(prior).encode()))
        assert not lpg.pglog.contains(acked_ver)
        # THE pg_temp cut: pin the lagging copy as primary
        cluster.osds[primary].monc.send_pg_temp(
            primary, {str(pgid): [lag, acting[2], primary]})
        end = time.time() + 30
        while time.time() < end:
            lm = cluster.osds[lag].osdmap
            _u, a = lm.pg_to_up_acting_osds(pgid)
            if a and a[0] == lag:
                break
            cluster.tick(0.2)
            time.sleep(0.05)
        assert cluster.osds[lag].get_pg(pgid).is_primary
        # retry the acked write against the new (lagging) primary: it
        # answers EAGAIN while the authority proof runs (inactive
        # until the auth log is merged), then RE-REPLIES the original
        # version — never a re-execution
        lreplies = []
        lorig = cluster.osds[lag].reply_to_client
        cluster.osds[lag].reply_to_client = \
            lambda conn, msg: lreplies.append(msg)
        try:
            end = time.time() + 45
            final = None
            while time.time() < end:
                n0 = len(lreplies)
                lpg.do_op(FakeConn(),
                          crafted_op(cluster.osds[lag].osdmap.epoch))
                while len(lreplies) == n0 and time.time() < end:
                    time.sleep(0.02)
                if lreplies[n0:] and lreplies[n0].result == 0:
                    final = lreplies[n0]
                    break
                time.sleep(0.2)
        finally:
            cluster.osds[lag].reply_to_client = lorig
        assert final is not None, "lagging primary never served"
        # the authority proof ran: the lag merged the auth log
        perf = cluster.osds[lag]._perf_dump()["osd"]
        assert perf["peering_auth_catchups"] >= 1
        assert perf["peering_getlog_merges"] >= 1
        # dedup across the primary change: same version, no re-mint
        assert tuple(final.version) == acked_ver
        with lpg.lock:
            assert tuple(lpg.pglog.objects["dup"]) == acked_ver
        # and the acked payload survived the cut
        assert bytes(io.read("dup")) == b"acked-v2"


class TestReplicatedDivergentRewind:
    """The replicated stale-primary drill (deterministic): a primary
    holds a divergent never-acked suffix (the state a partition
    leaves), the surviving majority serves a newer interval, and the
    stale copy reconciles through rewind_divergent_log — counter-
    asserted, recovery proportional to the divergence, every acked
    write ledger-verified bit-exact."""

    def test_stale_primary_rewinds_and_ledger_stays_clean(
            self, cluster):
        from ceph_tpu.client.ledger import DurabilityLedger
        from ceph_tpu.store.objectstore import Transaction as Txn
        rados = cluster.client()
        rados.create_pool("rewindp", pg_num=4, size=3, min_size=2)
        io = rados.open_ioctx("rewindp")
        end = time.time() + 60
        while True:
            try:
                io.write_full("settle", b"s")
                break
            except Exception:
                if time.time() > end:
                    raise
                time.sleep(0.3)
        ledger = DurabilityLedger()
        filler = {f"fill{i:02d}": bytes([i]) * 32768 for i in range(12)}
        for oid, body in filler.items():
            ledger.write(io, oid, body)
        v1 = b"acked-and-safe" * 100
        ledger.write(io, "vic", v1)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "vic")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        stale = acting[0]
        apg = cluster.osds[stale].get_pg(pgid)
        # divergent, never-acked suffix on the primary only — the
        # exact state a partition mid-fan-out leaves behind
        v2div = b"divergent-lost!" * 100
        with apg.lock:
            apg.version += 1
            dev = (apg.interval_epoch, apg.version)
            prior = tuple(apg.pglog.objects["vic"])
            txn, kind, _out = apg._build_txn("vic",
                                             [("writefull", v2div)],
                                             dev)
            apg._log_and_apply(txn, {
                "ev": dev, "oid": "vic", "op": kind, "prior": prior,
                "rollback": None, "shard": None})
        assert bytes(cluster.osds[stale].store.read(
            apg.cid, "vic")) == v2div
        # the majority serves a NEWER interval while the stale copy
        # is out (les advances past the divergent branch)
        cluster.mark_osd_out(stale)
        end = time.time() + 60
        while time.time() < end:
            m2 = cluster.leader().osdmon.osdmap
            _u2, a2 = m2.pg_to_up_acting_osds(pgid)
            if a2 and stale not in a2:
                npg = cluster.osds[a2[0]].get_pg(pgid)
                if npg is not None and npg.active:
                    break
            cluster.tick(0.3)
            time.sleep(0.05)
        v3 = b"served-after-partition" * 50
        ledger.write(io, "vic2", v3)
        b_rw0 = cluster.osds[stale]._perf_dump()["osd"][
            "peering_divergent_rewinds"]
        rec0 = sum(o._perf_dump()["osd"]["recovery_bytes"]
                   for o in cluster.osds.values())
        # partition heals: the stale copy re-enters and re-claims
        # primacy — it must rewind through the shared core, NOT
        # out-version the acked history
        rados.mon_command({"prefix": "osd in", "id": stale})
        cluster.wait_for_clean(timeout=90)
        end = time.time() + 60
        while time.time() < end:
            perf = cluster.osds[stale]._perf_dump()["osd"]
            if perf["peering_divergent_rewinds"] > b_rw0:
                break
            cluster.tick(0.3)
            time.sleep(0.05)
        perf = cluster.osds[stale]._perf_dump()["osd"]
        assert perf["peering_divergent_rewinds"] > b_rw0, \
            "reconciliation never went through rewind_divergent_log"
        assert perf["peering_divergent_entries"] >= 1
        # acked state bit-exact, divergent write gone
        assert bytes(io.read("vic")) == v1
        assert bytes(io.read("vic2")) == v3
        ledger.verify(io)
        # recovery proportional to DIVERGENCE, not pg size: the
        # filler corpus (12 x 32 KiB x 3 replicas ≈ 1.2 MiB) must not
        # have been re-pushed object-map style
        rec1 = sum(o._perf_dump()["osd"]["recovery_bytes"]
                   for o in cluster.osds.values())
        divergence_bytes = len(v1) + len(v3)
        assert rec1 - rec0 <= 6 * divergence_bytes + 65536, \
            f"object-map-shaped recovery: {rec1 - rec0} bytes"


class TestReplicatedTriangle:
    def test_third_replica_auth_converges_in_one_round(self, cluster):
        """The auth copy lives on a NON-primary replica while BOTH the
        primary and the other replica are stale: one peering round
        must heal everyone (the primary pulls, and delegates a push to
        the other stale peer — no waiting for a later re-peer)."""
        from ceph_tpu.client import RadosError
        rados = cluster.client()
        rados.create_pool("tri", pg_num=4, size=3, min_size=2)
        io = rados.open_ioctx("tri")
        end = time.time() + 60
        while True:
            try:
                io.write_full("settle", b"s")
                break
            except RadosError:
                if time.time() > end:
                    raise
                cluster.tick(0.3)
        io.write_full("tri-obj", b"authoritative-content")
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "tri-obj")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary, rep1, rep2 = acting
        # regress the object on the PRIMARY and one replica: holder of
        # the auth copy becomes the OTHER replica (rep1)
        for osd_id in (primary, rep2):
            osd = cluster.osds[osd_id]
            pg = osd.pgs[pgid]
            with pg.lock:
                osd.store.apply_transaction(
                    Transaction().remove(f"pg_{pgid}", "tri-obj"))
                pg.pglog.objects.pop("tri-obj", None)
                pg.pglog.entries = [
                    e for e in pg.pglog.entries
                    if e["oid"] != "tri-obj"]
        # force a peering round on the primary
        ppg = cluster.osds[primary].pgs[pgid]
        ppg.start_peering()
        end = time.time() + 30
        while True:
            healed = all(
                cluster.osds[o].store.exists(f"pg_{pgid}", "tri-obj")
                and cluster.osds[o].store.read(
                    f"pg_{pgid}", "tri-obj") == b"authoritative-content"
                for o in acting)
            if healed:
                break
            if time.time() > end:
                stat = {o: cluster.osds[o].store.exists(
                    f"pg_{pgid}", "tri-obj") for o in acting}
                raise AssertionError(
                    f"triangle did not converge in one round: {stat}")
            cluster.tick(0.3)
            time.sleep(0.05)
        assert io.read("tri-obj") == b"authoritative-content"
