"""CephFS: MDS metadata ops + striped file I/O through the client.

client/Client.h + mds/Server.cc semantics at single-rank scope:
namespace ops resolve at the MDS, file bytes go straight to the data
pool, sizes flow back through setattr.
"""

import time

import pytest

from ceph_tpu.fs import CephFS, FsError, data_oid
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    c.start_mds("a")
    yield c
    c.stop()


@pytest.fixture(scope="module")
def fs(cluster):
    rados = cluster.client()
    f = CephFS(rados)
    end = time.time() + 40
    while True:
        try:
            return f.mount(timeout=10.0)
        except FsError:
            if time.time() > end:
                raise
            cluster.tick(0.5)


class TestNamespace:
    def test_mkdir_listdir_stat(self, fs):
        fs.mkdir("/home")
        fs.mkdir("/home/user")
        assert fs.listdir("/") == ["home"]
        assert fs.listdir("/home") == ["user"]
        st = fs.stat("/home/user")
        assert st["type"] == "dir"

    def test_mkdirs(self, fs):
        fs.mkdirs("/a/b/c")
        assert fs.listdir("/a/b") == ["c"]
        fs.mkdirs("/a/b/c")          # idempotent

    def test_mkdir_missing_parent(self, fs):
        with pytest.raises(FsError) as ei:
            fs.mkdir("/no/such/parent")
        assert ei.value.errno == 2

    def test_rmdir(self, fs):
        fs.mkdir("/tmpdir")
        fs.rmdir("/tmpdir")
        with pytest.raises(FsError):
            fs.stat("/tmpdir")

    def test_rmdir_nonempty_refused(self, fs):
        with pytest.raises(FsError) as ei:
            fs.rmdir("/a/b")
        assert ei.value.errno == 39

    def test_rename(self, fs):
        fs.mkdir("/olddir")
        fs.rename("/olddir", "/newdir")
        assert "newdir" in fs.listdir("/")
        assert "olddir" not in fs.listdir("/")


class TestFileIO:
    def test_write_read_roundtrip(self, fs):
        with fs.open("/home/user/hello.txt", "w") as f:
            f.write(b"Hello, CephFS!")
        with fs.open("/home/user/hello.txt") as f:
            assert f.read() == b"Hello, CephFS!"
        st = fs.stat("/home/user/hello.txt")
        assert st["type"] == "file" and st["size"] == 14

    def test_large_file_stripes_across_objects(self, fs):
        payload = bytes(range(256)) * 40000        # ~10 MB, 4M objects
        with fs.open("/big.bin", "w") as f:
            f.write(payload)
        with fs.open("/big.bin") as f:
            assert f.read() == payload
        st = fs.stat("/big.bin")
        # data landed in multiple backing objects in the data pool
        assert fs.data.stat(data_oid(st["ino"], 0))["size"] > 0
        assert fs.data.stat(data_oid(st["ino"], 1))["size"] > 0

    def test_pread_pwrite(self, fs):
        with fs.open("/sparse.bin", "w") as f:
            f.write(b"END", offset=1000)
        with fs.open("/sparse.bin") as f:
            data = f.read(offset=0)
            assert len(data) == 1003
            assert data[:1000] == b"\x00" * 1000
            assert data[1000:] == b"END"

    def test_append_mode(self, fs):
        with fs.open("/log.txt", "w") as f:
            f.write(b"line1\n")
        with fs.open("/log.txt", "a") as f:
            f.write(b"line2\n")
        with fs.open("/log.txt") as f:
            assert f.read() == b"line1\nline2\n"

    def test_truncate_on_w_mode(self, fs):
        with fs.open("/shrink.txt", "w") as f:
            f.write(b"a lot of old data here")
        with fs.open("/shrink.txt", "w") as f:
            f.write(b"new")
        with fs.open("/shrink.txt") as f:
            assert f.read() == b"new"

    def test_unlink_purges_data(self, fs):
        with fs.open("/doomed.bin", "w") as f:
            f.write(b"x" * 100000)
        ino = fs.stat("/doomed.bin")["ino"]
        fs.unlink("/doomed.bin")
        with pytest.raises(FsError):
            fs.stat("/doomed.bin")
        from ceph_tpu.client import RadosError
        with pytest.raises(RadosError):
            fs.data.stat(data_oid(ino, 0))

    def test_read_only_mode_rejects_write(self, fs):
        with fs.open("/home/user/hello.txt") as f:
            with pytest.raises(FsError) as ei:
                f.write(b"sneaky")
            assert ei.value.errno == 9

    def test_open_directory_as_file_fails(self, fs):
        with pytest.raises(FsError) as ei:
            fs.open("/home")
        assert ei.value.errno == 21


class TestTwoClients:
    def test_cross_client_visibility(self, fs, cluster):
        rados2 = cluster.client("client.second-mount")
        fs2 = CephFS(rados2).mount()
        with fs.open("/shared.txt", "w") as f:
            f.write(b"from client one")
        with fs2.open("/shared.txt") as f:
            assert f.read() == b"from client one"
        fs2.mkdir("/from-two")
        assert "from-two" in fs.listdir("/")


class TestRenameEdges:
    def test_rename_into_own_subtree_rejected(self, fs):
        fs.mkdirs("/cycle/sub")
        with pytest.raises(FsError) as ei:
            fs.rename("/cycle", "/cycle/sub/x")
        assert ei.value.errno == 22
        assert "cycle" in fs.listdir("/")

    def test_rename_replaces_file_atomically(self, fs):
        with fs.open("/target.txt", "w") as f:
            f.write(b"old-old-old" * 100)
        old_ino = fs.stat("/target.txt")["ino"]
        with fs.open("/target.tmp", "w") as f:
            f.write(b"new")
        fs.rename("/target.tmp", "/target.txt")
        with fs.open("/target.txt") as f:
            assert f.read() == b"new"
        assert "target.tmp" not in fs.listdir("/")
        from ceph_tpu.client import RadosError
        with pytest.raises(RadosError):
            fs.data.stat(data_oid(old_ino, 0))   # old data purged

    def test_rename_over_directory_rejected(self, fs):
        fs.mkdir("/dst-dir")
        with fs.open("/src-file", "w") as f:
            f.write(b"x")
        with pytest.raises(FsError) as ei:
            fs.rename("/src-file", "/dst-dir")
        assert ei.value.errno == 17
