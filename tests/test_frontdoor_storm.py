"""Every front door under fire: one seeded mixed-door schedule —
raw rados, S3 over the real RGW HTTP stack, CephFS through the MDS,
and RBD striped image I/O — against ONE cluster, while the fault
script partitions the two RGW zones, deletes through the primary
mid-split, crashes the secondary gateway, and kills+rebirths an OSD.

Gates: zero unexplained errors, zero stale reads at ANY door, the
two-zone durability ledger clean (acked puts bit-exact at the
replica after heal; the mid-partition delete tombstones at both
zones, never resurrects), and the sync agent's merged counters show
exponential backoff across the cut — degraded, never wedged, never
lying.
"""

import time

import pytest

from ceph_tpu.client import CephFSDoor, RGWDoor, RadosError
from ceph_tpu.rgw.sync import RGWSyncAgent
from ceph_tpu.tools.loadgen import (RBDImageDoor, TenantSpec,
                                    run_frontdoor_storm)
from ceph_tpu.utils import faults
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster

CONF = {
    "mon_tick_interval": 0.5,
    "osd_heartbeat_interval": 0.5,
    "osd_heartbeat_grace": 8.0,
    "mon_osd_min_down_reporters": 2,
    "mon_osd_down_out_interval": 5.0,
    # fail blocked ops fast: the doors own their resends
    # (TenantSpec.retry_window), and the MDS journals metadata under
    # its big lock — a 30-virtual-second objecter stall there starves
    # every client request for minutes of real time after an OSD kill
    "objecter_op_timeout": 5.0,
}

SLOT = 64 << 10


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3,
                    conf=Config(dict(CONF))).start()
    r = c.client()
    r.create_pool("doors", pg_num=4)
    io = r.open_ioctx("doors")
    end = time.time() + 40
    while True:
        try:
            io.write_full("w", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            c.tick(0.3)
    yield c
    c.stop()


def test_mixed_doors_two_zone_storm(cluster):
    r = cluster.client()
    rados_io = r.open_ioctx("doors")

    # -- CephFS door: MDS + mounted client ------------------------------
    from ceph_tpu.fs import CephFS, FsError
    cluster.start_mds("a")
    fs = CephFS(cluster.client("client.fsdoor"))
    end = time.time() + 60
    while True:
        try:
            fs.mount(timeout=10.0)
            break
        except FsError:
            if time.time() > end:
                raise
            cluster.tick(0.5)
    fs_door = CephFSDoor(fs, root="/doors")

    # -- RBD door: one striped image, slot-per-object -------------------
    from ceph_tpu.rbd import RBD, Image
    r.create_pool("rbdp", pg_num=4)
    rbd_io = r.open_ioctx("rbdp")
    RBD(rbd_io).create("img", size=16 * SLOT, order=16)
    img = Image(rbd_io, "img")
    rbd_door = RBDImageDoor(img, slot_bytes=SLOT)

    # -- two RGW zones on disjoint pools + the sync agent ---------------
    gw_a = cluster.start_rgw(data_pool="zone_a")     # primary
    gw_b = cluster.start_rgw(data_pool="zone_b")     # replica
    agent = RGWSyncAgent(gw_b, f"http://127.0.0.1:{gw_a.port}",
                         interval=0.2).start()
    s3_door = RGWDoor(f"http://127.0.0.1:{gw_a.port}", bucket="s3door")

    def respawn():
        gw2 = cluster.start_rgw(port=gw_b.port, data_pool="zone_b")
        ag2 = RGWSyncAgent(gw2, f"http://127.0.0.1:{gw_a.port}",
                           interval=0.2).start()
        return gw2, ag2

    zones = {"primary": gw_a, "secondary": gw_b, "agent": agent,
             "respawn": respawn}
    tenants = [
        TenantSpec("doors", rate=40.0, duration=4.0, obj_count=32,
                   read_frac=0.5, append_frac=0.2, delete_frac=0.15,
                   payload=8192, door="rados", retry_window=45.0),
        TenantSpec("s3", rate=18.0, duration=4.0, obj_count=16,
                   read_frac=0.5, delete_frac=0.15, payload=4096,
                   door="s3", retry_window=45.0, max_workers=16),
        TenantSpec("fs", rate=10.0, duration=4.0, obj_count=12,
                   read_frac=0.5, delete_frac=0.1, payload=4096,
                   door="cephfs", retry_window=45.0, max_workers=8),
        TenantSpec("rbd", rate=16.0, duration=4.0, obj_count=16,
                   read_frac=0.5, payload=4096, door="rbd",
                   retry_window=45.0, max_workers=8),
    ]
    ioctxs = {"doors": rados_io, "s3": s3_door, "fs": fs_door,
              "rbd": rbd_door}
    try:
        res = run_frontdoor_storm(cluster, ioctxs, tenants,
                                  zones=zones, seed=0xD00B)
    finally:
        img.close()
        zones["agent"].shutdown()

    # every door took ops; none of them lied
    assert set(res["doors"]) == {"rados", "s3", "cephfs", "rbd"}, res
    for door, stats in res["doors"].items():
        assert stats["ops"] > 0, (door, stats)
        assert stats["errors"] == 0, (door, stats)
        assert stats["stale_reads"] == 0, (door, stats)
        assert stats["p99_ms"] >= stats["p50_ms"] >= 0.0, (door, stats)
    assert res["errors"] == 0, res
    assert res["stale_reads"] == 0, res

    # the storm window saw real load (the faults landed DURING
    # traffic, not beside it) — window_report slices per pool
    storm = res["storm"]
    assert sum(p["ops"] for p in storm.values()) > 0, storm

    # two-zone durability oracle: acked puts bit-exact at the replica
    # after heal; the mid-partition delete never resurrects
    assert res["zone_ledger_ok"], res["zone_ledger_detail"]
    zl = res["zone_ledger"]
    assert zl["replica_converged"] >= 4, zl   # ldg-0/1, zdel, ldg-deg
    assert zl["deletes_held_both_zones"] == 1, zl      # zdel held
    assert zl["primary"]["acked_deletes"] == 1, zl

    # the cut was FELT and the agent backed off (no wedge, no tight
    # error loop) — counters merged across both agent incarnations
    assert res["sync"]["sync_errors"] > 0, res["sync"]
    assert res["sync"]["sync_backoff_secs"] > 0, res["sync"]
    # the respawned agent resumed rounds after the crash
    assert res["sync"]["sync_rounds"] > 0, res["sync"]

    # recovery actually ran (OSD kill + rebirth inside the window)
    assert res["recovery_wall_s"] > 0.0, res
