"""PG split: pg_num increase on a loaded pool (VERDICT r3 #2).

The reference flow (mon/OSDMonitor.cc:3649 `pool set pg_num`,
osd/OSD.cc:7553 `OSD::split_pgs`): pg_num may only grow; new children
start pg_temp-pinned to their parent's acting set while every member
splits its local collections in place; primaries then backfill the
CRUSH targets and release the pin, so placement converges to fresh
CRUSH computation with every object readable throughout.
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.osd.osdmap import PgId, parent_seed
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster

CONF = {
    "osd_heartbeat_interval": 0.5,
    "osd_heartbeat_grace": 8.0,
    "mon_osd_min_down_reporters": 2,
}


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3,
                    conf=Config(dict(CONF))).start()
    yield c
    c.stop()


def _settle(io, timeout=60.0):
    end = time.time() + timeout
    while True:
        try:
            io.write_full("settle", b"s")
            return
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)


def _read_retry(io, oid, timeout=60.0):
    end = time.time() + timeout
    while True:
        try:
            return io.read(oid)
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)


class TestParentSeed:
    def test_stable_mod_ancestry(self):
        from ceph_tpu.osd.osdmap import ceph_stable_mod, pg_num_mask
        # every object that maps to a child under the new pg_num must
        # have mapped to parent_seed(child) under the old pg_num
        for old, new in ((2, 4), (4, 8), (3, 6), (5, 9), (8, 11)):
            for x in range(4096):
                old_seed = ceph_stable_mod(x, old, pg_num_mask(old))
                new_seed = ceph_stable_mod(x, new, pg_num_mask(new))
                if new_seed >= old:
                    assert parent_seed(new_seed, old) == old_seed, \
                        (old, new, x)
                else:
                    assert new_seed == old_seed, (old, new, x)


class TestPgSplit:
    def test_double_pg_num_stays_readable_and_converges(self, cluster):
        rados = cluster.client()
        # size=2 on 3 osds: children's CRUSH subsets differ from their
        # parents', so the pin release requires REAL backfill of new
        # targets (size=3 would trivially map every pg to all osds)
        rados.create_pool("grow", pg_num=2, size=2, min_size=1)
        io = rados.open_ioctx("grow")
        _settle(io)
        objs = {}
        for i in range(40):
            data = f"split-{i}-".encode() * 30
            io.write_full(f"g{i}", data)
            objs[f"g{i}"] = data
        rv, out, _ = rados.mon_command({
            "prefix": "osd pool set", "pool": "grow",
            "var": "pg_num", "val": "4"})
        assert rv == 0, out
        # decrease is rejected (split-only, like the reference)
        rv, out, _ = rados.mon_command({
            "prefix": "osd pool set", "pool": "grow",
            "var": "pg_num", "val": "2"})
        assert rv != 0
        # every object stays readable THROUGH the split
        for name, data in objs.items():
            assert _read_retry(io, name) == data
        # new seeds actually receive objects
        end = time.time() + 60
        while time.time() < end:
            m = cluster.leader().osdmon.osdmap
            pool = m.pool_by_name("grow")
            if pool.pg_num == 4:
                break
            time.sleep(0.3)
        m = cluster.leader().osdmon.osdmap
        new_seeds = {m.object_to_pg(io.pool_id, n).seed for n in objs}
        assert any(s >= 2 for s in new_seeds), \
            "no object re-bucketed to a child pg"
        # the pin releases: pg_temp drains and placement matches
        # fresh CRUSH computation, with the CRUSH acting set actually
        # holding each object
        end = time.time() + 90
        while time.time() < end:
            m = cluster.leader().osdmon.osdmap
            if not any(pgid.pool == io.pool_id
                       for pgid in m.pg_temp):
                break
            time.sleep(0.5)
        m = cluster.leader().osdmon.osdmap
        assert not any(pgid.pool == io.pool_id for pgid in m.pg_temp), \
            f"pg_temp never drained: {m.pg_temp}"
        end = time.time() + 60
        bad = None
        while time.time() < end:
            bad = None
            # re-sample the live map each round: a transiently down
            # osd changes acting mid-poll, and ITEM_NONE (2^31-1) must
            # not be indexed as a daemon id
            m = cluster.leader().osdmon.osdmap
            for name, data in objs.items():
                pgid = m.object_to_pg(io.pool_id, name)
                _up, acting = m.pg_to_up_acting_osds(pgid)
                holders = [o for o in acting if o in cluster.osds]
                if not holders:
                    bad = (name, None, "empty acting")
                    break
                for o in holders:
                    try:
                        got = cluster.osds[o].store.read(
                            f"pg_{pgid}", name)
                    except Exception:
                        bad = (name, o, "missing")
                        break
                    if got != data:
                        bad = (name, o, "stale")
                        break
                if bad:
                    break
            if bad is None:
                break
            time.sleep(0.5)
        assert bad is None, f"object not on CRUSH acting set: {bad}"
        # and the client still reads everything at the end
        for name, data in objs.items():
            assert _read_retry(io, name) == data

    def test_ec_pool_split_keeps_objects_decodable(self, cluster):
        """EC pools split the same way: shard files re-bucket into
        child collections locally; every object stays readable."""
        rados = cluster.client()
        rados.create_ec_pool("growec", "k2m1s",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "technique": "reed_sol_van"}, pg_num=2)
        io = rados.open_ioctx("growec")
        _settle(io)
        objs = {}
        for i in range(20):
            data = f"ecsplit-{i}-".encode() * 200
            io.write_full(f"e{i}", data)
            objs[f"e{i}"] = data
        rv, out, _ = rados.mon_command({
            "prefix": "osd pool set", "pool": "growec",
            "var": "pg_num", "val": "4"})
        assert rv == 0, out
        for name, data in objs.items():
            assert _read_retry(io, name) == data
        # shard files actually re-bucketed to child collections
        end = time.time() + 60
        while time.time() < end:
            m = cluster.leader().osdmon.osdmap
            pool = m.pool_by_name("growec")
            seeds = {m.object_to_pg(pool.id, n).seed for n in objs}
            if pool.pg_num == 4 and any(s >= 2 for s in seeds):
                break
            time.sleep(0.3)
        assert any(s >= 2 for s in seeds), "no EC object re-bucketed"
        moved = next(n for n in objs
                     if m.object_to_pg(pool.id, n).seed >= 2)
        pgid = m.object_to_pg(pool.id, moved)
        end = time.time() + 60     # loaded CI: give re-bucketing room
        ok = False
        while time.time() < end and not ok:
            # re-sample acting each round (see above): placement must
            # match the CURRENT acting order, and role remaps converge
            # via the post-peering shard audit
            m = cluster.leader().osdmon.osdmap
            _up, acting = m.pg_to_up_acting_osds(pgid)
            holders = [(s, o) for s, o in enumerate(acting)
                       if o in cluster.osds]
            ok = bool(holders) and all(
                cluster.osds[o].store.exists(f"pg_{pgid}",
                                             f"{moved}.s{s}")
                for s, o in holders)
            if not ok:
                time.sleep(0.5)
        assert ok, f"shards of {moved} not in child {pgid}"
        for name, data in objs.items():
            assert _read_retry(io, name) == data
