"""Mon health/PGMap aggregation + paxos trim/full-sync.

References: mon/PGMonitor.cc (PGMap aggregation feeding `ceph -s`),
mon/HealthMonitor.cc, mon/Paxos.cc trim + Monitor sync (a mon behind
the trim point rejoins via full store sync).
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.mon import MonMap, Monitor
from ceph_tpu.utils import denc
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


def wait_for(pred, timeout=15, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestHealthStatus:
    @pytest.fixture(scope="class")
    def cluster(self):
        conf = Config({
            "mon_tick_interval": 0.5,
            "osd_heartbeat_interval": 0.5,
            "osd_heartbeat_grace": 8.0,
            "mon_osd_min_down_reporters": 2,
            "mon_osd_down_out_interval": 600.0,   # stay "down+in"
        })
        c = MiniCluster(num_mons=3, num_osds=3, conf=conf).start()
        yield c
        c.stop()

    def _status(self, rados):
        rv, out, _ = rados.mon_command({"prefix": "status"})
        assert rv == 0
        return out

    def test_healthy_cluster_reports_ok_and_clean_pgs(self, cluster):
        rados = cluster.client()
        rados.create_pool("health-p", pg_num=8)
        io = rados.open_ioctx("health-p")
        end = time.time() + 60
        while True:
            try:
                io.write_full("x", b"1")
                break
            except RadosError:
                if time.time() > end:
                    raise
                cluster.tick(0.3)
        # stats flow on the heartbeat; health settles to OK
        end = time.time() + 30
        while True:
            out = self._status(rados)
            if "HEALTH_OK" in out and "active+clean" in out:
                break
            if time.time() > end:
                raise AssertionError(f"never became healthy:\n{out}")
            cluster.tick(0.5)
            time.sleep(0.05)
        assert "pgs:" in out

    def test_down_osd_reports_health_warn(self, cluster):
        rados = cluster.client()
        cluster.kill_osd(2)
        cluster.wait_for_osd_down(2)
        end = time.time() + 30
        while True:
            out = self._status(rados)
            if "HEALTH_WARN" in out and "osds down" in out:
                break
            if time.time() > end:
                raise AssertionError(f"no WARN after osd down:\n{out}")
            cluster.tick(0.5)
            time.sleep(0.05)
        # degraded pgs surface once primaries re-report
        end = time.time() + 30
        while True:
            out = self._status(rados)
            if "degraded" in out or "undersized" in out:
                break
            if time.time() > end:
                raise AssertionError(f"no degraded pgs shown:\n{out}")
            cluster.tick(0.5)
            time.sleep(0.05)
        rv, health_out, _ = rados.mon_command({"prefix": "health"})
        assert rv == 0 and "HEALTH_WARN" in health_out
        rv, dump, _ = rados.mon_command({"prefix": "pg dump"})
        assert rv == 0 and "degraded" in dump
        # restart and recover to OK
        cluster.start_osd(2)
        cluster.wait_for_osds(3)
        end = time.time() + 60
        while True:
            out = self._status(rados)
            if "HEALTH_OK" in out:
                break
            if time.time() > end:
                raise AssertionError(f"never recovered:\n{out}")
            cluster.tick(0.5)
            time.sleep(0.05)


def _make_mons(n=3, conf=None):
    import socket
    conf = conf or Config({"mon_tick_interval": 0.2})
    mm = MonMap(fsid="trim-fsid")
    socks = []
    for i in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        mm.add(chr(ord("a") + i), ("127.0.0.1", s.getsockname()[1]))
        socks.append(s)
    for s in socks:
        s.close()
    mons = [Monitor(name, mm, conf=conf) for name in mm.ranks()]
    for m in mons:
        m.start()
    return mm, mons


class TestPaxosTrim:
    def test_trim_bounds_the_committed_window(self):
        conf = Config({"mon_tick_interval": 0.2,
                       "paxos_max_versions": 20,
                       "paxos_trim_keep": 5})
        mm, mons = _make_mons(3, conf)
        try:
            assert wait_for(lambda: any(m.is_leader() for m in mons))
            leader = next(m for m in mons if m.is_leader())
            for i in range(40):
                with leader.lock:
                    leader.paxos.propose(denc.dumps(
                        [("set", "t", f"k{i}", b"v")]))
                time.sleep(0.01)
            assert wait_for(
                lambda: leader.paxos.last_committed >= 40, timeout=20)
            # trim rides the tick; the window must shrink below max
            assert wait_for(
                lambda: leader.paxos.last_committed
                - leader.paxos.first_committed <= 21, timeout=20), \
                (leader.paxos.first_committed,
                 leader.paxos.last_committed)
            assert leader.paxos.first_committed > 1
            # trimmed versions are really gone from the store
            assert leader.store.get_version(
                "paxos", leader.paxos.first_committed - 1) is None
            # peons trimmed identically (the erase rode the log)
            peon = next(m for m in mons if not m.is_leader())
            assert wait_for(
                lambda: peon.paxos.first_committed ==
                leader.paxos.first_committed, timeout=10)
        finally:
            for m in mons:
                m.shutdown()

    def test_mon_behind_trim_point_full_syncs(self):
        conf = Config({"mon_tick_interval": 0.2,
                       "paxos_max_versions": 20,
                       "paxos_trim_keep": 5})
        mm, mons = _make_mons(3, conf)
        try:
            assert wait_for(lambda: any(m.is_leader() for m in mons))
            # take mon c down; drive the survivors far past the trim
            victim = mons[2]
            victim.shutdown()
            leader = next(m for m in mons[:2] if m.is_leader()) \
                if any(m.is_leader() for m in mons[:2]) else None
            if leader is None:
                for m in mons[:2]:
                    with m.lock:
                        m.elector.start()
                assert wait_for(
                    lambda: any(m.is_leader() for m in mons[:2]))
                leader = next(m for m in mons[:2] if m.is_leader())
            for i in range(60):
                with leader.lock:
                    leader.paxos.propose(denc.dumps(
                        [("set", "t", f"k{i}", b"v")]))
                time.sleep(0.01)
            assert wait_for(
                lambda: leader.paxos.first_committed > 10, timeout=20)
            # rejoin as a FRESH mon c (empty store: v0, far behind)
            reborn = Monitor("c", mm, conf=conf)
            reborn.start()
            mons.append(reborn)
            for m in (leader, reborn):
                with m.lock:
                    m.elector.start()
            assert wait_for(
                lambda: reborn.paxos.last_committed >=
                leader.paxos.first_committed, timeout=20), \
                (reborn.paxos.last_committed,
                 leader.paxos.first_committed)
            # synced state includes the services' data
            assert wait_for(
                lambda: reborn.store.get("t", "k59") == b"v", timeout=10)
        finally:
            for m in mons:
                if not m._stopped:
                    m.shutdown()


class TestStaleMdsRankPruning:
    def test_silent_mds_rank_pruned_live_rank_kept(self):
        """A rank whose daemon stops beaconing is dropped from the map
        after mds_beacon_grace (clients must stop routing to its dead
        address); a rank that keeps beaconing stays."""
        conf = Config({"mon_tick_interval": 0.2,
                       "mds_beacon_grace": 1.5})
        mm, mons = _make_mons(1, conf)
        try:
            assert wait_for(lambda: any(m.is_leader() for m in mons))
            leader = next(m for m in mons if m.is_leader())

            def beacon(name, rank, port):
                with leader.lock:
                    leader.osdmon.handle_mds_beacon(
                        name, ("127.0.0.1", port), rank=rank)

            beacon("live", 0, 7001)
            beacon("doomed", 1, 7002)
            assert wait_for(
                lambda: 1 in leader.osdmon.osdmap.mds_ranks, timeout=10)
            # rank 0 keeps beaconing; rank 1 goes silent
            end = time.time() + 20
            while 1 in leader.osdmon.osdmap.mds_ranks \
                    and time.time() < end:
                beacon("live", 0, 7001)
                time.sleep(0.2)
            assert 1 not in leader.osdmon.osdmap.mds_ranks, \
                "silent rank survived past its beacon grace"
            assert 0 in leader.osdmon.osdmap.mds_ranks, \
                "beaconing rank was wrongly pruned"
            assert leader.osdmon.osdmap.mds_name == "live"
        finally:
            for m in mons:
                m.shutdown()
