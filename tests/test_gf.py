"""GF(2^8) core: field axioms, matrix constructions, bit-matrix expansion."""

import numpy as np
import pytest

from ceph_tpu.ops import gf


def test_field_basics():
    assert gf.gf_mul(0, 7) == 0
    assert gf.gf_mul(1, 7) == 7
    # alpha=2 is primitive: powers cover all 255 nonzero elements
    assert len({gf.gf_pow(2, i) for i in range(255)}) == 255
    # known value under 0x11d: 2*128 = 0x11d ^ 0x100 = 0x1d
    assert gf.gf_mul(2, 128) == 0x1D


def test_mul_associative_distributive():
    rng = np.random.default_rng(0)
    a, b, c = rng.integers(0, 256, size=(3, 512), dtype=np.uint8)
    assert np.array_equal(gf.gf_mul(a, gf.gf_mul(b, c)),
                          gf.gf_mul(gf.gf_mul(a, b), c))
    assert np.array_equal(gf.gf_mul(a, b ^ c),
                          gf.gf_mul(a, b) ^ gf.gf_mul(a, c))


def test_inverse():
    a = np.arange(1, 256, dtype=np.uint8)
    assert np.all(gf.gf_mul(a, gf.gf_inv(a)) == 1)
    with pytest.raises(ZeroDivisionError):
        gf.gf_inv(0)


def test_mat_inv_roundtrip():
    rng = np.random.default_rng(1)
    for n in (2, 3, 8):
        while True:
            m = rng.integers(0, 256, size=(n, n), dtype=np.uint8)
            try:
                inv = gf.gf_mat_inv(m)
                break
            except np.linalg.LinAlgError:
                continue
        assert np.array_equal(gf.gf_matmul(m, inv), np.eye(n, dtype=np.uint8))


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (4, 2), (6, 3), (8, 3), (10, 4)])
def test_reed_sol_van_mds(k, m):
    """Every k-subset of generator rows must be invertible (MDS property)."""
    import itertools
    coding = gf.reed_sol_van_matrix(k, m)
    assert coding.shape == (m, k)
    assert np.all(coding[0] == 1)  # known property of the construction
    gen = gf.systematic_generator(coding, k)
    n = k + m
    # sample subsets (all for small n)
    subsets = list(itertools.combinations(range(n), k))
    if len(subsets) > 200:
        rng = np.random.default_rng(2)
        subsets = [subsets[i] for i in rng.choice(len(subsets), 200, replace=False)]
    for rows in subsets:
        gf.gf_mat_inv(gen[list(rows)])  # raises if singular


def test_reed_sol_r6():
    coding = gf.reed_sol_r6_matrix(4)
    assert np.all(coding[0] == 1)
    assert list(coding[1]) == [1, 2, 4, 8]


@pytest.mark.parametrize("k,m", [(2, 1), (8, 3)])
def test_isa_rs_matrix_decodable(k, m):
    import itertools
    gen = gf.systematic_generator(gf.isa_rs_matrix(k, m), k)
    for rows in itertools.combinations(range(k + m), k):
        gf.gf_mat_inv(gen[list(rows)])


@pytest.mark.parametrize("builder", [gf.cauchy_orig_matrix, gf.cauchy_good_matrix,
                                     gf.isa_cauchy_matrix])
def test_cauchy_mds(builder):
    import itertools
    k, m = 6, 3
    gen = gf.systematic_generator(builder(k, m), k)
    for rows in itertools.combinations(range(k + m), k):
        gf.gf_mat_inv(gen[list(rows)])


def test_cauchy_good_first_row_ones():
    assert np.all(gf.cauchy_good_matrix(6, 3)[0] == 1)


def test_encode_decode_np_roundtrip():
    rng = np.random.default_rng(3)
    k, m, L = 8, 3, 4096
    coding = gf.reed_sol_van_matrix(k, m)
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    parity = gf.encode_np(coding, data)
    gen = gf.systematic_generator(coding, k)
    # lose chunks 0, 5, 9 -> decode from survivors
    chunks = np.concatenate([data, parity], axis=0)
    present = [i for i in range(k + m) if i not in (0, 5, 9)][:k]
    dec = gf.decode_matrix(gen, k, present)
    rebuilt = np.zeros_like(data)
    tbl = gf.mul_table()
    for i in range(k):
        acc = np.zeros(L, dtype=np.uint8)
        for idx, p in enumerate(present):
            acc ^= tbl[dec[i, idx]][chunks[p]]
        rebuilt[i] = acc
    assert np.array_equal(rebuilt, data)


def test_byte_bitmatrix_equals_gf_mul():
    rng = np.random.default_rng(4)
    for e in [0, 1, 2, 3, 0x1D, 0xFF, 0x53]:
        M = gf.byte_bitmatrix(e)
        for x in rng.integers(0, 256, size=16):
            bits = np.array([(int(x) >> b) & 1 for b in range(8)], dtype=np.uint8)
            out_bits = (M @ bits) % 2
            out = int(sum(int(v) << b for b, v in enumerate(out_bits)))
            assert out == int(gf.gf_mul(e, int(x))), (e, x)


def test_expand_bitmatrix_encode_is_gf2_linear():
    """bitmatrix_encode over packets == GF(2) matvec per (superblock, lane)."""
    rng = np.random.default_rng(5)
    k, m, w, ps = 3, 2, 8, 4
    coding = gf.cauchy_orig_matrix(k, m)
    bm = gf.expand_bitmatrix(coding, w)
    L = w * ps * 6
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    out = gf.bitmatrix_encode_np(bm, data, w, ps)
    nblk = L // (w * ps)
    d = data.reshape(k, nblk, w, ps)
    o = out.reshape(m, nblk, w, ps)
    dbits = np.unpackbits(d, axis=-1, bitorder="little").reshape(k, nblk, w, ps, 8)
    obits = np.unpackbits(o, axis=-1, bitorder="little").reshape(m, nblk, w, ps, 8)
    # vector over input packet-bit index (j*w+t) for fixed (s, p, bitlane)
    vin = dbits.transpose(1, 3, 4, 0, 2).reshape(nblk, ps, 8, k * w)
    vout = obits.transpose(1, 3, 4, 0, 2).reshape(nblk, ps, 8, m * w)
    expect = (vin @ bm.T) % 2
    assert np.array_equal(expect.astype(np.uint8), vout)
