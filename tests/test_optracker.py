"""Op tracing plane: span mechanics, historic/slow rings, the
span-completeness property on a real traced write, cross-daemon
trace-id correlation over CTM2, slow-op HEALTH_WARN set+clear, and
the flight recorder (unit + ledger-violation trigger).

The acceptance property (ISSUE 12): a seeded loadgen write traced
end-to-end attributes >= 95% of its measured wall time to named spans
(queue / device / journal / replica / execute), and the historic dump
round-trips through tools/trace_dump.py into valid Chrome-trace JSON.
"""

import json
import pathlib
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.utils import optracker
from ceph_tpu.utils.clock import ManualClock
from ceph_tpu.utils.config import Config
from ceph_tpu.utils.optracker import FlightRecorder, OpTracker
from ceph_tpu.vstart import MiniCluster


def merged_coverage(spans: list[dict]) -> float:
    """Total length of the UNION of span intervals (nesting and
    overlap collapse — the honest 'time attributed to at least one
    named phase' number)."""
    ivs = sorted((s["t0"], s["t1"]) for s in spans)
    total = 0.0
    cur0 = cur1 = None
    for t0, t1 in ivs:
        if cur1 is None or t0 > cur1:
            if cur1 is not None:
                total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    if cur1 is not None:
        total += cur1 - cur0
    return total


# ---------------------------------------------------------------------------
# unit: span mechanics + rings
# ---------------------------------------------------------------------------


class TestSpanMechanics:
    def test_spans_nest_and_autoclose(self):
        trk = OpTracker(ManualClock(), daemon="osd.t")
        op = trk.create("osd_op(test)", trace_id="c:1")
        op.span_begin("queue")
        op.span_end("queue")
        op.span_begin("execute")
        op.span_begin("journal", bytes=42)
        op.span_end("journal")
        op.span_begin("replica_wait", peers=2)
        op.span_end("execute")          # out-of-order close: by name
        op.finish()                     # auto-closes replica_wait
        doc = op.dump()
        names = [s["name"] for s in doc["spans"]]
        assert names == ["queue", "journal", "execute", "replica_wait"]
        j = next(s for s in doc["spans"] if s["name"] == "journal")
        assert j["args"] == {"bytes": 42}
        rw = next(s for s in doc["spans"] if s["name"] == "replica_wait")
        assert rw["t1"] >= rw["t0"]
        assert doc["trace_id"] == "c:1"
        assert doc["daemon"] == "osd.t"
        # post-finish calls are inert, never raising
        op.span_begin("late")
        op.span_end()
        op.mark_event("late")
        assert [s["name"] for s in op.dump()["spans"]] == names

    def test_thread_local_current_op(self):
        trk = OpTracker(ManualClock())
        op = trk.create("op")
        assert optracker.current() is None
        with optracker.op_context(op):
            assert optracker.current() is op
            with optracker.span("journal", bytes=7):
                pass
            optracker.add_span("ec.d2h", op.mstart, op.mstart + 0.001)
        assert optracker.current() is None
        names = {s[0] for s in op.spans}
        assert names == {"journal", "ec.d2h"}
        # span() without a current op is a silent passthrough
        with optracker.span("nothing"):
            pass

    def test_pipeline_phase_translation(self):
        trk = OpTracker(ManualClock())
        op = trk.create("op")
        base = time.monotonic()
        with optracker.op_context(op):
            optracker.note_pipeline_phases({
                "submit": base, "picked": base + 0.002,
                "stage0": base + 0.002, "stage1": base + 0.003,
                "issue": base + 0.003, "collect0": base + 0.005,
                "done": base + 0.006, "requeues": 1})
        names = {s[0] for s in op.spans}
        assert names == {"ec.coalesce", "ec.stage_h2d",
                         "ec.device_compute", "ec.d2h"}
        assert any("ec_degraded_requeues:1" == e[2] for e in op.events)

    def test_disabled_tracker_is_inert(self):
        clock = ManualClock()
        trk = OpTracker(clock, enabled=False)
        op = trk.create("osd_op(untracked)")
        op.span_begin("queue")
        op.mark_event("x")
        clock.advance(2.0)
        assert op.age(clock.now()) == pytest.approx(2.0)  # latency
        op.span_end("queue")                              # still works
        op.finish()
        assert trk.dump_ops_in_flight()["num_ops"] == 0
        assert trk.dump_historic_ops()["num_ops"] == 0


class TestHistoricRings:
    def test_size_eviction(self):
        trk = OpTracker(ManualClock(), history_size=3)
        for i in range(5):
            trk.create(f"op{i}").finish()
        dump = trk.dump_historic_ops()
        assert dump["num_ops"] == 3
        assert [op["description"] for op in dump["ops"]] == \
            ["op2", "op3", "op4"]

    def test_duration_pruning(self):
        trk = OpTracker(ManualClock(), history_size=10,
                        history_duration=3600.0)
        trk.create("old").finish()
        time.sleep(0.02)
        trk.history_duration = 0.01     # everything is now too old
        assert trk.dump_historic_ops()["num_ops"] == 0
        trk.history_duration = 3600.0
        trk.create("fresh").finish()
        assert trk.dump_historic_ops()["num_ops"] == 1

    def test_slow_ring_and_summary(self):
        clock = ManualClock()
        trk = OpTracker(clock, complaint_age=5.0)
        fast = trk.create("fast")
        fast.finish()
        slow = trk.create("slow")
        clock.advance(10.0)
        n, oldest = trk.slow_ops_summary()
        assert n == 1 and oldest >= 10.0
        slow.finish()
        n, _oldest = trk.slow_ops_summary()     # level-triggered:
        assert n == 0                           # clears on completion
        dump = trk.dump_historic_slow_ops()
        assert dump["num_ops"] == 1
        assert dump["ops"][0]["description"] == "slow"
        assert trk.dump_historic_ops()["num_ops"] == 2


# ---------------------------------------------------------------------------
# cluster: end-to-end tracing
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "mon_osd_down_out_interval": 5.0,
        # big enough rings that a loadgen round survives to the assert
        "osd_op_history_size": 512,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf,
                    store_kind="filestore",
                    store_dir=str(tmp_path_factory.mktemp("trace"))
                    ).start()
    yield c
    c.stop()


def _settle(cluster, name, ec=False):
    rados = cluster.client()
    if ec:
        rados.create_ec_pool(
            name, f"{name}-prof",
            {"plugin": "tpu", "k": 2, "m": 1, "host_cutover": 1},
            pg_num=4)
    else:
        rados.create_pool(name, pg_num=4)
    io = rados.open_ioctx(name)
    end = time.time() + 60
    while True:
        try:
            io.write_full("settle", b"s")
            return io
        except RadosError:
            if time.time() > end:
                raise
            cluster.tick(0.3)


def _historic_client_ops(cluster):
    out = []
    for osd in cluster.osds.values():
        for op in osd.op_tracker.dump_historic_ops()["ops"]:
            if op["kind"] == "client":
                out.append(op)
    return out


class TestSpanCompleteness:
    def test_seeded_loadgen_write_covered_95pct(self, cluster):
        """The acceptance property: a seeded loadgen write's spans
        are in-bounds, and their merged union covers >= 95% of the
        op's measured wall time — on BOTH pool types (replicated:
        queue/execute/journal/replica_wait; EC: + the pipeline
        phases) — and the op round-trips through trace_dump.py."""
        from ceph_tpu.tools.loadgen import LoadGen, TenantSpec
        io_rep = _settle(cluster, "trace-rep")
        io_ec = _settle(cluster, "trace-ec", ec=True)
        gen = LoadGen([
            TenantSpec("trace-rep", rate=30, duration=1.5,
                       obj_count=8, read_frac=0.0, payload=8192),
            TenantSpec("trace-ec", rate=30, duration=1.5,
                       obj_count=8, read_frac=0.0, payload=8192),
        ], seed=0x7ACE5)
        trackers = [o.op_tracker for o in cluster.osds.values()]
        report = gen.run({"trace-rep": io_rep, "trace-ec": io_ec},
                         phase_sources=trackers)
        assert sum(p["errors"] for p in report["pools"].values()) == 0
        checked = 0
        span_names: set[str] = set()
        for op in _historic_client_ops(cluster):
            if "writefull" not in op["description"] \
                    or "obj0" not in op["description"]:
                continue
            dur = op["duration"]
            assert dur > 0
            assert op["spans"], op["description"]
            eps = 2e-3
            for s in op["spans"]:
                assert s["t0"] >= op["mstart"] - eps
                assert s["t1"] <= op["mstart"] + dur + eps
                assert s["t1"] >= s["t0"]
            cov = merged_coverage(op["spans"]) / dur
            assert cov >= 0.95, \
                (f"{op['description']}: only {cov:.1%} of "
                 f"{dur * 1e3:.2f}ms attributed: {op['spans']}")
            span_names |= {s["name"] for s in op["spans"]}
            checked += 1
        assert checked >= 10, "loadgen writes did not reach history"
        assert {"queue", "execute"} <= span_names
        assert "replica_wait" in span_names      # size-3 / k2m1 pools
        assert "journal" in span_names           # filestore WAL+fsync
        # the EC tenant's writes crossed the pipeline: at least one
        # device-or-host encode phase span was attributed
        assert span_names & {"ec.coalesce", "ec.stage_h2d",
                             "ec.device_compute", "ec.d2h",
                             "ec.host_encode"}, span_names
        # loadgen's report broke the same spans down per phase
        # bucket (warm-up writes precede the timed window, so the
        # breakdown op count is a subset of the history's)
        phases = report["phases"]
        assert {"queue", "execute"} <= set(phases)
        assert phases["queue"]["ops"] >= 10
        for st in phases.values():
            assert st["p99_ms"] >= st["p50_ms"] >= 0

    def test_trace_dump_round_trip(self, cluster, tmp_path):
        """dump_historic_ops -> trace_dump.py -> valid Chrome-trace
        JSON: every traced op becomes a complete event with its spans
        as slices on the same pid/tid lane."""
        from ceph_tpu.tools import trace_dump
        docs = {}
        for osd in cluster.osds.values():
            path = tmp_path / f"{osd.entity}.json"
            doc = osd.op_tracker.dump_historic_ops()
            path.write_text(json.dumps(doc))
            docs[osd.entity] = doc
        out = tmp_path / "trace.json"
        rc = trace_dump.main(
            ["--dump", *(str(tmp_path / f"{o.entity}.json")
                         for o in cluster.osds.values()),
             "--out", str(out)])
        assert rc == 0
        trace = json.loads(out.read_text())
        events = trace["traceEvents"]
        assert events
        complete = [e for e in events if e["ph"] == "X"]
        metas = [e for e in events if e["ph"] == "M"]
        assert complete and metas
        # pick one traced client write and follow it into the trace
        ops = [op for doc in docs.values() for op in doc["ops"]
               if op["kind"] == "client" and op["spans"]]
        assert ops
        op = ops[-1]
        mine = [e for e in complete
                if e.get("args", {}).get("trace_id") == op["trace_id"]]
        assert mine, op["trace_id"]
        lane = (mine[0]["pid"], mine[0]["tid"])
        slices = [e for e in complete if e["cat"] == "span"
                  and (e["pid"], e["tid"]) == lane]
        assert {s["name"] for s in op["spans"]} <= \
            {e["name"] for e in slices}
        for e in events:
            assert e.get("ts", 0) >= 0      # rebased, µs, non-negative
        json.dumps(trace)                    # serializable end-to-end


class TestCrossDaemonCorrelation:
    def test_subops_carry_the_trace_id(self, cluster):
        """A replicated write's sub-ops ride CTM2 to the replicas
        with the client op's trace id: every daemon that touched the
        write dumps a timeline under ONE id."""
        rados = cluster.client()
        io = rados.open_ioctx("trace-rep")
        io.write_full("correlate-me", b"x" * 4096)
        primary_ops = [
            op for op in _historic_client_ops(cluster)
            if "correlate-me" in op["description"]
            and "writefull" in op["description"]]
        assert primary_ops
        trace_id = primary_ops[-1]["trace_id"]
        assert trace_id
        sub_daemons = set()
        for osd in cluster.osds.values():
            for op in osd.op_tracker.dump_historic_ops()["ops"]:
                if op["kind"] == "subop" \
                        and op["trace_id"] == trace_id:
                    sub_daemons.add(op["daemon"])
                    # the replica's own timeline is spanned too
                    assert {"queue", "execute"} <= \
                        {s["name"] for s in op["spans"]}
        assert len(sub_daemons) == 2        # size-3 pool: 2 replicas
        assert primary_ops[-1]["daemon"] not in sub_daemons


class TestSlowOpHealth:
    def test_health_warn_sets_and_clears(self, cluster):
        """An op blocked past osd_op_complaint_time raises the
        reference's 'N slow ops, oldest blocked for Xs' HEALTH_WARN
        through the leased pg-stats flag plumbing, and the warning
        clears by itself once the op completes."""
        osd = next(iter(cluster.osds.values()))
        old_age = osd.op_tracker.complaint_age
        osd.op_tracker.complaint_age = 2.0
        op = osd.op_tracker.create("osd_op(deliberately-stuck)")
        try:
            cluster.tick(3.0)       # age past the complaint threshold

            def warned() -> bool:
                _status, warns = cluster.leader().osdmon.health()
                return any("slow ops" in w and "oldest blocked" in w
                           for w in warns)

            cluster._wait(warned, 30.0, "slow-op HEALTH_WARN")
            n, oldest = osd.op_tracker.slow_ops_summary()
            assert n == 1 and oldest > 2.0
            dump = osd.asok.execute("perf dump")
            assert dump["slow_ops"]["count"] == 1
            assert dump["slow_ops"]["oldest_age"] > 2.0
        finally:
            op.finish()
            osd.op_tracker.complaint_age = old_age
        cluster._wait(lambda: not warned(), 30.0,
                      "slow-op HEALTH_WARN clear")
        assert osd.op_tracker.dump_historic_slow_ops()["num_ops"] >= 1


class TestDaemonInfoBlock:
    def test_perf_dump_daemon_block(self, cluster):
        for osd in cluster.osds.values():
            d = osd.asok.execute("perf dump")["daemon"]
            assert d["entity"] == osd.entity
            assert d["role"] == "osd"
            assert d["store_backend"] == "filestore"
            assert d["uptime"] >= 0
            assert d["ticks"] >= 1
            assert d["conf_epoch"] >= 0
            assert d["op_tracker_enabled"] is True
        m = cluster.leader().asok.execute("perf dump")["daemon"]
        assert m["role"] == "mon"
        assert m["ticks"] >= 1
        assert m["quorum"]

    def test_historic_slow_ops_asok(self, cluster):
        osd = next(iter(cluster.osds.values()))
        dump = osd.asok.execute("dump_historic_slow_ops")
        assert isinstance(dump["num_ops"], int)
        assert "complaint_time" in dump


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_record_writes_per_daemon_docs(self, tmp_path):
        rec = FlightRecorder()
        rec.register("osd.0", lambda: {"ops_in_flight": {"num_ops": 1}})
        rec.register("osd.1", lambda: {"ops_in_flight": {"num_ops": 0}})
        rec.register("bad", lambda: 1 / 0)   # a wedged daemon still
        assert rec.record("nothing") is None           # disarmed
        rec.arm(str(tmp_path / "fr"), max_records=2)
        path = rec.record("deg ACKED write lost",
                          extra={"oid": "k2"})
        assert path is not None
        files = sorted(p.name for p in
                       pathlib.Path(path).iterdir())
        assert files == ["bad.json", "extra.json", "manifest.json",
                         "osd.0.json", "osd.1.json"]
        manifest = json.loads(
            (pathlib.Path(path) / "manifest.json")
            .read_text())
        assert manifest["reason"] == "deg ACKED write lost"
        assert set(manifest["daemons"]) == {"osd.0", "osd.1", "bad"}
        bad = json.loads((pathlib.Path(path)
                          / "bad.json").read_text())
        assert "error" in bad
        extra = json.loads((pathlib.Path(path)
                            / "extra.json").read_text())
        assert extra["oid"] == "k2"
        # bounded: the cap stops a crash soak from filling the disk
        assert rec.record("two") is not None
        assert rec.record("three") is None
        assert len(rec.records) == 2

    def test_ledger_violation_triggers_capture(self, tmp_path):
        """The test_ledger_doors wiring, unit-sized: a verify that
        detects a lost ACKED write snapshots every registered daemon
        BEFORE raising."""
        from ceph_tpu.client.ledger import (DurabilityLedger,
                                            LedgerViolation)
        rec = optracker.recorder()
        rec.register("osd.fake",
                     lambda: {"ops_in_flight": {"num_ops": 0}})
        rec.arm(str(tmp_path / "fr2"))
        try:
            ledger = DurabilityLedger()
            ledger.note_submit("lost", b"payload")
            ledger.note_ack("lost", b"payload")

            class GoneIo:
                def read(self, oid):
                    raise RadosError(2, "absent")

            with pytest.raises(LedgerViolation, match="ACKED"):
                ledger.verify(GoneIo(), retry_window=0.1)
            assert rec.records, "violation did not capture"
            incident = pathlib.Path(rec.records[-1])
            assert (incident / "osd.fake.json").exists()
            extra = json.loads((incident / "extra.json").read_text())
            assert extra["oid"] == "lost"
            assert "ACKED" in extra["violation"]
        finally:
            rec.unregister("osd.fake")
            rec.disarm()
            rec.records.clear()

    def test_trace_dump_reads_incident_dir(self, tmp_path):
        """trace_dump --dump-dir over a flight-recorder incident:
        daemon docs (ops_in_flight/historic) merge into one trace."""
        from ceph_tpu.tools import trace_dump
        trk = OpTracker(ManualClock(), daemon="osd.9")
        op = trk.create("osd_op(incident)", trace_id="c:9")
        op.span_begin("queue")
        op.span_end("queue")
        op.finish()
        rec = FlightRecorder()
        rec.register("osd.9", lambda: {
            "ops_in_flight": trk.dump_ops_in_flight(),
            "historic_ops": trk.dump_historic_ops()})
        rec.arm(str(tmp_path / "fr3"))
        incident = rec.record("smoke")
        doc = trace_dump.chrome_trace(
            trace_dump.load_dump_dir(incident))
        names = [e["name"] for e in doc["traceEvents"]]
        assert "osd_op(incident)" in names
        assert "queue" in names
