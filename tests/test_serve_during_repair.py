"""Serve-during-repair: client ops BLOCK on recovery pulls instead of
serving stale store bytes (ReplicatedPG wait_for_unreadable_object /
wait_for_degraded_object semantics), the blocked object's pull is
promoted to the front of the recovery queue, and the op resumes
bit-exact once the push applies.

Covered here:
  * missing-object read and write block-then-resume bit-exact
    (replicated + EC), with the recovery_blocked_ops /
    recovery_unblocked_ops / recovery_prio_promotions counters and
    the recovery_wait span;
  * blocked-op promotion ordering (AsyncReserver front lane);
  * a dup-op resend arriving while its first copy is recovery-blocked
    does not re-execute;
  * the stale-read oracle + storm-window slicing the recovery-storm
    drill (tools/loadgen.run_recovery_storm, bench --smoke gate)
    is built from;
  * perf dump `qos.recovery` (the @recovery class's grants/stalls).
"""

import threading
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.store.objectstore import Transaction
from ceph_tpu.utils.config import Config
from ceph_tpu.utils.reserver import AsyncReserver
from ceph_tpu.vstart import MiniCluster

CONF = {
    "mon_tick_interval": 0.5,
    "osd_heartbeat_interval": 0.5,
    "osd_heartbeat_grace": 8.0,
    "mon_osd_min_down_reporters": 2,
    "mon_osd_down_out_interval": 5.0,
    "osd_qos_recovery": "0:2:0",
    # blocked ops resume well under 2s here; a tight op deadline only
    # bounds the damage when a drill wedges (30s default would stall
    # the whole tier-1 run, and the shared cluster poisons the file)
    "objecter_op_timeout": 10.0,
}


def _settle(io, timeout=60.0):
    end = time.time() + timeout
    while True:
        try:
            io.write_full("settle", b"s")
            return
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3,
                    conf=Config(dict(CONF))).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("sdr", pg_num=1, size=3, min_size=2)
    ctx = rados.open_ioctx("sdr")
    _settle(ctx)
    return ctx


def _primary_pg(cluster, io, oid):
    m = cluster.leader().osdmon.osdmap
    pgid = m.object_to_pg(io.pool_id, oid)
    _up, acting = m.pg_to_up_acting_osds(pgid)
    primary = next(o for o in acting if o >= 0)
    osd = cluster.osds[primary]
    return osd, osd.get_pg(pgid)


def _counters(osd):
    d = osd._perf_dump()["osd"]
    return (d["recovery_blocked_ops"], d["recovery_unblocked_ops"],
            d["recovery_prio_promotions"])


def _make_missing(osd, pg, oid, stale=b"STALE-BYTES"):
    """Construct the exact hole the tentpole closes: the log claims
    the object's current version but the store holds other bytes —
    the state a GetLog merge / divergent rewind leaves behind until
    the recovery pull lands."""
    with pg.lock:
        cur = pg.pglog.objects[oid]
        osd.store.apply_transaction(
            Transaction().truncate(pg.cid, oid, 0)
            .write(pg.cid, oid, 0, stale))
        pg.pglog.missing[oid] = cur
    return cur


class TestReserverFrontLane:
    def test_front_request_jumps_fifo_waiters(self):
        """Blocked-op promotion ordering: a front grant runs before
        every queued FIFO waiter, FIFO order otherwise preserved."""
        order = []
        res = AsyncReserver(1)
        release_holder = []

        def holder(release):
            release_holder.append(release)

        res.request(holder)                       # occupies the slot
        for name in ("bg1", "bg2"):
            res.request(lambda rel, n=name: (order.append(n), rel()))
        res.request(lambda rel: (order.append("promoted"), rel()),
                    front=True)
        release_holder[0]()
        assert order == ["promoted", "bg1", "bg2"]

    def test_front_runs_immediately_when_slot_free(self):
        ran = []
        res = AsyncReserver(1)
        res.request(lambda rel: (ran.append(True), rel()), front=True)
        assert ran == [True]


class TestMissingBlockingReplicated:
    def test_read_blocks_then_resumes_bit_exact(self, cluster, io):
        body = b"PRISTINE-" * 200
        io.write_full("blk-r", body)
        osd, pg = _primary_pg(cluster, io, "blk-r")
        b0, u0, p0 = _counters(osd)
        _make_missing(osd, pg, "blk-r")
        got = io.read("blk-r")
        # bit-exact: the promoted pull restored the authoritative
        # copy BEFORE the read executed — never the stale store bytes
        assert bytes(got) == body
        b1, u1, p1 = _counters(osd)
        assert b1 > b0, "read never blocked"
        assert u1 - u0 == b1 - b0, "blocked op not resumed"
        assert p1 > p0, "pull never promoted"
        with pg.lock:
            assert "blk-r" not in pg.pglog.missing
            assert not pg._recovery_blocked

    def test_blocked_read_carries_recovery_wait_span(self, cluster,
                                                     io):
        body = b"SPAN-" * 100
        io.write_full("blk-span", body)
        osd, pg = _primary_pg(cluster, io, "blk-span")
        _make_missing(osd, pg, "blk-span")
        assert bytes(io.read("blk-span")) == body
        hist = osd.op_tracker.dump_historic_ops()["ops"]
        spans = [s for op in hist if "blk-span" in op["description"]
                 for s in op["spans"]]
        names = {s["name"] for s in spans}
        assert "recovery_wait" in names, sorted(names)
        wait = next(s for s in spans if s["name"] == "recovery_wait")
        assert wait["t1"] > wait["t0"]

    def test_write_blocks_then_resumes_bit_exact(self, cluster, io):
        """An append to a missing object must not build its txn over
        stale bytes: it parks, the pull restores the base, and the
        append lands on the restored content."""
        body = b"BASE-" * 150
        io.write_full("blk-w", body)
        osd, pg = _primary_pg(cluster, io, "blk-w")
        b0, u0, _ = _counters(osd)
        _make_missing(osd, pg, "blk-w")
        io.append("blk-w", b"+TAIL")
        assert bytes(io.read("blk-w")) == body + b"+TAIL"
        b1, u1, _ = _counters(osd)
        assert b1 > b0 and u1 - u0 == b1 - b0

    def test_dup_resend_while_blocked_not_reexecuted(self, cluster,
                                                     io):
        """A client resend arriving while its first copy is
        recovery-blocked parks too; on resume the first executes and
        the resend re-replies through the dedup table — the op runs
        ONCE."""
        from types import SimpleNamespace
        from ceph_tpu.osd.messages import MOSDOp
        body = b"ONCE-" * 120
        io.write_full("blk-dup", body)
        osd, pg = _primary_pg(cluster, io, "blk-dup")
        with pg.lock:
            cur = pg.pglog.objects["blk-dup"]
            # claim a FUTURE version missing: the promoted pull (a
            # peer's current copy) cannot retire it, so the ops stay
            # parked until the test releases them deliberately
            pg.pglog.missing["blk-dup"] = (cur[0], cur[1] + 1000)
        replies = []
        orig_reply = osd.reply_to_client
        osd.reply_to_client = \
            lambda conn, msg: replies.append((msg.tid, msg.result,
                                              msg.version))
        try:
            conn = SimpleNamespace(peer_name="client.dup",
                                   peer_addr=("127.0.0.1", 1))
            def mk():
                m = MOSDOp(tid=77001, pgid=str(pg.pgid),
                           oid="blk-dup",
                           ops=[("writefull", b"DUP-PAYLOAD" * 50)],
                           epoch=osd.osdmap.epoch)
                m.src = "client.dup"
                return m
            pg.do_op(conn, mk())          # first copy: parks
            pg.do_op(conn, mk())          # resend: parks too
            with pg.lock:
                assert len(pg._recovery_blocked["blk-dup"]["ops"]) == 2
                entries_before = sum(
                    1 for e in pg.pglog.entries
                    if e["oid"] == "blk-dup")
                # release: drop the artificial claim and wake
                del pg.pglog.missing["blk-dup"]
                pg._wake_recovery_blocked("blk-dup")
            # the resumes serialize on the pg's op shard: copy 1
            # executes, copy 2 lands in the dup table (in-flight or
            # completed) and is ANSWERED ONCE through the original
            # gather — exactly one reply, one log entry, one apply
            end = time.time() + 20
            while not replies and time.time() < end:
                time.sleep(0.05)
            time.sleep(1.0)               # a re-execution would have
            assert len(replies) == 1, replies    # produced a 2nd reply
            assert replies[0][1] == 0, replies
            with pg.lock:
                entries_after = sum(1 for e in pg.pglog.entries
                                    if e["oid"] == "blk-dup")
            assert entries_after == entries_before + 1
        finally:
            osd.reply_to_client = orig_reply
        assert bytes(io.read("blk-dup")) == b"DUP-PAYLOAD" * 50

    def test_interval_change_drops_blocked_ops_with_eagain(
            self, cluster, io):
        """A new interval EAGAINs parked ops back (the client
        resends against the re-peered pg) — nothing stays stranded."""
        from types import SimpleNamespace
        from ceph_tpu.osd.messages import MOSDOp
        io.write_full("blk-iv", b"IV" * 64)
        osd, pg = _primary_pg(cluster, io, "blk-iv")
        with pg.lock:
            cur = pg.pglog.objects["blk-iv"]
            pg.pglog.missing["blk-iv"] = (cur[0], cur[1] + 1000)
        replies = []
        orig_reply = osd.reply_to_client
        osd.reply_to_client = \
            lambda conn, msg: replies.append(msg.result)
        try:
            conn = SimpleNamespace(peer_name="client.iv",
                                   peer_addr=("127.0.0.1", 1))
            m = MOSDOp(tid=77002, pgid=str(pg.pgid), oid="blk-iv",
                       ops=[("read", 0, 0)], epoch=osd.osdmap.epoch)
            m.src = "client.iv"
            pg.do_op(conn, m)
            with pg.lock:
                assert pg._recovery_blocked
                pg.update_acting(list(pg.up), list(pg.acting[::-1]))
            assert replies == [-11]
            with pg.lock:
                assert not pg._recovery_blocked
                pg.pglog.missing.pop("blk-iv", None)
        finally:
            osd.reply_to_client = orig_reply
        # restore the pg for later tests (the reversed acting set is
        # fiction; the real map re-peers it)
        m2 = cluster.leader().osdmon.osdmap
        pgid = m2.object_to_pg(io.pool_id, "blk-iv")
        up, acting = m2.pg_to_up_acting_osds(pgid)
        with pg.lock:
            pg.update_acting(up, acting)
        end = time.time() + 30
        while time.time() < end:
            try:
                io.write_full("blk-iv", b"post")
                break
            except RadosError:
                time.sleep(0.3)


class TestMissingBlockingEC:
    def test_ec_read_blocks_then_resumes_bit_exact(self, cluster):
        rados = cluster.client()
        rados.create_ec_pool("sdrec", "sdrk2m1",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "technique": "reed_sol_van"}, pg_num=1)
        ioe = rados.open_ioctx("sdrec")
        _settle(ioe)
        body = b"ECBODY-" * 400
        ioe.write_full("eblk", body)
        osd, pg = _primary_pg(cluster, ioe, "eblk")
        b0, u0, p0 = _counters(osd)
        with pg.lock:
            cur = pg.pglog.objects["eblk"]
            pg.pglog.missing["eblk"] = cur
        assert bytes(ioe.read("eblk")) == body
        b1, u1, p1 = _counters(osd)
        assert b1 > b0, "EC read never blocked"
        assert u1 - u0 == b1 - b0
        assert p1 > p0, "EC rebuild never promoted"
        with pg.lock:
            assert "eblk" not in pg.pglog.missing

    def test_ec_write_blocks_then_resumes(self, cluster):
        ioe = cluster.client().open_ioctx("sdrec")
        body = b"ECW-" * 300
        ioe.write_full("eblk2", body)
        osd, pg = _primary_pg(cluster, ioe, "eblk2")
        b0, u0, _ = _counters(osd)
        with pg.lock:
            pg.pglog.missing["eblk2"] = pg.pglog.objects["eblk2"]
        ioe.append("eblk2", b"+ETAIL")
        assert bytes(ioe.read("eblk2")) == body + b"+ETAIL"
        b1, u1, _ = _counters(osd)
        assert b1 > b0 and u1 - u0 == b1 - b0


class TestBackfillTargetDiscipline:
    def test_parked_subop_on_backfill_target_promotes_base_pull(
            self, cluster, io):
        """A live sub-op landing on a backfill TARGET ahead of its
        base object's push (the primary's routing frontier runs ahead
        of landed pushes) parks on the prior gap, counts as
        recovery-blocked, and promotes the base pull from the primary
        — then applies in order when the push lands."""
        from types import SimpleNamespace
        from ceph_tpu.osd.messages import MOSDRepOp
        io.write_full("bft", b"BASE" * 64)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "bft")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary, replica = acting[0], acting[1]
        rosd = cluster.osds[replica]
        rpg = rosd.get_pg(pgid)
        b0, u0, _ = _counters(rosd)
        pulls = []
        orig_pull = rosd.pg_request_push
        rosd.pg_request_push = \
            lambda pgid_, holder, oid, front=False: pulls.append(
                (holder, oid, front))
        sent = []
        orig_send = rosd.send_osd_reply
        rosd.send_osd_reply = lambda conn, msg: sent.append(msg)
        try:
            with rpg.lock:
                cur = rpg.pglog.objects["bft"]
                # construct the race: the target is mid-backfill and
                # a sub-op arrives whose prior (a version the scan
                # has not pushed here yet) is absent locally
                rpg.set_backfill_state(False, "zzz")
                rpg.pglog.objects.pop("bft")
            entry = {"ev": (cur[0], cur[1] + 2), "oid": "bft",
                     "op": "modify", "prior": (cur[0], cur[1] + 1),
                     "rollback": None, "shard": None}
            sub = MOSDRepOp(reqid=("client.bft", 1),
                            pgid=str(pgid),
                            ops=Transaction().write(
                                rpg.cid, "bft", 0, b"RACED").ops,
                            log=entry, epoch=rosd.osdmap.epoch)
            sub.src = f"osd.{primary}"
            conn = SimpleNamespace(peer_name=f"osd.{primary}",
                                   peer_addr=("127.0.0.1", 1))
            rpg.handle_rep_op(conn, sub)
            with rpg.lock:
                assert rpg._parked, "sub-op did not park"
            b1, u1, _ = _counters(rosd)
            assert b1 > b0, "parked sub-op not counted as blocked"
            assert pulls == [(primary, "bft", True)], pulls
            # the base push lands: the parked sub-op applies in order
            with rpg.lock:
                rpg.pglog.record_recovered(
                    (cur[0], cur[1] + 1), "bft")
                rpg._flush_parked("bft")
                assert not rpg._parked
            b2, u2, _ = _counters(rosd)
            assert u2 - u0 == b2 - b0, "park release not balanced"
            assert sent and sent[-1].result == 0
        finally:
            rosd.pg_request_push = orig_pull
            rosd.send_osd_reply = orig_send
            with rpg.lock:
                rpg.set_backfill_state(True)
                # rewind the artificially minted entries (cur+1,
                # cur+2): they sit AHEAD of the primary's version
                # counter, so the next two real writes to this pool
                # would dedup as already-applied on this replica and
                # silently skip — polluting every later test in the
                # shared module cluster
                rpg.pglog.rewind(cur, lambda e: True)
                rpg.version = cur[1]
        # heal the replica for later tests
        io.write_full("bft", b"HEAL" * 64)


class TestStrandedMissingLiveness:
    def test_replica_missing_claim_is_healed_by_nudge(self, cluster,
                                                      io):
        """The run-12 wedge class: a REPLICA holds a missing claim
        whose heal push was lost (rewind-exposed prior, lost wire
        push).  Nothing used to retry — the copy sat data-incomplete
        behind a clean-looking head forever (and wait_for_clean now
        refuses to call that clean).  The heartbeat treats a
        non-empty missing set as incomplete: the replica nudges its
        primary, the peering round reads the peer's missing set off
        get_info (pg_missing_t rides the exchange) and re-pushes
        exactly those objects."""
        cluster.wait_for_clean(60)    # settle prior tests' backfill churn
        body = b"NUDGE-" * 120
        io.write_full("strand", body)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "strand")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        replica = acting[1]
        rosd = cluster.osds[replica]
        rpg = rosd.get_pg(pgid)
        # wait until the replica both holds the bytes AND indexes the
        # write in its live pglog, then strand it: stale bytes + a
        # missing claim at the current version
        end = time.time() + 30
        while time.time() < end:
            rpg = rosd.get_pg(pgid)
            try:
                with rpg.lock:
                    landed = "strand" in rpg.pglog.objects
                if landed and rosd.store.read(rpg.cid, "strand"):
                    break
            except Exception:
                pass
            time.sleep(0.2)
        with rpg.lock:
            cur = rpg.pglog.objects["strand"]
            rosd.store.apply_transaction(
                Transaction().truncate(rpg.cid, "strand", 0)
                .write(rpg.cid, "strand", 0, b"STALE"))
            rpg.pglog.missing["strand"] = cur
        assert rpg.get_info().get("missing"), "claim not advertised"
        # no client op touches it: only the liveness nudge can heal
        end = time.time() + 45
        while time.time() < end:
            with rpg.lock:
                if "strand" not in rpg.pglog.missing:
                    break
            cluster.tick(0.3)
        with rpg.lock:
            assert "strand" not in rpg.pglog.missing, \
                "missing claim stranded: nudge/re-push never healed it"
        assert bytes(rosd.store.read(rpg.cid, "strand")) == body
        cluster.wait_for_clean(30)


class TestQosRecoveryDump:
    def test_perf_dump_exposes_recovery_class(self, cluster, io):
        osd = next(iter(cluster.osds.values()))
        qos = osd._perf_dump()["qos"]
        assert "recovery" in qos
        rec = qos["recovery"]
        for key in ("configured", "res_grants", "prop_grants",
                    "deadline_misses", "throttle_stalls"):
            assert key in rec, key
        assert rec["configured"] == CONF["osd_qos_recovery"]

    def test_per_client_throttle_stalls_counted(self):
        from ceph_tpu.utils.dmclock import DmClockState, QosSpec
        t = [100.0]
        st = DmClockState(clock=lambda: t[0])
        st.configure({"capped": QosSpec(res=0.0, weight=1.0, lim=1.0)})
        # first grant advances l_tag a full second; the next pick has
        # nothing servable -> a stall attributed to the capped class
        got, _, _ = st.pick({"capped": 99.0}, now=t[0])
        assert got == "capped"
        got, _, _ = st.pick({"capped": 100.0}, now=t[0])
        assert got is None
        ent = st.stats()["clients"]["capped"]
        assert ent["throttle_stalls"] == 1


class TestStaleReadOracle:
    """The verify-mode oracle the storm drill's zero-stale-bytes gate
    rides (tools/loadgen._Verifier)."""

    def _pay(self, seed):
        from ceph_tpu.tools.loadgen import _payload_bytes
        return _payload_bytes(seed, 64)

    def test_current_write_is_not_stale(self):
        from ceph_tpu.tools.loadgen import _Verifier
        v = _Verifier()
        v.note_submit("p", "o", 1, 1.0)
        v.note_ack("p", "o", 1, 2.0)
        assert not v.judge_read("p", "o", self._pay(1), 5.0)

    def test_superseded_before_read_began_is_stale(self):
        from ceph_tpu.tools.loadgen import _Verifier
        v = _Verifier()
        v.note_submit("p", "o", 1, 1.0)
        v.note_ack("p", "o", 1, 2.0)
        v.note_submit("p", "o", 2, 3.0)       # after w1 fully acked
        v.note_ack("p", "o", 2, 4.0)
        # read began at 5.0, after w2 acked: observing w1 is stale
        assert v.judge_read("p", "o", self._pay(1), 5.0)
        assert not v.judge_read("p", "o", self._pay(2), 5.0)

    def test_concurrent_write_never_false_positives(self):
        from ceph_tpu.tools.loadgen import _Verifier
        v = _Verifier()
        v.note_submit("p", "o", 1, 1.0)
        v.note_ack("p", "o", 1, 4.0)          # overlaps w2's submit
        v.note_submit("p", "o", 2, 3.0)
        v.note_ack("p", "o", 2, 5.0)
        # w1 was still in flight when w2 was submitted: either answer
        # is linearizable for a read starting at 6.0
        assert not v.judge_read("p", "o", self._pay(1), 6.0)
        assert not v.judge_read("p", "o", self._pay(2), 6.0)

    def test_unknown_bytes_are_stale(self):
        from ceph_tpu.tools.loadgen import _Verifier
        v = _Verifier()
        v.note_warm("p", "o", 7)
        assert v.judge_read("p", "o", self._pay(99), 1.0)
        assert v.judge_read("p", "o", b"short", 1.0)
        assert not v.judge_read("p", "o", self._pay(7), 1.0)

    def test_in_flight_write_is_valid(self):
        from ceph_tpu.tools.loadgen import _Verifier
        v = _Verifier()
        v.note_warm("p", "o", 7)
        v.note_submit("p", "o", 8, 1.0)       # never acked
        assert not v.judge_read("p", "o", self._pay(8), 9.0)


class TestWindowReport:
    def test_storm_window_slices_by_scheduled_arrival(self):
        from ceph_tpu.tools.loadgen import LoadGen, TenantSpec, _Rec
        gen = LoadGen([TenantSpec("p", rate=1, duration=0.01)])
        gen.last_records = [
            _Rec("p", "read", 0.010, 10, True, False, 0.5, False),
            _Rec("p", "read", 0.500, 10, True, False, 1.5, False),
            _Rec("p", "read", 0.020, 10, True, False, 2.5, True),
            _Rec("p", "write_full", 0.1, 10, False, True, 1.7, False),
        ]
        win = gen.window_report(1.0, 2.0)
        assert win["p"]["ops"] == 2
        assert win["p"]["errors"] == 1
        assert win["p"]["stale_reads"] == 0
        assert win["p"]["p99_ms"] == 500.0
        full = gen.window_report(0.0, 10.0)
        assert full["p"]["ops"] == 4
        assert full["p"]["stale_reads"] == 1
