"""Test harness config: run JAX on 8 virtual CPU devices.

Multi-chip sharding paths are exercised on a virtual CPU mesh (no TPU pod
in CI); the driver separately dry-run-compiles the multi-chip path via
__graft_entry__.dryrun_multichip, and bench.py uses the one real TPU chip.

The axon runtime pins the platform from its own sitecustomize, so env
vars (JAX_PLATFORMS) are NOT enough — the platform must also be forced
via jax.config before any backend initializes.  CPU keeps first-shape
jit compiles to ~100ms instead of 20-40s, which matters for cluster
tests with client op timeouts.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests (chaos soaks) excluded from tier-1 "
        "via -m 'not slow'")

