"""Test harness config: run JAX on 8 virtual CPU devices.

Multi-chip sharding paths are exercised on a virtual CPU mesh (no TPU pod
in CI); the driver separately dry-run-compiles the multi-chip path via
__graft_entry__.dryrun_multichip, and bench.py uses the one real TPU chip.

Must run before jax initializes, hence top of conftest.  The axon
sitecustomize re-asserts JAX_PLATFORMS=axon, so this must be a hard
override, not setdefault.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
