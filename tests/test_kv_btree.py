"""Distributed flat B-tree (kv_flat_btree_async analog): splits,
merges, concurrent-client safety, crash healing.

The reference's test harness (test/kv_store_test.cc) runs randomized
ops against a live cluster and verifies structure; same model here:
node-size invariants are checked after every settle.
"""

import random
import threading
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.client.kv_btree import DEAD_KEY, INF, KvFlatBtree
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    r = c.client()
    r.create_pool("kvb", pg_num=8)
    io = r.open_ioctx("kvb")
    end = time.time() + 30
    while True:
        try:
            io.write_full("settle", b"s")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    yield c
    c.stop()


@pytest.fixture()
def io(cluster):
    return cluster.client().open_ioctx("kvb")


class TestBasics:
    def test_set_get_remove_roundtrip(self, io):
        t = KvFlatBtree(io, "t1", k=2)
        t.set("alpha", b"1")
        t.set("beta", b"2")
        assert t.get("alpha") == b"1"
        t.remove("alpha")
        with pytest.raises(KeyError):
            t.get("alpha")
        assert t.items() == {"beta": b"2"}
        t.check_invariants()

    def test_split_at_2k(self, io):
        t = KvFlatBtree(io, "t2", k=2)
        for i in range(12):
            t.set(f"key{i:03d}", str(i).encode())
        inv = t.check_invariants()
        assert inv["entries"] == 12
        assert inv["leaves"] >= 3       # 12 entries can't fit 2 leaves
        assert t.items() == {f"key{i:03d}": str(i).encode()
                             for i in range(12)}

    def test_merge_on_drain(self, io):
        t = KvFlatBtree(io, "t3", k=2)
        for i in range(16):
            t.set(f"m{i:03d}", b"x")
        assert t.check_invariants()["leaves"] > 2
        for i in range(15):
            t.remove(f"m{i:03d}")
        inv = t.check_invariants()
        assert inv["entries"] == 1
        assert inv["leaves"] <= 2       # merged back down (index+leaf)
        assert t.items() == {"m015": b"x"}

    def test_two_handles_one_tree(self, io):
        a = KvFlatBtree(io, "t4", k=2)
        b = KvFlatBtree(io, "t4", k=2)
        a.set("x", b"from-a")
        assert b.get("x") == b"from-a"
        b.set("x", b"from-b")
        assert a.get("x") == b"from-b"


class TestConcurrent:
    def test_randomized_concurrent_model(self, io, cluster):
        """4 writer threads, randomized insert/delete over a shared
        keyspace; a model dict (guarded per-key by last-writer-wins on
        disjoint key ranges) must match, and node-size invariants must
        hold after every settle."""
        t0 = KvFlatBtree(io, "conc", k=3)
        nthreads = 4
        errors: list = []
        models: list[dict] = [dict() for _ in range(nthreads)]

        def worker(wid: int):
            # each worker owns a disjoint key range: the merged models
            # are exact, while the TREE structure is fully shared and
            # contended
            rng = random.Random(1000 + wid)
            tree = KvFlatBtree(io, "conc", k=3)
            model = models[wid]
            try:
                for step in range(120):
                    key = f"w{wid}-{rng.randrange(40):02d}"
                    if key in model and rng.random() < 0.4:
                        tree.remove(key)
                        del model[key]
                    else:
                        val = f"{wid}.{step}".encode()
                        tree.set(key, val)
                        model[key] = val
            except Exception as e:       # pragma: no cover
                import traceback
                errors.append((wid, e, traceback.format_exc()))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(nthreads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
        assert not errors, errors[0]
        expect: dict = {}
        for m in models:
            expect.update(m)
        tree = KvFlatBtree(io, "conc", k=3)
        inv = tree.check_invariants()
        assert tree.items() == expect
        assert inv["entries"] == len(expect)

    def test_settle_invariants_under_churn(self, io):
        """Single client, adversarial sizes: check invariants after
        EVERY operation (the reference's verification mode)."""
        t = KvFlatBtree(io, "churn", k=2)
        rng = random.Random(0xBEEF)
        model: dict = {}
        for step in range(150):
            key = f"c{rng.randrange(30):02d}"
            if key in model and rng.random() < 0.45:
                t.remove(key)
                del model[key]
            else:
                model[key] = str(step).encode()
                t.set(key, model[key])
            t.check_invariants()
        assert t.items() == model


class TestCrashHealing:
    def test_stale_split_marker_rolls_forward(self, io):
        """Kill a client between writing the new leaves and the index
        swap: the next client heals by rolling the split forward."""
        t = KvFlatBtree(io, "heal1", k=2, prefix_timeout=0.2)
        for i in range(3):
            t.set(f"h{i}", b"x")
        # hand-craft the dangerous window: mark, kill, write new
        # leaves, then "die" before update_index
        from ceph_tpu.utils import denc
        idx = t._read_index()
        bound, entry = next(iter(idx.items()))
        t.set("h3", b"x")                 # 4 == 2k: would split
        # if the auto-split already ran, force another window manually
        idx = t._read_index()
        bound = sorted(idx, key=lambda b: (b == INF, b))[0]
        entry = idx[bound]
        content = {k: v for k, v in io.get_omap(entry["oid"]).items()
                   if not k.startswith("\x00")}
        if len(content) < 2:
            pytest.skip("layout shifted; covered by churn test")
        new = [t._leaf_oid(), t._leaf_oid()]
        marked = t._mark_prefix({bound: entry},
                                {"op": "split", "new": new,
                                 "old": [entry["oid"]]})
        assert marked is not None
        assert t._kill_leaf(entry["oid"], entry["ver"]) is not None
        keys = sorted(content)
        half = max(1, len(keys) // 2)
        t._write_leaf(new[0], {k: content[k] for k in keys[:half]})
        t._write_leaf(new[1], {k: content[k] for k in keys[half:]})
        # ... client dies here.  A fresh handle must heal on first use
        time.sleep(0.3)
        t2 = KvFlatBtree(io, "heal1", k=2, prefix_timeout=0.2)
        assert t2.get("h0") == b"x"
        t2.check_invariants()

    def test_stale_marker_rolls_back(self, io):
        """Marker set but nothing else happened: heal must roll back
        and the tree stays writable."""
        t = KvFlatBtree(io, "heal2", k=2, prefix_timeout=0.2)
        t.set("a", b"1")
        idx = t._read_index()
        bound, entry = next(iter(idx.items()))
        marked = t._mark_prefix({bound: entry},
                                {"op": "split",
                                 "new": [t._leaf_oid()],
                                 "old": [entry["oid"]]})
        assert marked is not None
        time.sleep(0.3)
        t2 = KvFlatBtree(io, "heal2", k=2, prefix_timeout=0.2)
        t2.set("b", b"2")
        assert t2.get("a") == b"1"
        t2.check_invariants()


class TestInvariantChecker:
    def test_out_of_order_leaf_is_detected(self, io):
        """check_invariants must FAIL on a cross-leaf ordering break
        (a key planted in a later leaf that sorts before an earlier
        leaf's max) — the `prev` walk was once vacuously true."""
        t = KvFlatBtree(io, "tinv", k=2)
        for i in range(12):
            t.set(f"key{i:03d}", str(i).encode())
        inv = t.check_invariants()
        assert inv["leaves"] > 2
        idx = t._read_index()
        from ceph_tpu.client.kv_btree import INF, _bound_key
        bounds = sorted(b for b in idx if b != INF)
        # plant a key that BELONGS in the first leaf into the last one
        assert _bound_key("key000a") < bounds[0]
        io.set_omap(idx[INF]["oid"], {"key000a": b"rogue"})
        with pytest.raises(AssertionError):
            t.check_invariants()
        io.rm_omap_keys(idx[INF]["oid"], ["key000a"])
        t.check_invariants()
