"""AuthMonitor + LogMonitor: paxos-replicated keyring and cluster log
(mon/AuthMonitor.cc + mon/LogMonitor.cc scenarios)."""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "mon_osd_down_out_interval": 600.0,
    })
    c = MiniCluster(num_mons=3, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rados(cluster):
    return cluster.client()


class TestAuthMonitor:
    def test_get_or_create_add_rm_ls(self, rados):
        rv, out, _ = rados.mon_command(
            {"prefix": "auth get-or-create", "entity": "client.app",
             "caps": "rwx"})
        assert rv == 0 and "[client.app]" in out and "key = " in out
        key_line = [ln for ln in out.splitlines()
                    if ln.startswith("key")][0]
        # idempotent: same key back
        rv, out2, _ = rados.mon_command(
            {"prefix": "auth get-or-create", "entity": "client.app"})
        assert rv == 0 and key_line in out2
        # add of an existing entity conflicts
        rv, out, _ = rados.mon_command(
            {"prefix": "auth add", "entity": "client.app"})
        assert rv == -17
        rv, out, _ = rados.mon_command({"prefix": "auth ls"})
        assert rv == 0 and "client.app" in out
        rv, out, _ = rados.mon_command(
            {"prefix": "auth get", "entity": "client.app"})
        assert rv == 0 and key_line in out
        rv, out, _ = rados.mon_command(
            {"prefix": "auth rm", "entity": "client.app"})
        assert rv == 0
        rv, out, _ = rados.mon_command(
            {"prefix": "auth get", "entity": "client.app"})
        assert rv == -2

    def test_keys_replicate_to_peons(self, cluster, rados):
        rv, out, _ = rados.mon_command(
            {"prefix": "auth get-or-create", "entity": "osd.99"})
        assert rv == 0
        end = time.time() + 20
        while True:
            if all("osd.99" in m.authmon.keys for m in cluster.mons):
                break
            if time.time() > end:
                state = {m.name: sorted(m.authmon.keys)
                         for m in cluster.mons}
                raise AssertionError(f"keyring not replicated: {state}")
            cluster.tick(0.3)
            time.sleep(0.05)

    def test_export_is_keyring_format(self, cluster, rados):
        rv, out, _ = rados.mon_command(
            {"prefix": "auth get-or-create", "entity": "client.exp"})
        assert rv == 0
        rv, text, data = rados.mon_command({"prefix": "auth export"})
        assert rv == 0 and "[client.exp]" in text
        # the session layer's KeyRing parser accepts the export
        import configparser
        parser = configparser.ConfigParser()
        parser.read_string(text)
        assert parser.get("client.exp", "key")


class TestLogMonitor:
    def test_inject_and_read_back(self, rados):
        rv, out, _ = rados.mon_command(
            {"prefix": "log", "text": "hello-cluster-log"})
        assert rv == 0
        end = time.time() + 20
        while True:
            rv, out, _ = rados.mon_command(
                {"prefix": "log last", "num": 50})
            assert rv == 0
            if "hello-cluster-log" in out:
                break
            if time.time() > end:
                raise AssertionError(f"entry never committed:\n{out}")
            time.sleep(0.1)

    def test_osd_down_logged(self, cluster, rados):
        cluster.kill_osd(2)
        cluster.wait_for_osd_down(2)
        end = time.time() + 30
        while True:
            rv, out, _ = rados.mon_command(
                {"prefix": "log last", "num": 100})
            if "osd.2 marked down" in out:
                break
            if time.time() > end:
                raise AssertionError(f"down not logged:\n{out}")
            cluster.tick(0.3)
            time.sleep(0.05)
        cluster.start_osd(2)
        cluster.wait_for_osds(3)
        end = time.time() + 30
        while True:
            rv, out, _ = rados.mon_command(
                {"prefix": "log last", "num": 100})
            if "osd.2 boot" in out:
                break
            if time.time() > end:
                raise AssertionError(f"boot not logged:\n{out}")
            cluster.tick(0.3)
            time.sleep(0.05)
