"""Encode-bytes non-regression corpus.

The analog of the reference's ceph_erasure_code_non_regression
(test/erasure-code/ceph_erasure_code_non_regression.cc:71 --create /
--check against ceph-erasure-code-corpus): every plugin x technique x
config encodes a pinned pseudorandom input and the CRC32C of every
chunk must match the archived corpus.  A kernel or matrix refactor
that silently changes on-disk parity fails here before it can strand
data written by an older build.

Regenerate (only for deliberate, documented format changes):
    python tests/test_corpus.py --create
"""

import json
import os
import sys

import numpy as np

CORPUS_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "encode_corpus.json")

CONFIGS = [
    ("jerasure", {"technique": "reed_sol_van", "k": "3", "m": "2"}),
    ("jerasure", {"technique": "reed_sol_r6_op", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "cauchy_orig", "k": "4", "m": "3",
                  "packetsize": "128"}),
    ("jerasure", {"technique": "cauchy_good", "k": "6", "m": "3",
                  "packetsize": "128"}),
    ("jerasure", {"technique": "liberation", "k": "5", "m": "2",
                  "w": "7", "packetsize": "128"}),
    ("jerasure", {"technique": "blaum_roth", "k": "5", "m": "2",
                  "w": "6", "packetsize": "128"}),
    ("jerasure", {"technique": "liber8tion", "k": "6", "m": "2",
                  "packetsize": "128"}),
    ("isa", {"technique": "reed_sol_van", "k": "8", "m": "3"}),
    ("isa", {"technique": "cauchy", "k": "4", "m": "3"}),
    ("tpu", {"technique": "reed_sol_van", "k": "8", "m": "3"}),
    ("tpu", {"technique": "isa_reed_sol_van", "k": "6", "m": "2"}),
    ("shec", {"k": "5", "m": "3", "c": "2"}),
    ("lrc", {"k": "4", "m": "2", "l": "3"}),
]


def _key(plugin: str, profile: dict) -> str:
    items = ",".join(f"{k}={v}" for k, v in sorted(profile.items()))
    return f"{plugin}({items})"


def build_corpus() -> dict:
    from ceph_tpu.erasure.registry import registry
    from ceph_tpu.ops import crc32c as crc_mod

    data = bytes(np.random.default_rng(0xCEF).integers(
        0, 256, 100_000, dtype=np.uint8))
    out = {}
    for plugin, profile in CONFIGS:
        codec = registry.factory(plugin, dict(profile))
        km = codec.get_chunk_count()
        chunks = codec.encode(range(km), data)
        out[_key(plugin, profile)] = {
            "chunk_size": len(chunks[0]),
            "crcs": [crc_mod.crc32c(0, chunks[i]) for i in range(km)],
        }
    return out


def test_encode_corpus_stable():
    assert os.path.exists(CORPUS_PATH), \
        "corpus missing — run: python tests/test_corpus.py --create"
    with open(CORPUS_PATH) as f:
        archived = json.load(f)
    current = build_corpus()
    assert set(current) == set(archived), (
        sorted(set(current) ^ set(archived)))
    for key in sorted(archived):
        assert current[key] == archived[key], \
            f"encode bytes CHANGED for {key}: archived {archived[key]} " \
            f"vs current {current[key]} — on-disk parity would diverge"


if __name__ == "__main__":
    if "--create" in sys.argv:
        os.makedirs(os.path.dirname(CORPUS_PATH), exist_ok=True)
        with open(CORPUS_PATH, "w") as f:
            json.dump(build_corpus(), f, indent=1, sort_keys=True)
        print(f"wrote {CORPUS_PATH}")
    else:
        test_encode_corpus_stable()
        print("corpus check OK")
