"""Erasure plugin framework tests.

Mirrors the reference's unit-test tiers (SURVEY.md §4):
TestErasureCode (base chunk math), TestErasureCodeJerasure/Isa/Shec/Lrc
(per-technique roundtrips incl. every erasure pattern), and
TestErasureCodePlugin* (registry failure fixtures).
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.erasure import ErasureCodeError  # noqa: F401  (re-export check)
from ceph_tpu.erasure.interface import ErasureCodeError
from ceph_tpu.erasure.registry import (ErasureCodePlugin,
                                       ErasureCodePluginRegistry, registry)
from ceph_tpu.ops import crc32c as crc_mod

RNG = np.random.default_rng(1234)


def roundtrip(codec, data: bytes, erasure_patterns=None):
    """Encode, then decode every erasure pattern and check bit-equality."""
    n = codec.get_chunk_count()
    k = codec.get_data_chunk_count()
    encoded = codec.encode(range(n), data)
    chunk_size = len(encoded[0])
    # decode_concat recovers the original (plus padding)
    if erasure_patterns is None:
        m = n - k
        erasure_patterns = [c for r in range(1, min(m, 2) + 1)
                            for c in itertools.combinations(range(n), r)]
    for pattern in erasure_patterns:
        avail = {i: encoded[i] for i in range(n) if i not in pattern}
        try:
            minimum = codec.minimum_to_decode(list(pattern), avail.keys())
        except ErasureCodeError:
            continue  # pattern not recoverable (e.g. shec beyond c)
        picked = {i: avail[i] for i in minimum if i in avail}
        out = codec.decode(list(pattern), picked, chunk_size)
        for c in pattern:
            assert np.array_equal(out[c], encoded[c]), (
                f"chunk {c} mismatch for erasures {pattern}")
    # full data roundtrip through decode_concat
    got = codec.decode_concat({i: encoded[i] for i in range(k)})
    assert got[: len(data)] == data


class TestBaseChunkMath:
    def test_chunk_size_padding(self):
        codec = registry.factory("jerasure", {"k": "3", "m": "2"})
        cs = codec.get_chunk_size(1000)
        assert cs * 3 >= 1000
        assert cs % 128 == 0

    def test_encode_pads_with_zeros(self):
        codec = registry.factory("jerasure", {"k": "2", "m": "1"})
        data = b"xy" * 100
        out = codec.encode(range(3), data)
        joined = b"".join(out[i].tobytes() for i in range(2))
        assert joined[: len(data)] == data
        assert set(joined[len(data):]) <= {0}

    def test_minimum_to_decode_prefers_data(self):
        codec = registry.factory("jerasure", {"k": "2", "m": "2"})
        assert codec.minimum_to_decode([0, 1], [0, 1, 2, 3]) == [0, 1]
        assert codec.minimum_to_decode([0, 1], [1, 2, 3]) == [1, 2]
        with pytest.raises(ErasureCodeError):
            codec.minimum_to_decode([0], [3])


class TestJerasure:
    @pytest.mark.parametrize("technique,k,m", [
        ("reed_sol_van", 2, 1),
        ("reed_sol_van", 4, 2),
        ("reed_sol_van", 8, 3),
        ("reed_sol_r6_op", 4, 2),
        ("cauchy_orig", 3, 2),
        ("cauchy_good", 6, 3),
    ])
    def test_roundtrip(self, technique, k, m):
        profile = {"k": str(k), "m": str(m), "technique": technique,
                   "packetsize": "128"}
        codec = registry.factory("jerasure", profile)
        data = RNG.integers(0, 256, size=k * 512, dtype=np.uint8).tobytes()
        roundtrip(codec, data)

    def test_first_parity_is_xor(self):
        # reed_sol_van row 0 is all ones -> parity 0 == XOR of data chunks
        codec = registry.factory("jerasure", {"k": "4", "m": "2"})
        data = RNG.integers(0, 256, size=4 * 256, dtype=np.uint8)
        chunks = data.reshape(4, 256)
        parity = codec.encode_chunks(chunks)
        assert np.array_equal(parity[0],
                              np.bitwise_xor.reduce(chunks, axis=0))

    @pytest.mark.parametrize("technique,k,w", [
        ("liberation", 5, 7),       # w prime, k <= w
        ("liberation", 7, 7),
        ("liberation", 4, 11),
        ("blaum_roth", 5, 6),       # w+1 prime
        ("blaum_roth", 6, 10),
        ("liber8tion", 6, 8),       # w = 8 fixed
        ("liber8tion", 8, 8),
    ])
    def test_bitmatrix_raid6_roundtrip(self, technique, k, w):
        """Minimal-density m=2 techniques: every 2-erasure combination
        must decode (ErasureCodeJerasure.h:176-259 family)."""
        import itertools
        codec = registry.factory("jerasure", {
            "technique": technique, "k": str(k), "m": "2", "w": str(w),
            "packetsize": "128"})
        data = bytes(np.random.default_rng(k * w).integers(
            0, 256, 20000, dtype=np.uint8))
        out = codec.encode(range(k + 2), data)
        for lost in itertools.combinations(range(k + 2), 2):
            have = {i: out[i] for i in range(k + 2) if i not in lost}
            assert codec.decode_concat(have)[:len(data)] == data, lost

    def test_bitmatrix_invalid_params_raise(self):
        with pytest.raises(ErasureCodeError):        # w not prime
            registry.factory("jerasure", {"technique": "liberation",
                                          "k": "4", "m": "2", "w": "6"})
        with pytest.raises(ErasureCodeError):        # m != 2
            registry.factory("jerasure", {"technique": "liberation",
                                          "k": "4", "m": "3", "w": "7"})
        with pytest.raises(ErasureCodeError):        # w+1 not prime
            registry.factory("jerasure", {"technique": "blaum_roth",
                                          "k": "4", "m": "2", "w": "7"})
        with pytest.raises(ErasureCodeError):        # k > 8
            registry.factory("jerasure", {"technique": "liber8tion",
                                          "k": "9", "m": "2"})


class TestIsa:
    @pytest.mark.parametrize("technique,k,m", [
        ("reed_sol_van", 7, 3),
        ("reed_sol_van", 8, 3),
        ("cauchy", 4, 3),
    ])
    def test_roundtrip(self, technique, k, m):
        codec = registry.factory("isa", {"k": str(k), "m": str(m),
                                         "technique": technique})
        data = RNG.integers(0, 256, size=k * 300, dtype=np.uint8).tobytes()
        roundtrip(codec, data)


class TestTpu:
    @pytest.mark.parametrize("technique,k,m", [
        ("reed_sol_van", 2, 1),
        ("reed_sol_van", 8, 3),
        ("isa_reed_sol_van", 8, 3),
        ("isa_cauchy", 4, 3),
        ("cauchy_good", 4, 2),
    ])
    def test_roundtrip(self, technique, k, m):
        profile = {"k": str(k), "m": str(m), "technique": technique,
                   "packetsize": "128", "host_cutover": "0"}
        codec = registry.factory("tpu", profile)
        data = RNG.integers(0, 256, size=k * 1024, dtype=np.uint8).tobytes()
        roundtrip(codec, data)

    def test_bit_identical_to_jerasure(self):
        """Device chunks must equal the host oracle byte-for-byte."""
        for technique in ("reed_sol_van", "cauchy_good"):
            profile = {"k": "4", "m": "2", "technique": technique,
                       "packetsize": "128", "host_cutover": "0"}
            host = registry.factory("jerasure", profile)
            dev = registry.factory("tpu", profile)
            data = RNG.integers(0, 256, size=4096 * 4, dtype=np.uint8)
            chunks = data.reshape(4, 4096)
            assert np.array_equal(host.encode_chunks(chunks),
                                  dev.encode_chunks(chunks)), technique

    def test_bit_identical_to_isa(self):
        host = registry.factory("isa", {"k": "8", "m": "3"})
        dev = registry.factory("tpu", {"k": "8", "m": "3",
                                       "technique": "isa_reed_sol_van",
                                       "host_cutover": "0"})
        data = RNG.integers(0, 256, size=8 * 2048, dtype=np.uint8)
        chunks = data.reshape(8, 2048)
        assert np.array_equal(host.encode_chunks(chunks),
                              dev.encode_chunks(chunks))

    def test_encode_batch_and_decode_batch(self):
        codec = registry.factory("tpu", {"k": "4", "m": "2",
                                         "host_cutover": "0"})
        batch = RNG.integers(0, 256, size=(8, 4, 512), dtype=np.uint8)
        parity = codec.encode_batch(batch)
        assert parity.shape == (8, 2, 512)
        # knock out chunks 0 and 5 (parity 1), rebuild from survivors
        present = [1, 2, 3, 4]
        chunks = np.concatenate([batch, parity], axis=1)
        rebuilt = codec.decode_batch([0, 5], present,
                                     chunks[:, present, :])
        assert np.array_equal(rebuilt[:, 0, :], batch[:, 0, :])
        assert np.array_equal(rebuilt[:, 1, :], parity[:, 1, :])

    def test_encode_with_crcs(self):
        codec = registry.factory("tpu", {"k": "2", "m": "1",
                                         "host_cutover": "0"})
        batch = RNG.integers(0, 256, size=(4, 2, 256), dtype=np.uint8)
        parity, crcs = codec.encode_with_crcs(batch)
        assert crcs.shape == (4, 3)
        for b in range(4):
            for c in range(2):
                assert crcs[b, c] == crc_mod.crc32c_sw(0, batch[b, c])
            assert crcs[b, 2] == crc_mod.crc32c_sw(0, parity[b, 0])


class TestShec:
    def test_local_repair_uses_fewer_than_k(self):
        codec = registry.factory("shec", {"k": "8", "m": "4", "c": "3"})
        n = codec.get_chunk_count()
        minimum = codec.minimum_to_decode([0], set(range(n)) - {0})
        assert len(minimum) < 8, minimum

    @pytest.mark.parametrize("k,m,c", [(4, 3, 2), (8, 4, 3), (6, 3, 2)])
    def test_roundtrip_all_c_erasures(self, k, m, c):
        codec = registry.factory("shec",
                                 {"k": str(k), "m": str(m), "c": str(c)})
        n = k + m
        data = RNG.integers(0, 256, size=k * 256, dtype=np.uint8).tobytes()
        patterns = [p for r in range(1, c + 1)
                    for p in itertools.combinations(range(n), r)]
        roundtrip(codec, data, patterns)

    def test_all_c_failures_recoverable(self):
        """Any c erasures must be decodable (the SHEC guarantee)."""
        k, m, c = 4, 3, 2
        codec = registry.factory("shec",
                                 {"k": str(k), "m": str(m), "c": str(c)})
        n = k + m
        for pattern in itertools.combinations(range(n), c):
            avail = set(range(n)) - set(pattern)
            codec.minimum_to_decode(list(pattern), avail)  # must not raise

    def test_invalid_profile(self):
        with pytest.raises(ErasureCodeError):
            registry.factory("shec", {"k": "2", "m": "4", "c": "1"})


class TestLrc:
    def test_kml_generation(self):
        codec = registry.factory("lrc", {"k": "4", "m": "2", "l": "3"})
        assert codec.get_chunk_count() == 8  # 4 data + 2 global + 2 local
        assert codec.get_data_chunk_count() == 4

    def test_local_repair_is_cheap(self):
        codec = registry.factory("lrc", {"k": "4", "m": "2", "l": "3"})
        n = codec.get_chunk_count()
        minimum = codec.minimum_to_decode([0], set(range(n)) - {0})
        assert len(minimum) == 3, minimum  # l chunks, not k=4

    def test_roundtrip(self):
        codec = registry.factory("lrc", {"k": "4", "m": "2", "l": "3"})
        n = codec.get_chunk_count()
        data = RNG.integers(0, 256, size=4 * 400, dtype=np.uint8).tobytes()
        patterns = [(i,) for i in range(n)] + [(0, 4), (1, 5), (0, 1)]
        roundtrip(codec, data, patterns)

    def test_explicit_layers(self):
        profile = {
            "mapping": "DD_DD_",
            "layers": '[["DDc___", ""], ["___DDc", ""]]',
        }
        codec = registry.factory("lrc", profile)
        assert codec.get_data_chunk_count() == 4
        data = RNG.integers(0, 256, size=4 * 300, dtype=np.uint8).tobytes()
        roundtrip(codec, data, [(i,) for i in range(6)])


class TestPluginRegistry:
    def test_unknown_plugin(self):
        with pytest.raises(ErasureCodeError, match="unknown"):
            registry.factory("no-such-plugin", {})

    def test_preload(self):
        r = ErasureCodePluginRegistry()
        r.preload(("jerasure", "isa"))
        assert r.loaded_plugins() == ["isa", "jerasure"]

    def test_missing_entry_point(self, tmp_path, monkeypatch):
        r = ErasureCodePluginRegistry()
        with pytest.raises(ErasureCodeError, match="entry point"):
            r.load("bad", module="json")  # real module, no entry point

    def test_entry_point_raises(self):
        r = ErasureCodePluginRegistry()
        import sys
        import types
        mod = types.ModuleType("_ec_fail_init")
        def boom(reg, name):
            raise RuntimeError("fixture failure")
        mod.__erasure_code_init__ = boom
        sys.modules["_ec_fail_init"] = mod
        try:
            with pytest.raises(ErasureCodeError, match="entry point failed"):
                r.load("failinit", module="_ec_fail_init")
        finally:
            del sys.modules["_ec_fail_init"]

    def test_entry_point_registers_nothing(self):
        r = ErasureCodePluginRegistry()
        import sys
        import types
        mod = types.ModuleType("_ec_noreg")
        mod.__erasure_code_init__ = lambda reg, name: None
        sys.modules["_ec_noreg"] = mod
        try:
            with pytest.raises(ErasureCodeError, match="did not register"):
                r.load("noreg", module="_ec_noreg")
        finally:
            del sys.modules["_ec_noreg"]

    def test_version_mismatch(self):
        r = ErasureCodePluginRegistry()
        import sys
        import types

        class OldPlugin(ErasureCodePlugin):
            version = 0

        mod = types.ModuleType("_ec_oldver")
        mod.__erasure_code_init__ = (
            lambda reg, name: reg.add(name, OldPlugin()))
        sys.modules["_ec_oldver"] = mod
        try:
            with pytest.raises(ErasureCodeError, match="version"):
                r.load("oldver", module="_ec_oldver")
        finally:
            del sys.modules["_ec_oldver"]

    def test_profile_validation_errors(self):
        with pytest.raises(ErasureCodeError):
            registry.factory("jerasure", {"k": "abc"})
        with pytest.raises(ErasureCodeError):
            registry.factory("jerasure", {"technique": "nope"})
        with pytest.raises(ErasureCodeError):
            registry.factory("jerasure", {"k": "300", "m": "10"})
