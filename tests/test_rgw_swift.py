"""Swift dialect (rgw_rest_swift.cc reduced): TempAuth + container/
object workflow over the same namespace the S3 surface serves.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    r = c.client()
    r.create_pool("warm", pg_num=4)
    io = r.open_ioctx("warm")
    end = time.time() + 30
    while True:
        try:
            io.write_full("w", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def gw(cluster):
    return cluster.start_rgw(access_key="swiftacct",
                             secret_key="swiftkey")


def req(method, url, data=None, headers=None):
    r = urllib.request.Request(url, data=data, method=method,
                               headers=headers or {})
    return urllib.request.urlopen(r, timeout=30)


class TestSwift:
    def test_tempauth_and_workflow(self, gw):
        base = f"http://127.0.0.1:{gw.port}"
        # bad creds rejected
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/auth/v1.0",
                headers={"X-Auth-User": "swiftacct",
                         "X-Auth-Key": "wrong"})
        assert ei.value.code == 401
        r = req("GET", f"{base}/auth/v1.0",
                headers={"X-Auth-User": "swiftacct",
                         "X-Auth-Key": "swiftkey"})
        token = r.headers["X-Auth-Token"]
        surl = r.headers["X-Storage-Url"]
        assert "/v1/AUTH_swiftacct" in surl
        h = {"X-Auth-Token": token}
        # tokenless access refused
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/v1/AUTH_swiftacct")
        assert ei.value.code == 401
        # container + object lifecycle
        assert req("PUT", f"{surl}/cont", headers=h).status == 201
        assert req("PUT", f"{surl}/cont", headers=h).status == 202
        r = req("PUT", f"{surl}/cont/obj%20one", b"swift body",
                headers=h)
        assert r.status == 201 and r.headers["ETag"]
        assert req("GET", f"{surl}/cont/obj%20one",
                   headers=h).read() == b"swift body"
        listing = req("GET", f"{surl}/cont?format=json",
                      headers=h).read()
        ents = json.loads(listing)
        assert ents[0]["name"] == "obj one"
        assert ents[0]["bytes"] == 10
        # account listing shows the container
        acct = req("GET", f"{surl}?format=json", headers=h).read()
        assert any(c["name"] == "cont" for c in json.loads(acct))
        # non-empty delete refused; empty ok
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("DELETE", f"{surl}/cont", headers=h)
        assert ei.value.code == 409
        assert req("DELETE", f"{surl}/cont/obj%20one",
                   headers=h).status == 204
        assert req("DELETE", f"{surl}/cont", headers=h).status == 204

    def test_s3_and_swift_share_namespace(self, gw):
        """radosgw semantics: S3 buckets ARE Swift containers."""
        from ceph_tpu.rgw import auth_v4
        from urllib.parse import urlparse
        base = f"http://127.0.0.1:{gw.port}"
        host = urlparse(base).netloc

        def s3(method, path, data=b""):
            hh = auth_v4.sign_v4(method, path, "", {"host": host},
                                 data, "swiftacct", "swiftkey")
            hh["Host"] = host
            return req(method, base + path, data=data or None,
                       headers=hh)

        s3("PUT", "/shared")
        s3("PUT", "/shared/from-s3", b"wrote via S3")
        tok = req("GET", f"{base}/auth/v1.0",
                  headers={"X-Auth-User": "swiftacct",
                           "X-Auth-Key": "swiftkey"}
                  ).headers["X-Auth-Token"]
        h = {"X-Auth-Token": tok}
        got = req("GET", f"{base}/v1/AUTH_swiftacct/shared/from-s3",
                  headers=h).read()
        assert got == b"wrote via S3"
        req("PUT", f"{base}/v1/AUTH_swiftacct/shared/from-swift",
            b"wrote via Swift", headers=h)
        assert s3("GET", "/shared/from-swift").read() == \
            b"wrote via Swift"


class TestTokenExpiry:
    """TempAuth tokens embed a mint timestamp and expire: a leaked
    token is only as good as the validity window, not the creds."""

    def test_token_roundtrip_and_window(self):
        from ceph_tpu.rgw import swift
        tok = swift.mint_token("acct", "sekrit")
        assert swift.check_token("acct", "sekrit", tok)
        # expired: minted TTL+1 seconds ago
        old = swift.mint_token("acct", "sekrit",
                               now=time.time() - swift.TOKEN_TTL - 1)
        assert not swift.check_token("acct", "sekrit", old)
        # minted too far in the future (skew beyond grace)
        future = swift.mint_token("acct", "sekrit",
                                  now=time.time() + swift.TOKEN_SKEW + 5)
        assert not swift.check_token("acct", "sekrit", future)
        # tampering with the embedded timestamp breaks the signature
        ts, _, sig = tok.partition("_")
        forged = f"{int(ts) + 60}_{sig}"
        assert not swift.check_token("acct", "sekrit", forged)
        # wrong secret / malformed tokens rejected
        assert not swift.check_token("acct", "wrong", tok)
        assert not swift.check_token("acct", "sekrit", "garbage")
        assert not swift.check_token("acct", "sekrit", "")

    def test_expired_token_rejected_by_gateway(self, cluster, gw):
        from ceph_tpu.rgw import swift
        base = f"http://127.0.0.1:{gw.port}"
        stale = swift.mint_token("swiftacct", "swiftkey",
                                 now=time.time() - swift.TOKEN_TTL - 1)
        r = urllib.request.Request(
            f"{base}/v1/AUTH_swiftacct",
            headers={"X-Auth-Token": stale})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(r)
        assert ei.value.code == 401
