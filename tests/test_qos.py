"""Per-pool QoS: dmClock tag math, the QoS op queue, the EC pipeline's
tenant picks, and the cluster-level noisy-neighbor drill (a reserved
pool's tail latency bounded while another tenant saturates the
cluster — and the SAME seed starving without QoS, so the mechanism is
provably load-bearing, not vacuous)."""

import threading
import time

import pytest

from ceph_tpu.utils.dmclock import (DmClockState, QosSpec, parse_spec,
                                    RES, PROP)


class TestSpecGrammar:
    def test_parse_full(self):
        s = parse_spec("100:2:500")
        assert (s.res, s.weight, s.lim) == (100.0, 2.0, 500.0)

    def test_parse_partial(self):
        assert parse_spec("50") == QosSpec(res=50.0)
        assert parse_spec("0:3") == QosSpec(res=0.0, weight=3.0)
        assert parse_spec("10::") == QosSpec(res=10.0)

    def test_parse_rejects_garbage(self):
        for bad in ("a:b:c", "1:2:3:4", "1:-2:0", "5:1:2"):
            with pytest.raises(ValueError):
                parse_spec(bad)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class TestDmClockState:
    def test_unconstrained_is_fifo(self):
        clk = FakeClock()
        st = DmClockState(clock=clk)
        # no specs anywhere: oldest arrival wins, exactly FIFO
        got, phase, _ = st.pick({"a": 99.0, "b": 98.0}, now=clk.t)
        assert (got, phase) == ("b", RES)

    def test_reservation_beats_weight(self):
        clk = FakeClock()
        st = DmClockState(clock=clk)
        st.configure({"gold": QosSpec(res=10.0, weight=1.0),
                      "noise": QosSpec(res=0.0, weight=100.0)})
        # both queued since t-1: gold's reservation tag is due, noise
        # has only a proportional claim — gold wins the slot
        got, phase, _ = st.pick({"gold": clk.t - 1.0,
                                 "noise": clk.t - 1.0}, now=clk.t)
        assert (got, phase) == ("gold", RES)

    def test_reservation_rate_is_bounded(self):
        clk = FakeClock()
        st = DmClockState(clock=clk)
        st.configure({"gold": QosSpec(res=10.0, weight=1.0),
                      "noise": QosSpec(res=0.0, weight=1.0)})
        # serve 20 slots in zero elapsed time: gold's r_tag runs ahead
        # of now after its first grant, so the surplus splits by
        # weight instead of gold eating every slot
        grants = {"gold": 0, "noise": 0}
        for _ in range(20):
            got, _phase, _ = st.pick({"gold": clk.t - 5.0,
                                      "noise": clk.t - 5.0},
                                     now=clk.t)
            grants[got] += 1
        assert grants["noise"] >= 8   # ~weight-fair after the 1st res

    def test_weight_shares_track_ratio(self):
        clk = FakeClock()
        st = DmClockState(clock=clk)
        st.configure({"a": QosSpec(weight=3.0),
                      "b": QosSpec(weight=1.0)})
        grants = {"a": 0, "b": 0}
        for _ in range(40):
            got, phase, _ = st.pick({"a": clk.t - 1.0,
                                     "b": clk.t - 1.0}, now=clk.t)
            assert phase == PROP
            grants[got] += 1
        assert 25 <= grants["a"] <= 35          # ~3:1

    def test_limit_throttles(self):
        clk = FakeClock()
        st = DmClockState(clock=clk)
        st.configure({"capped": QosSpec(res=0.0, weight=1.0,
                                        lim=10.0)})
        served = 0
        for _ in range(5):
            got, _phase, wake = st.pick({"capped": clk.t - 1.0},
                                        now=clk.t)
            if got is not None:
                served += 1
        # 1 grant consumes 1/10s of limit credit; with the clock
        # frozen only the first pick serves, the rest are throttled
        assert served == 1
        assert wake > clk.t
        # time passes -> credit returns
        clk.t += 0.2
        got, _phase, _ = st.pick({"capped": clk.t - 1.0}, now=clk.t)
        assert got == "capped"

    def test_deadline_miss_counted(self):
        clk = FakeClock()
        st = DmClockState(clock=clk)
        st.configure({"gold": QosSpec(res=10.0)})
        st.pick({"gold": clk.t}, now=clk.t)
        # next due tag ~t+0.1; serve it 5s late -> a recorded miss
        clk.t += 5.0
        st.pick({"gold": clk.t - 5.0}, now=clk.t)
        stats = st.stats()
        assert stats["clients"]["gold"]["deadline_misses"] >= 1
        assert stats["enabled"] is True

    def test_bytes_weighted_cost_scales_limit(self):
        """The cost model beyond cost=1: a big op advances its
        client's tags by cost/rate, so a limit meters BYTES — one
        cost-10 grant exhausts as much credit as ten cost-1 grants."""
        clk = FakeClock()
        st = DmClockState(clock=clk)
        st.configure({"p": QosSpec(lim=10.0)})
        got, phase, _ = st.pick({"p": clk.t}, now=clk.t,
                                costs={"p": 10.0})
        assert got == "p" and phase == PROP
        # next opportunity immediately after: over limit (throttled)
        got, _phase, _wake = st.pick({"p": clk.t}, now=clk.t + 0.05)
        assert got is None
        # still throttled where a cost-1 grant would have recharged
        got, _p, _w = st.pick({"p": clk.t}, now=clk.t + 0.15)
        assert got is None
        # credit returns only after cost/lim = 1s
        got, _p, _w = st.pick({"p": clk.t}, now=clk.t + 1.01)
        assert got == "p"

    def test_bytes_weighted_cost_scales_reservation(self):
        clk = FakeClock()
        st = DmClockState(clock=clk)
        st.configure({"r": QosSpec(res=100.0)})
        got, phase, _ = st.pick({"r": clk.t}, now=clk.t,
                                costs={"r": 50.0})
        assert got == "r" and phase == RES
        # a 50-cost grant consumed 0.5s of a 100/s reservation
        got2, phase2, _ = st.pick({"r": clk.t}, now=clk.t + 0.1)
        assert (got2, phase2) == ("r", PROP)   # res tag not due yet

    def test_stats_schema(self):
        st = DmClockState()
        st.configure({"p": QosSpec(res=5.0, weight=2.0, lim=50.0)})
        st.pick({"p": 0.0}, now=1.0)
        s = st.stats()
        assert s["clients"]["p"]["spec"] == "5:2:50"
        for key in ("res_grants", "prop_grants", "deadline_misses"):
            assert key in s["clients"]["p"]
        assert "throttle_stalls" in s


class TestQosQueue:
    def test_untagged_fifo_and_join(self):
        from ceph_tpu.utils.workqueue import QosQueue
        q = QosQueue(DmClockState())
        got = []
        for i in range(5):
            q.put(i)
        while True:
            try:
                got.append(q.get(timeout=0.05))
            except Exception:
                break
        assert got == [0, 1, 2, 3, 4]

    def test_limit_blocks_then_serves(self):
        from ceph_tpu.utils.workqueue import QosQueue
        st = DmClockState()
        st.configure({"capped": QosSpec(lim=20.0)})
        q = QosQueue(st)
        for i in range(4):
            q.put(i, client="capped")
        t0 = time.monotonic()
        got = [q.get(timeout=2.0) for _ in range(4)]
        took = time.monotonic() - t0
        assert got == [0, 1, 2, 3]
        # 4 grants at 20/s: the last waits ~3/20s for credit
        assert took >= 0.1
        assert st.throttle_stalls >= 1

    def test_sharded_pool_runs_tagged_work(self):
        from ceph_tpu.utils.workqueue import ShardedThreadPool
        st = DmClockState()
        st.configure({"gold": QosSpec(res=100.0, weight=4.0)})
        pool = ShardedThreadPool("qos-t", 2, qos_state=st)
        pool.start()
        done = []
        lock = threading.Lock()

        def work(tag, i):
            with lock:
                done.append((tag, i))

        for i in range(10):
            pool.queue(("pg", i % 2), work, "gold", i, qos="gold")
            pool.queue(("pg", i % 2), work, None, i)
        pool.drain()
        pool.stop()
        assert len(done) == 20
        assert st.stats()["clients"]["gold"]["res_grants"] + \
            st.stats()["clients"]["gold"]["prop_grants"] >= 1


class TestPipelineTenantQos:
    def test_dispatches_never_mix_tenants(self):
        """Items of different service classes must coalesce into
        SEPARATE dispatches — a reserved pool's stripes can never ride
        (and wait) inside a noisy pool's mega-batch."""
        import numpy as np
        from ceph_tpu.ops import pipeline as ec_pipeline
        pipe = ec_pipeline.EcDevicePipeline(depth=1,
                                            coalesce_wait=0.001)
        with pipe._lock:
            pipe._qos.configure(
                {"gold": QosSpec(res=100.0, weight=4.0),
                 "noise": QosSpec(weight=1.0)})
            pipe._qos_enabled = True
        batches = []
        block = threading.Event()

        def host_fn(batch):
            block.wait(2.0)
            batches.append(batch.shape[0])
            return (batch,)

        chan = ec_pipeline.PipelineChannel(key=("t", "mix"),
                                           host_fn=host_fn)
        futs = []
        # first submission occupies the dispatcher inside host_fn;
        # the rest queue behind it per tenant
        futs.append(pipe.submit(chan, np.zeros((1, 4),
                                               dtype=np.uint8),
                                qos="noise"))
        time.sleep(0.1)
        for _ in range(3):
            futs.append(pipe.submit(chan, np.zeros((1, 4),
                                                   dtype=np.uint8),
                                    qos="noise"))
        for _ in range(2):
            futs.append(pipe.submit(chan, np.zeros((1, 4),
                                                   dtype=np.uint8),
                                    qos="gold"))
        block.set()
        for f in futs:
            f.result(timeout=10)
        pipe.stop()
        # 1 (first) + one noise batch (3) + one gold batch (2): the
        # queued noise and gold items must NOT have merged into one
        # 5-stripe dispatch
        assert sorted(batches) == [1, 2, 3], batches

    def test_configure_qos_module_surface(self):
        from ceph_tpu.ops import pipeline as ec_pipeline
        ec_pipeline.configure_qos({"p": QosSpec(res=10.0)},
                                  cost_unit=8192)
        try:
            s = ec_pipeline.qos_stats()
            assert s["enabled"] is True
            assert "p" in s["clients"]
            assert ec_pipeline.get().qos_cost_unit == 8192
        finally:
            ec_pipeline.configure_qos({})

    def test_picks_charge_per_candidate_head_bytes(self):
        """The dispatch-lane tenant picker charges each pick by its
        head batch's staged bytes (1 + bytes/unit), not cost=1: the
        dmClock state must receive a per-candidate costs map whose
        values scale with the head item sizes, and the pipeline's
        qos_cost_picks counter must move."""
        import numpy as np
        from ceph_tpu.ops import pipeline as ec_pipeline
        pipe = ec_pipeline.EcDevicePipeline(depth=1,
                                            coalesce_wait=0.001,
                                            qos_cost_unit=1024)
        seen_costs = []
        real_pick = pipe._qos.pick

        def spy_pick(cands, now=None, cost=1.0, costs=None):
            if costs is not None:
                seen_costs.append(dict(costs))
            return real_pick(cands, now=now, cost=cost, costs=costs)

        pipe._qos.pick = spy_pick
        with pipe._lock:
            pipe._qos.configure({"big": QosSpec(weight=1.0),
                                 "small": QosSpec(weight=1.0)})
            pipe._qos_enabled = True
        block = threading.Event()

        def host_fn(batch):
            block.wait(2.0)
            return (batch,)

        chan = ec_pipeline.PipelineChannel(key=("t", "cost"),
                                           host_fn=host_fn)
        futs = [pipe.submit(chan, np.zeros((1, 16), dtype=np.uint8),
                            qos="small")]
        time.sleep(0.1)          # occupy the dispatcher inside host_fn
        futs.append(pipe.submit(chan, np.zeros((1, 4096),
                                               dtype=np.uint8),
                                qos="big"))
        futs.append(pipe.submit(chan, np.zeros((1, 16),
                                               dtype=np.uint8),
                                qos="small"))
        block.set()
        for f in futs:
            f.result(timeout=10)
        stats = pipe.stats()
        pipe.stop()
        assert stats["qos_cost_picks"] >= 1
        assert stats["qos_cost_unit"] == 1024
        # at least one pick saw both tenants queued with costs that
        # scale with their head bytes (1 + nbytes/unit)
        both = [c for c in seen_costs if "big" in c and "small" in c]
        assert both, seen_costs
        assert both[0]["big"] == 1.0 + 4096 / 1024
        assert both[0]["small"] == 1.0 + 16 / 1024


# ---------------------------------------------------------------------------
# The noisy-neighbor drill: load-bearing proof on a real cluster.
# ---------------------------------------------------------------------------

DRILL_SEED = 0x90D1


def _drill(qos: bool) -> dict:
    """One seeded open-loop round: a noisy tenant saturates a
    deterministically-throttled cluster (every client op costs 20 ms
    on its op shard) while the gold tenant offers light traffic.
    Returns the gold pool's report."""
    from ceph_tpu.tools.loadgen import LoadGen, TenantSpec
    from ceph_tpu.utils.config import Config
    from ceph_tpu.vstart import MiniCluster
    conf = {
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "mon_osd_down_out_interval": 5.0,
        # known capacity: 2 shards/osd x 50 ops/s = overloadable
        "osd_op_num_shards": 2,
        "osd_debug_inject_dispatch_delay_probability": 1.0,
        "osd_debug_inject_dispatch_delay_duration": 0.02,
        "objecter_op_timeout": 60.0,
    }
    if qos:
        # gold: 80 IOPS reserved, 4x surplus weight, no cap
        conf["osd_pool_qos_gold"] = "80:4:0"
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf=Config(conf)).start()
    try:
        rados = cluster.client()
        rados.create_pool("gold", pg_num=4)
        rados.create_pool("noise", pg_num=4)
        io_gold = rados.open_ioctx("gold")
        io_noise = rados.open_ioctx("noise")
        end = time.time() + 60
        while True:
            try:
                io_gold.write_full("settle", b"s")
                io_noise.write_full("settle", b"s")
                break
            except Exception:
                if time.time() > end:
                    raise
                time.sleep(0.3)
        tenants = [
            TenantSpec("gold", rate=15, duration=3.0, obj_count=8,
                       read_frac=0.5, payload=4096, max_workers=16),
            # offered ~3x the delay-throttled service capacity: the
            # op shards RUN A QUEUE for the whole window
            TenantSpec("noise", rate=220, duration=3.0, obj_count=16,
                       read_frac=0.0, payload=8192, max_workers=64),
        ]
        gen = LoadGen(tenants, seed=DRILL_SEED)
        report = gen.run({"gold": io_gold, "noise": io_noise})
        out = dict(report["pools"]["gold"])
        out["noise_ops"] = report["pools"]["noise"]["ops"]
        if qos:
            # the mechanism must actually have granted reservations
            qd = [o for o in cluster.osds.values()]
            grants = 0
            for osd in qd:
                st = osd._qos.stats()
                ent = st["clients"].get("gold")
                if ent:
                    grants += ent["res_grants"] + ent["prop_grants"]
            out["gold_grants"] = grants
        return out
    finally:
        cluster.stop()


class TestNoisyNeighborDrill:
    def test_reserved_pool_p99_bounded_and_mechanism_load_bearing(
            self):
        """With QoS: the reserved pool's p99 stays bounded while the
        noisy tenant saturates every op shard.  WITHOUT QoS, the same
        seed shows the starvation — FIFO queues the gold ops behind
        hundreds of noise ops.  Both halves run the identical offered
        schedule (seed-deterministic), so the only variable is the
        scheduler."""
        with_qos = _drill(qos=True)
        without = _drill(qos=False)
        assert with_qos["errors"] == 0, with_qos
        assert with_qos["gold_grants"] >= 1, with_qos
        # bounded: a reserved op waits at most ~the op in service +
        # scheduling slack, not the noise backlog
        assert with_qos["p99_ms"] < 1000.0, (with_qos, without)
        # load-bearing: the same seed WITHOUT QoS starves gold — its
        # tail rides the noise queue, several times the bounded p99
        assert without["p99_ms"] > 2.0 * with_qos["p99_ms"], \
            (with_qos, without)
        assert without["p99_ms"] > 1000.0, (with_qos, without)


class TestRecoveryQosClass:
    """QoS-aware recovery: with osd_qos_recovery set, MPGPush
    payloads are scheduled under the "@recovery" dmClock class
    (bytes-weighted) instead of the unconstrained control plane."""

    def test_backfill_pushes_ride_recovery_class(self):
        from ceph_tpu.utils.config import Config
        from ceph_tpu.vstart import MiniCluster
        conf = {
            "osd_heartbeat_interval": 0.5,
            "osd_heartbeat_grace": 8.0,
            "mon_osd_min_down_reporters": 2,
            "mon_osd_down_out_interval": 5.0,
            "osd_pg_log_max_entries": 16,
            # generous limit: throttleable, not test-slowing
            "osd_qos_recovery": "0:1:5000",
        }
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf=Config(conf)).start()
        try:
            rados = cluster.client()
            rados.create_pool("recq", pg_num=1)
            io = rados.open_ioctx("recq")
            end = time.time() + 60
            while True:
                try:
                    io.write_full("settle", b"s")
                    break
                except Exception:
                    if time.time() > end:
                        raise
                    time.sleep(0.3)
            for i in range(40):      # > log bound: forces backfill
                io.write_full(f"r{i:03d}", b"x" * 8192)
            m = cluster.leader().osdmon.osdmap
            pgid = m.object_to_pg(io.pool_id, "r000")
            _up, acting = m.pg_to_up_acting_osds(pgid)
            victim = acting[-1]
            cluster.kill_osd(victim)
            cluster.wait_for_osd_down(victim, timeout=40)
            cluster.start_osd(victim)     # memstore: reborn EMPTY
            cluster.wait_for_osds(3, timeout=40)
            vic = cluster.osds[victim]
            end = time.time() + 90
            while time.time() < end:
                have = sum(1 for i in range(40)
                           if vic.store.exists(f"pg_{pgid}",
                                               f"r{i:03d}"))
                if have == 40:
                    break
                time.sleep(0.5)
            assert have == 40, f"backfill incomplete: {have}/40"
            # the reborn peer's qos block shows the recovery class
            # actually granted work (the pushes it received)
            qos = vic._perf_dump()["qos"]["clients"]
            assert "@recovery" in qos, qos
            ent = qos["@recovery"]
            assert ent["res_grants"] + ent["prop_grants"] >= 10
        finally:
            cluster.stop()


class TestRecoveryDecodeLane:
    """The rebuild's DECODE half must sit under the repair cap too:
    reconstructing a dead shard from survivors tags its pipeline
    dispatch with the "@recovery" class, exactly like the re-encode —
    otherwise repair reads escape osd_qos_recovery."""

    def test_rebuild_decode_rides_recovery_class(self):
        from ceph_tpu.ops import pipeline as ec_pipeline
        from ceph_tpu.utils.config import Config
        from ceph_tpu.vstart import MiniCluster
        conf = {
            "osd_heartbeat_interval": 0.5,
            "osd_heartbeat_grace": 8.0,
            "mon_osd_min_down_reporters": 2,
            "mon_osd_down_out_interval": 5.0,
            "osd_qos_recovery": "0:1:5000",
            # force the rebuild to actually DECODE: no HBM stripe
            # cache shortcut serving the payload without a gather
            "osd_ec_hbm_cache_bytes": 0,
        }
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf=Config(conf)).start()
        pipe = ec_pipeline.get()
        picks: list[tuple] = []
        orig = pipe.submit

        def spy(chan, arr, cache=None, qos=None, arena=None):
            picks.append((chan.key[0], qos))
            return orig(chan, arr, cache=cache, qos=qos, arena=arena)

        pipe.submit = spy
        try:
            rados = cluster.client()
            # host_cutover=1 forces pipeline routing on the host-only
            # test rig, so decode lane picks actually reach submit()
            rados.create_ec_pool("decq", "dq_k2m1",
                                 {"plugin": "tpu", "k": 2, "m": 1,
                                  "host_cutover": "1"}, pg_num=1)
            io = rados.open_ioctx("decq")
            end = time.time() + 60
            while True:
                try:
                    io.write_full("settle", b"s" * 1024)
                    break
                except Exception:
                    if time.time() > end:
                        raise
                    time.sleep(0.3)
            for i in range(12):
                io.write_full(f"d{i:02d}", b"x" * 8192)
            m = cluster.leader().osdmon.osdmap
            pgid = m.object_to_pg(io.pool_id, "d00")
            _up, acting = m.pg_to_up_acting_osds(pgid)
            victim = acting[1]   # a DATA shard: its rebuild decodes
            cluster.kill_osd(victim)
            cluster.wait_for_osd_down(victim, timeout=40)
            cluster.start_osd(victim)     # memstore: reborn EMPTY
            cluster.wait_for_osds(3, timeout=40)
            end = time.time() + 90
            while time.time() < end:
                if any(k == "dec" and q == "@recovery"
                       for k, q in picks):
                    break
                time.sleep(0.3)
            dec_classes = {q for k, q in picks if k == "dec"}
            assert "@recovery" in dec_classes, \
                (dec_classes, picks[-20:])
        finally:
            pipe.submit = orig
            cluster.stop()
