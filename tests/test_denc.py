"""denc: the versioned data-only wire/disk codec (utils/denc.py).

Mirrors the reference's encoding discipline tests
(test/encoding/test_denc.cc): primitive roundtrips, struct versioning
with compat failure on newer versions, and clean errors on hostile or
corrupt input (the property pickle lacked).
"""

import numpy as np
import pytest

from ceph_tpu.utils import denc
from ceph_tpu.utils.denc import DencError, denc_type


def rt(obj):
    return denc.loads(denc.dumps(obj))


class TestPrimitives:
    def test_scalars(self):
        for v in (None, True, False, 0, 1, -1, 2**100, -(2**100),
                  127, 128, 1 << 63, 0.0, -2.5, float("inf")):
            assert rt(v) == v
            assert type(rt(v)) is type(v)

    def test_bytes_str(self):
        assert rt(b"") == b""
        assert rt(b"\x00\xff" * 100) == b"\x00\xff" * 100
        assert rt("héllo☃") == "héllo☃"

    def test_containers(self):
        v = {"a": [1, 2, (3, b"x")], ("t", 1): {4, 5}, 2: None}
        assert rt(v) == v
        assert type(rt((1, 2))) is tuple
        assert type(rt([1, 2])) is list

    def test_ndarray(self):
        a = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
        b = rt(a)
        np.testing.assert_array_equal(a, b)
        assert b.dtype == a.dtype
        s = rt(np.float32(1.5))
        assert s == 1.5

    def test_trailing_bytes_rejected(self):
        with pytest.raises(DencError):
            denc.loads(denc.dumps(1) + b"x")


@denc_type
class Point:
    DENC_VERSION = 2

    def __init__(self, x, y, z=0):
        self.x, self.y, self.z = x, y, z

    def __eq__(self, other):
        return (self.x, self.y, self.z) == (other.x, other.y, other.z)

    @staticmethod
    def _denc_upgrade(fields, version):
        if version == 1:
            fields = dict(fields)
            fields.setdefault("z", 0)
        return fields


class TestStructs:
    def test_roundtrip(self):
        p = rt(Point(1, 2, 3))
        assert p == Point(1, 2, 3)

    def test_private_fields_skipped(self):
        p = Point(1, 2)
        p._cache = "scratch"
        q = rt(p)
        assert not hasattr(q, "_cache")

    def test_old_version_upgrades(self):
        # hand-build a v1 frame: obj tag, name, version=1, fields
        out = bytearray([denc.T_OBJ])
        out += denc._uvarint(len(b"Point")) + b"Point"
        out += denc._uvarint(1)
        out += denc.dumps({"x": 7, "y": 8})
        p = denc.loads(bytes(out))
        assert p == Point(7, 8, 0)

    def test_newer_version_rejected(self):
        out = bytearray([denc.T_OBJ])
        out += denc._uvarint(len(b"Point")) + b"Point"
        out += denc._uvarint(3)
        out += denc.dumps({"x": 7, "y": 8})
        with pytest.raises(DencError, match="newer"):
            denc.loads(bytes(out))

    def test_unknown_type_rejected(self):
        out = bytearray([denc.T_OBJ])
        out += denc._uvarint(len(b"NoSuchThing")) + b"NoSuchThing"
        out += denc._uvarint(1)
        out += denc.dumps({})
        with pytest.raises(DencError, match="unknown"):
            denc.loads(bytes(out))

    def test_unregistered_type_not_encodable(self):
        class Rogue:
            pass
        with pytest.raises(DencError, match="not denc-encodable"):
            denc.dumps(Rogue())


class TestHostileInput:
    """Corrupt frames raise DencError — never execute code, never
    raise from arbitrary depth."""

    def test_truncated(self):
        frame = denc.dumps({"a": [1, 2, 3], "b": b"xyz"})
        for cut in range(len(frame)):
            with pytest.raises(DencError):
                denc.loads(frame[:cut])

    def test_bad_tag(self):
        with pytest.raises(DencError):
            denc.loads(b"\xfe")

    def test_fuzz_random_bytes(self):
        rng = np.random.default_rng(42)
        for _ in range(300):
            blob = rng.integers(0, 256, rng.integers(1, 60),
                                dtype=np.uint8).tobytes()
            try:
                denc.loads(blob)
            except DencError:
                pass  # the only acceptable failure mode

    def test_huge_varint_rejected(self):
        with pytest.raises(DencError):
            denc.loads(bytes([denc.T_INT]) + b"\xff" * 200)

    def test_ndarray_size_mismatch(self):
        # declared shape (1,) x uint8 but 8 payload bytes
        out = bytearray([denc.T_NDARRAY])
        out += denc._uvarint(3) + b"|u1"
        out += denc._uvarint(1) + denc._uvarint(1)
        out += denc._uvarint(8) + b"\x00" * 8
        with pytest.raises(DencError, match="mismatch"):
            denc.loads(bytes(out))

    def test_object_dtype_rejected(self):
        out = bytearray([denc.T_NDARRAY])
        out += denc._uvarint(3) + b"|O8"
        out += denc._uvarint(1) + denc._uvarint(1)
        out += denc._uvarint(8) + b"\x00" * 8
        with pytest.raises(DencError):
            denc.loads(bytes(out))


class TestSystemTypes:
    def test_osdmap_roundtrip(self):
        from ceph_tpu.osd.osdmap import OSDMap, OSDMapIncremental, Pool
        m = OSDMap()
        inc = OSDMapIncremental(epoch=1)
        inc.new_pools[0] = Pool(id=0, name="data", pg_num=4)
        inc.new_up[0] = ("127.0.0.1", 5000)
        m.apply_incremental(inc)
        m2 = OSDMap.decode(m.encode())
        assert m2.epoch == 1
        assert m2.pools[0].name == "data"
        assert m2.pg_to_raw_osds.__self__  # bound, real object

    def test_monmap_roundtrip(self):
        from ceph_tpu.mon.monmap import MonMap
        mm = MonMap(fsid="f")
        mm.add("a", ("127.0.0.1", 1))
        m2 = MonMap.decode(mm.encode())
        assert m2.mons == {"a": ("127.0.0.1", 1)}

    def test_pgid_namedtuple(self):
        from ceph_tpu.osd.osdmap import PgId
        p = rt(PgId(3, 0x1f))
        assert isinstance(p, PgId)
        assert p.pool == 3 and p.seed == 0x1f

    def test_message_roundtrip(self):
        from ceph_tpu.msg.message import Message
        from ceph_tpu.osd.messages import MOSDOp
        msg = MOSDOp(tid=1, pgid="0.1", oid="foo",
                     ops=[("writefull", b"data")], epoch=3)
        msg.src = "client.1"
        frame = msg.encode(seq=9)
        tid, plen, seq = Message.parse_header(frame[:Message.header_size()])
        out = Message.decode(tid, seq, frame[Message.header_size():])
        assert out.oid == "foo"
        assert out.ops == [("writefull", b"data")]

    def test_message_hostile_payload(self):
        from ceph_tpu.msg.message import Message
        from ceph_tpu.osd.messages import MOSDOp
        with pytest.raises(DencError):
            Message.decode(MOSDOp.TYPE, 0, b"\x93\x01\x02\x03")


class TestSchemaUpgrades:
    def test_old_pool_and_incremental_blobs_decode(self):
        """Pre-snap/pre-mgr blobs must upgrade, not AttributeError —
        mons replay stored incrementals across code upgrades."""
        from ceph_tpu.osd.osdmap import OSDMap, OSDMapIncremental, Pool
        import ceph_tpu.utils.denc as denc_mod

        def encode_as_version(obj, version, drop):
            fields = {k: v for k, v in obj.__dict__.items()
                      if not k.startswith("_") and k not in drop}
            out = bytearray()
            out.append(denc_mod.T_OBJ)
            name = type(obj).__name__.encode()
            out += denc_mod._uvarint(len(name)) + name
            out += denc_mod._uvarint(version)
            denc_mod._encode(fields, out)
            return bytes(out)

        pool = Pool(1, "p")
        blob = encode_as_version(pool, 1, {"snap_seq", "removed_snaps"})
        old = denc_mod.loads(blob)
        assert old.snap_seq == 0 and old.removed_snaps == []

        inc = OSDMapIncremental(epoch=1)
        blob = encode_as_version(
            inc, 1, {"new_pool_snap_seq", "new_removed_snaps",
                     "new_mgr"})
        old_inc = denc_mod.loads(blob)
        assert old_inc.new_mgr is None
        assert old_inc.new_pool_snap_seq == {}
        # and it applies cleanly
        m = OSDMap()
        m.apply_incremental(old_inc)
        assert m.epoch == 1

    def test_newer_version_rejected(self):
        from ceph_tpu.osd.osdmap import Pool
        import ceph_tpu.utils.denc as denc_mod
        out = bytearray()
        out.append(denc_mod.T_OBJ)
        out += denc_mod._uvarint(len(b"Pool")) + b"Pool"
        out += denc_mod._uvarint(99)
        denc_mod._encode({}, out)
        with pytest.raises(denc_mod.DencError):
            denc_mod.loads(bytes(out))
