"""RGW multisite sync (rgw_data_sync.h full/incremental reduced):
a secondary zone mirrors a primary through the S3 surface + bilog.
"""

import time
import urllib.error
import urllib.request

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.rgw import RGWDaemon
from ceph_tpu.rgw.sync import RGWSyncAgent
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    r = c.client()
    r.create_pool("warm", pg_num=4)
    io = r.open_ioctx("warm")
    end = time.time() + 30
    while True:
        try:
            io.write_full("w", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def zones(cluster):
    """Two gateways over DISJOINT pools on one plane: zone A is the
    primary, zone B runs the sync agent.  A MUTABLE holder: the
    restart test swaps in a replacement agent, so test order (pytest
    randomization) never leaves the module agent-less."""
    a = RGWDaemon(cluster.client("client.zoneA"),
                  data_pool="zone_a").start()
    b = RGWDaemon(cluster.client("client.zoneB"),
                  data_pool="zone_b").start()
    z = {"a": a, "b": b,
         "agent": RGWSyncAgent(b, f"http://127.0.0.1:{a.port}",
                               interval=0.2).start()}
    yield z
    z["agent"].shutdown()
    a.shutdown()
    b.shutdown()


def req(method, url, data=None):
    r = urllib.request.Request(url, data=data, method=method)
    return urllib.request.urlopen(r, timeout=30)


def wait_for(pred, timeout=30):
    end = time.time() + timeout
    while time.time() < end:
        try:
            if pred():
                return True
        except urllib.error.HTTPError:
            pass
        time.sleep(0.2)
    return False


class TestMultisite:
    def test_full_then_incremental_sync(self, zones):
        a, b = zones["a"], zones["b"]
        pa, pb = f"http://127.0.0.1:{a.port}", \
            f"http://127.0.0.1:{b.port}"
        req("PUT", f"{pa}/mirror")
        req("PUT", f"{pa}/mirror/seed1", b"one")
        req("PUT", f"{pa}/mirror/seed2", b"two" * 1000)
        # full sync brings existing objects over
        assert wait_for(lambda: req(
            "GET", f"{pb}/mirror/seed1").read() == b"one")
        assert wait_for(lambda: req(
            "GET", f"{pb}/mirror/seed2").read() == b"two" * 1000)
        # incremental: a NEW put replicates
        req("PUT", f"{pa}/mirror/live", b"incremental")
        assert wait_for(lambda: req(
            "GET", f"{pb}/mirror/live").read() == b"incremental")
        # ... and an overwrite
        req("PUT", f"{pa}/mirror/live", b"updated")
        assert wait_for(lambda: req(
            "GET", f"{pb}/mirror/live").read() == b"updated")

    def test_delete_propagates(self, zones):
        a, b = zones["a"], zones["b"]
        pa, pb = f"http://127.0.0.1:{a.port}", \
            f"http://127.0.0.1:{b.port}"
        req("PUT", f"{pa}/mirror/doomed", b"bye")
        assert wait_for(lambda: req(
            "GET", f"{pb}/mirror/doomed").read() == b"bye")
        req("DELETE", f"{pa}/mirror/doomed")

        def gone():
            try:
                req("GET", f"{pb}/mirror/doomed")
                return False
            except urllib.error.HTTPError as e:
                return e.code == 404
        assert wait_for(gone)

    def test_versioned_bucket_current_state_mirrors(self, zones):
        a, b = zones["a"], zones["b"]
        pa, pb = f"http://127.0.0.1:{a.port}", \
            f"http://127.0.0.1:{b.port}"
        req("PUT", f"{pa}/vsync")
        vc = (b"<VersioningConfiguration><Status>Enabled</Status>"
              b"</VersioningConfiguration>")
        req("PUT", f"{pa}/vsync?versioning", vc)
        req("PUT", f"{pa}/vsync/doc", b"gen1")
        req("PUT", f"{pa}/vsync/doc", b"gen2")
        assert wait_for(lambda: req(
            "GET", f"{pb}/vsync/doc").read() == b"gen2")
        # delete marker hides it on the secondary too
        d = req("DELETE", f"{pa}/vsync/doc")
        mvid = d.headers["x-amz-version-id"]

        def hidden():
            try:
                req("GET", f"{pb}/vsync/doc")
                return False
            except urllib.error.HTTPError as e:
                return e.code == 404
        assert wait_for(hidden)
        # removing the marker restores — secondary follows
        req("DELETE", f"{pa}/vsync/doc?versionId={mvid}")
        assert wait_for(lambda: req(
            "GET", f"{pb}/vsync/doc").read() == b"gen2")

    def test_partitioned_delete_tombstones_not_resurrects(self, zones):
        """DELETE at the primary while the zone link is partitioned:
        after heal the replica must replay the tombstone from the
        bilog — never re-full-sync the object back into existence —
        and the agent's counters must show exponential backoff (not a
        wedge or a tight error loop) for the partition window."""
        from ceph_tpu.utils import faults
        a, b = zones["a"], zones["b"]
        agent = zones["agent"]
        pa, pb = f"http://127.0.0.1:{a.port}", \
            f"http://127.0.0.1:{b.port}"
        req("PUT", f"{pa}/tombz")
        req("PUT", f"{pa}/tombz/doomed", b"to-be-tombstoned")
        assert wait_for(lambda: req(
            "GET", f"{pb}/tombz/doomed").read() == b"to-be-tombstoned")
        before = agent.perf.dump()
        fid = faults.get().partition(agent.entity, agent.peer_entity)
        try:
            req("DELETE", f"{pa}/tombz/doomed")
            # the agent is FAILING its rounds (and backing off) —
            # not wedged, not silently succeeding through the cut
            assert wait_for(
                lambda: agent.perf.dump()["sync_errors"]
                > before["sync_errors"], timeout=30)
            # async replication is LAG, never divergence: the replica
            # still serves the pre-delete object mid-partition
            assert req("GET", f"{pb}/tombz/doomed").read() \
                == b"to-be-tombstoned"
        finally:
            faults.get().clear(fid)

        def gone():
            try:
                req("GET", f"{pb}/tombz/doomed")
                return False
            except urllib.error.HTTPError as e:
                return e.code == 404
        assert wait_for(gone, timeout=60)
        after = agent.perf.dump()
        assert after["sync_backoff_secs"] > before["sync_backoff_secs"]
        # no resurrection: several MORE healthy rounds (any full sync
        # racing the tombstone) must not copy the object back
        rounds = agent.perf.dump()["sync_rounds"]
        assert wait_for(
            lambda: agent.perf.dump()["sync_rounds"] >= rounds + 3,
            timeout=30)
        assert gone()

    def test_agent_restart_resumes_from_marker(self, cluster, zones):
        a, b = zones["a"], zones["b"]
        pa, pb = f"http://127.0.0.1:{a.port}", \
            f"http://127.0.0.1:{b.port}"
        req("PUT", f"{pa}/mirror2")
        req("PUT", f"{pa}/mirror2/pre-stop", b"before")
        assert wait_for(lambda: req(
            "GET", f"{pb}/mirror2/pre-stop").read() == b"before")
        zones["agent"].shutdown()
        req("PUT", f"{pa}/mirror2/while-down", b"missed?")
        time.sleep(0.5)
        # the replacement stays: later (randomized-order) tests and
        # the fixture teardown own it via the holder
        zones["agent"] = RGWSyncAgent(
            b, f"http://127.0.0.1:{a.port}", interval=0.2).start()
        # durable marker: the gap written while the agent was down
        # replays on restart
        assert wait_for(lambda: req(
            "GET", f"{pb}/mirror2/while-down").read() == b"missed?")
