"""Watch/notify + object classes (osd/Watch.h, objclass/objclass.h).

The in-OSD RPC surface RBD is built on: cls methods executing against
the object inside the OSD (replicating via the op's transaction), and
watch/notify fan-out with gathered replies.
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.utils import denc
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("clspool", pg_num=4)
    ctx = rados.open_ioctx("clspool")
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warm", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


class TestCls:
    def test_rd_method(self, cluster, io):
        io.write_full("greet", b"x")
        out = io.execute("greet", "hello", "say_hello", b"tpu")
        assert out == b"Hello, tpu!"

    def test_wr_method_writes_and_replicates(self, cluster, io):
        io.execute("recorded", "hello", "record_hello", b"osd")
        assert io.read("recorded") == b"Hello, osd!"
        # duplicate greeting -> EEXIST from inside the method
        with pytest.raises(RadosError) as ei:
            io.execute("recorded", "hello", "record_hello", b"osd")
        assert ei.value.errno == 17
        # the mutation replicated like a normal write
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "recorded")
        up, acting = m.pg_to_up_acting_osds(pgid)
        time.sleep(0.3)
        for osd_id in acting:
            assert cluster.osds[osd_id].store.read(
                f"pg_{pgid}", "recorded") == b"Hello, osd!"

    def test_wr_method_with_output(self, cluster, io):
        io.write_full("eleven", b"quiet words")
        out = io.execute("eleven", "hello", "turn_it_to_11")
        assert denc.loads(out) == len(b"quiet words")
        assert io.read("eleven") == b"QUIET WORDS"

    def test_unknown_method_errors(self, cluster, io):
        with pytest.raises(RadosError) as ei:
            io.execute("greet", "hello", "no_such_method")
        assert ei.value.errno == 95

    def test_cls_lock_exclusive(self, cluster, io):
        req = {"name": "main", "type": "exclusive",
               "entity": "client.a", "cookie": "c1"}
        io.execute("locked", "lock", "lock", denc.dumps(req))
        # second taker busy
        req2 = dict(req, entity="client.b")
        with pytest.raises(RadosError) as ei:
            io.execute("locked", "lock", "lock", denc.dumps(req2))
        assert ei.value.errno == 16
        info = denc.loads(io.execute("locked", "lock", "get_info",
                                     denc.dumps({"name": "main"})))
        assert info["type"] == "exclusive"
        assert ["client.a", "c1"] in info["holders"]
        # unlock then the other client gets it
        io.execute("locked", "lock", "unlock", denc.dumps(req))
        io.execute("locked", "lock", "lock", denc.dumps(req2))

    def test_cls_lock_shared_and_break(self, cluster, io):
        a = {"name": "sh", "type": "shared", "entity": "x", "cookie": ""}
        b = {"name": "sh", "type": "shared", "entity": "y", "cookie": ""}
        io.execute("shared-lock", "lock", "lock", denc.dumps(a))
        io.execute("shared-lock", "lock", "lock", denc.dumps(b))
        io.execute("shared-lock", "lock", "break_lock", denc.dumps(a))
        info = denc.loads(io.execute(
            "shared-lock", "lock", "get_info", denc.dumps({"name": "sh"})))
        assert info["holders"] == [["y", ""]]


class TestWatchNotify:
    def test_notify_reaches_watcher_and_gathers_reply(self, cluster, io):
        io.write_full("tv", b"channel")
        got = []

        def on_notify(notify_id, payload):
            got.append(payload)
            return b"ack:" + payload

        cookie = io.watch("tv", on_notify)
        replies = io.notify("tv", b"breaking news")
        assert got == [b"breaking news"]
        assert list(replies.values()) == [b"ack:breaking news"]
        io.unwatch("tv", cookie)
        # after unwatch: no watchers -> empty gather
        assert io.notify("tv", b"anyone?") == {}

    def test_notify_two_watchers(self, cluster, io):
        rados2 = cluster.client("client.second")
        io2 = rados2.open_ioctx("clspool")
        io.write_full("radio", b"w")
        seen1, seen2 = [], []
        c1 = io.watch("radio", lambda n, p: seen1.append(p) or b"one")
        c2 = io2.watch("radio", lambda n, p: seen2.append(p) or b"two")
        replies = io.notify("radio", b"ping")
        assert seen1 == [b"ping"] and seen2 == [b"ping"]
        assert sorted(replies.values()) == [b"one", b"two"]
        io.unwatch("radio", c1)
        io2.unwatch("radio", c2)

    def test_watcher_death_drops_watch(self, cluster, io):
        rados3 = cluster.client("client.doomed")
        io3 = rados3.open_ioctx("clspool")
        io.write_full("fragile", b"w")
        io3.watch("fragile", lambda n, p: b"never")
        rados3.shutdown()
        cluster._clients.remove(rados3)
        # the notify must not hang on the dead watcher: either the
        # reset pruned it already or the timeout completes the gather
        t0 = time.time()
        io.notify("fragile", b"hello?", timeout=3.0)
        assert time.time() - t0 < 15


class TestRefcountClass:
    """cls/refcount/cls_refcount.cc semantics over librados exec."""

    def test_tags_gate_removal(self, io):
        from ceph_tpu.utils import denc
        io.write_full("shared", b"dedup-payload")
        io.execute("shared", "refcount", "get",
                   denc.dumps({"tag": "userA"}))
        io.execute("shared", "refcount", "get",
                   denc.dumps({"tag": "userB"}))
        tags = denc.loads(io.execute("shared", "refcount", "read",
                                     b""))
        assert sorted(tags) == ["userA", "userB"]
        left = denc.loads(io.execute("shared", "refcount", "put",
                                     denc.dumps({"tag": "userA"})))
        assert left == 1
        assert io.read("shared") == b"dedup-payload"   # still alive
        io.execute("shared", "refcount", "put",
                   denc.dumps({"tag": "userB"}))
        with pytest.raises(RadosError) as ei:
            io.read("shared")
        assert ei.value.errno == 2                     # gone

    def test_implicit_ref_put_removes(self, io):
        from ceph_tpu.utils import denc
        io.write_full("plain", b"x")
        io.execute("plain", "refcount", "put",
                   denc.dumps({"tag": "whatever"}))
        with pytest.raises(RadosError):
            io.read("plain")

    def test_strict_put_unknown_tag_rejected(self, io):
        from ceph_tpu.utils import denc
        io.write_full("st", b"x")
        io.execute("st", "refcount", "get", denc.dumps({"tag": "t1"}))
        with pytest.raises(RadosError) as ei:
            io.execute("st", "refcount", "put",
                       denc.dumps({"tag": "nope", "strict": True}))
        assert ei.value.errno == 2


class TestVersionClass:
    """cls/version/cls_version.cc semantics over librados exec."""

    def test_inc_and_conditions(self, io):
        from ceph_tpu.utils import denc
        io.write_full("vobj", b"meta")
        v1 = denc.loads(io.execute("vobj", "version", "inc", b""))
        assert v1["ver"] == 1 and v1["tag"]
        v2 = denc.loads(io.execute("vobj", "version", "inc", b""))
        assert v2["ver"] == 2 and v2["tag"] == v1["tag"]
        # guarded inc: expect current version
        denc.loads(io.execute("vobj", "version", "inc", denc.dumps(
            {"conds": [{"op": "eq", "ver": 2}]})))
        # stale expectation -> ECANCELED
        with pytest.raises(RadosError) as ei:
            io.execute("vobj", "version", "inc", denc.dumps(
                {"conds": [{"op": "eq", "ver": 2}]}))
        assert ei.value.errno == 125
        cur = denc.loads(io.execute("vobj", "version", "read", b""))
        assert cur["ver"] == 3

    def test_check_gate_and_set(self, io):
        from ceph_tpu.utils import denc
        io.write_full("vg", b"x")
        io.execute("vg", "version", "set",
                   denc.dumps({"ver": 41, "tag": "pinned"}))
        io.execute("vg", "version", "check", denc.dumps(
            {"conds": [{"op": "ge", "ver": 41},
                       {"op": "tag_eq", "tag": "pinned"}]}))
        with pytest.raises(RadosError) as ei:
            io.execute("vg", "version", "check", denc.dumps(
                {"conds": [{"op": "gt", "ver": 41}]}))
        assert ei.value.errno == 125


class TestLogClass:
    """cls/log/cls_log.cc semantics: stamped entries, marker paging,
    trim."""

    def test_add_list_trim(self, io):
        from ceph_tpu.utils import denc
        io.execute("logobj", "log", "add", denc.dumps({"entries": [
            {"section": "meta", "name": f"e{i}", "data": bytes([i]),
             "stamp": 1000.0 + i} for i in range(6)]}))
        out = denc.loads(io.execute("logobj", "log", "list",
                                    denc.dumps({"max_entries": 4})))
        assert len(out["entries"]) == 4 and out["truncated"]
        assert [e["name"] for e in out["entries"]] == \
            ["e0", "e1", "e2", "e3"]
        # resume from the marker
        out2 = denc.loads(io.execute("logobj", "log", "list",
                                     denc.dumps(
                                         {"marker": out["marker"]})))
        assert [e["name"] for e in out2["entries"]] == ["e4", "e5"]
        assert not out2["truncated"]
        # trim through e3; only the tail remains
        io.execute("logobj", "log", "trim",
                   denc.dumps({"to_marker": out["marker"]}))
        rest = denc.loads(io.execute("logobj", "log", "list", b""))
        assert [e["name"] for e in rest["entries"]] == ["e4", "e5"]


class TestNumopsClass:
    """cls/numops/cls_numops.cc: atomic arithmetic on omap cells."""

    def test_add_sub_mul(self, io):
        from ceph_tpu.utils import denc
        v = denc.loads(io.execute("counters", "numops", "add",
                                  denc.dumps({"key": "n",
                                              "value": 5})))
        assert v == 5
        v = denc.loads(io.execute("counters", "numops", "add",
                                  denc.dumps({"key": "n",
                                              "value": 2.5})))
        assert v == 7.5
        v = denc.loads(io.execute("counters", "numops", "sub",
                                  denc.dumps({"key": "n",
                                              "value": 0.5})))
        assert v == 7.0
        v = denc.loads(io.execute("counters", "numops", "mul",
                                  denc.dumps({"key": "n",
                                              "value": 3})))
        assert v == 21.0
        # non-numeric cell rejected
        io.set_omap("counters", {"junk": b"not-a-number"})
        with pytest.raises(RadosError) as ei:
            io.execute("counters", "numops", "add",
                       denc.dumps({"key": "junk", "value": 1}))
        assert ei.value.errno == 22

    def test_concurrent_adders_lose_nothing(self, io):
        import threading
        from ceph_tpu.utils import denc
        errs = []

        def adder():
            try:
                for _ in range(20):
                    io.execute("shared-ctr", "numops", "add",
                               denc.dumps({"key": "c", "value": 1}))
            except Exception as e:       # pragma: no cover
                errs.append(e)
        threads = [threading.Thread(target=adder) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs
        assert int(io.get_omap("shared-ctr")["c"]) == 80


class TestTimeindexClass:
    """cls/timeindex/cls_timeindex.cc: time-windowed index."""

    def test_window_list_and_trim(self, io):
        from ceph_tpu.utils import denc
        io.execute("tidx", "timeindex", "add", denc.dumps({
            "entries": [{"name": f"n{i}", "value": b"v",
                         "stamp": 100.0 + i} for i in range(8)]}))
        win = denc.loads(io.execute("tidx", "timeindex", "list",
                                    denc.dumps({"from": 102.0,
                                                "to": 105.0})))
        assert [e["name"] for e in win["entries"]] == \
            ["n2", "n3", "n4"]
        io.execute("tidx", "timeindex", "trim",
                   denc.dumps({"to": 104.0}))
        rest = denc.loads(io.execute("tidx", "timeindex", "list",
                                     b""))
        assert [e["name"] for e in rest["entries"]] == \
            [f"n{i}" for i in range(4, 8)]
