"""Multi-chip sharded EC dispatch: placement, mega-batch splitting,
per-lane quarantine + redrain.

conftest.py forces an 8-device CPU host platform, so these exercise
the REAL multi-device placement/split/quarantine code paths the TPU
pod runs — the tier-1 contracts pinned here:

  * sharded dispatch (split across chips, odd batch sizes, uneven
    shards) is BIT-EXACT vs a single-device pipeline vs the host
    oracle;
  * a device failure on one chip of eight quarantines THAT lane only:
    its work redrains onto surviving chips bit-identically, the codec
    does NOT degrade, and the quarantine counters move;
  * an injected `tpu_error` targeted at one device index does the
    same through the plugin path (untargeted injection still degrades
    the whole codec, as PR 1/2 pinned);
  * host fallback (and the owner's on_error degrade) happens only
    once EVERY chip is quarantined.
"""

import threading
import time

import numpy as np
import pytest

from ceph_tpu.erasure.registry import registry
from ceph_tpu.ops import ec_kernels, gf
from ceph_tpu.ops import pipeline as ec_pipeline
from ceph_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)
    pipe = ec_pipeline.get()
    st = pipe.stats()
    if st["devices"] and any(d["quarantined"]
                             for d in st["devices"].values()):
        pipe.reset_devices()


K, M, L = 3, 2, 256
MATRIX = gf.reed_sol_van_matrix(K, M)


def _host_fn(batch):
    from ceph_tpu.erasure.matrix_codec import NumpyBackend
    return (np.asarray(NumpyBackend().apply_bytes(MATRIX, batch)),)


def _ready_device_fn(bad_indices=(), errors=None):
    """A device fn that is ALWAYS warm (CPU jit compiles inline in
    ~100ms) so placement/split runs deterministically; devices whose
    jax id is in `bad_indices` blow up like a dead chip."""
    fn = ec_kernels.make_codec_fn(MATRIX)

    def device_fn(padded, device=None):
        if device is not None and device.id in bad_indices:
            if errors is not None:
                errors.append(device.id)
            raise RuntimeError(f"chip {device.id} down")
        return (fn(padded),)

    return device_fn


def _submit_odd_batches(pipe, chan, seed=0):
    """Stagger odd-sized submissions so coalescing builds mega-batches
    that straddle bucket boundaries and split unevenly."""
    rng = np.random.default_rng(seed)
    batches = [rng.integers(0, 256, size=(B, K, L), dtype=np.uint8)
               for B in (1, 3, 5, 7, 2, 9, 4, 17, 1, 6)]
    futs = [pipe.submit(chan, b) for b in batches]
    return batches, [f.result(timeout=60) for f in futs]


def _assert_oracle(batches, results, want_path=None):
    for arr, (path, (parity,)) in zip(batches, results):
        if want_path is not None:
            assert path == want_path
        expect = np.stack([gf.encode_np(MATRIX, arr[b])
                           for b in range(arr.shape[0])])
        assert np.array_equal(np.asarray(parity), expect)


def test_sharded_split_bitexact_vs_single_device_and_oracle():
    """Odd batch sizes + uneven splits across 8 chips == 1 chip ==
    host oracle, bit for bit."""
    chan = ec_pipeline.PipelineChannel(
        key=("mc", "enc"), host_fn=_host_fn,
        device_fn=_ready_device_fn(), route=lambda n: True)
    sharded = ec_pipeline.EcDevicePipeline(depth=2, split_min=1,
                                           coalesce_wait=0.001)
    single = ec_pipeline.EcDevicePipeline(depth=2, split_min=1,
                                          coalesce_wait=0.001,
                                          device_shards=1)
    try:
        b8, r8 = _submit_odd_batches(sharded, chan)
        b1, r1 = _submit_odd_batches(single, chan)
        _assert_oracle(b8, r8)
        _assert_oracle(b1, r1)
        for (p8, (o8,)), (p1, (o1,)) in zip(r8, r1):
            assert np.array_equal(np.asarray(o8), np.asarray(o1))
        st8, st1 = sharded.stats(), single.stats()
        assert st8["dev_dispatches"] >= 1
        assert st8["active_devices"] == 8
        assert st1["active_devices"] == 1
        used = [d for d in st8["devices"].values()
                if d["dispatches"] > 0]
        assert len(used) >= 2, st8["devices"]
    finally:
        sharded.stop()
        single.stop()


def test_large_batch_splits_across_idle_lanes():
    """One coalesced mega-batch splits into per-chip shards (uneven
    row counts included) and reassembles in submit order."""
    chan = ec_pipeline.PipelineChannel(
        key=("mc", "split"), host_fn=_host_fn,
        device_fn=_ready_device_fn(), route=lambda n: True)
    pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=1,
                                        coalesce_wait=0.001)
    try:
        rng = np.random.default_rng(7)
        arr = rng.integers(0, 256, size=(13, K, L), dtype=np.uint8)
        path, (parity,) = pipe.submit(chan, arr).result(timeout=60)
        assert path == "dev"
        expect = np.stack([gf.encode_np(MATRIX, arr[b])
                           for b in range(13)])
        assert np.array_equal(np.asarray(parity), expect)
        st = pipe.stats()
        assert st["split_dispatches"] >= 1, st
        used = [d for d in st["devices"].values()
                if d["dispatches"] > 0]
        assert len(used) >= 2
    finally:
        pipe.stop()


def test_one_bad_chip_quarantines_lane_and_redrains():
    """A real device failure on one chip of eight: that lane
    quarantines, its batch redrains to surviving chips bit-exactly,
    and the channel owner's on_error (codec degrade) does NOT fire."""
    degraded = []
    errors: list = []
    chan = ec_pipeline.PipelineChannel(
        key=("mc", "bad1"), host_fn=_host_fn,
        device_fn=_ready_device_fn(bad_indices=(0,), errors=errors),
        route=lambda n: True,
        on_error=lambda e: degraded.append(e))
    pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=64,
                                        coalesce_wait=0.001)
    try:
        batches, results = _submit_odd_batches(pipe, chan)
        _assert_oracle(batches, results)
        st = pipe.stats()
        assert st["quarantines"] == 1, st
        assert st["devices"]["0"]["quarantined"]
        assert st["active_devices"] == 7
        assert st["redrained"] >= 1
        assert errors, "bad chip never probed"
        assert not degraded, "codec degraded despite 7 live chips"
        # the quarantined lane takes no further dispatches
        q_before = st["devices"]["0"]["dispatches"]
        more, res = _submit_odd_batches(pipe, chan, seed=1)
        _assert_oracle(more, res)
        assert pipe.stats()["devices"]["0"]["dispatches"] == q_before
    finally:
        pipe.stop()


def test_all_chips_quarantined_falls_back_to_host_and_degrades():
    """Host fallback ONLY when every chip is quarantined — and then
    the owner's on_error fires (the plugin degrade hook)."""
    degraded = []
    chan = ec_pipeline.PipelineChannel(
        key=("mc", "allbad"), host_fn=_host_fn,
        device_fn=_ready_device_fn(bad_indices=tuple(range(8))),
        route=lambda n: True,
        on_error=lambda e: degraded.append(e))
    pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=64,
                                        coalesce_wait=0.001)
    try:
        rng = np.random.default_rng(3)
        arr = rng.integers(0, 256, size=(5, K, L), dtype=np.uint8)
        path, (parity,) = pipe.submit(chan, arr).result(timeout=60)
        assert path == "host"
        expect = np.stack([gf.encode_np(MATRIX, arr[b])
                           for b in range(5)])
        assert np.array_equal(np.asarray(parity), expect)
        st = pipe.stats()
        assert st["active_devices"] == 0
        assert st["quarantines"] == 8
        assert degraded, "owner never heard the exhaustion"
    finally:
        pipe.stop()


def test_targeted_tpu_error_quarantines_without_codec_degrade():
    """Injected `tpu_error 1.0 <device>` through the PLUGIN path: the
    pipeline quarantines that chip's lane at placement time, results
    stay bit-exact, and the codec does NOT degrade."""
    pipe = ec_pipeline.get()
    pipe.reset_devices()
    codec = registry.factory("tpu", {"k": "2", "m": "1",
                                     "host_cutover": "1"})
    oracle = registry.factory("jerasure", {"k": "2", "m": "1"})
    faults.get().tpu_device_error(1.0, device="0")
    rng = np.random.default_rng(11)
    batches = [rng.integers(0, 256, size=(B, 2, 128), dtype=np.uint8)
               for B in (1, 3, 2, 5)]
    handles = [codec.encode_stripes_with_crcs_async(b)
               for b in batches]
    for arr, h in zip(batches, handles):
        allc, crcs = h.result(timeout=60)
        allc_o, crcs_o = oracle.encode_stripes_with_crcs(arr)
        assert np.array_equal(allc, allc_o)
        assert np.array_equal(crcs, crcs_o)
    assert not codec.degraded
    st = pipe.stats()
    assert st["quarantines"] >= 1
    assert st["devices"]["0"]["quarantined"]
    assert st["active_devices"] == 7


def test_untargeted_tpu_error_still_degrades_codec():
    """The PR 1/2 contract is unchanged: an untargeted device error
    degrades the whole codec to the host matrix-codec path."""
    codec = registry.factory("tpu", {"k": "2", "m": "1",
                                     "host_cutover": "1"})
    faults.get().tpu_device_error(1.0)
    rng = np.random.default_rng(13)
    stripes = rng.integers(0, 256, size=(3, 2, 128), dtype=np.uint8)
    allc, crcs = codec.encode_stripes_with_crcs(stripes)
    assert codec.degraded
    oracle = registry.factory("jerasure", {"k": "2", "m": "1"})
    allc_o, crcs_o = oracle.encode_stripes_with_crcs(stripes)
    assert np.array_equal(allc, allc_o)
    assert np.array_equal(crcs, crcs_o)


def test_reset_devices_clears_quarantine():
    chan = ec_pipeline.PipelineChannel(
        key=("mc", "reset"), host_fn=_host_fn,
        device_fn=_ready_device_fn(bad_indices=(1,)),
        route=lambda n: True)
    pipe = ec_pipeline.EcDevicePipeline(depth=1, split_min=64,
                                        coalesce_wait=0.001)
    try:
        # force a dispatch onto every lane until lane 1 trips
        deadline = time.time() + 30
        while time.time() < deadline:
            arrs, res = _submit_odd_batches(pipe, chan)
            _assert_oracle(arrs, res)
            if pipe.stats()["quarantines"]:
                break
        assert pipe.stats()["quarantines"] == 1
        pipe.reset_devices()
        st = pipe.stats()
        assert st["active_devices"] in (0, 8)   # rebuilt lazily
        arrs, res = _submit_odd_batches(pipe, chan, seed=2)
        _assert_oracle(arrs, res)
        assert pipe.stats()["active_devices"] >= 7
    finally:
        pipe.stop()
