"""Messenger tests: delivery, ordering, loopback, reconnect, injection."""

import queue
import threading
import time

import pytest

from ceph_tpu.msg import (Dispatcher, Message, Messenger, Policy,
                          register_message)
from ceph_tpu.utils.config import Config


@register_message
class MPing(Message):
    TYPE = 9001


@register_message
class MData(Message):
    TYPE = 9002


class QueueDispatcher(Dispatcher):
    def __init__(self):
        self.q: queue.Queue = queue.Queue()
        self.resets = []

    def ms_dispatch(self, conn, msg):
        self.q.put((conn, msg))
        return True

    def ms_handle_reset(self, conn):
        self.resets.append(conn)

    def get(self, timeout=5):
        return self.q.get(timeout=timeout)


def make_msgr(name, conf=None):
    m = Messenger(name, conf=conf)
    m.bind(("127.0.0.1", 0))
    disp = QueueDispatcher()
    m.add_dispatcher_tail(disp)
    m.start()
    return m, disp


class TestWire:
    def test_roundtrip_encoding(self):
        msg = MData(a=1, blob=b"\x00\xff" * 100, name="x")
        frame = msg.encode(seq=42)
        type_id, plen, seq = Message.parse_header(
            frame[: Message.header_size()])
        out = Message.decode(type_id, seq, frame[Message.header_size():])
        assert isinstance(out, MData)
        assert out.a == 1 and out.blob == b"\x00\xff" * 100
        assert out.seq == 42

    def test_unknown_type_raises(self):
        with pytest.raises(ValueError):
            Message.decode(55555, 0, b"")


class TestDelivery:
    def test_basic_send(self):
        a, _ = make_msgr("a")
        b, bd = make_msgr("b")
        try:
            a.send_message(MData(x=7), "b", b.addr)
            conn, msg = bd.get()
            assert msg.x == 7
            assert msg.src == "a"
            assert conn.peer_name == "a"
        finally:
            a.shutdown()
            b.shutdown()

    def test_reply_via_peer_addr(self):
        a, ad = make_msgr("a")
        b, bd = make_msgr("b")
        try:
            a.send_message(MPing(n=1), "b", b.addr)
            conn, msg = bd.get()
            # reply using the peer address learned from the banner
            b.send_message(MPing(n=2), conn.peer_name, conn.peer_addr)
            _, reply = ad.get()
            assert reply.n == 2 and reply.src == "b"
        finally:
            a.shutdown()
            b.shutdown()

    def test_ordering_many_messages(self):
        a, _ = make_msgr("a")
        b, bd = make_msgr("b")
        try:
            for i in range(200):
                a.send_message(MData(i=i), "b", b.addr)
            got = [bd.get()[1].i for _ in range(200)]
            assert got == list(range(200))
        finally:
            a.shutdown()
            b.shutdown()

    def test_loopback_fast_dispatch(self):
        a, ad = make_msgr("a")
        try:
            a.send_message(MPing(n=5), "a", a.addr)
            conn, msg = ad.get()
            assert msg.n == 5
            assert conn.peer_name == "a"
        finally:
            a.shutdown()

    def test_large_message(self):
        a, _ = make_msgr("a")
        b, bd = make_msgr("b")
        try:
            blob = bytes(range(256)) * 40000   # ~10 MB
            a.send_message(MData(blob=blob), "b", b.addr)
            _, msg = bd.get(timeout=15)
            assert msg.blob == blob
        finally:
            a.shutdown()
            b.shutdown()


class TestResilience:
    def test_lossless_reconnect_after_peer_restart(self):
        a, _ = make_msgr("a")
        b, bd = make_msgr("b")
        port = b.addr[1]
        try:
            a.send_message(MData(i=1), "b", b.addr)
            assert bd.get()[1].i == 1
            b.shutdown()
            # peer down: queue a message while unreachable (lossless
            # policy keeps it and retries with backoff)
            a.send_message(MData(i=2), "b", ("127.0.0.1", port))
            time.sleep(0.3)
            b2 = Messenger("b")
            b2.bind(("127.0.0.1", port))
            bd2 = QueueDispatcher()
            b2.add_dispatcher_tail(bd2)
            b2.start()
            _, msg = bd2.get(timeout=10)
            assert msg.i == 2
            b2.shutdown()
        finally:
            a.shutdown()

    def test_socket_failure_injection_still_delivers(self):
        conf = Config({"ms_inject_socket_failures": 10})
        a, _ = make_msgr("a", conf)
        b, bd = make_msgr("b")   # clean receiving side
        try:
            n = 100
            for i in range(n):
                a.send_message(MData(i=i), "b", b.addr)
            got = sorted(bd.get(timeout=20)[1].i for _ in range(n))
            assert got == list(range(n))
        finally:
            a.shutdown()
            b.shutdown()

    def test_sender_restart_fresh_seq_space_delivers(self):
        """A restarted peer (new incarnation nonce, seq restarts at 1)
        must not have its first frames dropped by the acceptor's stale
        in_seq from the previous incarnation."""
        b, bd = make_msgr("b")
        try:
            a1, _ = make_msgr("a")
            for i in range(5):
                a1.send_message(MData(i=i), "b", b.addr)
            for i in range(5):
                assert bd.get()[1].i == i
            a1.shutdown()        # acceptor-side conn "a" keeps in_seq=5
            a2, _ = make_msgr("a")   # restart: fresh nonce, seq from 1
            for i in range(10, 13):
                a2.send_message(MData(i=i), "b", b.addr)
            got = [bd.get()[1].i for _ in range(3)]
            assert got == [10, 11, 12]
            a2.shutdown()
        finally:
            b.shutdown()

    def test_undecodable_frame_skipped_link_survives(self):
        """A corrupt payload frame is dropped with an error, but the
        connection and subsequent frames keep flowing."""
        import socket
        import struct as _s

        from ceph_tpu.msg import messenger as msgr_mod
        from ceph_tpu.msg.message import _HDR, MAGIC

        b, bd = make_msgr("b")
        try:
            s = socket.create_connection(b.addr, timeout=5)
            name = b"evil"
            addr = msgr_mod._pack_addr(("127.0.0.1", 1))
            s.sendall(msgr_mod._BANNER.pack(
                msgr_mod.BANNER_MAGIC, 7, len(name), len(addr))
                + name + addr)
            rep = s.recv(msgr_mod._BANNER_REPLY.size)
            assert len(rep) == msgr_mod._BANNER_REPLY.size
            # frame 1: valid header, garbage payload
            garbage = b"\xfe\xfd\xfc"
            s.sendall(_HDR.pack(MAGIC, MData.TYPE, len(garbage), 1)
                      + garbage)
            # frame 2: a real message
            good = MData(i=99)
            good.src = "evil"
            s.sendall(good.encode(seq=2))
            _, msg = bd.get(timeout=5)
            assert msg.i == 99
            s.close()
        finally:
            b.shutdown()

    def test_lossy_client_reset_notifies(self):
        conf = Config()
        a, ad = make_msgr("a", conf)
        a.set_default_policy(Policy.lossy_client())
        try:
            # connect to a dead port: lossy -> reset, no retry loop
            a.send_message(MData(i=1), "dead", ("127.0.0.1", 1))
            deadline = time.time() + 5
            while time.time() < deadline and not ad.resets:
                time.sleep(0.05)
            assert ad.resets, "expected ms_handle_reset for lossy conn"
        finally:
            a.shutdown()
