"""MDS journaling (MDLog) + CephFS snapshots (VERDICT r3 #4).

MDLog: every metadata mutation journals one idempotent event to the
metadata pool before applying; dirty dir omaps flush lazily.  An MDS
killed before any flush must replay the journal on restart and
converge (mds/MDLog.cc + journal replay).

Snapshots: `mkdir d/.snap/name` freezes d's metadata subtree and
allocates a data-pool snapid; `d/.snap/name/...` reads resolve the
frozen tree with file data served at that snapid; snapshots are
read-only and removable (SnapServer/snaprealm reduced).
"""

import time

import pytest

from ceph_tpu.fs import CephFS, FsError
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


def _mount(cluster, name):
    rados = cluster.client(name)
    f = CephFS(rados)
    end = time.time() + 40
    while True:
        try:
            return f.mount(timeout=10.0)
        except FsError:
            if time.time() > end:
                raise
            cluster.tick(0.5)


class TestMdlogReplay:
    def test_kill_mid_burst_replay_converges(self, cluster):
        mds1 = cluster.start_mds("jr", metadata_pool="jr_meta",
                                 data_pool="jr_data")
        rados = cluster.client("client.jr")
        fs = CephFS(rados, data_pool="jr_data")
        end = time.time() + 40
        while True:
            try:
                fs.mount(timeout=10.0)
                break
            except FsError:
                if time.time() > end:
                    raise
                cluster.tick(0.5)
        # simulate dying before ANY omap flush: every mutation from
        # here on exists only in the journal
        mds1._flush_mdlog = lambda: None
        fs.mkdir("/burst")
        for i in range(25):
            with fs.open(f"/burst/f{i}", "w") as fh:
                fh.write(f"payload-{i}".encode())
        fs.mkdir("/burst/sub")
        fs.rename("/burst/f0", "/burst/sub/renamed")
        fs.unlink("/burst/f1")
        mds1.kill()                     # journaled, never flushed
        # a fresh MDS on the same pools must replay to convergence
        mds2 = cluster.start_mds("jr2", metadata_pool="jr_meta",
                                 data_pool="jr_data")
        fs2 = _mount_named(cluster, "client.jr2", "jr_meta", "jr_data")
        names = set(fs2.listdir("/burst"))
        assert "sub" in names
        assert "f1" not in names and "f0" not in names
        for i in range(2, 25):
            assert f"f{i}" in names
            with fs2.open(f"/burst/f{i}") as fh:
                assert fh.read() == f"payload-{i}".encode()
        assert fs2.listdir("/burst/sub") == ["renamed"]
        with fs2.open("/burst/sub/renamed") as fh:
            assert fh.read() == b"payload-0"
        mds2.shutdown()


def _mount_named(cluster, client, meta, data):
    rados = cluster.client(client)
    fs = CephFS(rados, data_pool=data)
    end = time.time() + 40
    while True:
        try:
            return fs.mount(timeout=10.0)
        except FsError:
            if time.time() > end:
                raise
            cluster.tick(0.5)


class TestSnapshots:
    @pytest.fixture(scope="class")
    def fs(self, cluster):
        cluster.start_mds("sn")
        return _mount(cluster, "client.snap")

    def test_snapshot_freezes_tree_and_data(self, fs):
        fs.mkdir("/d")
        with fs.open("/d/f", "w") as fh:
            fh.write(b"version-one")
        fs.mkdir("/d/sub")
        with fs.open("/d/sub/deep", "w") as fh:
            fh.write(b"deep-v1")
        fs.mkdir("/d/.snap/s1")
        # mutate AFTER the snapshot
        with fs.open("/d/f", "w") as fh:
            fh.write(b"version-TWO!")
        with fs.open("/d/g", "w") as fh:
            fh.write(b"new-file")
        fs.unlink("/d/sub/deep")
        # live tree reflects the mutations
        assert set(fs.listdir("/d")) >= {"f", "sub", "g"}
        with fs.open("/d/f") as fh:
            assert fh.read() == b"version-TWO!"
        # the snapshot is frozen: old names, old data
        snap_names = set(fs.listdir("/d/.snap/s1"))
        assert snap_names == {"f", "sub"}
        with fs.open("/d/.snap/s1/f") as fh:
            assert fh.read() == b"version-one"
        with fs.open("/d/.snap/s1/sub/deep") as fh:
            assert fh.read() == b"deep-v1"
        # .snap lists the snapshots
        assert "s1" in fs.listdir("/d/.snap")

    def test_snapshots_are_read_only(self, fs):
        with pytest.raises(FsError) as ei:
            fs.open("/d/.snap/s1/f", "w")
        assert ei.value.errno == 30
        with pytest.raises(FsError) as ei:
            fs.unlink("/d/.snap/s1/f")
        assert ei.value.errno == 30

    def test_second_snapshot_independent(self, fs):
        fs.mkdir("/d/.snap/s2")
        with fs.open("/d/f", "w") as fh:
            fh.write(b"version-3")
        with fs.open("/d/.snap/s1/f") as fh:
            assert fh.read() == b"version-one"
        with fs.open("/d/.snap/s2/f") as fh:
            assert fh.read() == b"version-TWO!"
        with fs.open("/d/f") as fh:
            assert fh.read() == b"version-3"

    def test_snapshot_remove(self, fs):
        fs.mkdir("/d/.snap/gone")
        assert "gone" in fs.listdir("/d/.snap")
        fs.rmdir("/d/.snap/gone")
        assert "gone" not in fs.listdir("/d/.snap")
        with pytest.raises(FsError):
            fs.open("/d/.snap/gone/f")

    def test_snapshot_survives_mds_restart(self, cluster, fs):
        """Snapshot registry + snapc persist: a fresh MDS serves the
        same frozen trees and hands clients the same snap context."""
        mds = cluster.mdss[-1]
        mds.shutdown()
        cluster.start_mds("sn2")
        fs2 = _mount(cluster, "client.snap2")
        with fs2.open("/d/.snap/s1/f") as fh:
            assert fh.read() == b"version-one"
        with fs2.open("/d/f") as fh:
            assert fh.read() == b"version-3"
