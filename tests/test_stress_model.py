"""Model-based randomized stress (test/osd/RadosModel.h + TestRados
analog): a seeded random op sequence runs against a live cluster while
a python dict models expected object state; every object is verified
against the model at checkpoints and at the end — under socket-failure
injection, so retries/resends/reconnects are part of the exercise.

This module once exposed a real wedge: an unexpected exception
escaping the acceptor's read loop abandoned the socket without closing
it, so the peer kept writing into a black hole past every retry.  The
messenger now closes sockets on ANY loop exit — keep the injection
rate aggressive so regressions of that class resurface here.
"""

import random
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster

OPS = ("write_full", "append", "write_at", "delete", "read_verify",
       "xattr", "snap_roundtrip")
# EC pools are append-only per object: no partial overwrites
EC_OPS = ("write_full", "append", "delete", "read_verify", "xattr")


def _retry(fn, what: str, window: float = 90.0):
    """Single-op timeouts under sustained injection are retried — the
    model asserts STATE correctness, and clients of a real cluster
    retry timed-out ops exactly like this (teuthology thrashing
    semantics).  Non-timeout errors propagate immediately."""
    end = time.time() + window
    while True:
        try:
            return fn()
        except RadosError as e:
            if e.errno != 110 or time.time() > end:
                raise RadosError(e.errno, f"{what}: {e}") from e
            time.sleep(0.5)


def run_model(io, cluster, seed: int, nops: int,
              snapshots: bool, ops=OPS,
              model: dict | None = None) -> dict:
    """Run `nops` seeded random ops, verifying against `model`.

    `model` carries expected object state ACROSS calls: a caller
    looping rounds against one live cluster MUST pass the previous
    round's return value back in — a fresh empty model would assert
    "absent" for every object the earlier rounds legitimately left
    behind (the old 0xFA57 soak flake: round 2's first read_verify of
    a round-1 survivor "failed" on a healthy cluster)."""
    rng = random.Random(seed)
    if model is None:
        model = {}
    oids = [f"m{i}" for i in range(12)]

    def verify(oid: str) -> None:
        expect = model.get(oid)
        if expect is None:
            with pytest.raises(RadosError):
                io.read(oid)
        else:
            got = _retry(lambda: io.read(oid), f"read {oid}")
            assert got == bytes(expect), \
                f"seed={seed} oid={oid} diverged"

    for step in range(nops):
        oid = rng.choice(oids)
        op = rng.choice(ops)
        if op == "write_full":
            data = rng.randbytes(rng.randrange(1, 8000))
            _retry(lambda: io.write_full(oid, data), f"wf {oid}")
            model[oid] = bytearray(data)
        elif op == "append":
            # append is NOT idempotent: a timed-out attempt may have
            # committed, so reconcile against the cluster instead of
            # blindly re-issuing (double-append would diverge)
            data = rng.randbytes(rng.randrange(1, 2000))
            expect = bytes(model.get(oid, bytearray())) + data
            try:
                io.append(oid, data)
            except RadosError as e:
                if e.errno != 110:
                    raise
                got = _retry(lambda: io.read(oid), f"reconcile {oid}")
                if got != expect:
                    _retry(lambda: io.write_full(oid, expect),
                           f"repair {oid}")
            model[oid] = bytearray(expect)
        elif op == "write_at":
            if oid not in model:
                continue
            off = rng.randrange(0, max(1, len(model[oid])))
            data = rng.randbytes(rng.randrange(1, 500))
            _retry(lambda: io.write(oid, data, offset=off),
                   f"write {oid}")
            buf = model[oid]
            if len(buf) < off + len(data):
                buf.extend(b"\x00" * (off + len(data) - len(buf)))
            buf[off: off + len(data)] = data
        elif op == "delete":
            if oid in model:
                try:
                    _retry(lambda: io.remove_object(oid), f"rm {oid}")
                except RadosError as e:
                    if e.errno != 2:
                        raise    # ENOENT = a timed-out try committed
                del model[oid]
        elif op == "read_verify":
            verify(oid)
        elif op == "xattr":
            if oid in model:
                val = rng.randbytes(16)
                _retry(lambda: io.set_xattr(oid, "stress", val),
                       f"xattr {oid}")
                assert _retry(lambda: io.get_xattr(oid, "stress"),
                              f"gx {oid}") == val
        elif op == "snap_roundtrip" and snapshots:
            if oid not in model:
                continue
            before = bytes(model[oid])
            snap = io.create_selfmanaged_snap()
            data = rng.randbytes(rng.randrange(1, 3000))
            _retry(lambda: io.write_full(oid, data), f"swf {oid}")
            model[oid] = bytearray(data)
            assert _retry(lambda: io.snap_read(oid, snap),
                          f"sr {oid}") == before
            io.remove_selfmanaged_snap(snap)
        if step % 5 == 4:
            # advance cluster (virtual) time: paxos/election watchdogs
            # and RPC timeouts need it to recover from injected drops
            cluster.tick(0.25)
        if step % 25 == 24:
            verify(rng.choice(oids))
    for oid in oids:
        verify(oid)
    return model


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
        "mon_osd_down_out_interval": 5.0,
        # 1-in-N sends drops its connection: resends/reconnects are
        # continuously exercised underneath the model
        "ms_inject_socket_failures": 400,
    })
    c = MiniCluster(num_mons=3, num_osds=3, conf=conf).start()
    yield c
    c.stop()


def _settle(rados, pool, **kw):
    ctx = None
    end = time.time() + 90     # new-pool peering under injection churn
    while True:
        try:
            if ctx is None:
                ctx = rados.open_ioctx(pool)
            ctx.write_full("settle", b"s")
            return ctx
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)


class TestRadosModel:
    def test_replicated_pool_random_ops(self, cluster):
        rados = cluster.client()
        rados.create_pool("model-rep", pg_num=8)
        io = _settle(rados, "model-rep")
        run_model(io, cluster, seed=0xC3F5, nops=220, snapshots=True)

    def test_ec_pool_random_ops(self, cluster):
        rados = cluster.client()
        rados.create_ec_pool("model-ec", "mk2m1",
                             {"plugin": "tpu", "k": 2, "m": 1})
        io = _settle(rados, "model-ec")
        run_model(io, cluster, seed=0xEC42, nops=150, snapshots=False,
                  ops=EC_OPS)

    def test_survives_osd_bounce_mid_stream(self, cluster):
        """Model correctness must hold across an OSD failure and
        recovery happening in the middle of the op stream."""
        rados = cluster.client()
        rados.create_pool("model-bounce", pg_num=8)
        io = _settle(rados, "model-bounce")
        rng = random.Random(7)
        model = {}
        for i in range(40):
            data = rng.randbytes(500)
            _retry(lambda: io.write_full(f"b{i}", data), f"b{i}")
            model[f"b{i}"] = data
        victim = 2
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim)
        end = time.time() + 30
        for i in range(40, 60):
            data = rng.randbytes(500)
            while True:
                try:
                    io.write_full(f"b{i}", data)
                    break
                except RadosError:
                    if time.time() > end:
                        raise
                    cluster.tick(0.3)
            model[f"b{i}"] = data
        cluster.start_osd(victim)
        cluster.wait_for_osds(3)
        for oid, expect in model.items():
            end = time.time() + 30
            while True:
                try:
                    assert io.read(oid) == expect, oid
                    break
                except RadosError:
                    if time.time() > end:
                        raise
                    cluster.tick(0.3)
