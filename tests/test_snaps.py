"""Self-managed snapshots on replicated pools.

make_writeable / SnapSet / SnapMapper semantics
(osd/ReplicatedPG.cc make_writeable, osd/SnapMapper.h:98): a write
under a newer snap context clones the head, snap reads resolve to the
covering clone, rollback restores the head from it, removal trims
clones cluster-wide.
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=3, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("snappool", pg_num=4)
    ctx = rados.open_ioctx("snappool")
    # first write can race pool creation; settle it here
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warmup", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


class TestSelfManagedSnaps:
    def test_snap_read_sees_old_state(self, cluster, io):
        io.write_full("obj", b"version-one")
        snap = io.create_selfmanaged_snap()
        io.write_full("obj", b"version-TWO!")
        assert io.read("obj") == b"version-TWO!"
        assert io.snap_read("obj", snap) == b"version-one"

    def test_multiple_snaps_layer(self, cluster, io):
        io.write_full("layers", b"aaa")
        s1 = io.create_selfmanaged_snap()
        io.write_full("layers", b"bbbb")
        s2 = io.create_selfmanaged_snap()
        io.write_full("layers", b"ccccc")
        assert io.snap_read("layers", s1) == b"aaa"
        assert io.snap_read("layers", s2) == b"bbbb"
        assert io.read("layers") == b"ccccc"

    def test_rollback(self, cluster, io):
        io.write_full("rb", b"keep-this")
        snap = io.create_selfmanaged_snap()
        io.write_full("rb", b"scribbled-over")
        io.snap_rollback("rb", snap)
        assert io.read("rb") == b"keep-this"

    def test_delete_head_keeps_clones(self, cluster, io):
        io.write_full("ghost", b"haunting")
        snap = io.create_selfmanaged_snap()
        io.remove_object("ghost")
        with pytest.raises(RadosError):
            io.read("ghost")
        assert io.snap_read("ghost", snap) == b"haunting"

    def test_snap_of_unmodified_object_reads_head(self, cluster, io):
        io.write_full("still", b"unchanged")
        snap = io.create_selfmanaged_snap()
        # no write after the snap: the head IS the snap state
        assert io.snap_read("still", snap) == b"unchanged"

    def test_shared_clone_survives_partial_snap_removal(self, cluster, io):
        """One clone can back several snaps (no writes between them):
        removing ONE of those snaps must not destroy the others."""
        io.write_full("shared", b"original!")
        s1 = io.create_selfmanaged_snap()
        s2 = io.create_selfmanaged_snap()     # no write between
        io.write_full("shared", b"rewritten")  # clone covers s1 AND s2
        assert io.snap_read("shared", s1) == b"original!"
        io.remove_selfmanaged_snap(s2)
        end = time.time() + 10
        while time.time() < end:
            cluster.tick(0.25)
        # s1 was never removed: its data must still resolve
        assert io.snap_read("shared", s1) == b"original!"
        with pytest.raises(RadosError):
            io.snap_read("shared", s2)

    def test_snap_of_nonexistent_object_enoent(self, cluster, io):
        """A snap taken while the object was deleted must read ENOENT
        even after the object is recreated."""
        io.write_full("phoenix", b"first life")
        s1 = io.create_selfmanaged_snap()
        io.remove_object("phoenix")
        s2 = io.create_selfmanaged_snap()     # object absent at s2
        io.write_full("phoenix", b"second life")
        assert io.snap_read("phoenix", s1) == b"first life"
        with pytest.raises(RadosError):
            io.snap_read("phoenix", s2)
        assert io.read("phoenix") == b"second life"

    def test_recovery_pushes_clones(self, cluster, io):
        """A rebuilt replica must receive snap clones along with heads
        — otherwise its SnapSet references objects it does not hold."""
        io.write_full("rec", b"past-state!")
        snap = io.create_selfmanaged_snap()
        io.write_full("rec", b"present-one")
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "rec")
        up, acting = m.pg_to_up_acting_osds(pgid)
        victim = acting[-1]
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim)
        cluster.start_osd(victim)
        cluster.wait_for_osds(3)
        from ceph_tpu.osd.pg import clone_oid
        cname = clone_oid("rec", snap)
        # recovery pushes ride bounded reservation slots behind every
        # other PG's peering/backfill after the restart — under a
        # loaded suite the push can land well past 30s, so give the
        # machinery a realistic window before declaring it broken
        end = time.time() + 120
        while time.time() < end:
            store = cluster.osds[victim].store
            if store.collection_exists(f"pg_{pgid}") and \
                    store.exists(f"pg_{pgid}", "rec") and \
                    store.exists(f"pg_{pgid}", cname):
                break
            cluster.tick(0.25)
        store = cluster.osds[victim].store
        assert store.exists(f"pg_{pgid}", "rec")
        assert store.exists(f"pg_{pgid}", cname), \
            "clone not pushed during recovery"
        assert io.snap_read("rec", snap) == b"past-state!"

    def test_snap_remove_trims_clones(self, cluster, io):
        io.write_full("trimme", b"old-state")
        snap = io.create_selfmanaged_snap()
        io.write_full("trimme", b"new-state")
        assert io.snap_read("trimme", snap) == b"old-state"
        io.remove_selfmanaged_snap(snap)
        # removed snap becomes unreadable once the map propagates
        end = time.time() + 40
        while time.time() < end:
            try:
                io.snap_read("trimme", snap)
            except RadosError:
                break
            cluster.tick(0.25)
        with pytest.raises(RadosError):
            io.snap_read("trimme", snap)
        assert io.read("trimme") == b"new-state"
        # the clone objects themselves get trimmed from the stores
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "trimme")
        end = time.time() + 40
        while time.time() < end:
            leftovers = [
                n for osd in cluster.osds.values()
                for n in (osd.store.collection_list(f"pg_{pgid}")
                          if osd.store.collection_exists(f"pg_{pgid}")
                          else [])
                if n.startswith("trimme@") and not n.endswith("@dir")]
            if not leftovers:
                break
            cluster.tick(0.25)
        assert not leftovers, leftovers
