"""HBM-resident EC stripe cache + zero-copy transfer plane contracts.

The tier-1 contracts pinned here:

  * accounting — stage/commit/lookup move the hit/miss/insert
    counters; an uncommitted (staged-only) entry never serves; a
    wrong-version lookup is a miss; LRU eviction keeps resident bytes
    within ``osd_ec_hbm_cache_bytes`` and recent touches survive;
  * store coherence — every applied store transaction is scanned:
    overwrite/append/truncate/remove/clone/move of a cached object's
    shard files invalidates the entry UNLESS the transaction attests
    the entry's exact version via the per-shard version xattr (the EC
    write fan-out landing the same content on more shards); a raw
    un-attested write (silent bitrot, test corruption) always
    invalidates, so a cache hit is as trustworthy as the disk read it
    replaces;
  * quarantine — a device failure drops the quarantined lane's
    entries (never serve from a chip in an unknown state) and the
    redrained work still resolves bit-exact vs the host oracle;
  * transfer plane — a warm device dispatch uploads exactly the
    padded data batch and reads back ONLY parity + CRCs (the
    bytes_h2d / bytes_d2h counters prove the no-data-echo identity);
  * cost-aware placement — measured per-(shape, chip) service-time
    EMAs override the least-loaded pick for a measured-faster lane,
    counted in cost_placements / cost_diverged; the knob off restores
    pure least-loaded.
"""

import numpy as np
import pytest

from ceph_tpu.ops import ec_kernels, gf, hbm_cache
from ceph_tpu.ops import pipeline as ec_pipeline
from ceph_tpu.ops.crc32c import crc32c_batch
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.objectstore import Transaction
from ceph_tpu.utils import faults

K, M, L = 3, 2, 256
MATRIX = gf.reed_sol_van_matrix(K, M)
VER_KEY = "_v"


@pytest.fixture(autouse=True)
def _clean():
    faults.get().reset(seed=0)
    hbm_cache.configure(64 << 20)
    hbm_cache.get().clear()
    yield
    faults.get().reset(seed=0)
    hbm_cache.get().clear()
    hbm_cache.configure(64 << 20)


def _entry_arrays(rng, S=2):
    data = rng.integers(0, 256, size=(S, K, L), dtype=np.uint8)
    parity = np.stack([gf.encode_np(MATRIX, data[s])
                       for s in range(S)])
    chunks = np.concatenate([data, parity], axis=1)
    crcs = np.stack([crc32c_batch(chunks[s]) for s in range(S)]) \
        .astype(np.uint32)
    return data, parity, crcs


def _stage_commit(cache, cid, oid, version, rng, S=2):
    data, parity, crcs = _entry_arrays(rng, S)
    intent = hbm_cache.CacheIntent(cid, oid, version, S * K * L, L)
    cache.stage(intent, 0, data, parity, crcs)
    assert cache.commit(cid, oid, version)
    return data, parity, crcs


class TestAccounting:
    def test_stage_commit_lookup_roundtrip(self):
        rng = np.random.default_rng(1)
        cache = hbm_cache.HbmStripeCache()
        data, parity, crcs = _stage_commit(cache, "pg_a", "obj",
                                           (1, 1), rng)
        ent = cache.lookup("pg_a", "obj", version=(1, 1))
        assert ent is not None
        assert ent.data_bytes() == data.tobytes()
        # per-shard fetch: data shards then parity shards
        for j in range(K):
            assert ent.shard_bytes(j) == data[:, j].tobytes()
        for j in range(M):
            assert ent.shard_bytes(K + j) == parity[:, j].tobytes()
        assert np.array_equal(ent.crcs, crcs)
        st = cache.stats()
        assert st["insert"] == 1 and st["hit"] == 1
        assert st["entries"] == 1 and st["pending"] == 0

    def test_staged_but_uncommitted_never_serves(self):
        rng = np.random.default_rng(2)
        cache = hbm_cache.HbmStripeCache()
        data, parity, crcs = _entry_arrays(rng)
        intent = hbm_cache.CacheIntent("pg_a", "obj", (1, 1),
                                       2 * K * L, L)
        cache.stage(intent, 0, data, parity, crcs)
        assert cache.lookup("pg_a", "obj") is None
        st = cache.stats()
        assert st["miss"] == 1 and st["hit"] == 0
        assert st["pending"] == 1 and st["entries"] == 0

    def test_wrong_version_lookup_misses(self):
        rng = np.random.default_rng(3)
        cache = hbm_cache.HbmStripeCache()
        _stage_commit(cache, "pg_a", "obj", (1, 1), rng)
        assert cache.lookup("pg_a", "obj", version=(1, 2)) is None
        assert cache.lookup("pg_a", "obj", version=(1, 1)) is not None

    def test_pending_entries_respect_byte_budget(self):
        """Staged-but-uncommitted entries pin device HBM exactly like
        committed ones: total resident bytes (committed + pending)
        must stay within capacity, oldest pending evicted first — an
        orphaned stage (producer died before commit) can't overcommit
        the chip."""
        rng = np.random.default_rng(6)
        one = _entry_arrays(rng)[0].nbytes * 2   # ~entry size bound
        cache = hbm_cache.HbmStripeCache(capacity=3 * one)
        for i in range(8):
            data, parity, crcs = _entry_arrays(rng)
            cache.stage(hbm_cache.CacheIntent("pg_a", f"o{i}", (1, i),
                                              2 * K * L, L),
                        0, data, parity, crcs)
            st = cache.stats()
            assert st["bytes"] + st["pending_bytes"] <= cache.capacity
        # newest pendings survived the budget, oldest were dropped
        assert cache.stats()["pending"] >= 1
        assert not cache.commit("pg_a", "o0", (1, 0))

    def test_configure_shrink_evicts_immediately(self):
        """Lowering osd_ec_hbm_cache_bytes at runtime takes effect at
        once — not at the next commit — so a read-only workload can't
        hold the old budget indefinitely."""
        rng = np.random.default_rng(7)
        cache = hbm_cache.configure(64 << 20)
        for i in range(4):
            _stage_commit(cache, "pg_a", f"o{i}", (1, i + 1), rng)
        big = cache.stats()["bytes"]
        assert big > 0
        hbm_cache.configure(big // 2)
        st = cache.stats()
        assert st["bytes"] + st["pending_bytes"] <= big // 2
        # most-recently-used survive
        assert cache.lookup("pg_a", "o3") is not None

    def test_drop_lane_spares_other_lanes_entries(self):
        """Regression: quarantining a lane drops only entries RESIDENT
        on that chip.  A rewrite's pending entry staged on the failed
        lane must not take down the same object's still-valid
        committed entry on a healthy chip (and vice versa)."""
        rng = np.random.default_rng(5)
        cache = hbm_cache.HbmStripeCache()
        data, parity, crcs = _entry_arrays(rng)
        intent = hbm_cache.CacheIntent("pg_a", "obj", (1, 1),
                                       2 * K * L, L)
        cache.stage(intent, 0, data, parity, crcs)
        assert cache.commit("pg_a", "obj", (1, 1))     # lane 0
        d2, p2, c2 = _entry_arrays(rng)
        cache.stage(hbm_cache.CacheIntent("pg_a", "obj", (1, 2),
                                          2 * K * L, L),
                    1, d2, p2, c2)                     # lane 1 pending
        cache.drop_lane(1)
        # committed lane-0 entry survives; the lane-1 pending is gone
        ent = cache.lookup("pg_a", "obj", version=(1, 1))
        assert ent is not None and ent.data_bytes() == data.tobytes()
        assert not cache.commit("pg_a", "obj", (1, 2))
        # reverse: pending on the healthy lane survives a committed
        # entry's lane failing, and can still commit
        cache.stage(hbm_cache.CacheIntent("pg_a", "obj", (1, 3),
                                          2 * K * L, L),
                    1, d2, p2, c2)
        cache.drop_lane(0)
        assert cache.lookup("pg_a", "obj", version=(1, 1)) is None
        assert cache.commit("pg_a", "obj", (1, 3))
        ent = cache.lookup("pg_a", "obj", version=(1, 3))
        assert ent is not None and ent.data_bytes() == d2.tobytes()

    def test_mesh_resident_entry_roundtrip_and_lane_membership(self):
        """A mesh dispatch stages entries addressed to EVERY member
        lane (tuple lane), with each chunk front-padded for even
        sharding: data_bytes/shard_bytes strip the pad, and losing ANY
        member chip drops the entry (a slice of the stripes lived
        there)."""
        rng = np.random.default_rng(21)
        cache = hbm_cache.HbmStripeCache()
        data, parity, crcs = _entry_arrays(rng)
        pad = 6
        pdata = np.zeros((2, K, L + pad), dtype=np.uint8)
        pdata[:, :, pad:] = data
        pparity = np.zeros((2, M, L + pad), dtype=np.uint8)
        pparity[:, :, pad:] = parity
        intent = hbm_cache.CacheIntent("pg_a", "obj", (1, 1),
                                       2 * K * L, L)
        cache.stage(intent, (0, 1, 2), pdata, pparity, crcs, pad=pad)
        assert cache.commit("pg_a", "obj", (1, 1))
        ent = cache.lookup("pg_a", "obj", version=(1, 1))
        assert ent is not None and ent.lane == (0, 1, 2)
        from ceph_tpu.utils import copyaudit
        c0 = copyaudit.snapshot()["sites"].get(
            "cache.mesh_unpad", {"copies": 0})["copies"]
        assert ent.data_bytes() == data.tobytes()
        # the pad-strip contiguous copy is a read-path
        # materialization and must be audited
        c1 = copyaudit.snapshot()["sites"].get(
            "cache.mesh_unpad", {"copies": 0})["copies"]
        assert c1 == c0 + 1
        for j in range(K):
            assert ent.shard_bytes(j) == data[:, j].tobytes()
        for j in range(M):
            assert ent.shard_bytes(K + j) == parity[:, j].tobytes()
        # a non-member lane's quarantine spares it...
        cache.drop_lane(5)
        assert cache.lookup("pg_a", "obj", version=(1, 1)) is not None
        # ...any member lane's quarantine drops it
        cache.drop_lane(1)
        assert cache.lookup("pg_a", "obj", version=(1, 1)) is None
        assert cache.stats()["lane_drops"] >= 1

    def test_mesh_entry_append_through_invalidates_conservatively(self):
        """append_through of a mesh-resident entry would need a
        cross-mesh reshard: it must invalidate (never serve a stale
        whole-object entry) and report False."""
        rng = np.random.default_rng(22)
        cache = hbm_cache.HbmStripeCache()
        data, parity, crcs = _entry_arrays(rng)
        intent = hbm_cache.CacheIntent("pg_a", "obj", (1, 1),
                                       2 * K * L, L)
        cache.stage(intent, (0, 1), data, parity, crcs)
        assert cache.commit("pg_a", "obj", (1, 1))
        tail_d, tail_p, tail_c = _entry_arrays(rng, S=1)
        assert not cache.append_through(
            "pg_a", "obj", (1, 1), (1, 2), 3 * K * L, L, 2,
            tail_d, tail_p, tail_c)
        assert cache.lookup("pg_a", "obj") is None

    def test_commit_wrong_version_rejected(self):
        rng = np.random.default_rng(4)
        cache = hbm_cache.HbmStripeCache()
        data, parity, crcs = _entry_arrays(rng)
        intent = hbm_cache.CacheIntent("pg_a", "obj", (1, 7),
                                       2 * K * L, L)
        cache.stage(intent, 0, data, parity, crcs)
        assert not cache.commit("pg_a", "obj", (1, 8))
        assert cache.lookup("pg_a", "obj") is None

    def test_lru_respects_capacity_and_recency(self):
        rng = np.random.default_rng(5)
        one = None
        cache = hbm_cache.HbmStripeCache(capacity=1)
        # discover one entry's footprint, then budget for exactly 3
        data, parity, crcs = _entry_arrays(rng)
        one = hbm_cache.CacheEntry(
            hbm_cache.CacheIntent("c", "o", (1, 1), 2 * K * L, L),
            0, data, parity, crcs).nbytes
        cache = hbm_cache.HbmStripeCache(capacity=3 * one)
        for i in range(3):
            _stage_commit(cache, "pg_a", f"obj{i}", (1, i + 1), rng)
        # touch obj0 so obj1 is the LRU victim of the next insert
        assert cache.lookup("pg_a", "obj0") is not None
        _stage_commit(cache, "pg_a", "obj3", (1, 4), rng)
        st = cache.stats()
        assert st["bytes"] <= 3 * one
        assert st["evict"] == 1
        assert cache.lookup("pg_a", "obj1") is None      # evicted
        assert cache.lookup("pg_a", "obj0") is not None  # survived
        assert cache.lookup("pg_a", "obj3") is not None

    def test_oversized_entry_never_stages(self):
        rng = np.random.default_rng(6)
        cache = hbm_cache.HbmStripeCache(capacity=16)
        data, parity, crcs = _entry_arrays(rng)
        intent = hbm_cache.CacheIntent("pg_a", "big", (1, 1),
                                       2 * K * L, L)
        cache.stage(intent, 0, data, parity, crcs)
        assert not cache.commit("pg_a", "big", (1, 1))
        assert cache.stats()["entries"] == 0

    def test_zero_capacity_disables(self):
        rng = np.random.default_rng(7)
        cache = hbm_cache.HbmStripeCache(capacity=0)
        data, parity, crcs = _entry_arrays(rng)
        cache.stage(hbm_cache.CacheIntent("pg_a", "o", (1, 1),
                                          2 * K * L, L),
                    0, data, parity, crcs)
        assert not cache.commit("pg_a", "o", (1, 1))
        assert cache.stats()["entries"] == 0


class TestStoreCoherence:
    """The object-store hook: every applied transaction is scanned and
    un-attested shard-data mutations invalidate (module docstring of
    ops/hbm_cache.py)."""

    def _cached(self, store, cid="pg_c", oid="victim",
                version=(1, 1)):
        rng = np.random.default_rng(11)
        cache = hbm_cache.get()
        data, _p, _c = _stage_commit(cache, cid, oid, version, rng)
        # the shard files the store holds (content irrelevant to the
        # scan — only the op names matter)
        store.apply_transaction(Transaction().create_collection(cid))
        txn = Transaction()
        for j in range(K + M):
            txn.write(cid, f"{oid}.s{j}", 0, b"shardbytes")
            txn.setattr(cid, f"{oid}.s{j}", VER_KEY,
                        repr(tuple(version)).encode())
        store.apply_transaction(txn)
        # the versioned shard landing did NOT invalidate (attested)
        assert cache.lookup(cid, oid, version=version) is not None
        return cache

    @pytest.mark.parametrize("mutate", [
        lambda t: t.write("pg_c", "victim.s1", 2, b"\xbe\xef"),
        lambda t: t.write("pg_c", "victim.s0", 4096, b"tail"),
        lambda t: t.truncate("pg_c", "victim.s2", 1),
        lambda t: t.zero("pg_c", "victim.s1", 0, 4),
        lambda t: t.remove("pg_c", "victim.s3"),
        lambda t: t.clone("pg_c", "victim.s0", "victim.s1"),
        lambda t: t.collection_move_rename("pg_c", "victim.s0",
                                           "pg_c", "stash"),
    ], ids=["overwrite", "append", "truncate", "zero", "remove",
            "clone-onto", "move-away"])
    def test_unattested_mutation_invalidates(self, mutate):
        store = MemStore()
        cache = self._cached(store)
        inval0 = cache.stats()["invalidate"]
        txn = Transaction()
        mutate(txn)
        store.apply_transaction(txn)
        assert cache.lookup("pg_c", "victim") is None
        assert cache.stats()["invalidate"] == inval0 + 1

    def test_same_version_fanout_keeps_entry(self):
        """A peer sub-write / recovery push of the SAME version is the
        cached content landing on more shards — attested, kept."""
        store = MemStore()
        cache = self._cached(store, version=(1, 5))
        txn = Transaction()
        txn.write("pg_c", "victim.s2", 0, b"same content")
        txn.setattr("pg_c", "victim.s2", VER_KEY,
                    repr((1, 5)).encode())
        store.apply_transaction(txn)
        assert cache.lookup("pg_c", "victim",
                            version=(1, 5)) is not None

    def test_newer_version_write_invalidates(self):
        store = MemStore()
        cache = self._cached(store, version=(1, 5))
        txn = Transaction()
        txn.write("pg_c", "victim.s2", 0, b"new content")
        txn.setattr("pg_c", "victim.s2", VER_KEY,
                    repr((1, 6)).encode())
        store.apply_transaction(txn)
        assert cache.lookup("pg_c", "victim") is None

    def test_rewrite_keeps_attested_fresh_pending(self):
        """Regression: a rewrite of a cached object stages a fresh
        pending entry at the new version, then its store txn applies
        attesting that version.  The scan must judge committed and
        pending INDEPENDENTLY — drop the stale committed entry but
        keep the attested pending one, so the rewrite's commit lands
        and hot objects stay covered write after write (the old
        keep-condition dropped both, losing coverage on every other
        rewrite)."""
        store = MemStore()
        cache = self._cached(store, version=(1, 1))
        rng = np.random.default_rng(12)
        data, parity, crcs = _entry_arrays(rng)
        cache.stage(hbm_cache.CacheIntent("pg_c", "victim", (1, 2),
                                          2 * K * L, L),
                    0, data, parity, crcs)
        txn = Transaction()
        for j in range(K + M):
            txn.write("pg_c", f"victim.s{j}", 0, b"new bytes")
            txn.setattr("pg_c", f"victim.s{j}", VER_KEY,
                        repr((1, 2)).encode())
        store.apply_transaction(txn)
        # stale committed entry gone, fresh pending commits and serves
        assert cache.lookup("pg_c", "victim", version=(1, 1)) is None
        assert cache.commit("pg_c", "victim", (1, 2))
        ent = cache.lookup("pg_c", "victim", version=(1, 2))
        assert ent is not None and ent.data_bytes() == data.tobytes()

    def test_stash_ops_do_not_invalidate(self):
        """Rollback-stash traffic is NOT a shard mutation: the EC
        write path stashes the prior object and later trims acked
        stashes — neither changes current shard bytes (a write would
        otherwise self-invalidate at stash-trim time).  A stash
        RESTORE writes to the shard file itself and still
        invalidates."""
        store = MemStore()
        cache = self._cached(store)
        stash = "victim.s0@(1, 0)"
        txn = Transaction()
        txn.try_clone("pg_c", "victim.s0", stash)
        store.apply_transaction(txn)
        assert cache.lookup("pg_c", "victim") is not None
        store.apply_transaction(Transaction().try_remove("pg_c", stash))
        assert cache.lookup("pg_c", "victim") is not None
        # the restore direction targets the shard file: invalidates
        txn = Transaction()
        txn.write("pg_c", stash, 0, b"old bytes")
        store.apply_transaction(txn)
        assert cache.lookup("pg_c", "victim") is not None
        store.apply_transaction(
            Transaction().clone("pg_c", stash, "victim.s0"))
        assert cache.lookup("pg_c", "victim") is None

    def test_rmcoll_drops_whole_collection(self):
        store = MemStore()
        cache = self._cached(store)
        store.apply_transaction(Transaction().remove_collection("pg_c"))
        assert cache.lookup("pg_c", "victim") is None

    def test_unrelated_objects_and_collections_unaffected(self):
        store = MemStore()
        cache = self._cached(store)
        store.apply_transaction(Transaction().create_collection("pg_z"))
        txn = Transaction()
        txn.write("pg_c", "bystander.s1", 0, b"x")
        txn.write("pg_z", "victim.s1", 0, b"x")
        store.apply_transaction(txn)
        assert cache.lookup("pg_c", "victim") is not None


def _fused_channel(bad_indices=(), key=("hbm", "enc")):
    """An always-warm fused encode+CRC channel (CPU jit compiles
    inline) whose device fn blows up like a dead chip on the listed
    jax device ids."""
    fused = ec_kernels.make_encode_crc_fn(MATRIX, L)

    def device_fn(padded, device=None):
        if device is not None and device.id in bad_indices:
            raise RuntimeError(f"chip {device.id} down")
        return fused(padded)

    def host_fn(batch):
        parity = np.stack([gf.encode_np(MATRIX, batch[s])
                           for s in range(batch.shape[0])])
        chunks = np.concatenate([batch, parity], axis=1)
        crcs = np.stack([crc32c_batch(chunks[s])
                         for s in range(batch.shape[0])])
        return parity, crcs.astype(np.uint32)

    return ec_pipeline.PipelineChannel(
        key=key, host_fn=host_fn, device_fn=device_fn,
        route=lambda n: True)


class TestPipelineIntegration:
    def test_encode_stages_entry_and_counts_transfer(self):
        """A cache-tagged device encode leaves its stripes in HBM
        (slices of the uploaded input + computed parity — zero extra
        transfer) and the lane counters account exactly the padded
        upload and the parity+CRC readback."""
        chan = _fused_channel()
        pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=64,
                                            coalesce_wait=0.001)
        cache = hbm_cache.get()
        rng = np.random.default_rng(21)
        try:
            data = rng.integers(0, 256, size=(2, K, L),
                                dtype=np.uint8)
            intent = hbm_cache.CacheIntent("pg_p", "obj", (3, 9),
                                           2 * K * L, L)
            st0 = pipe.stats()
            path, (parity, crcs) = pipe.submit(
                chan, data, cache=intent).result(timeout=60)
            assert path == "dev"
            st1 = pipe.stats()
            # transfer identity: upload == padded data batch, readback
            # == parity + CRC vector only (no data-shard echo)
            S_pad = ec_pipeline.next_bucket(2)
            assert st1["bytes_h2d"] - st0["bytes_h2d"] == \
                S_pad * K * L
            assert st1["bytes_d2h"] - st0["bytes_d2h"] == \
                ec_kernels.encode_readback_bytes(S_pad, K, M, L)
            # entry staged by the collector, serves after commit
            assert cache.commit("pg_p", "obj", (3, 9))
            ent = cache.lookup("pg_p", "obj", version=(3, 9))
            assert ent is not None
            assert ent.data_bytes() == data.tobytes()
            expect_parity = np.stack([gf.encode_np(MATRIX, data[s])
                                      for s in range(2)])
            for j in range(M):
                assert ent.shard_bytes(K + j) == \
                    expect_parity[:, j].tobytes()
            assert np.array_equal(ent.crcs, np.asarray(crcs))
            # cached reads are D2H-only: pipeline h2d must not move
            st2 = pipe.stats()
            assert st2["bytes_h2d"] == st1["bytes_h2d"]
        finally:
            pipe.stop()

    def test_split_sized_tagged_batch_still_stages(self):
        """Regression (caught by the live-cluster drive): a cache-
        tagged batch big enough for the idle-lane splitter must still
        stage — row-split group parts can't stage (an item's rows
        straddle lanes), so placement cuts tagged batches at ITEM
        boundaries only; a single-item batch rides whole on one lane.
        Before the fix, 64 KiB objects never cached: every encode
        split across two idle lanes and the cache stayed empty."""
        chan = _fused_channel(key=("hbm", "split"))
        pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=1,
                                            coalesce_wait=0.001)
        cache = hbm_cache.get()
        rng = np.random.default_rng(23)
        try:
            S = 8      # untagged, this splits across the 8 idle lanes
            data = rng.integers(0, 256, size=(S, K, L),
                                dtype=np.uint8)
            intent = hbm_cache.CacheIntent("pg_s", "obj", (5, 1),
                                           S * K * L, L)
            path, _ = pipe.submit(chan, data,
                                  cache=intent).result(timeout=60)
            assert path == "dev"
            assert cache.commit("pg_s", "obj", (5, 1))
            ent = cache.lookup("pg_s", "obj", version=(5, 1))
            assert ent is not None
            assert ent.data_bytes() == data.tobytes()
            expect = np.stack([gf.encode_np(MATRIX, data[s])
                               for s in range(S)])
            for j in range(M):
                assert ent.shard_bytes(K + j) == \
                    expect[:, j].tobytes()
            # two tagged items in flight together (item-aligned split
            # or separate dispatches — either way BOTH must stage,
            # each whole on its own lane)
            d2 = [rng.integers(0, 256, size=(4, K, L), dtype=np.uint8)
                  for _ in range(2)]
            futs = [pipe.submit(chan, d2[i],
                                cache=hbm_cache.CacheIntent(
                                    "pg_s", f"o{i}", (5, 2 + i),
                                    4 * K * L, L))
                    for i in range(2)]
            for f in futs:
                f.result(timeout=60)
            for i in range(2):
                assert cache.commit("pg_s", f"o{i}", (5, 2 + i))
                e = cache.lookup("pg_s", f"o{i}")
                assert e is not None and \
                    e.data_bytes() == d2[i].tobytes()
        finally:
            pipe.stop()

    def test_quarantine_drops_lane_entries_and_redrains_bitexact(self):
        """A device failure on the chip holding cached entries drops
        them (redrain re-uploads from host, never serves stale HBM)
        and the redrained work still matches the host oracle."""
        cache = hbm_cache.get()
        warm = _fused_channel(key=("hbm", "warm"))
        pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=64,
                                            coalesce_wait=0.001)
        rng = np.random.default_rng(22)
        try:
            data = rng.integers(0, 256, size=(1, K, L),
                                dtype=np.uint8)
            intent = hbm_cache.CacheIntent("pg_q", "obj", (1, 1),
                                           K * L, L)
            path, _ = pipe.submit(warm, data,
                                  cache=intent).result(timeout=60)
            assert path == "dev"
            assert cache.commit("pg_q", "obj", (1, 1))
            ent = cache.lookup("pg_q", "obj")
            assert ent is not None
            victim_lane = ent.lane
            victim_dev = pipe._ensure_devset().lanes[victim_lane] \
                .device
            # every dispatch on the victim chip now dies; keep
            # submitting until placement lands one there
            bad = _fused_channel(bad_indices={victim_dev.id},
                                 key=("hbm", "bad"))
            drops0 = cache.stats()["lane_drops"]
            batches, results = [], []
            for i in range(32):
                b = rng.integers(0, 256, size=(1, K, L),
                                 dtype=np.uint8)
                batches.append(b)
                # sequential submit+wait: the placement rotation
                # visits every lane within 8 whole-batch dispatches,
                # so the victim chip is hit deterministically
                results.append(pipe.submit(bad, b).result(timeout=60))
                if pipe.stats()["quarantines"]:
                    break
            st = pipe.stats()
            assert st["quarantines"] >= 1, st
            # redrained results: bit-exact vs the host oracle
            for b, (_path, (parity, crcs)) in zip(batches, results):
                expect = np.stack([gf.encode_np(MATRIX, b[s])
                                   for s in range(b.shape[0])])
                assert np.array_equal(np.asarray(parity), expect)
            # the quarantined lane's entries are GONE
            assert cache.lookup("pg_q", "obj") is None
            assert cache.stats()["lane_drops"] > drops0
        finally:
            pipe.stop()


class TestCostAwarePlacement:
    def _seed_emas(self, pipe, nbytes, fast_lane=0,
                   fast=1e-9, slow=1e-3):
        ds = pipe._ensure_devset()
        bucket = (max(nbytes, 1) - 1).bit_length()
        for lane in ds.lanes:
            lane.spb[bucket] = {
                "spb": fast if lane.index == fast_lane else slow,
                "n": 5}
        return ds

    def test_measured_faster_lane_overrides_least_loaded(self):
        chan = _fused_channel(key=("hbm", "cost"))
        pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=64,
                                            coalesce_wait=0.0,
                                            cost_aware=True)
        rng = np.random.default_rng(31)
        try:
            # warm the fn on every lane the rotation visits first
            for _ in range(8):
                pipe.submit(chan, rng.integers(
                    0, 256, size=(1, K, L),
                    dtype=np.uint8)).result(timeout=60)
            ds = self._seed_emas(pipe, K * L, fast_lane=0)
            st0 = pipe.stats()
            d0 = {i: l.dispatches for i, l in enumerate(ds.lanes)}
            for _ in range(8):
                pipe.submit(chan, rng.integers(
                    0, 256, size=(1, K, L),
                    dtype=np.uint8)).result(timeout=60)
            st1 = pipe.stats()
            assert st1["cost_placements"] > st0["cost_placements"]
            # the rotation's least-loaded pick visits every lane; the
            # measured-cost override must have redirected to lane 0
            assert st1["cost_diverged"] > st0["cost_diverged"]
            gained = {i: l.dispatches - d0[i]
                      for i, l in enumerate(ds.lanes)}
            assert gained[0] == 8, gained
        finally:
            pipe.stop()

    def test_knob_off_restores_least_loaded(self):
        chan = _fused_channel(key=("hbm", "nocost"))
        pipe = ec_pipeline.EcDevicePipeline(depth=2, split_min=64,
                                            coalesce_wait=0.0,
                                            cost_aware=False)
        rng = np.random.default_rng(32)
        try:
            for _ in range(4):
                pipe.submit(chan, rng.integers(
                    0, 256, size=(1, K, L),
                    dtype=np.uint8)).result(timeout=60)
            self._seed_emas(pipe, K * L, fast_lane=0)
            for _ in range(8):
                pipe.submit(chan, rng.integers(
                    0, 256, size=(1, K, L),
                    dtype=np.uint8)).result(timeout=60)
            st = pipe.stats()
            assert st["cost_aware"] is False
            assert st["cost_placements"] == 0
            assert st["cost_diverged"] == 0
        finally:
            pipe.stop()

    def test_perf_dump_carries_cache_and_transfer_counters(self):
        """The observability contract bench/operators rely on: the
        shared pipeline's stats carry the transfer + cache counter
        set."""
        st = ec_pipeline.stats()
        for key in ("bytes_h2d", "bytes_d2h", "cost_placements",
                    "cost_diverged", "cache_hit", "cache_miss",
                    "cache_evict", "cache_insert", "cache_invalidate",
                    "cache_lane_drops", "cache_bytes",
                    "cache_capacity", "cache_entries"):
            assert key in st, key
