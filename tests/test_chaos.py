"""Cluster-level fault injection through the FaultSet registry.

Tier-1 scenarios are deterministic: a partition blocks real traffic
and ops fail with the DEFINED errno (ETIMEDOUT) then heal; a k=8,m=3
EC pool keeps serving reads with one and two shard OSDs down
(reconstruction from any k live shards); an injected TPU device error
degrades the tpu plugin to the matrix-codec fallback with a cluster
health warning instead of an op error.

The seeded chaos soak (@slow) runs the existing stress model
(tests/test_stress_model.run_model) under a randomized fault schedule
— partitions + targeted EIO + socket kills — and asserts zero data
loss with every op acked or failed with a defined errno; the schedule
derives purely from one seed, so a failure's printed seed reproduces
the identical fault sequence.
"""

import threading
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.client.objecter import ETIMEDOUT, ObjecterError
from ceph_tpu.utils import faults
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster

CONF = {
    "mon_tick_interval": 0.5,
    "osd_heartbeat_interval": 0.5,
    "osd_heartbeat_grace": 8.0,
    "mon_osd_min_down_reporters": 2,
    "mon_osd_down_out_interval": 5.0,
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)


def _settle(io, oid="settle", window=60.0):
    end = time.time() + window
    while True:
        try:
            io.write_full(oid, b"s")
            return
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)


class TestPartition:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = MiniCluster(num_mons=1, num_osds=3,
                        conf=Config(dict(CONF))).start()
        yield c
        c.stop()

    def test_partition_times_out_with_defined_errno_then_heals(
            self, cluster):
        """The tier-1 deterministic partition scenario: client<->osd
        traffic blocked -> the op fails with ETIMEDOUT (110), never
        hangs; after the heal the SAME op path succeeds again."""
        rados = cluster.client()
        rados.create_pool("chaos-part", pg_num=4)
        io = rados.open_ioctx("chaos-part")
        _settle(io)
        # install through a live OSD's admin socket — the operator
        # surface, not just the python API
        out = cluster.osds[0].asok.execute(
            {"prefix": "faults install",
             "rules": "partition client.* osd.*"})
        assert out["installed"]
        t0 = time.time()
        with pytest.raises(ObjecterError) as ei:
            rados.objecter.op_submit(io.pool_id, "blocked",
                                     [("writefull", b"x")], timeout=3.0)
        assert ei.value.errno == ETIMEDOUT
        assert time.time() - t0 < 20      # bounded, not hung
        cluster.osds[0].asok.execute({"prefix": "faults clear"})
        _settle(io, oid="healed")
        assert io.read("healed") == b"s"

    def test_resend_after_heal_completes_inflight_op(self, cluster):
        """An op submitted DURING the partition must survive it: the
        objecter's backoff resend picks up after the heal within the
        op's deadline (no lost op, no duplicate effect)."""
        rados = cluster.client()
        io = rados.open_ioctx("chaos-part")
        _settle(io)
        faults.get().partition("client.*", "osd.*")
        result = {}

        def submit():
            try:
                result["reply"] = rados.objecter.op_submit(
                    io.pool_id, "inflight", [("writefull", b"survived")],
                    timeout=30.0)
            except Exception as e:        # pragma: no cover
                result["error"] = e

        th = threading.Thread(target=submit)
        th.start()
        time.sleep(1.5)                   # op is resending into the wall
        assert "reply" not in result
        faults.get().clear()
        th.join(timeout=60)
        assert not th.is_alive()
        assert "error" not in result, result.get("error")
        assert result["reply"].result == 0
        assert io.read("inflight") == b"survived"

    def test_osd_pair_partition_recovers_replicated_writes(
            self, cluster):
        """Partitioning two OSDs from each other (client unaffected)
        stalls sub-op gathers; the primary's resend machinery must
        complete the write after the heal."""
        rados = cluster.client()
        io = rados.open_ioctx("chaos-part")
        _settle(io)
        rid = faults.get().partition("osd.1", "osd.2")
        t = threading.Timer(2.0, lambda: faults.get().clear(rid))
        t.start()
        try:
            end = time.time() + 60
            for i in range(8):
                while True:
                    try:
                        io.write_full(f"pp{i}", b"v" * 128)
                        break
                    except RadosError as e:
                        assert e.errno == ETIMEDOUT, e
                        if time.time() > end:
                            raise
                        cluster.tick(0.3)
        finally:
            t.cancel()
            faults.get().clear()
        for i in range(8):
            assert io.read(f"pp{i}") == b"v" * 128


class TestECDegradedRead:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = MiniCluster(num_mons=1, num_osds=13,
                        conf=Config(dict(CONF))).start()
        yield c
        c.stop()

    def test_k8m3_reads_survive_one_then_two_shards_down(self, cluster):
        rados = cluster.client()
        rados.create_ec_pool("ec83", "k8m3",
                             {"plugin": "tpu", "k": 8, "m": 3,
                              "technique": "reed_sol_van"}, pg_num=1)
        io = rados.open_ioctx("ec83")
        _settle(io, window=90.0)
        payload = bytes(range(256)) * 500          # ~4 stripes
        io.write_full("big", payload)
        assert io.read("big") == payload
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "big")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary = acting[0]
        victims = [o for o in acting[1:] if o >= 0][:2]
        assert len(victims) == 2, f"thin acting set {acting}"

        def read_back(window=90.0):
            end = time.time() + window
            while True:
                try:
                    return io.read("big")
                except RadosError:
                    if time.time() > end:
                        raise
                    cluster.tick(0.3)

        # one shard down: reconstruction from the remaining >= k
        cluster.kill_osd(victims[0])
        cluster.wait_for_osd_down(victims[0], timeout=60)
        assert read_back() == payload, "read failed with 1 shard down"
        # two shards down: still >= k live (m=3 tolerates it)
        cluster.kill_osd(victims[1])
        cluster.wait_for_osd_down(victims[1], timeout=60)
        assert read_back() == payload, "read failed with 2 shards down"
        assert primary not in victims    # reads went via reconstruction


class TestTpuDeviceErrorFallback:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = MiniCluster(num_mons=1, num_osds=3,
                        conf=Config(dict(CONF))).start()
        yield c
        c.stop()

    def test_injected_device_error_degrades_with_health_warning(
            self, cluster):
        rados = cluster.client()
        rados.create_ec_pool("ec-tpu", "dk2m1",
                             {"plugin": "tpu", "k": 2, "m": 1},
                             pg_num=2)
        io = rados.open_ioctx("ec-tpu")
        _settle(io)
        io.write_full("pre", b"before-fault" * 100)
        faults.get().tpu_device_error(1.0)
        # writes and reads keep SUCCEEDING: the plugin degrades to the
        # matrix-codec host path instead of failing the op
        end = time.time() + 60
        while True:
            try:
                io.write_full("post", b"during-fault" * 100)
                break
            except RadosError:
                if time.time() > end:
                    raise
                cluster.tick(0.3)
        assert io.read("post") == b"during-fault" * 100
        assert io.read("pre") == b"before-fault" * 100
        degraded = [o for o in cluster.osds.values()
                    if any(getattr(c, "degraded", False)
                           for c in o._ec_codecs.values())]
        assert degraded, "no codec degraded despite injected error"
        # ... and it surfaces as a cluster health warning
        end = time.time() + 60
        while True:
            rv, out, _ = rados.mon_command({"prefix": "health"})
            assert rv == 0
            if "EC device degraded" in out and "HEALTH_WARN" in out:
                break
            if time.time() > end:
                raise AssertionError(f"no degrade warning:\n{out}")
            cluster.tick(0.5)
            time.sleep(0.05)


class TestTpuSingleChipQuarantine:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = MiniCluster(num_mons=1, num_osds=3,
                        conf=Config(dict(CONF))).start()
        yield c
        c.stop()

    def test_one_chip_of_eight_quarantines_and_redrains(self, cluster):
        """An injected tpu_error targeted at ONE device index of the
        8-chip mesh: that chip's pipeline lane quarantines, its work
        redrains to the surviving chips (writes keep succeeding,
        bytes bit-exact), the codec does NOT degrade to the host
        matrix path, and the partial-fleet state surfaces as a
        HEALTH_WARN naming the quarantined chip count."""
        from ceph_tpu.ops import pipeline as ec_pipeline

        pipe = ec_pipeline.get()
        pipe.reset_devices()
        rados = cluster.client()
        # host_cutover=1 forces device routing so the placement path
        # (and with it the per-lane fault roll) actually runs
        rados.create_ec_pool("ec-mchip", "mck2m1",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "host_cutover": "1"}, pg_num=2)
        io = rados.open_ioctx("ec-mchip")
        _settle(io)
        io.write_full("pre", b"before-chip-fault" * 100)
        # the operator surface: a device-index-targeted rule
        out = cluster.osds[0].asok.execute(
            {"prefix": "faults install", "rules": "tpu_error 1.0 0"})
        assert out["installed"]
        try:
            end = time.time() + 60
            while True:
                try:
                    io.write_full("post", b"during-chip-fault" * 100)
                    break
                except RadosError:
                    if time.time() > end:
                        raise
                    cluster.tick(0.3)
            assert io.read("post") == b"during-chip-fault" * 100
            assert io.read("pre") == b"before-chip-fault" * 100
            stats = ec_pipeline.stats()
            assert stats["quarantines"] >= 1, stats
            assert stats["devices"]["0"]["quarantined"], stats
            assert stats["active_devices"] == 7, stats
            # single-chip failure must NOT degrade any codec: seven
            # chips survive and the host matrix fallback is reserved
            # for full-fleet loss
            degraded = [o for o in cluster.osds.values()
                        if any(getattr(c, "degraded", False)
                               for c in o._ec_codecs.values())]
            assert not degraded, "codec degraded on a 1/8 chip fault"
            # ... and the partial-fleet degrade surfaces in health
            end = time.time() + 60
            while True:
                rv, hout, _ = rados.mon_command({"prefix": "health"})
                assert rv == 0
                if "devices quarantined" in hout and \
                        "HEALTH_WARN" in hout and "1/8" in hout:
                    break
                if time.time() > end:
                    raise AssertionError(
                        f"no quarantine warning:\n{hout}")
                cluster.tick(0.5)
                time.sleep(0.05)
        finally:
            cluster.osds[0].asok.execute({"prefix": "faults clear"})
            pipe.reset_devices()
        # healed fleet: writes still flow and the lane is back
        _settle(io, oid="healed-mc")
        assert io.read("healed-mc") == b"s"


class TestHbmCacheScrubFault:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = MiniCluster(num_mons=1, num_osds=3,
                        conf=Config(dict(CONF))).start()
        yield c
        c.stop()

    def test_mid_scrub_chip_fault_on_cached_lane_falls_back(
            self, cluster):
        """A tpu_error fires MID-SCRUB on the chip whose HBM cache
        holds the scrubbed object: the lane quarantines, its cache
        entries drop (never serve shards from a chip in an unknown
        state), and the scrub falls back to the full read+CRC-fold
        path — still matching the host CRCs (clean result, no false
        inconsistency), with the codec NOT degraded."""
        from ceph_tpu.ops import hbm_cache
        from ceph_tpu.ops import pipeline as ec_pipeline

        pipe = ec_pipeline.get()
        pipe.reset_devices()
        hbm_cache.configure(64 << 20)
        rados = cluster.client()
        rados.create_ec_pool("ec-hbm", "hbmk2m1",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "host_cutover": "1"}, pg_num=1)
        io = rados.open_ioctx("ec-hbm")
        _settle(io)
        payload = bytes(range(256)) * 16
        # filler objects whose scrub folds must go through the
        # pipeline (their cache entries are invalidated below), so
        # the mid-scrub dispatch that rolls the injected fault is
        # guaranteed to happen
        fillers = [f"filler{i}" for i in range(4)]
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "cached")
        cid = f"pg_{pgid}"
        # the encode must ride a device for its stripes to stay in
        # HBM — rewrite until the warm-up race is over and the entry
        # committed (each rewrite stages a fresh entry at its version)
        ent = None
        end = time.time() + 90
        while ent is None:
            io.write_full("cached", payload)
            for f in fillers:
                io.write_full(f, payload)
            ent = hbm_cache.get().lookup(cid, "cached")
            if ent is None:
                assert time.time() < end, \
                    "no committed HBM cache entry after 90s"
                cluster.tick(0.2)
        victim_lane = ent.lane
        for f in fillers:
            hbm_cache.get().invalidate(cid, f)
        primary = m.pg_primary(pgid)
        pg = cluster.osds[primary].pgs[pgid]
        # the fault arms now but only FIRES at the scrub's first
        # device placement — i.e. mid-scrub, while the cache still
        # holds the scrubbed object on the victim chip
        faults.get().tpu_device_error(1.0, device=str(victim_lane))
        try:
            result = pg.scrub(deep=True)
            assert not result["inconsistent"], result
            stats = ec_pipeline.stats()
            assert stats["quarantines"] >= 1, stats
            assert stats["devices"][str(victim_lane)]["quarantined"]
            # the quarantined chip's entries are gone — the scrub
            # served from disk + host-oracle-exact CRC folds instead
            assert hbm_cache.get().lookup(cid, "cached") is None
            assert stats["cache_lane_drops"] >= 1, stats
            degraded = [o for o in cluster.osds.values()
                        if any(getattr(c, "degraded", False)
                               for c in o._ec_codecs.values())]
            assert not degraded, "codec degraded on a 1-chip fault"
        finally:
            faults.get().reset(seed=0)
            pipe.reset_devices()
        # the data is intact and a healed-fleet scrub is clean too
        assert io.read("cached") == payload
        result = pg.scrub(deep=True)
        assert not result["inconsistent"], result


# ---------------------------------------------------------------------------
# Crash-consistency plane: kill-restart drills against the durability
# ledger (the Jepsen acked-write oracle).
# ---------------------------------------------------------------------------


class TestCrashRestartDrill:
    """Tier-1 single-cycle drill: a FaultSet crash rule fires at a
    journal crash point mid-write, the daemon dies without acking,
    `restart_osd` remounts the same store (torn-tail replay included)
    and the DurabilityLedger proves no acked write was lost."""

    @pytest.fixture(scope="class")
    def cluster(self, tmp_path_factory):
        c = MiniCluster(num_mons=1, num_osds=3, conf=Config(dict(CONF)),
                        store_kind="filestore",
                        store_dir=str(tmp_path_factory.mktemp(
                            "crash-drill"))).start()
        yield c
        c.stop()

    def test_kill_restart_cycle_preserves_acked_writes(self, cluster):
        from ceph_tpu.client import DurabilityLedger
        rados = cluster.client()
        rados.create_pool("drill", pg_num=4)
        io = rados.open_ioctx("drill")
        _settle(io)
        ledger = DurabilityLedger()
        for i in range(12):
            assert ledger.write(io, f"d{i}", f"pre-{i}-".encode() * 40)
        # pre_fsync is the FIRST journal crash point consulted, so a
        # journal-glob rule deterministically tears the record: bytes
        # were handed to the OS, the fsync never ran, a seeded prefix
        # survives on disk
        faults.get().reset(seed=0xD121)
        faults.get().crash("journal.pre_fsync", 1.0, "osd.1")
        victim = cluster.osds[1]
        # overwrites: the crash must not cost the PRIOR acked payloads
        # either.  Every pg spans all 3 osds, so osd.1 sees the txn
        # (primary or replica) and dies on its first journal append;
        # the ledger keeps resending until the surviving pair acks.
        i = 0
        end = time.time() + 90
        while not victim.store.frozen:
            assert time.time() < end, "crash rule never fired"
            assert ledger.write(io, f"d{i % 12}",
                                f"rewrite-{i}-".encode() * 40,
                                retry_window=90,
                                on_retry=lambda: cluster.tick(0.3))
            i += 1
        assert victim.store.crash_site == "journal.pre_fsync"
        assert not faults.get().rules(), "crash rules are one-shot"
        # degraded writes while the victim is down still ack + count
        for i in range(3):
            assert ledger.write(io, f"deg{i}", f"deg-{i}-".encode() * 40,
                                retry_window=90,
                                on_retry=lambda: cluster.tick(0.3))
        assert ledger.delete(io, "d11", retry_window=90,
                             on_retry=lambda: cluster.tick(0.3))
        reborn = cluster.restart_osd(1, timeout=120)
        report = ledger.verify(io, retry_window=90,
                               on_retry=lambda: cluster.tick(0.3))
        # 12 d-oids + 3 deg-oids (the delete reuses d11)
        assert report["checked"] == 15, report
        assert report["acked_writes"] >= 15, report
        assert report["acked_deletes"] == 1, report
        # the remount replayed a checksummed journal and discarded the
        # torn record the crash left behind — surfaced in perf dump
        dump = reborn.asok.execute("perf dump")
        assert dump["journal"]["journal_torn_tail_discards"] == 1, \
            dump["journal"]
        # (journal_records_replayed may legitimately be 0: the
        # background committer can checkpoint right before the crash,
        # leaving only the torn record past the snapshot)
        assert dump["journal"]["journal_tail_bytes_discarded"] >= 1
        assert dump["crash"]["crashed"] == 0
        assert dump["crash"]["site"] == ""
        assert dump["crash"]["crash_rules"] == 0
        # an acked delete stays deleted through the crash-restart
        with pytest.raises(RadosError):
            io.read("d11")

    def test_crashed_osd_hbm_cache_starts_cold(self, cluster):
        """The crashed OSD's HBM stripe-cache entries are dropped at
        abort time: a restarted daemon must start COLD — its chip
        state is no longer trusted and replay may have discarded the
        journal tail backing those stripes."""
        from ceph_tpu.ops import hbm_cache
        from ceph_tpu.ops import pipeline as ec_pipeline
        ec_pipeline.get().reset_devices()
        hbm_cache.configure(64 << 20)
        rados = cluster.client()
        rados.create_ec_pool("drill-ec", "drillk2m1",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "host_cutover": "1"}, pg_num=1)
        io = rados.open_ioctx("drill-ec")
        _settle(io)
        payload = bytes(range(256)) * 16
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "cold")
        cid = f"pg_{pgid}"
        ent = None
        end = time.time() + 90
        while ent is None:
            io.write_full("cold", payload)
            ent = hbm_cache.get().lookup(cid, "cold")
            if ent is None:
                assert time.time() < end, \
                    "no committed HBM cache entry after 90s"
                cluster.tick(0.2)
        victim = m.pg_primary(pgid)
        cluster.kill_osd(victim)
        # in-process replicas share the cid key, so the conservative
        # crash drop clears the pg's entries outright
        assert hbm_cache.get().lookup(cid, "cold") is None
        cluster.restart_osd(victim, timeout=120)
        end = time.time() + 60
        while True:
            try:
                assert io.read("cold") == payload
                break
            except RadosError:
                assert time.time() < end
                cluster.tick(0.3)


class TestMonKillRestartDrill:
    """Tier-1 mon durability drill: the leader tears its paxos commit
    transaction mid-write and dies; the command is never falsely
    acked; the survivors self-elect via the peon lease watchdog;
    `restart_mon` remounts the SAME store (torn-commit detection +
    quorum repair at mount) and the roster converges with zero
    forgotten commits."""

    def test_leader_crash_mid_commit_and_rejoin(self):
        from ceph_tpu.client import DurabilityLedger
        c = MiniCluster(num_mons=3, num_osds=3,
                        conf=Config(dict(CONF))).start()
        try:
            rados = c.client()
            rados.create_pool("mondrill", pg_num=4)
            io = rados.open_ioctx("mondrill")
            _settle(io)
            ledger = DurabilityLedger()
            for i in range(4):
                assert ledger.write(io, f"m{i}",
                                    f"pre-{i}-".encode() * 30)
            victim = c.leader().name
            faults.get().reset(seed=0xD00D)
            faults.get().crash("paxos.mid_commit", 1.0, f"mon.{victim}")
            # a map-changing command tears the leader's commit txn;
            # the ack must never arrive (a falsely-acked map change
            # that vanishes is the mon-tier equivalent of losing an
            # acked write)
            rv1, _out, _ = rados.mon_command(
                {"prefix": "osd pool create", "pool": "torn-pool",
                 "pg_num": 1}, timeout=8)
            assert rv1 != 0, "a torn commit must not ack success"
            vmon = c.mon(victim)
            end = time.time() + 45
            while not vmon.store.frozen and time.time() < end:
                c.tick(0.2)
            assert vmon.store.frozen
            assert vmon.store.crash_site == "paxos.mid_commit"
            assert not faults.get().rules(), "crash rules are one-shot"
            # survivors self-elect (lease watchdog) — no manual poke
            end = time.time() + 90
            while time.time() < end:
                if any(m.is_leader() for m in c.mons
                       if m.name != victim):
                    break
                c.tick(0.25)
            assert any(m.is_leader() for m in c.mons
                       if m.name != victim), \
                "survivors never self-elected"
            # acked data-plane writes keep flowing under the 2/3 quorum
            for i in range(3):
                assert ledger.write(io, f"down{i}",
                                    f"down-{i}-".encode() * 30,
                                    retry_window=90,
                                    on_retry=lambda: c.tick(0.3))
            reborn = c.restart_mon(victim, timeout=120)
            # the retried command converges exactly-once
            end = time.time() + 60
            rv2 = -1
            while rv2 != 0 and time.time() < end:
                rv2, _out, _ = rados.mon_command(
                    {"prefix": "osd pool create", "pool": "torn-pool",
                     "pg_num": 1}, timeout=20)
            assert rv2 == 0
            end = time.time() + 60
            while time.time() < end:
                if all(m.osdmon.osdmap.pool_by_name("torn-pool")
                       for m in c.mons):
                    break
                c.tick(0.25)
            assert all(m.osdmon.osdmap.pool_by_name("torn-pool")
                       for m in c.mons), "roster diverged"
            report = ledger.verify(io, retry_window=90,
                                   on_retry=lambda: c.tick(0.3))
            assert report["checked"] == 7, report
            # the reborn mon's crash block is clean again and its
            # repair counters are surfaced
            dump = reborn.asok.execute("perf dump")
            assert dump["crash"]["crashed"] == 0
            assert "paxos_torn_commit_repairs" in dump["crash"]
        finally:
            faults.get().reset(seed=0)
            c.stop()


class TestBlockstoreTornWalDrill:
    """Tier-1 blockstore durability drill: a FaultSet crash rule tears
    the deferred-write WAL machinery mid-write (whichever wal.* site
    the next commit hits first), the daemon dies without acking,
    restart_osd remounts — WAL replay + freelist verification — and
    the ledger proves no acked write was lost or interleaved."""

    def test_torn_wal_cycle_preserves_acked_writes(self, tmp_path):
        from ceph_tpu.client import DurabilityLedger
        c = MiniCluster(num_mons=1, num_osds=3,
                        conf=Config(dict(CONF)),
                        store_kind="blockstore",
                        store_dir=str(tmp_path)).start()
        try:
            rados = c.client()
            rados.create_pool("bsdrill", pg_num=4)
            io = rados.open_ioctx("bsdrill")
            _settle(io)
            ledger = DurabilityLedger()
            for i in range(8):
                assert ledger.write(io, f"b{i}",
                                    f"pre-{i}-".encode() * 40)
            faults.get().reset(seed=0xB10C)
            faults.get().crash("wal.*", 1.0, "osd.1")
            victim = c.osds[1]
            i = 0
            end = time.time() + 90
            while not victim.store.frozen:
                assert time.time() < end, "wal crash rule never fired"
                assert ledger.write(io, f"b{i % 8}",
                                    f"rewrite-{i}-".encode() * 40,
                                    retry_window=90,
                                    on_retry=lambda: c.tick(0.3))
                i += 1
            assert victim.store.crash_site.startswith("wal.")
            # degraded writes + a delete while the victim is down
            for i in range(2):
                assert ledger.write(io, f"deg{i}",
                                    f"deg-{i}-".encode() * 40,
                                    retry_window=90,
                                    on_retry=lambda: c.tick(0.3))
            assert ledger.delete(io, "b7", retry_window=90,
                                 on_retry=lambda: c.tick(0.3))
            reborn = c.restart_osd(1, timeout=120)
            report = ledger.verify(io, retry_window=90,
                                   on_retry=lambda: c.tick(0.3))
            assert report["checked"] == 10, report
            assert report["acked_deletes"] == 1, report
            dump = reborn.asok.execute("perf dump")
            # the remount surfaced the WAL recovery counters
            assert "wal_records_replayed" in dump["journal"]
            assert "wal_torn_extent_repairs" in dump["journal"]
            assert dump["crash"]["crashed"] == 0
            with pytest.raises(RadosError):
                io.read("b7")
        finally:
            faults.get().reset(seed=0)
            c.stop()


CRASH_SITES = {
    "memstore": ["pglog.append", "store.pre_apply", "store.post_apply"],
    "filestore": ["journal.pre_fsync", "journal.post_fsync",
                  "journal.mid_apply", "pglog.append",
                  "snapshot.mid_write", "snapshot.pre_rename"],
    "blockstore": ["pglog.append", "store.pre_apply",
                   "store.post_apply", "wal.pre_kv_commit",
                   "wal.post_kv_commit", "wal.mid_apply",
                   "alloc.mid_cow"],
}

# filestore/blockstore cycles can additionally arm the fsync-reorder
# model: the crash then keeps an out-of-order SUBSET of un-fsync'd
# writes instead of a prefix — replay must still repair everything
REORDER_KINDS = {"filestore", "blockstore"}


@pytest.mark.slow
class TestCrashRestartSoak:
    """The acceptance soak: >= 20 crash-restart cycles at randomized
    crash sites across memstore/filestore/blockstore under concurrent
    client writes.  After every cycle the DurabilityLedger asserts
    each acked write readable bit-exact, unacked txns atomic (a read
    matches exactly one recorded whole payload, never a mix), deletes
    never resurrected, and all PGs back to active+clean.  The rotation
    includes the blockstore WAL/extent sites, seeded fsync-reorder
    windows on the journaled backends, and a mon kill-restart every
    third cycle (the singleton mon remounts its store and re-elects
    itself while the OSD crash cycle runs)."""

    CYCLES = 7          # per backend; 3 backends -> 21 cycles total

    @pytest.mark.parametrize("store_kind",
                             ["memstore", "filestore", "blockstore"])
    def test_crash_restart_soak(self, tmp_path, store_kind):
        from ceph_tpu.client import DurabilityLedger
        import random
        rng = random.Random(f"{CHAOS_SEED}:{store_kind}")
        sites = CRASH_SITES[store_kind]
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf=Config(dict(CONF)),
                              store_kind=store_kind,
                              store_dir=str(tmp_path / store_kind)
                              ).start()
        try:
            self._soak(cluster, rng, sites, store_kind)
        finally:
            faults.get().reset(seed=0)
            cluster.stop()

    def _soak(self, cluster, rng, sites, store_kind="memstore"):
        import random
        from ceph_tpu.client import DurabilityLedger
        rados = cluster.client()
        rados.create_pool("soak", pg_num=4)
        verify_io = rados.open_ioctx("soak")
        _settle(verify_io, window=90.0)
        ledger = DurabilityLedger()
        # one long-lived client per writer slot, connected ONCE —
        # reconnecting the same entity name every cycle collides with
        # the previous cycle's still-open mon session and the fresh
        # connect starves waiting for an osdmap
        writer_ios = [cluster.client(f"client.w{t}").open_ioctx("soak")
                      for t in range(2)]

        def writer(tid: int, seed: str, stop: threading.Event) -> None:
            io = writer_ios[tid]
            wrng = random.Random(seed)
            i = 0
            while not stop.is_set():
                oid = f"t{tid}-o{wrng.randrange(8)}"
                if wrng.random() < 0.15:
                    ledger.delete(io, oid, retry_window=20,
                                  on_retry=lambda: stop.wait(0.2))
                else:
                    ledger.write(io, oid,
                                 f"{tid}:{i}:".encode() * wrng.
                                 randrange(8, 64), retry_window=20,
                                 on_retry=lambda: stop.wait(0.2))
                i += 1

        for cycle in range(self.CYCLES):
            site = rng.choice(sites)
            victim_id = rng.randrange(3)
            faults.get().reseed(CHAOS_SEED + cycle)
            stop = threading.Event()
            threads = [threading.Thread(
                target=writer, args=(t, f"w{t}c{cycle}:{rng.random()}",
                                     stop), daemon=True)
                for t in range(2)]
            for th in threads:
                th.start()
            reorder_rid = None
            if store_kind in REORDER_KINDS and rng.random() < 0.5:
                # the crash (if it fires) keeps an out-of-order
                # SUBSET of un-fsync'd writes instead of a prefix
                reorder_rid = faults.get().fsync_reorder(
                    1.0, f"osd.{victim_id}")
            rid = faults.get().crash(site, 1.0, f"osd.{victim_id}")
            victim = cluster.osds[victim_id]
            if cycle % 3 == 2:
                # mon kill-restart rides the same cycle: the singleton
                # mon remounts its store (torn-commit integrity check)
                # and re-elects itself while the OSDs keep serving
                cluster.restart_mon(cluster.mons[0].name, timeout=240)
            end = time.time() + 45
            while not victim.store.frozen and time.time() < end:
                time.sleep(0.1)
            if not victim.store.frozen:
                # site not exercised in the window (e.g. a snapshot
                # checkpoint not yet due): hard-kill instead — still
                # an abrupt crash cycle
                faults.get().clear(rid)
            if reorder_rid is not None:
                faults.get().clear(reorder_rid)
            cluster.restart_osd(victim_id, timeout=240)
            stop.set()
            for th in threads:
                th.join(timeout=60)
                assert not th.is_alive(), "writer wedged"
            report = ledger.verify(
                verify_io, retry_window=120,
                on_retry=lambda: cluster.tick(0.3))
            assert report["checked"] >= 1, report
        assert ledger.acked_writes >= self.CYCLES, \
            "soak never got acked writes under fire"


# ---------------------------------------------------------------------------
# Seeded chaos soak (slow tier): stress model under a randomized
# FaultSet schedule.
# ---------------------------------------------------------------------------

CHAOS_SEED = 0xFA57


def _make_schedule(seed: int, steps: int) -> list[tuple]:
    """The full fault schedule as a pure function of the seed:
    (delay_s, kind, args, duration_s) per step."""
    import random
    rng = random.Random(seed)
    sched = []
    for _ in range(steps):
        delay = 0.2 + 0.4 * rng.random()
        kind = rng.choice(("partition", "eio", "kill"))
        if kind == "partition":
            a, b = rng.sample(range(3), 2)
            args = (f"osd.{a}", f"osd.{b}")
            dur = 0.4 + 0.6 * rng.random()
        elif kind == "eio":
            args = (f"osd.{rng.randrange(3)}", "m*", 0.3)
            dur = 0.5 + 0.7 * rng.random()
        else:
            args = (f"osd.{rng.randrange(3)}", 15)
            dur = 0.5 + 0.7 * rng.random()
        sched.append((round(delay, 3), kind, args, round(dur, 3)))
    return sched


@pytest.mark.slow
class TestChaosSoak:
    @pytest.fixture(scope="class")
    def cluster(self):
        c = MiniCluster(num_mons=3, num_osds=3,
                        conf=Config(dict(CONF))).start()
        yield c
        c.stop()

    def test_schedule_is_seed_deterministic(self):
        assert _make_schedule(CHAOS_SEED, 40) == \
            _make_schedule(CHAOS_SEED, 40)
        assert _make_schedule(CHAOS_SEED, 40) != \
            _make_schedule(CHAOS_SEED + 1, 40)

    def test_stress_model_under_faultset(self, cluster):
        from test_stress_model import EC_OPS, run_model
        faults.get().reseed(CHAOS_SEED)
        rados = cluster.client()
        rados.create_ec_pool("chaos-ec", "ck2m1",
                             {"plugin": "tpu", "k": 2, "m": 1},
                             pg_num=4)
        io = rados.open_ioctx("chaos-ec")
        _settle(io, window=90.0)
        schedule = _make_schedule(CHAOS_SEED, 200)
        stop = threading.Event()
        executed: list[tuple] = []

        def injector():
            fs = faults.get()
            for delay, kind, args, dur in schedule:
                if stop.wait(delay):
                    return
                if kind == "partition":
                    rid = fs.partition(*args)
                elif kind == "eio":
                    rid = fs.store_eio(args[0], args[1], prob=args[2])
                else:
                    rid = fs.socket_kill(args[0], one_in=args[1])
                executed.append((kind, args))
                stop.wait(dur)
                fs.clear(rid)
                if stop.is_set():
                    return

        th = threading.Thread(target=injector, daemon=True)
        th.start()
        try:
            # run_model asserts zero data loss (model vs cluster) and
            # only tolerates the DEFINED timeout errno — any other
            # error, lost ack, or diverged byte fails the soak.  The
            # fault windows run on a wall-clock schedule, and recovery
            # has gotten fast enough that one 300-op round can outrun
            # it — keep the model under fire until the schedule has
            # actually landed the required windows.
            rounds = 0
            model = None
            while True:
                # the model dict CARRIES across rounds: the cluster
                # keeps round N's objects, so round N+1 starting from
                # an empty model would assert "absent" for every
                # survivor and fail on a healthy cluster (the old
                # seed-0xFA57 "flake" — a model bookkeeping bug, not a
                # durability violation: it fired exactly when round 1
                # outran the fault schedule and a second round ran)
                model = run_model(io, cluster, seed=CHAOS_SEED + rounds,
                                  nops=300, snapshots=False, ops=EC_OPS,
                                  model=model)
                rounds += 1
                if len(executed) >= 8 and {k for k, _ in executed} >= \
                        {"partition", "eio", "kill"}:
                    break
                assert rounds < 12, \
                    f"only {len(executed)} fault windows " \
                    f"({sorted({k for k, _ in executed})}) after " \
                    f"{rounds} model rounds"
        except BaseException:
            print(f"\nCHAOS SOAK FAILED — reproduce with "
                  f"seed=0x{CHAOS_SEED:X} (schedule is a pure "
                  f"function of the seed)")
            raise
        finally:
            stop.set()
            th.join(timeout=30)
            faults.get().clear()
        # the soak must actually have been under fire, not idling
        assert len(executed) >= 8, \
            f"only {len(executed)} fault windows hit the model"
        assert {k for k, _ in executed} >= {"partition", "eio", "kill"}
