"""Striper extent math + aio + striped-object I/O.

osdc/Striper.cc semantics, libradosstriper API shape, librados aio.
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.client.striper import (Extent, Layout, StripedObject,
                                     file_to_extents, object_name)
from ceph_tpu.vstart import MiniCluster


class TestExtentMath:
    def test_single_object_simple(self):
        lo = Layout(stripe_unit=1024, stripe_count=1, object_size=4096)
        ext = file_to_extents(lo, 0, 100)
        assert ext == [Extent(0, 0, 100, 0)]

    def test_round_robin_across_columns(self):
        lo = Layout(stripe_unit=1024, stripe_count=3, object_size=4096)
        ext = file_to_extents(lo, 0, 3 * 1024)
        # one stripe row: block i -> object i at offset 0
        assert [(e.object_no, e.offset, e.length) for e in ext] == [
            (0, 0, 1024), (1, 0, 1024), (2, 0, 1024)]
        # second stripe row goes back to object 0 at su offset
        ext = file_to_extents(lo, 3 * 1024, 1024)
        assert [(e.object_no, e.offset, e.length) for e in ext] == [
            (0, 1024, 1024)]

    def test_object_set_rollover(self):
        lo = Layout(stripe_unit=1024, stripe_count=2, object_size=2048)
        # 2 stripes/object, 2 columns -> set size 4096 logical bytes
        ext = file_to_extents(lo, 4096, 1024)
        assert ext[0].object_no == 2       # next object set
        assert ext[0].offset == 0

    def test_unaligned_spans(self):
        lo = Layout(stripe_unit=1000, stripe_count=2, object_size=4000)
        ext = file_to_extents(lo, 500, 1000)
        assert [(e.object_no, e.offset, e.length) for e in ext] == [
            (0, 500, 500), (1, 0, 500)]
        assert sum(e.length for e in ext) == 1000

    def test_coverage_is_exact_and_ordered(self):
        lo = Layout(stripe_unit=512, stripe_count=3, object_size=2048)
        for off, ln in [(0, 10000), (123, 4567), (5000, 1)]:
            ext = file_to_extents(lo, off, ln)
            assert sum(e.length for e in ext) == ln
            pos = off
            for e in ext:
                assert e.logical_offset == pos
                pos += e.length

    def test_bad_layout_rejected(self):
        with pytest.raises(ValueError):
            Layout(stripe_unit=1000, object_size=1500)


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("striped", pg_num=8)
    ctx = rados.open_ioctx("striped")
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warm", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


class TestAio:
    def test_parallel_writes_and_reads(self, cluster, io):
        comps = [io.aio_write_full(f"a{i}", bytes([i]) * 500)
                 for i in range(8)]
        for c in comps:
            assert c.wait_for_complete(30)
            c.result()
        reads = [io.aio_read(f"a{i}") for i in range(8)]
        for i, c in enumerate(reads):
            assert c.result() == bytes([i]) * 500

    def test_callback_fires(self, cluster, io):
        fired = []
        c = io.aio_write_full("cb", b"x")
        c.set_callback(lambda comp: fired.append(comp.is_complete()))
        assert c.wait_for_complete(30)
        time.sleep(0.1)
        assert fired == [True]

    def test_error_surfaces_in_result(self, cluster, io):
        c = io.aio_read("does-not-exist-xyz")
        c.wait_for_complete(30)
        with pytest.raises(RadosError):
            c.result()


class TestStripedObject:
    def test_write_read_across_objects(self, cluster, io):
        lo = Layout(stripe_unit=1024, stripe_count=3, object_size=4096)
        so = StripedObject(io, "bigfile", lo)
        payload = bytes(range(256)) * 64        # 16 KiB
        so.write(payload)
        assert so.size() == len(payload)
        assert so.read() == payload
        # partial read across a stripe boundary
        assert so.read(900, 300) == payload[900:1200]
        # the data really is striped over multiple backing objects
        assert io.stat(object_name("bigfile", 0))["size"] > 0
        assert io.stat(object_name("bigfile", 1))["size"] > 0
        assert io.stat(object_name("bigfile", 2))["size"] > 0

    def test_overwrite_and_extend(self, cluster, io):
        lo = Layout(stripe_unit=512, stripe_count=2, object_size=1024)
        so = StripedObject(io, "grow", lo)
        so.write(b"A" * 1000)
        so.write(b"B" * 500, offset=750)
        assert so.size() == 1250
        data = so.read()
        assert data[:750] == b"A" * 750
        assert data[750:] == b"B" * 500

    def test_remove_cleans_backing_objects(self, cluster, io):
        lo = Layout(stripe_unit=512, stripe_count=2, object_size=1024)
        so = StripedObject(io, "gone", lo)
        so.write(b"x" * 3000)
        so.remove()
        assert so.size() == 0
        names = io.list_objects()
        assert not any(n.startswith("gone.") for n in names)
