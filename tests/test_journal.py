"""Client-side journal library: record/replay/commit/trim (journal/
Journaler semantics — the rbd-mirror substrate) — plus the OSD-side
write-ahead journal's crash-point matrix (seeded property tests over
torn tails, bit flips, bad lengths, and the FaultSet crash sites)."""

import os
import struct
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.journal import Journaler, JournalError, entry_oid
from ceph_tpu.ops.crc32c import crc32c
from ceph_tpu.store import CrashPoint, JournalFileStore, Transaction
from ceph_tpu.utils import faults
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("jrnl", pg_num=4)
    ctx = rados.open_ioctx("jrnl")
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warm", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


class TestJournaler:
    def test_record_and_replay(self, io):
        j = Journaler(io, "j1")
        j.create(splay_width=3)
        entries = [f"event-{i}".encode() for i in range(20)]
        for e in entries:
            j.append(e)
        # a fresh handle (different process model) replays everything
        j2 = Journaler(io, "j1", client_id="peer").open()
        got = [e for _pos, e in j2.replay()]
        assert got == entries

    def test_replay_from_position(self, io):
        j = Journaler(io, "j2")
        j.create(splay_width=2)
        for i in range(10):
            j.append(f"n{i}".encode())
        got = list(j.replay(from_position=6))
        assert [pos for pos, _ in got] == [6, 7, 8, 9]
        assert [e for _, e in got] == [b"n6", b"n7", b"n8", b"n9"]

    def test_splay_spreads_entries(self, io):
        j = Journaler(io, "j3")
        j.create(splay_width=4)
        for i in range(8):
            j.append(b"x" * 100)
        sizes = [io.stat(entry_oid("j3", i))["size"] for i in range(4)]
        assert all(s > 0 for s in sizes)

    def test_duplicate_create_fails(self, io):
        j = Journaler(io, "j4")
        j.create()
        with pytest.raises(JournalError):
            Journaler(io, "j4").create()

    def test_open_missing_fails(self, io):
        with pytest.raises(JournalError):
            Journaler(io, "nope").open()

    def test_commit_and_trim(self, io):
        j = Journaler(io, "j5", client_id="a")
        # small object_size -> sets roll quickly (per_obj = 1)
        j.create(splay_width=2, entries_per_object=1)
        j.register_client("a")
        j.register_client("b")
        for i in range(10):
            j.append(f"e{i}".encode())
        # only client a has consumed; floor is 0 -> nothing trims
        j.commit(8)
        assert j.trim() == 0
        jb = Journaler(io, "j5", client_id="b").open()
        jb.commit(6)
        removed = j.trim()          # floor 6 -> sets below entry 6 die
        assert removed > 0
        # the tail past the floor must still replay
        got = [e for _pos, e in j.replay(from_position=6)]
        assert got == [b"e6", b"e7", b"e8", b"e9"]

    def test_remove(self, io):
        j = Journaler(io, "j6")
        j.create(splay_width=2)
        for i in range(5):
            j.append(b"z")
        j.remove()
        with pytest.raises(JournalError):
            Journaler(io, "j6").open()
        assert not any(n.startswith("j6.")
                       for n in io.list_objects())

    def test_mirror_tail_pattern(self, io):
        """The rbd-mirror shape: a writer records, a peer tails
        incrementally with commits, trimming follows the slowest."""
        w = Journaler(io, "mir", client_id="primary")
        w.create(splay_width=2, entries_per_object=1)
        w.register_client("primary")
        w.register_client("peer")
        peer = Journaler(io, "mir", client_id="peer").open()
        applied = []
        pos = 0
        for batch in range(3):
            for i in range(4):
                w.append(f"b{batch}i{i}".encode())
            w.commit(4 * (batch + 1))
            for p, e in peer.replay(from_position=pos):
                applied.append(e)
                pos = p + 1
            peer.commit(pos)
            w.trim()
        assert len(applied) == 12
        assert applied[0] == b"b0i0" and applied[-1] == b"b2i3"

    def test_concurrent_appenders_unique_positions(self, io):
        """CAS position allocation: two recorders never collide and
        replay yields every entry exactly once in position order."""
        import threading
        j = Journaler(io, "conc")
        j.create(splay_width=3, entries_per_object=4)
        writers = [Journaler(io, "conc", client_id=f"w{i}").open()
                   for i in range(3)]
        recorded = [[] for _ in writers]

        def run(idx):
            for k in range(8):
                payload = f"w{idx}e{k}".encode()
                pos = writers[idx].append(payload)
                recorded[idx].append((pos, payload))

        ts = [threading.Thread(target=run, args=(i,))
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        positions = [p for r in recorded for p, _ in r]
        assert sorted(positions) == list(range(24))   # no collisions
        expect = {p: e for r in recorded for p, e in r}
        got = dict(j.replay())
        assert got == expect

    def test_reregistration_keeps_commit_position(self, io):
        j = Journaler(io, "rereg", client_id="a")
        j.create(splay_width=2, entries_per_object=1)
        j.register_client("a")
        for i in range(6):
            j.append(f"x{i}".encode())
        j.commit(5)
        j.register_client("a")      # daemon restart path: no-op
        assert j._commit_positions()["a"] == 5


# ---------------------------------------------------------------------------
# OSD write-ahead journal: recovery + crash-point matrix (no cluster —
# these drive JournalFileStore directly, the store_test.cc way).
# ---------------------------------------------------------------------------

def T():
    return Transaction()


def _mkstore(path, owner=""):
    s = JournalFileStore(str(path), commit_interval=3600)
    s.owner = owner
    s.mkfs()
    s.mount()
    return s


def _state(path):
    """Remount and dump {oid: data} + counters, then unmount."""
    s = JournalFileStore(str(path))
    s.mount()
    out = {}
    for cid in s.list_collections():
        for oid in s.collection_list(cid):
            out[oid] = s.read(cid, oid)
    counters = s.journal_stats()
    s.umount()
    return out, counters


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)


class TestJournalCorruptionMatrix:
    """Seeded property: N committed records, one corruption anywhere
    in the stream — replay recovers every record before the damage,
    never crashes, never applies garbage, and counts what it dropped."""

    N = 8

    def _fill(self, path):
        s = _mkstore(path)
        s.apply_transaction(T().create_collection("c"))
        offsets = []
        for i in range(self.N):
            offsets.append(s._journal_len)
            s.apply_transaction(T().write("c", f"o{i}", 0,
                                          bytes([i]) * (64 + i)))
        end = s._journal_len
        s._jf.close()             # crash: no checkpoint, no umount
        return offsets, end

    @pytest.mark.parametrize("seed", [1, 2, 3, 4])
    def test_torn_tail_at_random_cut(self, tmp_path, seed):
        import random
        rng = random.Random(seed)
        offsets, end = self._fill(tmp_path / "fs")
        victim = rng.randrange(1, self.N)
        cut = rng.randrange(offsets[victim] + 1,
                            offsets[victim + 1] if victim + 1 < self.N
                            else end)
        os.truncate(str(tmp_path / "fs" / "journal"), cut)
        state, counters = _state(tmp_path / "fs")
        # every record before the cut survives bit-exact; the torn one
        # and everything after are discarded
        for i in range(victim):
            assert state[f"o{i}"] == bytes([i]) * (64 + i)
        for i in range(victim, self.N):
            assert f"o{i}" not in state
        assert counters["journal_torn_tail_discards"] == 1
        # victim surviving writes + the create_collection record
        assert counters["journal_records_replayed"] == victim + 1

    @pytest.mark.parametrize("seed", [5, 6, 7])
    def test_bit_flip_halts_at_last_valid(self, tmp_path, seed):
        import random
        rng = random.Random(seed)
        offsets, end = self._fill(tmp_path / "fs")
        victim = rng.randrange(1, self.N)
        rec_end = offsets[victim + 1] if victim + 1 < self.N else end
        # flip one payload bit (skip the 20-byte header: header damage
        # is the bad-length case below)
        at = rng.randrange(offsets[victim] + 20, rec_end)
        jp = str(tmp_path / "fs" / "journal")
        with open(jp, "r+b") as f:
            f.seek(at)
            b = f.read(1)
            f.seek(at)
            f.write(bytes([b[0] ^ (1 << rng.randrange(8))]))
        state, counters = _state(tmp_path / "fs")
        for i in range(victim):
            assert state[f"o{i}"] == bytes([i]) * (64 + i)
        for i in range(victim, self.N):
            assert f"o{i}" not in state
        assert counters["journal_bad_record_halts"] == 1

    def test_bad_length_field_cannot_crash_replay(self, tmp_path):
        """A corrupted length promising absurd bytes must read as a
        discardable tail, not an allocation bomb or an exception."""
        offsets, end = self._fill(tmp_path / "fs")
        jp = str(tmp_path / "fs" / "journal")
        with open(jp, "r+b") as f:
            f.seek(offsets[3])
            f.write(struct.pack("<Q", 1 << 60))
        state, counters = _state(tmp_path / "fs")
        for i in range(3):
            assert state[f"o{i}"] == bytes([i]) * (64 + i)
        assert "o3" not in state
        assert counters["journal_torn_tail_discards"] == 1

    def test_seq_rollback_halts_replay(self, tmp_path):
        """A record carrying the wrong seq (resurrected/reordered
        write) is rejected even when its crc is self-consistent."""
        offsets, end = self._fill(tmp_path / "fs")
        jp = str(tmp_path / "fs" / "journal")
        with open(jp, "rb") as f:
            f.seek(offsets[2])
            hdr = f.read(20)
        blen, seq, crc = struct.unpack("<QQI", hdr)
        with open(jp, "r+b") as f:
            f.seek(offsets[2])
            f.write(struct.pack("<QQI", blen, seq + 7, crc))
        state, counters = _state(tmp_path / "fs")
        assert state["o1"] == bytes([1]) * 65
        assert "o2" not in state
        assert counters["journal_bad_record_halts"] == 1


class TestCrashPointMatrix:
    """FaultSet `crash` rules fire at the named write-path sites: the
    store freezes, the op never acks, and the remounted state is
    exactly what the site's durability point promises."""

    def _arm(self, site, owner="osd.7", seed=0x5EED):
        faults.get().reset(seed=seed)
        faults.get().crash(site, 1.0, owner)

    def _crash_write(self, s, oid, payload):
        acked = []
        t = T().write("c", oid, 0, payload)
        t.register_on_commit(lambda: acked.append(oid))
        with pytest.raises(CrashPoint):
            s.queue_transactions([t])
        assert not acked, "a crashed write must never ack"
        assert s.frozen
        return acked

    @pytest.mark.parametrize("site", ["journal.pre_fsync",
                                      "journal.post_fsync",
                                      "journal.mid_apply"])
    def test_journal_sites_never_ack_and_recover(self, tmp_path, site):
        s = _mkstore(tmp_path / "fs", owner="osd.7")
        s.apply_transaction(T().create_collection("c")
                            .write("c", "base", 0, b"before-crash"))
        self._arm(site)
        self._crash_write(s, "victim", b"unacked-payload")
        # one-shot: the rule consumed itself
        assert not faults.get().rules()
        # frozen: nothing else reaches disk, not even a checkpoint
        with pytest.raises(CrashPoint):
            s.apply_transaction(T().write("c", "late", 0, b"x"))
        s.umount()
        state, counters = _state(tmp_path / "fs")
        assert state["base"] == b"before-crash"
        got = state.get("victim")
        if site == "journal.pre_fsync":
            # un-fsync'd: an arbitrary seeded prefix survived — the
            # record replays whole or its torn tail is discarded,
            # NEVER a partial apply
            assert got in (None, b"unacked-payload")
        else:
            # past the fsync: durable even though never acked
            assert got == b"unacked-payload"
        assert "late" not in state

    def test_pre_fsync_torn_tail_is_seed_deterministic(self, tmp_path):
        outcomes = []
        for run in range(2):
            path = tmp_path / f"fs{run}"
            s = _mkstore(path, owner="osd.7")
            s.apply_transaction(T().create_collection("c"))
            self._arm("journal.pre_fsync", seed=0xABCD)
            self._crash_write(s, "v", b"T" * 300)
            s.umount()
            outcomes.append(os.path.getsize(str(path / "journal")))
        assert outcomes[0] == outcomes[1]

    @pytest.mark.parametrize("site", ["snapshot.mid_write",
                                      "snapshot.pre_rename"])
    def test_snapshot_sites_leave_old_snapshot_live(self, tmp_path,
                                                    site):
        s = _mkstore(tmp_path / "fs", owner="osd.7")
        s.apply_transaction(T().create_collection("c")
                            .write("c", "o", 0, b"snapshotted"))
        s._checkpoint()
        s.apply_transaction(T().write("c", "p", 0, b"post-ckpt"))
        self._arm(site)
        with pytest.raises(CrashPoint):
            s._checkpoint()
        s.umount()
        state, counters = _state(tmp_path / "fs")
        assert state["o"] == b"snapshotted"
        assert state["p"] == b"post-ckpt"
        # the interrupted tmp is ignored and cleaned at mount
        assert not os.path.exists(str(tmp_path / "fs" / "snapshot.tmp"))

    def test_owner_glob_scopes_the_crash(self, tmp_path):
        """A rule targeting osd.1 must not fire on osd.2's store."""
        s = _mkstore(tmp_path / "fs", owner="osd.2")
        s.apply_transaction(T().create_collection("c"))
        faults.get().crash("journal.*", 1.0, "osd.1")
        s.apply_transaction(T().write("c", "o", 0, b"survives"))
        assert s.read("c", "o") == b"survives"
        assert faults.get().rules()     # unfired: still installed
        s.umount()

    def test_checkpoint_errors_are_counted_not_swallowed(self,
                                                         tmp_path):
        """The real committer loop logs + counts checkpoint failures
        and trips the health warning after enough consecutive ones;
        a success clears the streak."""
        from ceph_tpu.store.filestore import CHECKPOINT_WARN_AFTER
        s = JournalFileStore(str(tmp_path / "fs"), commit_interval=0.02)
        s.owner = "osd.7"
        s.mkfs()
        s.mount()
        s.apply_transaction(T().create_collection("c"))
        orig = s._write_snapshot

        def enospc(*a):
            raise OSError(28, "No space left on device")

        assert s.health_warning() is None
        s._write_snapshot = enospc
        end = time.time() + 10
        while s.health_warning() is None and time.time() < end:
            time.sleep(0.02)
        assert s.journal_stats()["journal_checkpoint_errors"] >= \
            CHECKPOINT_WARN_AFTER
        assert "checkpoint failures" in (s.health_warning() or "")
        # recovery: the next successful checkpoint clears the warning
        s._write_snapshot = orig
        end = time.time() + 10
        while s.health_warning() is not None and time.time() < end:
            time.sleep(0.02)
        assert s.health_warning() is None
        s.umount()


class TestFsyncReorderWindow:
    """The ALICE reordering model on the filestore journal: the 4 KiB
    pages of an un-fsync'd record persist as a seeded SUBSET — a later
    page can be durable while an earlier one is lost.  Replay must
    still honor the prefix/atomicity promise: it halts at the damage
    and discards the tail, never applying a record whose earlier bytes
    are gone, even when its later bytes physically survived."""

    def _arm(self, seed):
        faults.get().reset(seed=seed)
        faults.get().fsync_reorder(1.0, "osd.7")
        faults.get().crash("journal.pre_fsync", 1.0, "osd.7")

    @pytest.mark.parametrize("seed", [0xA1, 0xA2, 0xA3, 0xA4])
    def test_reordered_record_never_applies_partially(self, tmp_path,
                                                      seed):
        s = _mkstore(tmp_path / "fs", owner="osd.7")
        s.apply_transaction(T().create_collection("c")
                            .write("c", "base", 0, b"acked-before"))
        self._arm(seed)
        big = bytes(range(256)) * 80          # ~20 KiB: many pages
        t = T().write("c", "victim", 0, big)
        acked = []
        t.register_on_commit(lambda: acked.append(1))
        with pytest.raises(CrashPoint):
            s.queue_transactions([t])
        assert not acked
        assert s.journal_stats()["fsync_reorder_windows"] == 1
        # both one-shot rules consumed together
        assert not faults.get().rules()
        s.umount()
        state, counters = _state(tmp_path / "fs")
        assert state["base"] == b"acked-before"
        # whole-or-nothing: zeroed pages fail the crc (or the torn
        # header fails to parse) and the tail is discarded — surviving
        # LATER pages must never resurrect a partial record
        assert state.get("victim") in (None, big)
        if state.get("victim") is None:
            assert counters["journal_torn_tail_discards"] + \
                counters["journal_bad_record_halts"] >= 1

    def test_reordered_checkpoint_falls_back_to_full_replay(
            self, tmp_path):
        """fsync reordering on the SNAPSHOT checkpoint write: the
        rename metadata commits while the body pages land as a seeded
        subset — mount finds a renamed-in but torn snapshot, detects
        it (crc/magic), counts the fallback, and rebuilds the whole
        state from full-journal replay.  No acked write is lost."""
        import random
        s = _mkstore(tmp_path / "fs", owner="osd.7")
        s.apply_transaction(T().create_collection("c"))
        bodies = {}
        for i in range(6):
            # incompressible payloads: the compressed snapshot must
            # span many 4 KiB pages so the seeded subset really tears
            bodies[f"o{i}"] = random.Random(i).randbytes(8192)
            s.apply_transaction(T().write("c", f"o{i}", 0,
                                          bodies[f"o{i}"]))
        faults.get().reset(seed=0xBEEF)
        faults.get().fsync_reorder(1.0, "osd.7")
        faults.get().crash("snapshot.mid_write", 1.0, "osd.7")
        with pytest.raises(CrashPoint):
            s._checkpoint()
        assert s.journal_stats()["fsync_reorder_windows"] == 1
        assert not faults.get().rules()      # both one-shots consumed
        s.umount()
        # the torn snapshot WAS renamed in (reordering put the rename
        # ahead of the body pages)
        assert os.path.exists(str(tmp_path / "fs" / "snapshot"))
        state, counters = _state(tmp_path / "fs")
        assert counters["snapshot_corrupt_fallbacks"] == 1
        # full-journal replay restored every acked write bit-exact
        for oid, body in bodies.items():
            assert state[oid] == body
        assert counters["journal_records_replayed"] >= 7

    def test_reorder_mask_is_seed_deterministic(self, tmp_path):
        sizes = []
        for run in range(2):
            path = tmp_path / f"fs{run}"
            s = _mkstore(path, owner="osd.7")
            s.apply_transaction(T().create_collection("c"))
            self._arm(0xD00D)
            with pytest.raises(CrashPoint):
                s.apply_transaction(
                    T().write("c", "v", 0, bytes(range(256)) * 64))
            s.umount()
            with open(str(path / "journal"), "rb") as f:
                sizes.append(f.read())
        assert sizes[0] == sizes[1]
