"""Client-side journal library: record/replay/commit/trim (journal/
Journaler semantics — the rbd-mirror substrate)."""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.journal import Journaler, JournalError, entry_oid
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("jrnl", pg_num=4)
    ctx = rados.open_ioctx("jrnl")
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warm", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


class TestJournaler:
    def test_record_and_replay(self, io):
        j = Journaler(io, "j1")
        j.create(splay_width=3)
        entries = [f"event-{i}".encode() for i in range(20)]
        for e in entries:
            j.append(e)
        # a fresh handle (different process model) replays everything
        j2 = Journaler(io, "j1", client_id="peer").open()
        got = [e for _pos, e in j2.replay()]
        assert got == entries

    def test_replay_from_position(self, io):
        j = Journaler(io, "j2")
        j.create(splay_width=2)
        for i in range(10):
            j.append(f"n{i}".encode())
        got = list(j.replay(from_position=6))
        assert [pos for pos, _ in got] == [6, 7, 8, 9]
        assert [e for _, e in got] == [b"n6", b"n7", b"n8", b"n9"]

    def test_splay_spreads_entries(self, io):
        j = Journaler(io, "j3")
        j.create(splay_width=4)
        for i in range(8):
            j.append(b"x" * 100)
        sizes = [io.stat(entry_oid("j3", i))["size"] for i in range(4)]
        assert all(s > 0 for s in sizes)

    def test_duplicate_create_fails(self, io):
        j = Journaler(io, "j4")
        j.create()
        with pytest.raises(JournalError):
            Journaler(io, "j4").create()

    def test_open_missing_fails(self, io):
        with pytest.raises(JournalError):
            Journaler(io, "nope").open()

    def test_commit_and_trim(self, io):
        j = Journaler(io, "j5", client_id="a")
        # small object_size -> sets roll quickly (per_obj = 1)
        j.create(splay_width=2, entries_per_object=1)
        j.register_client("a")
        j.register_client("b")
        for i in range(10):
            j.append(f"e{i}".encode())
        # only client a has consumed; floor is 0 -> nothing trims
        j.commit(8)
        assert j.trim() == 0
        jb = Journaler(io, "j5", client_id="b").open()
        jb.commit(6)
        removed = j.trim()          # floor 6 -> sets below entry 6 die
        assert removed > 0
        # the tail past the floor must still replay
        got = [e for _pos, e in j.replay(from_position=6)]
        assert got == [b"e6", b"e7", b"e8", b"e9"]

    def test_remove(self, io):
        j = Journaler(io, "j6")
        j.create(splay_width=2)
        for i in range(5):
            j.append(b"z")
        j.remove()
        with pytest.raises(JournalError):
            Journaler(io, "j6").open()
        assert not any(n.startswith("j6.")
                       for n in io.list_objects())

    def test_mirror_tail_pattern(self, io):
        """The rbd-mirror shape: a writer records, a peer tails
        incrementally with commits, trimming follows the slowest."""
        w = Journaler(io, "mir", client_id="primary")
        w.create(splay_width=2, entries_per_object=1)
        w.register_client("primary")
        w.register_client("peer")
        peer = Journaler(io, "mir", client_id="peer").open()
        applied = []
        pos = 0
        for batch in range(3):
            for i in range(4):
                w.append(f"b{batch}i{i}".encode())
            w.commit(4 * (batch + 1))
            for p, e in peer.replay(from_position=pos):
                applied.append(e)
                pos = p + 1
            peer.commit(pos)
            w.trim()
        assert len(applied) == 12
        assert applied[0] == b"b0i0" and applied[-1] == b"b2i3"

    def test_concurrent_appenders_unique_positions(self, io):
        """CAS position allocation: two recorders never collide and
        replay yields every entry exactly once in position order."""
        import threading
        j = Journaler(io, "conc")
        j.create(splay_width=3, entries_per_object=4)
        writers = [Journaler(io, "conc", client_id=f"w{i}").open()
                   for i in range(3)]
        recorded = [[] for _ in writers]

        def run(idx):
            for k in range(8):
                payload = f"w{idx}e{k}".encode()
                pos = writers[idx].append(payload)
                recorded[idx].append((pos, payload))

        ts = [threading.Thread(target=run, args=(i,))
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        positions = [p for r in recorded for p, _ in r]
        assert sorted(positions) == list(range(24))   # no collisions
        expect = {p: e for r in recorded for p, e in r}
        got = dict(j.replay())
        assert got == expect

    def test_reregistration_keeps_commit_position(self, io):
        j = Journaler(io, "rereg", client_id="a")
        j.create(splay_width=2, entries_per_object=1)
        j.register_client("a")
        for i in range(6):
            j.append(f"x{i}".encode())
        j.commit(5)
        j.register_client("a")      # daemon restart path: no-op
        assert j._commit_positions()["a"] == 5
