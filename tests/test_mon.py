"""Monitor tier tests: election, paxos, commands, map propagation.

The reference pattern (test/mon/*.sh on real daemons): real Monitor
instances with real messengers on localhost ports, one process.
"""

import time

import pytest

from ceph_tpu.mon import MonClient, MonMap, Monitor
from ceph_tpu.msg import Messenger
from ceph_tpu.utils.config import Config


def make_cluster(n=3, conf=None):
    conf = conf or Config({"mon_tick_interval": 0.5,
                           "mon_osd_down_out_interval": 2.0})
    mm = MonMap(fsid="test-fsid")
    mons = []
    # bind ephemeral ports first via temporary messengers? simpler:
    # pre-pick free ports by binding sockets
    import socket
    addrs = {}
    socks = []
    for i in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        addrs[chr(ord("a") + i)] = ("127.0.0.1", s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    for name, addr in addrs.items():
        mm.add(name, addr)
    for name in mm.ranks():
        mons.append(Monitor(name, mm, conf=conf))
    for m in mons:
        m.start()
    return mm, mons


def wait_for(pred, timeout=10, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def cluster():
    mm, mons = make_cluster(3)
    yield mm, mons
    for m in mons:
        m.shutdown()


def make_client(mm, name="client.admin"):
    msgr = Messenger(name)
    msgr.bind(("127.0.0.1", 0))
    msgr.start()
    return msgr, MonClient(msgr, mm)


class TestQuorum:
    def test_leader_elected(self, cluster):
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        leaders = [m for m in mons if m.paxos.is_leader()]
        assert len(leaders) == 1
        # lowest rank wins
        assert leaders[0].name == mm.ranks()[0]
        # everyone agrees on the quorum
        assert wait_for(lambda: all(
            len(m.elector.quorum) == 3 for m in mons))

    def test_paxos_commit_replicates(self, cluster):
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        leader = next(m for m in mons if m.is_leader())
        from ceph_tpu.utils import denc
        with leader.lock:
            leader.paxos.propose(denc.dumps(
                [("set", "testsvc", "key", b"value-1")]))
        assert wait_for(lambda: all(
            m.store.get("testsvc", "key") == b"value-1" for m in mons))
        assert all(m.paxos.last_committed >= 1 for m in mons)


class TestCommands:
    def test_status_and_pool_create(self, cluster):
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        msgr, mc = make_client(mm)
        try:
            rv, out, _ = mc.command({"prefix": "status"})
            assert rv == 0
            assert "quorum" in out
            rv, out, _ = mc.command({"prefix": "osd pool create",
                                     "pool": "data", "pg_num": 8})
            assert rv == 0, out
            rv, out, _ = mc.command({"prefix": "osd pool ls"})
            assert rv == 0
            assert "data" in out
            # pool visible on every mon (paxos-replicated)
            assert wait_for(lambda: all(
                m.osdmon.osdmap.pool_by_name("data") for m in mons))
        finally:
            msgr.shutdown()

    def test_ec_profile_validation(self, cluster):
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        msgr, mc = make_client(mm)
        try:
            rv, out, _ = mc.command({
                "prefix": "osd erasure-code-profile set", "name": "p1",
                "profile": ["plugin=jerasure", "k=4", "m=2",
                            "technique=reed_sol_van"]})
            assert rv == 0, out
            rv, out, _ = mc.command({
                "prefix": "osd erasure-code-profile get", "name": "p1"})
            assert rv == 0
            assert "k=4" in out
            # invalid profile rejected by plugin instantiation
            rv, out, _ = mc.command({
                "prefix": "osd erasure-code-profile set", "name": "bad",
                "profile": ["plugin=jerasure", "k=300", "m=5"]})
            assert rv != 0
            rv, out, _ = mc.command({
                "prefix": "osd erasure-code-profile ls"})
            assert "p1" in out and "bad" not in out
        finally:
            msgr.shutdown()

    def test_ec_pool_create(self, cluster):
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        msgr, mc = make_client(mm)
        try:
            rv, out, _ = mc.command({
                "prefix": "osd erasure-code-profile set", "name": "ec42",
                "profile": ["plugin=tpu", "k=4", "m=2"]})
            assert rv == 0, out
            rv, out, _ = mc.command({
                "prefix": "osd pool create", "pool": "ecpool",
                "pool_type": "erasure", "erasure_code_profile": "ec42"})
            assert rv == 0, out
            leader = next(m for m in mons if m.is_leader())
            pool = leader.osdmon.osdmap.pool_by_name("ecpool")
            assert pool.is_erasure
            assert pool.size == 6 and pool.min_size == 5
        finally:
            msgr.shutdown()


class TestOsdLifecycle:
    def test_boot_and_failure(self, cluster):
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        msgr, mc = make_client(mm, "osd.0")
        try:
            mc.send_boot(0, ("127.0.0.1", 7000))
            assert wait_for(lambda: all(
                m.osdmon.osdmap.is_up(0) for m in mons), timeout=10)
            # failure report marks it down
            mc.report_failure(0, 25.0)
            assert wait_for(lambda: not mons[0].osdmon.osdmap.is_up(0),
                            timeout=10)
            # ... and the down->out tick marks it out
            assert wait_for(
                lambda: not mons[0].osdmon.osdmap.is_in(0), timeout=15)
        finally:
            msgr.shutdown()

    def test_osdmap_subscription(self, cluster):
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        msgr, mc = make_client(mm)
        msgr2, mc2 = make_client(mm, "client.watcher")
        try:
            mc2.sub_want_osdmap(0)
            rv, _, _ = mc.command({"prefix": "osd pool create",
                                   "pool": "subtest"})
            assert rv == 0
            assert wait_for(
                lambda: mc2.osdmap.pool_by_name("subtest") is not None,
                timeout=10)
        finally:
            msgr.shutdown()
            msgr2.shutdown()

    def test_subscription_survives_session_drop(self, cluster):
        """A mon pops a session's subs when its lossy push link resets
        (monitor.py ms_handle_reset); the subscriber's own conn stays
        healthy so it never sees the drop.  Renewal must re-assert the
        sub so map updates keep flowing — without it, one dropped push
        link freezes the subscriber's map forever (the round-4 op-
        timeout wedge)."""
        mm, mons = cluster
        assert wait_for(lambda: any(m.is_leader() for m in mons))
        msgr, mc = make_client(mm)
        msgr2, mc2 = make_client(mm, "client.dropped")
        try:
            mc2.sub_want_osdmap(0)
            rv, _, _ = mc.command({"prefix": "osd pool create",
                                   "pool": "drop1"})
            assert rv == 0
            assert wait_for(
                lambda: mc2.osdmap.pool_by_name("drop1") is not None)
            # simulate the lossy push-link reset on EVERY mon: the
            # session (and its standing sub) vanishes server-side
            for m in mons:
                with m.lock:
                    m.subs.pop("client.dropped", None)
            rv, _, _ = mc.command({"prefix": "osd pool create",
                                   "pool": "drop2"})
            assert rv == 0
            # only the ~2s renewal can resubscribe and pull the gap
            assert wait_for(
                lambda: mc2.osdmap.pool_by_name("drop2") is not None,
                timeout=15)
        finally:
            msgr.shutdown()
            msgr2.shutdown()


class TestFailover:
    def test_leader_death_reelects(self):
        mm, mons = make_cluster(3)
        try:
            assert wait_for(lambda: any(m.is_leader() for m in mons))
            leader = next(m for m in mons if m.is_leader())
            survivors = [m for m in mons if m is not leader]
            leader.shutdown()
            # surviving mons must re-elect once they notice; nudge via
            # election restart (paxos lease timeout path)
            time.sleep(0.5)
            for m in survivors:
                with m.lock:
                    m.elector.start()
            assert wait_for(lambda: any(
                m.is_leader() for m in survivors), timeout=15)
            new_leader = next(m for m in survivors if m.is_leader())
            # quorum of 2 can still commit
            from ceph_tpu.utils import denc
            with new_leader.lock:
                new_leader.paxos.propose(denc.dumps(
                    [("set", "t", "k", b"after-failover")]))
            assert wait_for(lambda: all(
                m.store.get("t", "k") == b"after-failover"
                for m in survivors), timeout=10)
        finally:
            for m in mons:
                if not m._stopped:
                    m.shutdown()


class TestMembership:
    """mon/MonmapMonitor.cc:320 prepare_command: membership changes
    proposed through paxos; roster changes force re-election."""

    def _free_addrs(self, n):
        import socket
        socks = [socket.socket() for _ in range(n)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        addrs = [("127.0.0.1", s.getsockname()[1]) for s in socks]
        for s in socks:
            s.close()
        return addrs

    def test_grow_one_to_three_kill_leader(self):
        from ceph_tpu.mon.monmap import MonMap as MM
        addr_a, addr_b, addr_c = self._free_addrs(3)
        mm = MonMap(fsid="grow-fsid")
        mm.add("a", addr_a)
        mons = {"a": Monitor("a", mm)}
        mons["a"].start()
        try:
            assert wait_for(lambda: mons["a"].is_leader())
            msgr, mc = make_client(mm)
            try:
                mc.subscribe({"monmap": 0})
                assert wait_for(lambda: mc.monmap.size == 1)

                rv, out, _ = mc.command({"prefix": "mon add",
                                         "name": "b",
                                         "addr": list(addr_b)})
                assert rv == 0, out
                # the adoption push updates the client's monmap
                assert wait_for(lambda: "b" in mc.monmap.mons)
                # quorum now needs 2 of {a,b}: boot b seeded with the
                # pushed map; the stalled election completes
                mons["b"] = Monitor("b", mc.monmap.copy())
                mons["b"].start()
                assert wait_for(lambda: any(
                    m.is_leader() and len(m.elector.quorum) == 2
                    for m in mons.values()), timeout=15)

                rv, out, _ = mc.command({"prefix": "mon add",
                                         "name": "c",
                                         "addr": list(addr_c)})
                assert rv == 0, out
                assert wait_for(lambda: "c" in mc.monmap.mons,
                                timeout=15)
                mons["c"] = Monitor("c", mc.monmap.copy())
                mons["c"].start()
                assert wait_for(lambda: any(
                    m.is_leader() and len(m.elector.quorum) == 3
                    for m in mons.values()), timeout=15)

                # maps advance with the grown quorum
                rv, _, _ = mc.command({"prefix": "osd pool create",
                                       "pool": "grown"})
                assert rv == 0
                rv, _, data = mc.command({"prefix": "mon dump"})
                assert rv == 0
                committed = MM.decode(data)
                assert set(committed.ranks()) == {"a", "b", "c"}

                # kill the leader: survivors re-form quorum of 2 and
                # keep committing
                leader = next(m for m in mons.values()
                              if m.is_leader())
                survivors = [m for m in mons.values()
                             if m is not leader]
                leader.shutdown()
                time.sleep(0.5)
                for m in survivors:
                    with m.lock:
                        m.elector.start()
                assert wait_for(lambda: any(
                    m.is_leader() for m in survivors), timeout=20)
                rv, _, _ = mc.command({"prefix": "osd pool create",
                                       "pool": "after-failover"},
                                      timeout=60)
                assert rv == 0
                new_leader = next(m for m in survivors
                                  if m.is_leader())
                assert wait_for(lambda: all(
                    m.osdmon.osdmap.pool_by_name("after-failover")
                    is not None for m in survivors), timeout=10)
            finally:
                msgr.shutdown()
        finally:
            for m in mons.values():
                if not m._stopped:
                    m.shutdown()

    def test_remove_mon_shrinks_quorum(self):
        mm, mons = make_cluster(3)
        try:
            assert wait_for(lambda: any(m.is_leader() for m in mons))
            msgr, mc = make_client(mm)
            try:
                victim = mons[-1]
                rv, out, _ = mc.command({"prefix": "mon remove",
                                         "name": victim.name})
                assert rv == 0, out
                assert wait_for(lambda: all(
                    victim.name not in m.monmap.mons
                    for m in mons if m is not victim), timeout=15)
                victim.shutdown()
                # remaining 2-of-2 quorum still commits
                assert wait_for(lambda: any(
                    m.is_leader() and len(m.elector.quorum) == 2
                    for m in mons[:-1]), timeout=20)
                rv, _, _ = mc.command({"prefix": "osd pool create",
                                       "pool": "post-remove"},
                                      timeout=60)
                assert rv == 0
                # the last mon cannot be removed
                survivor_names = [m.name for m in mons[:-1]]
                rv, out, _ = mc.command({"prefix": "mon remove",
                                         "name": survivor_names[0]})
                assert rv == 0
                rv, out, _ = mc.command({"prefix": "mon remove",
                                         "name": survivor_names[1]},
                                        timeout=60)
                assert rv == -22
            finally:
                msgr.shutdown()
        finally:
            for m in mons:
                if not m._stopped:
                    m.shutdown()


# ---------------------------------------------------------------------------
# Mon paxos crash-point matrix (Protocol-Aware Recovery): a mon that
# accepted or committed a value never forgets it after an abrupt
# remount, and a torn local commit is detected and contained rather
# than silently adopted.
# ---------------------------------------------------------------------------


from ceph_tpu.mon.paxos import Paxos
from ceph_tpu.mon.store import MonitorDBStore
from ceph_tpu.utils import denc, faults
from ceph_tpu.utils.faults import CrashPoint


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)


def _mk_paxos(name, send=None, db=None):
    store = MonitorDBStore()
    if db is not None:
        store.db = db
    store.owner = name
    store.open()
    p = Paxos(name, store, send or (lambda peer, msg: None),
              on_commit=lambda v: None)
    return p, store


def _value(key, payload):
    return denc.dumps([("set", "tsvc", key, payload)])


class TestMonCrashMatrix:
    def test_pre_commit_crash_never_forgets_accepted(self):
        """Crash before any commit byte lands: the journaled
        (accepted) value survives the remount and singleton recovery
        re-commits it — an accepting mon never forgets."""
        p, store = _mk_paxos("mon.x")
        p.leader_init(["mon.x"], 0)
        assert p.is_writeable()
        faults.get().crash("paxos.pre_commit", 1.0, "mon.x")
        with pytest.raises(CrashPoint):
            p.propose(_value("k", b"accepted-v1"))
        assert store.frozen and store.crash_site == "paxos.pre_commit"
        assert store.get("tsvc", "k") is None, "nothing may commit"
        # remount the same "disk" through a fresh wrapper
        p2, store2 = _mk_paxos("mon.x", db=store.db)
        assert store2.check_integrity() == 0
        assert p2.uncommitted_v == 1, "accepted value forgotten"
        p2.leader_init(["mon.x"], 0)       # singleton recovery
        assert p2.last_committed == 1
        assert store2.get("tsvc", "k") == b"accepted-v1"

    @pytest.mark.parametrize("seed", [0x5EED, 0xA11CE, 0xBAD])
    def test_mid_commit_torn_txn_recovers_whole(self, seed):
        """The commit transaction tears at a seeded prefix: after
        remount + integrity check + singleton recovery the value is
        committed WHOLE — never a half-applied commit serving reads."""
        faults.get().reset(seed=seed)
        p, store = _mk_paxos("mon.x")
        p.leader_init(["mon.x"], 0)
        p.propose(_value("base", b"committed-clean"))
        assert p.last_committed == 1
        faults.get().crash("paxos.mid_commit", 1.0, "mon.x")
        with pytest.raises(CrashPoint):
            p.propose(_value("k", b"torn-v2"))
        p2, store2 = _mk_paxos("mon.x", db=store.db)
        store2.check_integrity()
        p2.leader_init(["mon.x"], 0)
        # the clean commit is untouched, and v2 either fully recovered
        # (re-committed from the surviving uncommitted record) or the
        # claim rolled back — but never a silent partial adoption
        assert store2.get("tsvc", "base") == b"committed-clean"
        assert p2.last_committed == 2
        assert store2.get("tsvc", "k") == b"torn-v2"

    def test_stale_last_committed_marker_detected(self):
        """The seeded corruption matrix's stale-marker case: a
        last_committed claim with no commit behind it (torn txn that
        landed ONLY the marker) is detected and rolled back."""
        p, store = _mk_paxos("mon.x")
        p.leader_init(["mon.x"], 0)
        for i in range(3):
            p.propose(_value(f"k{i}", f"v{i}".encode()))
        assert p.last_committed == 3
        txn = store.transaction()
        store.put_int(txn, "paxos", "last_committed", 5)
        store.db.submit_transaction(txn)
        store2 = MonitorDBStore()
        store2.db = store.db
        store2.owner = "mon.x"
        assert store2.check_integrity() == 2       # 5 -> 3
        assert store2.get_int("paxos", "last_committed") == 3
        assert store2.counters["paxos_torn_commit_repairs"] == 1

    def test_missing_head_blob_detected(self):
        """A torn commit that bumped last_committed but lost the
        version blob rolls back to the last verifiable version."""
        p, store = _mk_paxos("mon.x")
        p.leader_init(["mon.x"], 0)
        for i in range(3):
            p.propose(_value(f"k{i}", f"v{i}".encode()))
        txn = store.transaction()
        txn.rmkey("paxos", f"{3:020d}")            # lose blob v3
        store.db.submit_transaction(txn)
        store2 = MonitorDBStore()
        store2.db = store.db
        store2.owner = "mon.x"
        assert store2.check_integrity() >= 1
        assert store2.get_int("paxos", "last_committed") < 3
        assert store2.counters["paxos_torn_commit_repairs"] == 1

    def test_dropped_service_ops_healed_by_reapply(self):
        """A reordered subset tear can land the seal while dropping a
        SERVICE op of the same transaction — undetectable by markers
        alone.  check_integrity re-applies the head version's op list
        at every mount, healing the window."""
        p, store = _mk_paxos("mon.x")
        p.leader_init(["mon.x"], 0)
        p.propose(_value("k", b"the-payload"))
        txn = store.transaction()
        txn.rmkey("tsvc", "k")           # the dropped service op
        store.db.submit_transaction(txn)
        store2 = MonitorDBStore()
        store2.db = store.db
        store2.owner = "mon.x"
        assert store2.check_integrity() == 0       # markers all agree
        assert store2.get("tsvc", "k") == b"the-payload", \
            "head re-apply must heal dropped service ops"

    def test_post_accept_pre_ack_peon_reoffers(self):
        """PAR's core scenario: a peon journals an accepted value,
        crashes before the ACCEPT leaves, remounts — and must OFFER
        the value in the next collect round so the quorum re-commits
        it rather than losing an accept the leader counted on."""
        inboxes = {}

        def send_to(target_name, self_name):
            def send(peer, msg):
                msg.src = self_name
                inboxes.setdefault(peer, []).append(msg)
            return send

        a, astore = _mk_paxos("mon.a", send=send_to("mon.b", "mon.a"))
        b, bstore = _mk_paxos("mon.b", send=send_to("mon.a", "mon.b"))
        peers = {"mon.a": a, "mon.b": b}

        def pump(allow_crash=False):
            moved = True
            while moved:
                moved = False
                for name, queue in list(inboxes.items()):
                    while queue:
                        msg = queue.pop(0)
                        moved = True
                        try:
                            peers[name].handle(msg)
                        except CrashPoint:
                            if not allow_crash:
                                raise
                            queue.clear()
                            return

        a.leader_init(["mon.a", "mon.b"], 0)
        b.peon_init("mon.a", ["mon.a", "mon.b"], 1)
        pump()
        assert a.is_writeable()
        faults.get().crash("paxos.post_accept_pre_ack", 1.0, "mon.b")
        a.propose(_value("k", b"accepted-on-peon"))
        pump(allow_crash=True)               # b dies mid-BEGIN
        assert bstore.frozen
        assert a.last_committed == 0, "leader must still be waiting"
        # remount the peon; its accepted value must survive
        b2, bstore2 = _mk_paxos("mon.b", send=send_to("mon.a", "mon.b"),
                                db=bstore.db)
        assert bstore2.check_integrity() == 0
        assert b2.uncommitted_v == 1, "peon forgot its accept"
        peers["mon.b"] = b2
        inboxes.clear()
        # next election round: the collect must surface b's value
        a.leader_init(["mon.a", "mon.b"], 0)
        b2.peon_init("mon.a", ["mon.a", "mon.b"], 1)
        pump()
        assert a.last_committed == 1
        assert b2.last_committed == 1
        assert astore.get("tsvc", "k") == b"accepted-on-peon"
        assert bstore2.get("tsvc", "k") == b"accepted-on-peon"

    def test_torn_commit_repaired_from_quorum_not_adopted(self):
        """A leader's torn commit rolls back at remount and the next
        collect round repairs it from the quorum's committed copy."""
        inboxes = {}

        def send_to(self_name):
            def send(peer, msg):
                msg.src = self_name
                inboxes.setdefault(peer, []).append(msg)
            return send

        a, astore = _mk_paxos("mon.a", send=send_to("mon.a"))
        b, bstore = _mk_paxos("mon.b", send=send_to("mon.b"))
        peers = {"mon.a": a, "mon.b": b}

        def pump(allow_crash=False):
            moved = True
            while moved:
                moved = False
                for name, queue in list(inboxes.items()):
                    while queue:
                        msg = queue.pop(0)
                        moved = True
                        try:
                            peers[name].handle(msg)
                        except CrashPoint:
                            if not allow_crash:
                                raise
                            queue.clear()
                            return

        a.leader_init(["mon.a", "mon.b"], 0)
        b.peon_init("mon.a", ["mon.a", "mon.b"], 1)
        pump()
        a.propose(_value("w0", b"warm"))
        pump()
        assert a.last_committed == b.last_committed == 1
        # the leader's local commit tears; the peon, having journaled
        # the accept, is the surviving authority
        faults.get().crash("paxos.mid_commit", 1.0, "mon.a")
        a.propose(_value("k", b"quorum-repairs-me"))
        try:
            pump(allow_crash=True)
        except CrashPoint:
            pass                              # leader died committing
        assert astore.frozen
        a2, astore2 = _mk_paxos("mon.a", send=send_to("mon.a"),
                                db=astore.db)
        astore2.check_integrity()
        peers["mon.a"] = a2
        inboxes.clear()
        a2.leader_init(["mon.a", "mon.b"], 0)
        b.peon_init("mon.a", ["mon.a", "mon.b"], 1)
        pump()
        assert a2.last_committed == 2
        assert b.last_committed == 2
        assert astore2.get("tsvc", "k") == b"quorum-repairs-me"
        assert bstore.get("tsvc", "k") == b"quorum-repairs-me"


class TestLeaderDeathSelfHealing:
    def test_survivors_elect_without_manual_poke(self):
        """Peon lease watchdog: killing the leader abruptly (no
        goodbye, no manual elector.start) must produce a new leader
        among the survivors within a few lease windows."""
        mm, mons = make_cluster(3)
        try:
            assert wait_for(lambda: any(m.is_leader() for m in mons))
            leader = next(m for m in mons if m.is_leader())
            survivors = [m for m in mons if m is not leader]
            leader.abort()
            assert wait_for(lambda: any(m.is_leader()
                                        for m in survivors),
                            timeout=30), \
                "survivors never self-elected after leader death"
            # and the new quorum commits
            msgr, mc = make_client(mm)
            try:
                rv, _, _ = mc.command({"prefix": "osd pool create",
                                       "pool": "healed"}, timeout=60)
                assert rv == 0
            finally:
                msgr.shutdown()
        finally:
            for m in mons:
                if not m._stopped:
                    m.shutdown()
