"""Pod-scale EC mesh dispatch: ONE batch shard_mapped across the
device mesh, with donated pinned staging.

conftest.py forces an 8-device CPU host platform, so these exercise
the real mesh placement/degrade code paths a TPU pod runs.  Tier-1
contracts pinned here:

  * the mesh-sharded fused encode+CRC is BIT-EXACT vs the
    single-device fused kernel vs the host oracle over odd/uneven B
    and L — including L not divisible by the mesh width (front-padded
    shards) and an explicit dp x ls axis layout;
  * placement chooses mesh mode when a coalesced batch's staged bytes
    exceed a single lane's budget (osd_ec_mesh_min_bytes), and the
    plugin path serves it bit-exactly vs the oracle codec;
  * donation safety: a staging arena is exclusively owned while its
    dispatch is in flight (concurrent checkouts never share a
    buffer), a donated arena is never re-read by the pipeline, and
    release() recycles it zeroed;
  * the quarantine ladder: a device fault on one mesh member degrades
    the dispatch to surviving-lane row splits (then host)
    bit-identically, with mesh_dispatches / mesh_degrades counted;
  * the scrub CRC channel's mega-batches ride the mesh too, with
    per-shard partials combined on device.
"""

import threading
import time

import numpy as np
import pytest

from ceph_tpu.erasure.registry import registry
from ceph_tpu.ops import crc32c as crc_mod
from ceph_tpu.ops import ec_kernels, gf
from ceph_tpu.ops import pipeline as ec_pipeline
from ceph_tpu.utils import copyaudit, faults

K, M, L = 3, 2, 256
MATRIX = gf.reed_sol_van_matrix(K, M)
WARM = 120.0


@pytest.fixture(autouse=True)
def _clean():
    faults.get().reset(seed=0)
    pipe = ec_pipeline.get()
    saved = (pipe.mesh_min_bytes, pipe.device_mesh)
    yield
    faults.get().reset(seed=0)
    ec_pipeline.configure(mesh_min_bytes=saved[0],
                          device_mesh=saved[1])
    st = pipe.stats()
    if st["devices"] and any(d["quarantined"]
                             for d in st["devices"].values()):
        pipe.reset_devices()


def _oracle_encode_crc(matrix, batch):
    parity = np.stack([gf.encode_np(matrix, batch[b])
                       for b in range(batch.shape[0])])
    allc = np.concatenate([batch, parity], axis=1)
    B, km, length = allc.shape
    crcs = crc_mod.crc32c_batch(
        np.ascontiguousarray(allc).reshape(B * km, length)
    ).reshape(B, km).astype(np.uint32)
    return parity, crcs


@pytest.mark.parametrize("S,length,n_dp,n_ls", [
    (1, 192, 1, 8),     # minimal batch, L divides evenly
    (5, 250, 1, 8),     # odd S, L % 8 != 0 -> front-padded shards
    (3, 100, 2, 4),     # explicit dp x ls layout, S % dp != 0 too
])
def test_mesh_kernel_bitexact_vs_single_device_and_oracle(
        S, length, n_dp, n_ls):
    import jax
    devices = jax.devices()[: n_dp * n_ls]
    run = ec_kernels.make_mesh_encode_crc_fn(
        MATRIX, length, devices, n_dp, n_ls)
    rng = np.random.default_rng(S * 1000 + length)
    batch = rng.integers(0, 256, size=(S, K, length), dtype=np.uint8)
    parity, crcs, _res = run(batch)
    # single-device fused kernel (padded to its own pow2 bucket)
    single = ec_kernels.make_encode_crc_fn(MATRIX, length)
    padded = ec_pipeline.pad_batch(batch)
    sp, sc = (np.asarray(o)[:S] for o in single(padded))
    # host oracle
    hp, hc = _oracle_encode_crc(MATRIX, batch)
    np.testing.assert_array_equal(parity, hp)
    np.testing.assert_array_equal(crcs, hc)
    np.testing.assert_array_equal(sp, hp)
    np.testing.assert_array_equal(sc, hc)


def test_mesh_keeps_resident_arrays_unless_donated():
    import jax
    devices = jax.devices()
    run = ec_kernels.make_mesh_encode_crc_fn(MATRIX, 250, devices,
                                             1, len(devices))
    batch = np.arange(2 * K * 250, dtype=np.uint64).astype(
        np.uint8).reshape(2, K, 250)
    parity, crcs, res = run(batch, keep_resident=True)
    assert res is not None
    dev_data, dev_parity, pad = res
    assert pad == run.chunk_pad and pad > 0
    # per-shard addressing over the sharded arrays round-trips
    np.testing.assert_array_equal(
        np.asarray(dev_data)[:2, :, pad:], batch)
    np.testing.assert_array_equal(
        np.asarray(dev_parity)[:2, :, pad:], parity)
    donated = ec_kernels.make_mesh_encode_crc_fn(
        MATRIX, 250, devices, 1, len(devices), donate=True)
    _p, _c, res2 = donated(batch, keep_resident=True)
    assert res2 is None     # donated input: nothing to keep resident


def _drive_until_mesh(codec, batch, stats_key="mesh_dispatches",
                      window=WARM):
    """Submit `batch` until the pipeline serves one via the mesh
    (the mesh executable warms in a background thread); returns the
    last result and the stats delta."""
    pipe = ec_pipeline.get()
    start = pipe.stats()[stats_key]
    end = time.time() + window
    out = None
    while time.time() < end:
        out = codec.encode_stripes_with_crcs_async(batch.copy())\
            .result(60)
        if pipe.stats()[stats_key] > start:
            return out, pipe.stats()[stats_key] - start
        time.sleep(0.2)
    return out, pipe.stats()[stats_key] - start


class TestMeshDispatchThroughPlugin:
    def _codec(self):
        return registry.factory(
            "tpu", {"k": str(K), "m": str(M),
                    "technique": "reed_sol_van", "host_cutover": "1"})

    def test_over_budget_batch_rides_mesh_bitexact(self):
        codec = self._codec()
        oracle = registry.factory(
            "jerasure", {"k": str(K), "m": str(M),
                         "technique": "reed_sol_van"})
        ec_pipeline.configure(mesh_min_bytes=1024, device_mesh="auto")
        rng = np.random.default_rng(11)
        batch = rng.integers(0, 256, size=(5, K, L), dtype=np.uint8)
        (allc, crcs), meshed = _drive_until_mesh(codec, batch)
        assert meshed >= 1, ec_pipeline.stats()
        allc_o, crcs_o = oracle.encode_stripes_with_crcs(batch)
        np.testing.assert_array_equal(allc, allc_o)
        np.testing.assert_array_equal(crcs, crcs_o)
        st = ec_pipeline.stats()
        assert st["mesh"] is not None
        assert st["mesh"]["dp"] * st["mesh"]["ls"] >= 2
        # under the budget: classic lane placement, never the mesh
        small = rng.integers(0, 256, size=(1, K, 16), dtype=np.uint8)
        before = st["mesh_dispatches"]
        codec.encode_stripes_with_crcs_async(small).result(60)
        assert ec_pipeline.stats()["mesh_dispatches"] == before

    def test_one_mesh_member_fault_degrades_to_row_splits(self):
        codec = self._codec()
        oracle = registry.factory(
            "jerasure", {"k": str(K), "m": str(M),
                         "technique": "reed_sol_van"})
        ec_pipeline.configure(mesh_min_bytes=1024, device_mesh="auto")
        rng = np.random.default_rng(13)
        batch = rng.integers(0, 256, size=(5, K, L), dtype=np.uint8)
        _out, meshed = _drive_until_mesh(codec, batch)
        assert meshed >= 1
        st0 = ec_pipeline.stats()
        faults.get().tpu_device_error(1.0, device="2")
        allc, crcs = codec.encode_stripes_with_crcs_async(
            batch.copy()).result(60)
        faults.get().reset(seed=0)
        allc_o, crcs_o = oracle.encode_stripes_with_crcs(batch)
        np.testing.assert_array_equal(allc, allc_o)
        np.testing.assert_array_equal(crcs, crcs_o)
        st = ec_pipeline.stats()
        assert st["mesh_degrades"] > st0["mesh_degrades"]
        assert st["quarantines"] > st0["quarantines"]
        assert st["devices"]["2"]["quarantined"]
        # the codec must NOT degrade: survivors served the batch
        assert not codec.degraded
        ec_pipeline.get().reset_devices()

    def test_mesh_failure_midflight_requeues_to_row_splits(self):
        """An exception INSIDE the mesh computation (not attributable
        to one chip) drops the plane and requeues the batch latched
        off the mesh — no lane quarantines on this rung."""
        pipe = ec_pipeline.get()
        ec_pipeline.configure(mesh_min_bytes=1)
        calls = []

        def host_fn(batch):
            return (batch.astype(np.uint16) * 2,)

        def device_fn(padded, device=None):
            return None         # cold forever: host serves after mesh

        def mesh_fn(batch, plane, donate=False, keep_resident=False):
            calls.append(batch.shape)
            raise RuntimeError("mesh blew up")

        chan = ec_pipeline.PipelineChannel(
            key=("t", "meshfail"), host_fn=host_fn,
            device_fn=device_fn, route=lambda n: True,
            mesh_fn=mesh_fn)
        st0 = pipe.stats()
        arr = np.arange(4 * 8, dtype=np.uint64).astype(
            np.uint8).reshape(4, 8)
        path, (out,) = pipe.submit(chan, arr).result(30)
        st = pipe.stats()
        assert calls, "mesh_fn was never tried"
        np.testing.assert_array_equal(out, arr.astype(np.uint16) * 2)
        assert st["mesh_degrades"] > st0["mesh_degrades"]
        assert st["quarantines"] == st0["quarantines"]
        assert st["redrained"] > st0["redrained"]


class TestStagingArenas:
    def test_concurrent_checkouts_never_share_and_reuse_is_zeroed(self):
        pipe = ec_pipeline.EcDevicePipeline(mesh_min_bytes=1024)
        assert pipe.checkout_arena(512) is None     # under the budget
        a1 = pipe.checkout_arena(2048, payload_bytes=2000)
        a2 = pipe.checkout_arena(2048, payload_bytes=2000)
        assert a1 is not None and a2 is not None
        assert a1.buf is not a2.buf
        buf1 = a1.buf
        buf1[:] = 0xAB
        a1.noted = True                 # "the pipeline resolved it"
        a1.release()
        assert a1.buf is None
        a3 = pipe.checkout_arena(2048)
        assert a3.buf is buf1           # pooled reuse...
        assert not a3.buf.any()         # ...zeroed for the next write
        # tail-only zeroing: the caller-owned payload prefix is NOT
        # re-memset on reuse (it will be overwritten entirely), the
        # stripe-padding tail IS
        a3.noted = True
        a3.buf[:] = 0xCD
        a3.release()
        a4 = pipe.checkout_arena(2048, payload_bytes=2000)
        assert a4.buf is buf1
        assert not a4.buf[2000:].any()
        assert a4.buf[:2000].all()      # prefix left for the copy-in

    def test_unresolved_arena_is_dropped_not_recycled(self):
        """An arena whose item the pipeline never resolved (wedged
        dispatch, producer self-served) may still be viewed by the
        queued item — release must DROP it, never hand it to a new
        checkout that would zero it under the live reader."""
        pipe = ec_pipeline.EcDevicePipeline(mesh_min_bytes=1024)
        a1 = pipe.checkout_arena(2048, payload_bytes=2000)
        buf1 = a1.buf
        assert not (a1.consumed or a1.noted)
        a1.release()
        assert a1.buf is None
        a2 = pipe.checkout_arena(2048)
        assert a2.buf is not buf1

    def test_donated_arena_retires_ec_stage_and_is_not_reread(self):
        """On the mesh path the arena upload subsumes the staging
        copy: no ec.stage note, arena.consumed latches, and the
        pipeline resolves the batch purely from device outputs."""
        codec = registry.factory(
            "tpu", {"k": str(K), "m": str(M),
                    "technique": "reed_sol_van", "host_cutover": "1"})
        ec_pipeline.configure(mesh_min_bytes=1024)
        pipe = ec_pipeline.get()
        rng = np.random.default_rng(17)
        batch = rng.integers(0, 256, size=(5, K, L), dtype=np.uint8)
        # the DONATED executable is its own compile: retry with fresh
        # arenas until the donation lands (warming serves re-arm
        # ec.stage, which is exactly the re-arm contract)
        end = time.time() + WARM
        donated = False
        while time.time() < end and not donated:
            arena = pipe.checkout_arena(batch.nbytes,
                                        payload_bytes=batch.nbytes)
            assert arena is not None
            arena.buf[:] = batch.reshape(-1)
            stripes = arena.buf.reshape(batch.shape)
            d0 = pipe.stats()["arena_donations"]
            s0 = copyaudit.snapshot()["sites"].get(
                "ec.stage", {"copies": 0})["copies"]
            h = codec.encode_stripes_with_crcs_async(stripes,
                                                     arena=arena)
            allc, _crcs = h.result(60)
            np.testing.assert_array_equal(allc[:, :K], batch)
            if pipe.stats()["arena_donations"] > d0:
                donated = True
                s1 = copyaudit.snapshot()["sites"].get(
                    "ec.stage", {"copies": 0})["copies"]
                assert s1 == s0, \
                    "donated mesh write must not note ec.stage"
                assert arena.consumed and not arena.noted
            else:
                # not yet warm: the row-split/host serve must have
                # re-armed the staging-copy accounting instead
                assert arena.noted and not arena.consumed
            arena.release()
            time.sleep(0.1)
        assert donated, pipe.stats()

    def test_non_mesh_serve_rearms_ec_stage_accounting(self):
        """A batch staged into an arena that ends up host-served must
        still account its staging copy (the donation never happened)."""
        pipe = ec_pipeline.EcDevicePipeline(mesh_min_bytes=64)

        def host_fn(batch):
            return (batch,)

        chan = ec_pipeline.PipelineChannel(key=("t", "rearm"),
                                           host_fn=host_fn)
        arena = pipe.checkout_arena(256, payload_bytes=200)
        arr = arena.buf.reshape(16, 16)
        snap0 = copyaudit.snapshot()
        pipe.submit(chan, arr, arena=arena).result(10)
        snap1 = copyaudit.snapshot()
        pipe.stop()
        s0 = snap0["sites"].get("ec.stage", {"copies": 0, "bytes": 0})
        s1 = snap1["sites"].get("ec.stage", {"copies": 0, "bytes": 0})
        assert s1["copies"] == s0["copies"] + 1
        assert s1["bytes"] == s0["bytes"] + 200
        assert arena.noted and not arena.consumed


def test_scrub_crc_channel_rides_mesh():
    """Deep-scrub CRC folds over the lane budget shard_map too: the
    per-shard partials combine on device and only 4 bytes per row
    cross D2H."""
    size = 2048
    pipe = ec_pipeline.get()
    ec_pipeline.configure(mesh_min_bytes=1024)
    chan = ec_pipeline.crc_channel(size)
    rng = np.random.default_rng(19)
    batch = rng.integers(0, 256, size=(4, size), dtype=np.uint8)
    want = crc_mod.crc32c_batch(batch)
    start = pipe.stats()["mesh_dispatches"]
    end = time.time() + WARM
    meshed = False
    while time.time() < end and not meshed:
        _path, (out,) = pipe.submit(chan, batch.copy()).result(60)
        np.testing.assert_array_equal(out, want)
        meshed = pipe.stats()["mesh_dispatches"] > start
        time.sleep(0.2)
    assert meshed, pipe.stats()
