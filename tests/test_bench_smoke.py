"""bench.py --smoke as a tier-1 gate: the benchmark's import surface,
plugin wiring and pipeline path are exercised on tiny CPU-safe sizes,
so bench bit-rot is caught here instead of on the slow rig run."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_runs_and_validates():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=560)
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert lines, (f"no stdout from --smoke (rc={proc.returncode}):\n"
                   f"{proc.stderr[-3000:]}")
    out = json.loads(lines[-1])
    # per-gate asserts FIRST: when the smoke trips, the failure names
    # the gate (the bare returncode hides it behind a stderr tail)
    bad = sorted(k for k, v in out.items()
                 if k.endswith("_ok") and v is False)
    assert not bad, f"--smoke gates failed: {bad}\n{proc.stderr[-3000:]}"
    assert proc.returncode == 0, \
        f"--smoke failed:\n{proc.stderr[-3000:]}"
    assert out["metric"] == "bench_smoke"
    assert out["smoke"] is True
    assert out["ok"] is True            # pipelined == serial == oracle
    assert out["e2e_pipelined_gbs"] > 0
    assert out["e2e_serial_gbs"] > 0
    assert out["pipeline_dispatches"] >= 1
    # multichip surface: the smoke runs sharded on the forced
    # 8-device CPU mesh — placement, mega-batch splitting and the
    # one-chip quarantine drill all really executed
    assert out["devices"] == 8
    assert out["sharded_ok"] is True
    assert out["lanes_used"] >= 2
    assert out["split_dispatches"] >= 1
    assert out["quarantine_ok"] is True
    assert out["quarantines"] >= 1
    assert out["active_after_quarantine"] == 7
    # zero-copy host data path: the write pipeline (rope -> encode
    # staging -> shard-view fan-out -> store) stays within the copy
    # budget — a per-hop copy regression fails CI here
    assert out["copy_ok"] is True
    assert out["host_copies_per_write"] <= out["copy_budget"]
    # serving plane: the seeded mini load harness ran against a real
    # cluster — tail latency sane, zero errors, and the READ path
    # within its copy budget (read-side zero-copy regression gate)
    assert out["load_ok"] is True
    assert out["load_p99_ms"] is not None and out["load_p99_ms"] > 0
    assert out["load_errors"] == 0
    assert out["host_copies_per_read"] <= out["read_copy_budget"]
    # op tracing plane: the tracer-overhead gate ran the same seeded
    # round with tracing off and on — p99 and goodput within 5%, and
    # the traced round produced a per-phase breakdown (queue/execute
    # at minimum), so the plane is cheap enough to leave on
    assert out["trace_overhead_ok"] is True
    assert out["trace_p99_off_ms"] and out["trace_p99_on_ms"]
    assert out["trace_p99_on_ms"] <= out["trace_p99_off_ms"] * 1.05
    assert out["trace_phases"] and "queue" in out["trace_phases"]
    # serve-during-repair: the mini seeded recovery-storm gate — one
    # OSD kill + rebirth under open-loop load: zero client errors,
    # zero stale-byte reads (verify oracle), every recovery-blocked
    # op resumed (counter-balanced), the reserved pool's p99 bounded,
    # and recovery completing
    assert out["storm_ok"] is True
    assert out["storm_errors"] == 0
    assert out["storm_stale_reads"] == 0
    assert out["storm_blocked_ops"] == out["storm_unblocked_ops"]
    assert out["storm_p99_ms"] is not None
    assert out["storm_p99_ms"] < out["storm_p99_bound_ms"]
    assert out["storm_recovery_s"] is not None
    # log-authoritative peering: a full peering round exchanges log
    # BOUNDS only, so wall time at 10x the object count stays flat —
    # an O(objects) term creeping into info/election/recovery fails
    assert out["peering_flat_ok"] is True
    assert out["peering_ms_at_1x"] is not None
    assert out["peering_ms_at_10x"] is not None
    # pod-scale mesh dispatch: a payload over a single lane's staging
    # budget rode ONE shard_mapped dispatch across the 8-device mesh,
    # bit-exact vs the oracle, with the staging arena donated — and
    # the donated path's per-write copy floor held
    assert out["mesh_ok"] is True
    assert out["mesh_dispatches"] >= 1
    assert out["arena_donations"] >= 1
    assert out["mesh_copies_per_write"] <= out["mesh_copy_budget"]
    # front doors under fire: the mini mixed-door round (rados + S3 +
    # CephFS + RBD) rode one seeded schedule through a zone
    # partition, a secondary-gateway crash and an OSD kill — zero
    # errors, zero stale reads at every door, the two-zone ledger
    # clean (partitioned delete tombstoned, never resurrected), and
    # the sync agent backing off rather than wedging
    assert out["frontdoor_ok"] is True
    assert out["frontdoor_errors"] == 0
    assert out["frontdoor_stale_reads"] == 0
    assert out["frontdoor_zone_ledger_ok"] is True
    assert out["frontdoor_doors"] == ["cephfs", "rados", "rbd", "s3"]
    assert out["frontdoor_sync_errors"] > 0
    assert out["frontdoor_sync_backoff_secs"] > 0
    # async serving plane: 256 full client sessions held open at once
    # against an ms_type=async cluster — zero errors, tail bounded,
    # peak thread growth bounded by the storm's own driver pool (NOT
    # per-session threads), and zero thread/FD residue after every
    # session closed (connection-churn hygiene)
    assert out["conn_ok"] is True
    assert out["conn_sessions"] >= 256
    assert out["conn_errors"] == 0
    assert out["conn_p99_ms"] is not None
    assert out["conn_p99_ms"] < out["conn_p99_bound_ms"]
    assert out["conn_event_workers"] >= 1
    assert out["conn_peak_threads"] - out["conn_base_threads"] < 256
    assert out["conn_quiesce_threads"] <= out["conn_base_threads"]
    assert out["conn_quiesce_fds"] <= out["conn_base_fds"]
