"""RGW S3 gateway: bucket/object REST workflow over HTTP.

rgw_rest_s3.cc core surface driven with urllib like an S3 SDK would.
"""

import time
import urllib.error
import urllib.request

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.rgw import _http_date, sign_v2
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    # settle the client before the gateway creates its pool
    r = c.client()
    r.create_pool("warmup", pg_num=4)
    io = r.open_ioctx("warmup")
    end = time.time() + 20
    while True:
        try:
            io.write_full("w", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rgw(cluster):
    return cluster.start_rgw()


@pytest.fixture(scope="module")
def base(rgw):
    return f"http://127.0.0.1:{rgw.port}"


def req(method: str, url: str, data: bytes | None = None,
        headers: dict | None = None):
    r = urllib.request.Request(url, data=data, method=method,
                               headers=headers or {})
    return urllib.request.urlopen(r, timeout=30)


class TestBuckets:
    def test_create_list_delete(self, base):
        assert req("PUT", f"{base}/bkt1").status == 200
        assert req("PUT", f"{base}/bkt2").status == 200
        body = req("GET", f"{base}/").read().decode()
        assert "<Name>bkt1</Name>" in body and "bkt2" in body
        assert req("DELETE", f"{base}/bkt2").status == 204
        body = req("GET", f"{base}/").read().decode()
        assert "bkt2" not in body

    def test_duplicate_create_conflicts(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{base}/bkt1")
        assert ei.value.code == 409

    def test_missing_bucket_404(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/nothere")
        assert ei.value.code == 404


class TestObjects:
    def test_put_get_head_delete(self, base):
        payload = b"s3 object body " * 1000
        resp = req("PUT", f"{base}/bkt1/docs/readme.txt", payload)
        assert resp.status == 200
        etag = resp.headers["ETag"]
        resp = req("GET", f"{base}/bkt1/docs/readme.txt")
        assert resp.read() == payload
        assert resp.headers["ETag"] == etag
        resp = req("HEAD", f"{base}/bkt1/docs/readme.txt")
        assert int(resp.headers["Content-Length"]) == len(payload)
        assert req("DELETE",
                   f"{base}/bkt1/docs/readme.txt").status == 204
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/bkt1/docs/readme.txt")
        assert ei.value.code == 404

    def test_overwrite_replaces(self, base):
        req("PUT", f"{base}/bkt1/over", b"version one, long body")
        req("PUT", f"{base}/bkt1/over", b"v2")
        assert req("GET", f"{base}/bkt1/over").read() == b"v2"

    def test_list_with_prefix(self, base):
        for key in ("logs/a", "logs/b", "data/c"):
            req("PUT", f"{base}/bkt1/{key}", b"x")
        body = req("GET", f"{base}/bkt1?prefix=logs/").read().decode()
        assert "logs/a" in body and "logs/b" in body
        assert "data/c" not in body
        assert "<KeyCount>2</KeyCount>" in body

    def test_nonempty_bucket_delete_refused(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("DELETE", f"{base}/bkt1")
        assert ei.value.code == 409


class TestAuth:
    def test_signature_required_and_verified(self, cluster):
        rgw = cluster.start_rgw(access_key="AKIATEST",
                                secret_key="s3cr3t")
        base = f"http://127.0.0.1:{rgw.port}"
        # unsigned -> 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/")
        assert ei.value.code == 403
        # bad secret -> 403
        date = _http_date()
        bad = sign_v2("GET", "/", date, "AKIATEST", "wrong")
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/", headers={"Date": date,
                                            "Authorization": bad})
        assert ei.value.code == 403
        # good signature -> 200
        good = sign_v2("GET", "/", date, "AKIATEST", "s3cr3t")
        resp = req("GET", f"{base}/", headers={"Date": date,
                                               "Authorization": good})
        assert resp.status == 200


class TestPagination:
    def test_marker_pagination_pages_whole_bucket(self, base):
        req("PUT", f"{base}/pages")
        keys = [f"k{i:04d}" for i in range(57)]
        for k in keys:
            req("PUT", f"{base}/pages/{k}", data=b"x")
        got, marker = [], ""
        rounds = 0
        while True:
            url = f"{base}/pages?max-keys=10"
            if marker:
                url += f"&marker={marker}"
            body = req("GET", url).read().decode()
            import re
            page = re.findall(r"<Key>([^<]+)</Key>", body)
            got.extend(page)
            rounds += 1
            assert rounds < 20, "pagination never terminated"
            m = re.search(r"<NextMarker>([^<]+)</NextMarker>", body)
            if "<IsTruncated>true</IsTruncated>" in body:
                assert m is not None
                marker = m.group(1)
            else:
                break
        assert got == keys
        assert rounds == 6          # 5 full pages + the short tail

    def test_prefix_with_marker(self, base):
        req("PUT", f"{base}/prefpage")
        for i in range(8):
            req("PUT", f"{base}/prefpage/a{i}", data=b"x")
            req("PUT", f"{base}/prefpage/b{i}", data=b"x")
        body = req("GET",
                   f"{base}/prefpage?prefix=a&max-keys=5").read().decode()
        import re
        assert re.findall(r"<Key>([^<]+)</Key>", body) == \
            [f"a{i}" for i in range(5)]
        assert "<IsTruncated>true</IsTruncated>" in body
        m = re.search(r"<NextMarker>([^<]+)</NextMarker>", body)
        body2 = req("GET", f"{base}/prefpage?prefix=a&max-keys=5"
                           f"&marker={m.group(1)}").read().decode()
        assert re.findall(r"<Key>([^<]+)</Key>", body2) == \
            [f"a{i}" for i in range(5, 8)]
        assert "<IsTruncated>false</IsTruncated>" in body2


class TestMultipart:
    def test_multipart_round_trip(self, base):
        import re
        req("PUT", f"{base}/mp")
        body = req("POST", f"{base}/mp/big.bin?uploads",
                   data=b"").read().decode()
        upload_id = re.search(r"<UploadId>([^<]+)</UploadId>",
                              body).group(1)
        # three parts, boto-style, out of order
        parts = {1: b"A" * 100_000, 2: b"B" * 50_000, 3: b"C" * 7}
        etags = {}
        for n in (2, 1, 3):
            r = req("PUT",
                    f"{base}/mp/big.bin?uploadId={upload_id}"
                    f"&partNumber={n}", data=parts[n])
            etags[n] = r.headers["ETag"]
        # in-progress upload is listed
        lst = req("GET", f"{base}/mp?uploads").read().decode()
        assert upload_id in lst and "big.bin" in lst
        xml = ("<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{n}</PartNumber>"
            f"<ETag>{etags[n]}</ETag></Part>" for n in (1, 2, 3))
            + "</CompleteMultipartUpload>").encode()
        done = req("POST", f"{base}/mp/big.bin?uploadId={upload_id}",
                   data=xml).read().decode()
        assert "-3" in done          # multipart etag suffix
        got = req("GET", f"{base}/mp/big.bin").read()
        assert got == parts[1] + parts[2] + parts[3]
        # upload record gone
        lst = req("GET", f"{base}/mp?uploads").read().decode()
        assert upload_id not in lst

    def test_abort_cleans_up(self, base):
        import re
        req("PUT", f"{base}/mpa")
        body = req("POST", f"{base}/mpa/tmp?uploads",
                   data=b"").read().decode()
        upload_id = re.search(r"<UploadId>([^<]+)</UploadId>",
                              body).group(1)
        req("PUT", f"{base}/mpa/tmp?uploadId={upload_id}&partNumber=1",
            data=b"zzz")
        assert req("DELETE",
                   f"{base}/mpa/tmp?uploadId={upload_id}").status == 204
        lst = req("GET", f"{base}/mpa?uploads").read().decode()
        assert upload_id not in lst
        # completing a dead upload -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("POST", f"{base}/mpa/tmp?uploadId={upload_id}",
                data=b"")
        assert ei.value.code == 404
        # the object never materialized
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/mpa/tmp")
        assert ei.value.code == 404

    def test_bad_part_number_rejected(self, base):
        import re
        req("PUT", f"{base}/mpb")
        body = req("POST", f"{base}/mpb/x?uploads",
                   data=b"").read().decode()
        upload_id = re.search(r"<UploadId>([^<]+)</UploadId>",
                              body).group(1)
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{base}/mpb/x?uploadId={upload_id}"
                       f"&partNumber=0", data=b"x")
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{base}/mpb/x?uploadId=deadbeef&partNumber=1",
                data=b"x")
        assert ei.value.code == 404
