"""RGW S3 gateway: bucket/object REST workflow over HTTP.

rgw_rest_s3.cc core surface driven with urllib like an S3 SDK would.
"""

import time
import urllib.error
import urllib.request

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.rgw import _http_date, sign_v2
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    # settle the client before the gateway creates its pool
    r = c.client()
    r.create_pool("warmup", pg_num=4)
    io = r.open_ioctx("warmup")
    end = time.time() + 20
    while True:
        try:
            io.write_full("w", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rgw(cluster):
    return cluster.start_rgw()


@pytest.fixture(scope="module")
def base(rgw):
    return f"http://127.0.0.1:{rgw.port}"


def req(method: str, url: str, data: bytes | None = None,
        headers: dict | None = None):
    r = urllib.request.Request(url, data=data, method=method,
                               headers=headers or {})
    return urllib.request.urlopen(r, timeout=30)


class TestBuckets:
    def test_create_list_delete(self, base):
        assert req("PUT", f"{base}/bkt1").status == 200
        assert req("PUT", f"{base}/bkt2").status == 200
        body = req("GET", f"{base}/").read().decode()
        assert "<Name>bkt1</Name>" in body and "bkt2" in body
        assert req("DELETE", f"{base}/bkt2").status == 204
        body = req("GET", f"{base}/").read().decode()
        assert "bkt2" not in body

    def test_duplicate_create_conflicts(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{base}/bkt1")
        assert ei.value.code == 409

    def test_missing_bucket_404(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/nothere")
        assert ei.value.code == 404


class TestObjects:
    def test_put_get_head_delete(self, base):
        payload = b"s3 object body " * 1000
        resp = req("PUT", f"{base}/bkt1/docs/readme.txt", payload)
        assert resp.status == 200
        etag = resp.headers["ETag"]
        resp = req("GET", f"{base}/bkt1/docs/readme.txt")
        assert resp.read() == payload
        assert resp.headers["ETag"] == etag
        resp = req("HEAD", f"{base}/bkt1/docs/readme.txt")
        assert int(resp.headers["Content-Length"]) == len(payload)
        assert req("DELETE",
                   f"{base}/bkt1/docs/readme.txt").status == 204
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/bkt1/docs/readme.txt")
        assert ei.value.code == 404

    def test_overwrite_replaces(self, base):
        req("PUT", f"{base}/bkt1/over", b"version one, long body")
        req("PUT", f"{base}/bkt1/over", b"v2")
        assert req("GET", f"{base}/bkt1/over").read() == b"v2"

    def test_list_with_prefix(self, base):
        for key in ("logs/a", "logs/b", "data/c"):
            req("PUT", f"{base}/bkt1/{key}", b"x")
        body = req("GET", f"{base}/bkt1?prefix=logs/").read().decode()
        assert "logs/a" in body and "logs/b" in body
        assert "data/c" not in body
        assert "<KeyCount>2</KeyCount>" in body

    def test_nonempty_bucket_delete_refused(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("DELETE", f"{base}/bkt1")
        assert ei.value.code == 409


class TestAuth:
    def test_signature_required_and_verified(self, cluster):
        rgw = cluster.start_rgw(access_key="AKIATEST",
                                secret_key="s3cr3t")
        base = f"http://127.0.0.1:{rgw.port}"
        # unsigned -> 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/")
        assert ei.value.code == 403
        # bad secret -> 403
        date = _http_date()
        bad = sign_v2("GET", "/", date, "AKIATEST", "wrong")
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/", headers={"Date": date,
                                            "Authorization": bad})
        assert ei.value.code == 403
        # good signature -> 200
        good = sign_v2("GET", "/", date, "AKIATEST", "s3cr3t")
        resp = req("GET", f"{base}/", headers={"Date": date,
                                               "Authorization": good})
        assert resp.status == 200
