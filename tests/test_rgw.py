"""RGW S3 gateway: bucket/object REST workflow over HTTP.

rgw_rest_s3.cc core surface driven with urllib like an S3 SDK would.
"""

import time
import urllib.error
import urllib.request

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.rgw import _http_date, auth_v4, sign_v2
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    # settle the client before the gateway creates its pool
    r = c.client()
    r.create_pool("warmup", pg_num=4)
    io = r.open_ioctx("warmup")
    end = time.time() + 20
    while True:
        try:
            io.write_full("w", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rgw(cluster):
    return cluster.start_rgw()


@pytest.fixture(scope="module")
def base(rgw):
    return f"http://127.0.0.1:{rgw.port}"


def req(method: str, url: str, data: bytes | None = None,
        headers: dict | None = None):
    r = urllib.request.Request(url, data=data, method=method,
                               headers=headers or {})
    return urllib.request.urlopen(r, timeout=30)


class TestBuckets:
    def test_create_list_delete(self, base):
        assert req("PUT", f"{base}/bkt1").status == 200
        assert req("PUT", f"{base}/bkt2").status == 200
        body = req("GET", f"{base}/").read().decode()
        assert "<Name>bkt1</Name>" in body and "bkt2" in body
        assert req("DELETE", f"{base}/bkt2").status == 204
        body = req("GET", f"{base}/").read().decode()
        assert "bkt2" not in body

    def test_duplicate_create_conflicts(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{base}/bkt1")
        assert ei.value.code == 409

    def test_missing_bucket_404(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/nothere")
        assert ei.value.code == 404


class TestObjects:
    def test_put_get_head_delete(self, base):
        payload = b"s3 object body " * 1000
        resp = req("PUT", f"{base}/bkt1/docs/readme.txt", payload)
        assert resp.status == 200
        etag = resp.headers["ETag"]
        resp = req("GET", f"{base}/bkt1/docs/readme.txt")
        assert resp.read() == payload
        assert resp.headers["ETag"] == etag
        resp = req("HEAD", f"{base}/bkt1/docs/readme.txt")
        assert int(resp.headers["Content-Length"]) == len(payload)
        assert req("DELETE",
                   f"{base}/bkt1/docs/readme.txt").status == 204
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/bkt1/docs/readme.txt")
        assert ei.value.code == 404

    def test_overwrite_replaces(self, base):
        req("PUT", f"{base}/bkt1/over", b"version one, long body")
        req("PUT", f"{base}/bkt1/over", b"v2")
        assert req("GET", f"{base}/bkt1/over").read() == b"v2"

    def test_list_with_prefix(self, base):
        for key in ("logs/a", "logs/b", "data/c"):
            req("PUT", f"{base}/bkt1/{key}", b"x")
        body = req("GET", f"{base}/bkt1?prefix=logs/").read().decode()
        assert "logs/a" in body and "logs/b" in body
        assert "data/c" not in body
        assert "<KeyCount>2</KeyCount>" in body

    def test_nonempty_bucket_delete_refused(self, base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("DELETE", f"{base}/bkt1")
        assert ei.value.code == 409


class TestAuth:
    def test_signature_required_and_verified(self, cluster):
        rgw = cluster.start_rgw(access_key="AKIATEST",
                                secret_key="s3cr3t")
        base = f"http://127.0.0.1:{rgw.port}"
        # unsigned -> 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/")
        assert ei.value.code == 403
        # bad secret -> 403
        date = _http_date()
        bad = sign_v2("GET", "/", date, "AKIATEST", "wrong")
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/", headers={"Date": date,
                                            "Authorization": bad})
        assert ei.value.code == 403
        # good signature -> 200
        good = sign_v2("GET", "/", date, "AKIATEST", "s3cr3t")
        resp = req("GET", f"{base}/", headers={"Date": date,
                                               "Authorization": good})
        assert resp.status == 200


class TestPagination:
    def test_marker_pagination_pages_whole_bucket(self, base):
        req("PUT", f"{base}/pages")
        keys = [f"k{i:04d}" for i in range(57)]
        for k in keys:
            req("PUT", f"{base}/pages/{k}", data=b"x")
        got, marker = [], ""
        rounds = 0
        while True:
            url = f"{base}/pages?max-keys=10"
            if marker:
                url += f"&marker={marker}"
            body = req("GET", url).read().decode()
            import re
            page = re.findall(r"<Key>([^<]+)</Key>", body)
            got.extend(page)
            rounds += 1
            assert rounds < 20, "pagination never terminated"
            m = re.search(r"<NextMarker>([^<]+)</NextMarker>", body)
            if "<IsTruncated>true</IsTruncated>" in body:
                assert m is not None
                marker = m.group(1)
            else:
                break
        assert got == keys
        assert rounds == 6          # 5 full pages + the short tail

    def test_prefix_with_marker(self, base):
        req("PUT", f"{base}/prefpage")
        for i in range(8):
            req("PUT", f"{base}/prefpage/a{i}", data=b"x")
            req("PUT", f"{base}/prefpage/b{i}", data=b"x")
        body = req("GET",
                   f"{base}/prefpage?prefix=a&max-keys=5").read().decode()
        import re
        assert re.findall(r"<Key>([^<]+)</Key>", body) == \
            [f"a{i}" for i in range(5)]
        assert "<IsTruncated>true</IsTruncated>" in body
        m = re.search(r"<NextMarker>([^<]+)</NextMarker>", body)
        body2 = req("GET", f"{base}/prefpage?prefix=a&max-keys=5"
                           f"&marker={m.group(1)}").read().decode()
        assert re.findall(r"<Key>([^<]+)</Key>", body2) == \
            [f"a{i}" for i in range(5, 8)]
        assert "<IsTruncated>false</IsTruncated>" in body2


class TestMultipart:
    def test_multipart_round_trip(self, base):
        import re
        req("PUT", f"{base}/mp")
        body = req("POST", f"{base}/mp/big.bin?uploads",
                   data=b"").read().decode()
        upload_id = re.search(r"<UploadId>([^<]+)</UploadId>",
                              body).group(1)
        # three parts, boto-style, out of order
        parts = {1: b"A" * 100_000, 2: b"B" * 50_000, 3: b"C" * 7}
        etags = {}
        for n in (2, 1, 3):
            r = req("PUT",
                    f"{base}/mp/big.bin?uploadId={upload_id}"
                    f"&partNumber={n}", data=parts[n])
            etags[n] = r.headers["ETag"]
        # in-progress upload is listed
        lst = req("GET", f"{base}/mp?uploads").read().decode()
        assert upload_id in lst and "big.bin" in lst
        xml = ("<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{n}</PartNumber>"
            f"<ETag>{etags[n]}</ETag></Part>" for n in (1, 2, 3))
            + "</CompleteMultipartUpload>").encode()
        done = req("POST", f"{base}/mp/big.bin?uploadId={upload_id}",
                   data=xml).read().decode()
        assert "-3" in done          # multipart etag suffix
        got = req("GET", f"{base}/mp/big.bin").read()
        assert got == parts[1] + parts[2] + parts[3]
        # upload record gone
        lst = req("GET", f"{base}/mp?uploads").read().decode()
        assert upload_id not in lst

    def test_abort_cleans_up(self, base):
        import re
        req("PUT", f"{base}/mpa")
        body = req("POST", f"{base}/mpa/tmp?uploads",
                   data=b"").read().decode()
        upload_id = re.search(r"<UploadId>([^<]+)</UploadId>",
                              body).group(1)
        req("PUT", f"{base}/mpa/tmp?uploadId={upload_id}&partNumber=1",
            data=b"zzz")
        assert req("DELETE",
                   f"{base}/mpa/tmp?uploadId={upload_id}").status == 204
        lst = req("GET", f"{base}/mpa?uploads").read().decode()
        assert upload_id not in lst
        # completing a dead upload -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("POST", f"{base}/mpa/tmp?uploadId={upload_id}",
                data=b"")
        assert ei.value.code == 404
        # the object never materialized
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/mpa/tmp")
        assert ei.value.code == 404

    def test_bad_part_number_rejected(self, base):
        import re
        req("PUT", f"{base}/mpb")
        body = req("POST", f"{base}/mpb/x?uploads",
                   data=b"").read().decode()
        upload_id = re.search(r"<UploadId>([^<]+)</UploadId>",
                              body).group(1)
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{base}/mpb/x?uploadId={upload_id}"
                       f"&partNumber=0", data=b"x")
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{base}/mpb/x?uploadId=deadbeef&partNumber=1",
                data=b"x")
        assert ei.value.code == 404


def v4req(method: str, base: str, path: str, access: str,
          secret: str, data: bytes = b"", raw_query: str = "",
          tamper=None):
    """Issue a SigV4-signed request; `tamper(headers)` can corrupt it."""
    from urllib.parse import quote, urlparse
    host = urlparse(base).netloc
    headers = auth_v4.sign_v4(method, path, raw_query, {"host": host},
                              data, access, secret)
    headers["Host"] = host
    if tamper:
        tamper(headers)
    url = base + quote(path) + (f"?{raw_query}" if raw_query else "")
    return req(method, url, data=data or None, headers=headers)


class TestAuthV4:
    """rgw/rgw_auth_s3.h:24-32 v4 canonical request + signature."""

    @pytest.fixture(scope="class")
    def v4base(self, cluster):
        rgw = cluster.start_rgw(access_key="AKIAV4", secret_key="v4s")
        return f"http://127.0.0.1:{rgw.port}"

    def test_v4_signed_round_trip(self, v4base):
        assert v4req("PUT", v4base, "/v4bkt", "AKIAV4",
                     "v4s").status == 200
        assert v4req("PUT", v4base, "/v4bkt/key one", "AKIAV4", "v4s",
                     data=b"v4 payload").status == 200
        got = v4req("GET", v4base, "/v4bkt/key one", "AKIAV4", "v4s")
        assert got.read() == b"v4 payload"
        body = v4req("GET", v4base, "/v4bkt", "AKIAV4", "v4s",
                     raw_query="prefix=key&max-keys=10").read().decode()
        assert "key one" in body

    def test_v4_bad_secret_rejected(self, v4base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            v4req("GET", v4base, "/v4bkt", "AKIAV4", "WRONG")
        assert ei.value.code == 403

    def test_v4_tampered_signature_rejected(self, v4base):
        def flip(h):
            auth = h["Authorization"]
            h["Authorization"] = auth[:-4] + (
                "aaaa" if auth[-4:] != "aaaa" else "bbbb")
        with pytest.raises(urllib.error.HTTPError) as ei:
            v4req("GET", v4base, "/v4bkt", "AKIAV4", "v4s",
                  tamper=flip)
        assert ei.value.code == 403

    def test_v4_tampered_body_rejected(self, v4base):
        # body signed via x-amz-content-sha256: swap payload post-sign
        from urllib.parse import urlparse
        host = urlparse(v4base).netloc
        headers = auth_v4.sign_v4("PUT", "/v4bkt/tamper", "",
                                  {"host": host}, b"signed body",
                                  "AKIAV4", "v4s")
        headers["Host"] = host
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PUT", f"{v4base}/v4bkt/tamper", data=b"EVIL BODY!!",
                headers=headers)
        assert ei.value.code == 403

    def test_v4_wrong_access_key_rejected(self, v4base):
        with pytest.raises(urllib.error.HTTPError) as ei:
            v4req("GET", v4base, "/v4bkt", "AKIAOTHER", "v4s")
        assert ei.value.code == 403


class TestVersioning:
    """rgw/rgw_op.h:484-493 bucket versioning + delete markers."""

    def _enable(self, base, bucket):
        req("PUT", f"{base}/{bucket}")
        body = (b'<VersioningConfiguration>'
                b'<Status>Enabled</Status></VersioningConfiguration>')
        assert req("PUT", f"{base}/{bucket}?versioning",
                   data=body).status == 200
        got = req("GET", f"{base}/{bucket}?versioning").read()
        assert b"<Status>Enabled</Status>" in got

    def test_put_stacks_versions(self, base):
        self._enable(base, "vbkt")
        r1 = req("PUT", f"{base}/vbkt/doc", data=b"one")
        v1 = r1.headers["x-amz-version-id"]
        r2 = req("PUT", f"{base}/vbkt/doc", data=b"two!")
        v2 = r2.headers["x-amz-version-id"]
        assert v1 != v2
        # latest wins; explicit versionId reaches each generation
        assert req("GET", f"{base}/vbkt/doc").read() == b"two!"
        assert req("GET",
                   f"{base}/vbkt/doc?versionId={v1}").read() == b"one"
        assert req("GET",
                   f"{base}/vbkt/doc?versionId={v2}").read() == b"two!"
        lst = req("GET", f"{base}/vbkt?versions").read().decode()
        assert lst.count("<Version>") == 2
        assert f"<VersionId>{v2}</VersionId><IsLatest>true" in lst

    def test_delete_marker_and_restore(self, base):
        self._enable(base, "vbkt2")
        req("PUT", f"{base}/vbkt2/obj", data=b"precious")
        d = req("DELETE", f"{base}/vbkt2/obj")
        assert d.headers["x-amz-delete-marker"] == "true"
        marker_vid = d.headers["x-amz-version-id"]
        # plain GET now 404s (marker is latest) but flags the marker
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/vbkt2/obj")
        assert ei.value.code == 404
        assert ei.value.headers["x-amz-delete-marker"] == "true"
        # marker is hidden from a plain list, shown in ?versions
        plain = req("GET", f"{base}/vbkt2").read().decode()
        assert "<Key>obj</Key>" not in plain
        vers = req("GET", f"{base}/vbkt2?versions").read().decode()
        assert "<DeleteMarker>" in vers
        # deleting the marker restores the object (RGWDeleteObj
        # marker-removal path)
        req("DELETE", f"{base}/vbkt2/obj?versionId={marker_vid}")
        assert req("GET", f"{base}/vbkt2/obj").read() == b"precious"

    def test_pre_versioning_object_becomes_null(self, base):
        req("PUT", f"{base}/vbkt3")
        req("PUT", f"{base}/vbkt3/old", data=b"ancient")
        body = (b'<VersioningConfiguration>'
                b'<Status>Enabled</Status></VersioningConfiguration>')
        req("PUT", f"{base}/vbkt3?versioning", data=body)
        req("PUT", f"{base}/vbkt3/old", data=b"modern")
        assert req("GET", f"{base}/vbkt3/old").read() == b"modern"
        assert req(
            "GET",
            f"{base}/vbkt3/old?versionId=null").read() == b"ancient"
        vers = req("GET", f"{base}/vbkt3?versions").read().decode()
        assert "<VersionId>null</VersionId>" in vers

    def test_delete_specific_version_promotes_next(self, base):
        self._enable(base, "vbkt4")
        v1 = req("PUT", f"{base}/vbkt4/x",
                 data=b"gen1").headers["x-amz-version-id"]
        v2 = req("PUT", f"{base}/vbkt4/x",
                 data=b"gen2").headers["x-amz-version-id"]
        req("DELETE", f"{base}/vbkt4/x?versionId={v2}")
        assert req("GET", f"{base}/vbkt4/x").read() == b"gen1"
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{base}/vbkt4/x?versionId={v2}")
        assert ei.value.code == 404

    def test_suspended_writes_null(self, base):
        self._enable(base, "vbkt5")
        vid = req("PUT", f"{base}/vbkt5/s",
                  data=b"kept").headers["x-amz-version-id"]
        body = (b'<VersioningConfiguration><Status>Suspended'
                b'</Status></VersioningConfiguration>')
        req("PUT", f"{base}/vbkt5?versioning", data=body)
        r = req("PUT", f"{base}/vbkt5/s", data=b"null-a")
        assert r.headers["x-amz-version-id"] == "null"
        req("PUT", f"{base}/vbkt5/s", data=b"null-b")
        assert req("GET", f"{base}/vbkt5/s").read() == b"null-b"
        # the Enabled-era version survives; null was overwritten
        assert req("GET",
                   f"{base}/vbkt5/s?versionId={vid}").read() == b"kept"
        vers = req("GET", f"{base}/vbkt5?versions").read().decode()
        assert vers.count("<Version>") == 2

    def test_versioned_multipart_gets_version(self, base):
        self._enable(base, "vbkt6")
        init = req("POST", f"{base}/vbkt6/big?uploads").read().decode()
        import re
        uid = re.search(r"<UploadId>(\w+)</UploadId>", init).group(1)
        req("PUT", f"{base}/vbkt6/big?uploadId={uid}&partNumber=1",
            data=b"A" * 100)
        req("PUT", f"{base}/vbkt6/big?uploadId={uid}&partNumber=2",
            data=b"B" * 100)
        req("POST", f"{base}/vbkt6/big?uploadId={uid}")
        assert req("GET", f"{base}/vbkt6/big").read() == \
            b"A" * 100 + b"B" * 100
        vers = req("GET", f"{base}/vbkt6?versions").read().decode()
        assert "<Key>big</Key>" in vers

    def test_suspended_shorter_overwrite_no_stale_tail(self, base):
        """Write-never-truncates + skipped base remove left a stale
        tail when a shorter suspended PUT landed over old base data."""
        req("PUT", f"{base}/vbkt7")
        req("PUT", f"{base}/vbkt7/t", data=b"0123456789")
        ena = (b"<VersioningConfiguration><Status>Enabled</Status>"
               b"</VersioningConfiguration>")
        req("PUT", f"{base}/vbkt7?versioning", data=ena)
        req("PUT", f"{base}/vbkt7/t", data=b"versioned-gen")
        sus = (b"<VersioningConfiguration><Status>Suspended</Status>"
               b"</VersioningConfiguration>")
        req("PUT", f"{base}/vbkt7?versioning", data=sus)
        req("PUT", f"{base}/vbkt7/t", data=b"ab")
        assert req("GET", f"{base}/vbkt7/t").read() == b"ab"

    def test_null_version_addressable_before_migration(self, base):
        """A pre-versioning object answers to versionId=null right
        after enabling, before any write materializes the record."""
        req("PUT", f"{base}/vbkt8")
        req("PUT", f"{base}/vbkt8/pre", data=b"old data")
        ena = (b"<VersioningConfiguration><Status>Enabled</Status>"
               b"</VersioningConfiguration>")
        req("PUT", f"{base}/vbkt8?versioning", data=ena)
        got = req("GET", f"{base}/vbkt8/pre?versionId=null")
        assert got.read() == b"old data"
