"""Cross-op EC dispatch pipeline: coalescing, shape-bucket padding,
futures, measured-routing amortization, and degrade draining.

The tier-1 contracts pinned here:
  * padded shape-bucket dispatches are BIT-EXACT vs the unpadded host
    oracle for odd batch sizes across bucket boundaries (encode and
    decode);
  * an injected `tpu_error` landing mid-queue degrades the plugin and
    drains every queued/in-flight op to the host matrix-codec path
    with results identical to a pure-host codec — nothing lost or
    corrupted;
  * a REAL device_fn failure (exception, not injected flag) takes the
    same drain path;
  * the documented batch_stripes=N profile key is parsed, validated,
    and used as the coalesce-size cap;
  * crc32c_batch (the vectorized host scrub fold) matches the scalar
    reference byte-for-byte.
"""

import threading
import time

import numpy as np
import pytest

from ceph_tpu.erasure.interface import ErasureCodeError
from ceph_tpu.erasure.registry import registry
from ceph_tpu.ops import crc32c as crc_mod
from ceph_tpu.ops import ec_kernels, gf
from ceph_tpu.ops import pipeline as ec_pipeline
from ceph_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get().reset(seed=0)
    yield
    faults.get().reset(seed=0)
    # a test that exhausted/quarantined device lanes on the GLOBAL
    # pipeline must not leak host-only dispatch into later tests
    pipe = ec_pipeline.get()
    st = pipe.stats()
    if st["devices"] and any(d["quarantined"]
                             for d in st["devices"].values()):
        pipe.reset_devices()


def _tpu(profile):
    return registry.factory("tpu", dict(profile))


def _oracle(profile):
    p = {k: v for k, v in profile.items()
         if k in ("k", "m", "technique", "w", "packetsize")}
    return registry.factory("jerasure", p)


# ---------------------------------------------------------------------------
# shape-bucket padding
# ---------------------------------------------------------------------------


def test_next_bucket_and_pad():
    assert [ec_pipeline.next_bucket(n) for n in (1, 2, 3, 4, 5, 9, 17)] \
        == [1, 2, 4, 4, 8, 16, 32]
    arr = np.arange(3 * 2 * 4, dtype=np.uint8).reshape(3, 2, 4)
    padded = ec_pipeline.pad_batch(arr)
    assert padded.shape == (4, 2, 4)
    assert np.array_equal(padded[:3], arr)
    assert not padded[3:].any()
    same = np.zeros((4, 2, 4), dtype=np.uint8)
    assert ec_pipeline.pad_batch(same) is same


@pytest.mark.parametrize("B", [1, 3, 5, 7, 9, 17])
@pytest.mark.parametrize("L", [128, 384, 640])
def test_padded_bucket_encode_crc_bitexact(B, L):
    """Property: the fused kernel on a zero-padded power-of-two bucket,
    sliced back to B, matches the unpadded host oracle exactly — for
    odd B straddling bucket boundaries and non-power-of-two L."""
    k, m = 3, 2
    rng = np.random.default_rng(B * 1000 + L)
    matrix = gf.reed_sol_van_matrix(k, m)
    stripes = rng.integers(0, 256, size=(B, k, L), dtype=np.uint8)
    fn = ec_kernels.make_encode_crc_fn(matrix, L)
    padded = ec_pipeline.pad_batch(stripes)
    assert padded.shape[0] == ec_pipeline.next_bucket(B)
    parity, crcs = fn(padded)
    parity = np.asarray(parity)[:B]
    crcs = np.asarray(crcs)[:B]
    expect_parity = np.stack([gf.encode_np(matrix, stripes[b])
                              for b in range(B)])
    assert np.array_equal(parity, expect_parity)
    for b in range(B):
        allc = np.concatenate([stripes[b], expect_parity[b]], axis=0)
        for c in range(k + m):
            assert int(crcs[b, c]) == crc_mod.crc32c_sw(
                0, allc[c].tobytes())


@pytest.mark.parametrize("B", [1, 3, 5, 9])
def test_padded_bucket_decode_bitexact(B):
    """Same property for the decode rows-matrix path."""
    k, m, L = 4, 2, 256
    rng = np.random.default_rng(B)
    matrix = gf.reed_sol_van_matrix(k, m)
    gen = gf.systematic_generator(matrix, k)
    data = rng.integers(0, 256, size=(B, k, L), dtype=np.uint8)
    parity = np.stack([gf.encode_np(matrix, data[b]) for b in range(B)])
    allc = np.concatenate([data, parity], axis=1)
    present = [1, 3, 4, 5]
    dmat = gf.decode_matrix(gen, k, present)
    fn = ec_kernels.make_codec_fn(dmat)
    stack = np.ascontiguousarray(allc[:, present])
    out = np.asarray(fn(ec_pipeline.pad_batch(stack)))[:B]
    assert np.array_equal(out, data)


# ---------------------------------------------------------------------------
# pipeline mechanics
# ---------------------------------------------------------------------------


def test_pipeline_coalesces_concurrent_submissions():
    calls = []

    def host_fn(batch):
        calls.append(batch.shape[0])
        return (batch,)

    chan = ec_pipeline.PipelineChannel(key=("t", 1), host_fn=host_fn)
    pipe = ec_pipeline.EcDevicePipeline(depth=1)
    try:
        futs = [pipe.submit(chan, np.full((2, 8), i, dtype=np.uint8))
                for i in range(10)]
        for i, f in enumerate(futs):
            path, (out,) = f.result(timeout=20)
            assert path == "host"
            assert out.shape == (2, 8) and (out == i).all()
        stats = pipe.stats()
        assert stats["ops"] == 10
        assert stats["stripes"] == 20
        assert stats["dispatches"] == len(calls) <= 10
        assert stats["mean_batch_size"] >= 2.0 or len(calls) == 10
    finally:
        pipe.stop()


def test_pipeline_respects_max_coalesce():
    sizes = []

    def host_fn(batch):
        sizes.append(batch.shape[0])
        return (batch,)

    chan = ec_pipeline.PipelineChannel(key=("t", 2), host_fn=host_fn,
                                       max_coalesce=3)
    pipe = ec_pipeline.EcDevicePipeline(depth=1)
    try:
        # stall the dispatcher with a first slow item so the rest queue
        ev = threading.Event()
        slow = ec_pipeline.PipelineChannel(
            key=("t", "slow"),
            host_fn=lambda b: (ev.wait(10), (b,))[1])
        first = pipe.submit(slow, np.zeros((1, 4), dtype=np.uint8))
        futs = [pipe.submit(chan, np.zeros((2, 4), dtype=np.uint8))
                for _ in range(4)]
        ev.set()
        first.result(timeout=20)
        for f in futs:
            f.result(timeout=20)
        # 8 stripes, cap 3 -> no host batch exceeded one 2-stripe pair
        # plus one more (2+2 <= 3 is false, so singles of 2)
        assert all(s <= 3 for s in sizes)
    finally:
        pipe.stop()


def test_scrub_channel_yields_to_write_under_contention():
    """Per-pool pipeline QoS: with both classes queued, the scrub CRC
    channel yields its (older!) dispatch slot to client-write encode
    work and the qos_scrub_yields counter records it."""
    order = []

    def mk(name):
        def host_fn(batch, _n=name):
            order.append(_n)
            return (batch,)
        return host_fn

    scrub = ec_pipeline.PipelineChannel(
        key=("t", "scrub"), host_fn=mk("scrub"), qos_class="scrub")
    write = ec_pipeline.PipelineChannel(
        key=("t", "write"), host_fn=mk("write"))
    ev = threading.Event()
    slow = ec_pipeline.PipelineChannel(
        key=("t", "slow-q"),
        host_fn=lambda b: (ev.wait(10), (b,))[1])
    pipe = ec_pipeline.EcDevicePipeline(depth=1, scrub_weight=0.25)
    try:
        first = pipe.submit(slow, np.zeros((1, 4), dtype=np.uint8))
        time.sleep(0.1)          # dispatcher wedged inside `slow`
        fs = pipe.submit(scrub, np.zeros((1, 4), dtype=np.uint8))
        time.sleep(0.02)         # scrub item is strictly OLDER
        fw = pipe.submit(write, np.zeros((1, 4), dtype=np.uint8))
        ev.set()
        first.result(timeout=20)
        fs.result(timeout=20)
        fw.result(timeout=20)
        assert order.index("write") < order.index("scrub")
        assert pipe.stats()["qos_scrub_yields"] >= 1
    finally:
        ev.set()
        pipe.stop()


def test_scrub_weight_one_restores_fifo():
    """scrub_weight >= 1 disables yielding: strict FIFO across
    classes (the older scrub item dispatches first)."""
    order = []

    def mk(name):
        def host_fn(batch, _n=name):
            order.append(_n)
            return (batch,)
        return host_fn

    scrub = ec_pipeline.PipelineChannel(
        key=("t", "scrub2"), host_fn=mk("scrub"), qos_class="scrub")
    write = ec_pipeline.PipelineChannel(
        key=("t", "write2"), host_fn=mk("write"))
    ev = threading.Event()
    slow = ec_pipeline.PipelineChannel(
        key=("t", "slow-q2"),
        host_fn=lambda b: (ev.wait(10), (b,))[1])
    pipe = ec_pipeline.EcDevicePipeline(depth=1, scrub_weight=1.0)
    try:
        first = pipe.submit(slow, np.zeros((1, 4), dtype=np.uint8))
        time.sleep(0.1)
        fs = pipe.submit(scrub, np.zeros((1, 4), dtype=np.uint8))
        time.sleep(0.02)
        fw = pipe.submit(write, np.zeros((1, 4), dtype=np.uint8))
        ev.set()
        first.result(timeout=20)
        fs.result(timeout=20)
        fw.result(timeout=20)
        assert order.index("scrub") < order.index("write")
        assert pipe.stats()["qos_scrub_yields"] == 0
    finally:
        ev.set()
        pipe.stop()


def test_pipeline_host_error_sets_future_exception():
    def host_fn(batch):
        raise RuntimeError("boom")

    chan = ec_pipeline.PipelineChannel(key=("t", 3), host_fn=host_fn)
    pipe = ec_pipeline.EcDevicePipeline()
    try:
        fut = pipe.submit(chan, np.zeros((1, 4), dtype=np.uint8))
        with pytest.raises(RuntimeError, match="boom"):
            fut.result(timeout=20)
    finally:
        pipe.stop()


def test_pipeline_device_error_drains_to_host():
    """A device_fn that blows up mid-stream: on_error fires, the batch
    re-runs on host, results stay correct, later batches keep flowing."""
    errors = []

    def device_fn(padded):
        raise RuntimeError("device on fire")

    chan = ec_pipeline.PipelineChannel(
        key=("t", 4),
        host_fn=lambda b: (b + 1,),
        device_fn=device_fn,
        route=lambda nbytes: True,
        on_error=lambda e: errors.append(str(e)))
    pipe = ec_pipeline.EcDevicePipeline()
    try:
        futs = [pipe.submit(chan, np.full((1, 4), i, dtype=np.uint8))
                for i in range(5)]
        for i, f in enumerate(futs):
            path, (out,) = f.result(timeout=20)
            assert path == "host"
            assert (out == i + 1).all()
        assert errors
        assert pipe.stats()["device_errors"] >= 1
    finally:
        pipe.stop()


def test_pipeline_survives_on_error_callback_raising():
    """A failing device fetch whose on_error callback ALSO raises must
    resolve the futures (with the error) and leave the pipeline live
    for the next submission — never a dead collector + hung callers."""
    class _Lazy:
        def __iter__(self):
            raise RuntimeError("fetch failed")

    chan = ec_pipeline.PipelineChannel(
        key=("t", 5),
        host_fn=lambda b: (b,),
        device_fn=lambda padded: _Lazy(),   # blows up at collect
        route=lambda nbytes: True,
        on_error=lambda e: (_ for _ in ()).throw(
            RuntimeError("on_error broken")))
    pipe = ec_pipeline.EcDevicePipeline()
    try:
        fut = pipe.submit(chan, np.zeros((1, 4), dtype=np.uint8))
        with pytest.raises(RuntimeError):
            fut.result(timeout=20)
        # pipeline still serves after the failure
        ok = ec_pipeline.PipelineChannel(key=("t", 6),
                                         host_fn=lambda b: (b,))
        path, (out,) = pipe.submit(
            ok, np.ones((2, 4), dtype=np.uint8)).result(timeout=20)
        assert path == "host" and out.shape == (2, 4)
    finally:
        pipe.stop()


def test_stall_latch_keeps_new_work_flowing(monkeypatch):
    """A device fetch that HANGS (no exception) wedges a lane's
    collector; once every usable lane's overlap window stays full
    past STALL_TIMEOUT the dispatcher must latch host-only dispatch
    so new work keeps flowing instead of the whole process's EC I/O
    freezing.  Pinned to ONE device lane: with spare chips the
    pipeline rightly routes around a wedged lane instead of
    latching."""
    monkeypatch.setattr(ec_pipeline, "STALL_TIMEOUT", 0.2)
    ev = threading.Event()

    class _Blocker:
        def __array__(self, dtype=None):
            ev.wait(30)
            return np.zeros((1, 4), dtype=np.uint8)

    chan = ec_pipeline.PipelineChannel(
        key=("t", 7), host_fn=lambda b: (b + 1,),
        device_fn=lambda p: (_Blocker(),), route=lambda n: True)
    pipe = ec_pipeline.EcDevicePipeline(depth=1, coalesce_wait=0.01,
                                        device_shards=1)
    try:
        f1 = pipe.submit(chan, np.zeros((1, 4), dtype=np.uint8))
        time.sleep(0.1)     # collector picks f1 up and wedges
        f2 = pipe.submit(chan, np.zeros((1, 4), dtype=np.uint8))
        time.sleep(0.1)     # f2 dispatched into the full window
        f3 = pipe.submit(chan, np.full((1, 4), 3, dtype=np.uint8))
        path, (out,) = f3.result(timeout=20)
        assert path == "host" and (out == 4).all()
        assert pipe.stats()["stalled"]
    finally:
        ev.set()
        pipe.stop()


def test_pipelined_encode_self_serves_on_wedged_pipeline(monkeypatch):
    """A producer blocked past RESULT_TIMEOUT computes its encode on
    the host itself — correct bytes, no infinite hang."""
    from concurrent.futures import Future
    from ceph_tpu.erasure import plugin_tpu
    monkeypatch.setattr(ec_pipeline, "RESULT_TIMEOUT", 0.2)
    codec = _tpu({"k": "2", "m": "1"})
    oracle = _oracle({"k": "2", "m": "1"})
    rng = np.random.default_rng(11)
    stripes = rng.integers(0, 256, size=(3, 2, 128), dtype=np.uint8)
    wedged = plugin_tpu._PipelinedEncode(codec, stripes, Future())
    allc, crcs = wedged.result()       # never-resolving future
    allc_o, crcs_o = oracle.encode_stripes_with_crcs(stripes)
    assert np.array_equal(allc, allc_o)
    assert np.array_equal(crcs, crcs_o)


def test_crc_channel_latches_host_after_device_error():
    """A real post-warm device failure on the scrub CRC channel must
    latch the channel to the host fold (no per-batch retry storm)."""
    assert not ec_pipeline._crc_device_dead
    chan = ec_pipeline.crc_channel(64)
    try:
        assert chan.route(64) is True
        ec_pipeline._crc_on_error(RuntimeError("tunnel died"))
        assert ec_pipeline._crc_device_dead
        assert chan.route(64) is False
        # host path still produces correct CRCs through the pipeline
        rng = np.random.default_rng(9)
        arr = rng.integers(0, 256, size=(3, 64), dtype=np.uint8)
        _path, (crcs,) = ec_pipeline.get().submit(
            chan, arr).result(timeout=30)
        for i in range(3):
            assert int(crcs[i]) == crc_mod.crc32c_sw(
                0, arr[i].tobytes())
    finally:
        ec_pipeline._crc_device_dead = False


# ---------------------------------------------------------------------------
# plugin integration: degrade draining + bit-exactness
# ---------------------------------------------------------------------------


def test_tpu_error_mid_queue_matches_pure_host_codec():
    """Injected tpu_error lands while encodes are queued: every result
    (queued before AND submitted after) must match the pure-host
    codec bit-for-bit, and the plugin must degrade, not error."""
    profile = {"k": "3", "m": "2", "technique": "reed_sol_van",
               "host_cutover": "1"}      # prefer device -> fault path
    codec = _tpu(profile)
    oracle = _oracle(profile)
    rng = np.random.default_rng(42)
    batches = [rng.integers(0, 256, size=(B, 3, 256), dtype=np.uint8)
               for B in (1, 3, 2, 5, 1, 4, 2, 3)]
    handles = [codec.encode_stripes_with_crcs_async(b)
               for b in batches[:4]]
    faults.get().tpu_device_error(1.0)     # mid-queue
    handles += [codec.encode_stripes_with_crcs_async(b)
                for b in batches[4:]]
    for arr, h in zip(batches, handles):
        allc, crcs = h.result(timeout=60)
        allc_o, crcs_o = oracle.encode_stripes_with_crcs(arr)
        assert np.array_equal(allc, allc_o)
        assert np.array_equal(crcs, crcs_o)
    assert codec.degraded
    assert "device" in codec.degrade_reason


def test_real_device_failure_degrades_and_drains():
    """A device_fn exception (not the injected flag) must degrade the
    codec via on_error and still produce host-correct results."""
    profile = {"k": "2", "m": "1", "host_cutover": "1"}
    codec = _tpu(profile)
    oracle = _oracle(profile)

    # sabotage the backend: fused fn "ready" but explodes on use
    def bad_fused(matrix, shape, device=None):
        def fn(batch):
            raise RuntimeError("tunnel collapsed")
        return fn

    codec.backend.fused_fn_if_ready = bad_fused
    rng = np.random.default_rng(7)
    stripes = rng.integers(0, 256, size=(3, 2, 128), dtype=np.uint8)
    allc, crcs = codec.encode_stripes_with_crcs(stripes)
    assert codec.degraded
    allc_o, crcs_o = oracle.encode_stripes_with_crcs(stripes)
    assert np.array_equal(allc, allc_o)
    assert np.array_equal(crcs, crcs_o)


def test_pipelined_decode_matches_host():
    profile = {"k": "4", "m": "2", "technique": "reed_sol_van"}
    codec = _tpu(profile)
    rng = np.random.default_rng(3)
    stripes = rng.integers(0, 256, size=(5, 4, 256), dtype=np.uint8)
    allc, _ = codec.encode_stripes_with_crcs(stripes)
    want, present = [0, 2], [1, 3, 4, 5]
    stack = np.ascontiguousarray(allc[:, present])
    out = np.asarray(
        codec.decode_batch_async(want, present, stack).result(60))
    assert np.array_equal(out[:, 0], stripes[:, 0])
    assert np.array_equal(out[:, 1], stripes[:, 2])


# ---------------------------------------------------------------------------
# batch_stripes profile key
# ---------------------------------------------------------------------------


def test_batch_stripes_parsed_and_wired():
    codec = _tpu({"k": "2", "m": "1", "batch_stripes": "8"})
    assert codec.batch_stripes == 8
    chan = codec._encode_channel(128)
    assert chan.max_coalesce == 8
    # default: no per-codec cap (pipeline global cap applies)
    codec2 = _tpu({"k": "2", "m": "1"})
    assert codec2.batch_stripes is None
    assert codec2._encode_channel(128).max_coalesce is None


@pytest.mark.parametrize("bad", ["0", "-3", "x", ""])
def test_batch_stripes_validation(bad):
    with pytest.raises(ErasureCodeError):
        _tpu({"k": "2", "m": "1", "batch_stripes": bad})


# ---------------------------------------------------------------------------
# vectorized host CRC fold (degraded-mode scrub throughput)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("L", [1, 7, 8, 9, 100, 4096])
def test_crc32c_batch_matches_scalar(L):
    rng = np.random.default_rng(L)
    arr = rng.integers(0, 256, size=(6, L), dtype=np.uint8)
    got = crc_mod.crc32c_batch(arr, seed=0xDEADBEEF)
    for i in range(6):
        assert int(got[i]) == crc_mod.crc32c_sw(
            0xDEADBEEF, arr[i].tobytes())


def test_crc32c_batch_pure_python_fallback(monkeypatch):
    """The vectorized slicing-by-8 path (native ext masked off)."""
    import ceph_tpu.native as native
    monkeypatch.setattr(native, "available", lambda: False)
    rng = np.random.default_rng(1)
    arr = rng.integers(0, 256, size=(4, 333), dtype=np.uint8)
    got = crc_mod.crc32c_batch(arr)
    for i in range(4):
        assert int(got[i]) == crc_mod.crc32c_sw(0, arr[i].tobytes())


def test_encode_with_crcs_host_fallback_vectorized():
    """Degraded-mode encode_with_crcs: batched CRC fold, same bytes."""
    codec = _tpu({"k": "3", "m": "2"})
    faults.get().tpu_device_error(1.0)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, size=(4, 3, 256), dtype=np.uint8)
    parity, crcs = codec.encode_with_crcs(data)
    assert codec.degraded
    for b in range(4):
        expect_p = gf.encode_np(codec.coding_matrix, data[b])
        assert np.array_equal(parity[b], expect_p)
        allc = np.concatenate([data[b], expect_p], axis=0)
        for c in range(5):
            assert int(crcs[b, c]) == crc_mod.crc32c_sw(
                0, allc[c].tobytes())
