"""RBD block images: create/ls/rm, striped I/O, resize, snapshots,
exclusive lock, header watch refresh (librbd semantics)."""

import io as io_mod
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.rbd import RBD, Image, RbdError, data_oid
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("rbdpool", pg_num=8)
    ctx = rados.open_ioctx("rbdpool")
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warm", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


@pytest.fixture(scope="module")
def rbd(io):
    return RBD(io)


MB = 1 << 20


class TestImageLifecycle:
    def test_create_list_info(self, rbd, io):
        rbd.create("disk0", 8 * MB, order=20)     # 1 MiB objects
        assert "disk0" in rbd.list()
        with Image(io, "disk0") as img:
            st = img.stat()
            assert st["size"] == 8 * MB
            assert st["order"] == 20
            assert st["num_objs"] == 8

    def test_duplicate_create_fails(self, rbd):
        with pytest.raises(RadosError):
            rbd.create("disk0", MB)

    def test_open_missing_image(self, io):
        with pytest.raises(RbdError):
            Image(io, "nope")

    def test_remove(self, rbd, io):
        rbd.create("gone", 2 * MB, order=20)
        with Image(io, "gone") as img:
            img.write(0, b"x" * 4096)
        rbd.remove("gone")
        assert "gone" not in rbd.list()
        assert not any(n.startswith("rbd_data.gone")
                       for n in io.list_objects())


class TestImageIO:
    def test_write_read_cross_object(self, rbd, io):
        rbd.create("disk1", 4 * MB, order=20)
        with Image(io, "disk1") as img:
            payload = bytes(range(256)) * 8192     # 2 MiB
            img.write(MB - 1000, payload)          # crosses objects
            assert img.read(MB - 1000, len(payload)) == payload
            # data landed in multiple backing objects
            assert io.stat(data_oid("disk1", 0))["size"] > 0
            assert io.stat(data_oid("disk1", 1))["size"] > 0

    def test_unwritten_reads_as_zeros(self, io):
        with Image(io, "disk1") as img:
            assert img.read(3 * MB, 4096) == b"\x00" * 4096

    def test_out_of_bounds_rejected(self, io):
        with Image(io, "disk1") as img:
            with pytest.raises(RbdError):
                img.write(4 * MB - 10, b"x" * 100)
            with pytest.raises(RbdError):
                img.read(5 * MB, 10)

    def test_discard(self, io):
        with Image(io, "disk1") as img:
            img.write(0, b"D" * 8192)
            img.discard(0, 4096)
            assert img.read(0, 8192) == b"\x00" * 4096 + b"D" * 4096


class TestResize:
    def test_grow_and_shrink(self, rbd, io):
        rbd.create("disk2", 2 * MB, order=20)
        with Image(io, "disk2") as img:
            img.write(2 * MB - 4096, b"tail" * 1024)
            img.resize(4 * MB)
            assert img.size() == 4 * MB
            img.write(3 * MB, b"grown")
            img.resize(MB)          # shrink: drops objects past 1 MiB
            assert img.size() == MB
            with pytest.raises(RbdError):
                img.read(2 * MB, 10)
        assert not any(
            n == data_oid("disk2", 3) for n in io.list_objects())


class TestSnapshots:
    def test_snap_create_read_remove(self, rbd, io):
        rbd.create("disk3", 2 * MB, order=20)
        with Image(io, "disk3") as img:
            img.write(0, b"before-snap!")
            img.snap_create("s1")
            img.write(0, b"after-snap!!")
            assert [s["name"] for s in img.snap_list()] == ["s1"]
            assert img.read(0, 12) == b"after-snap!!"
        with Image(io, "disk3", snapshot="s1") as snap_img:
            assert snap_img.read(0, 12) == b"before-snap!"
            with pytest.raises(RbdError):
                snap_img.write(0, b"nope")
        with Image(io, "disk3") as img:
            img.snap_remove("s1")
            assert img.snap_list() == []

    def test_remove_with_snaps_refused(self, rbd, io):
        rbd.create("disk4", MB, order=20)
        with Image(io, "disk4") as img:
            img.snap_create("keep")
        with pytest.raises(RbdError):
            rbd.remove("disk4")
        with Image(io, "disk4") as img:
            img.snap_remove("keep")
        rbd.remove("disk4")


class TestExclusiveLock:
    def test_second_locker_refused(self, rbd, io, cluster):
        rbd.create("locked", MB, order=20)
        img1 = Image(io, "locked", exclusive=True)
        rados2 = cluster.client("client.other")
        io2 = rados2.open_ioctx("rbdpool")
        with pytest.raises(RbdError):
            Image(io2, "locked", exclusive=True)
        img1.close()
        # after release the other client can lock
        img2 = Image(io2, "locked", exclusive=True)
        info = img2.lock_info()
        assert info and info["type"] == "exclusive"
        img2.close()

    def test_break_lock(self, rbd, io, cluster):
        rados3 = cluster.client("client.dead")
        io3 = rados3.open_ioctx("rbdpool")
        img = Image(io3, "locked", exclusive=True)
        holder = img.lock_info()["holders"][0]
        # survivor breaks the dead client's lock and takes it
        with Image(io, "locked") as surv:
            surv.break_lock(holder[0], holder[1])
            assert surv.lock_info() is None
        img._lock_held = False      # it was broken away
        img.close()


class TestHeaderWatch:
    def test_resize_notifies_other_openers(self, rbd, io, cluster):
        rbd.create("shared", MB, order=20)
        rados2 = cluster.client("client.viewer")
        io2 = rados2.open_ioctx("rbdpool")
        viewer = Image(io2, "shared")
        try:
            with Image(io, "shared") as writer:
                writer.resize(2 * MB)
            end = time.time() + 10
            while time.time() < end and viewer.size() != 2 * MB:
                time.sleep(0.1)
            assert viewer.size() == 2 * MB
        finally:
            viewer.close()


class TestRbdCli:
    def test_cli_lifecycle(self, cluster, tmp_path):
        conf = tmp_path / "ceph.conf"
        mon_host = ",".join(
            f"{h}:{p}" for h, p in (cluster.monmap.addr_of(n)
                                    for n in cluster.monmap.ranks()))
        conf.write_text(f"[global]\nfsid = {cluster.monmap.fsid}\n"
                        f"mon_host = {mon_host}\n")
        from ceph_tpu.tools import rbd_cli
        buf = io_mod.StringIO()
        base = ["-c", str(conf), "-p", "rbdpool"]
        assert rbd_cli.main(base + ["--size", "4M", "--order", "20",
                                    "create", "clidisk"], out=buf) == 0
        assert rbd_cli.main(base + ["ls"], out=buf) == 0
        assert "clidisk" in buf.getvalue()
        buf = io_mod.StringIO()
        assert rbd_cli.main(base + ["info", "clidisk"], out=buf) == 0
        assert "4194304 bytes" in buf.getvalue()
        assert rbd_cli.main(base + ["snap", "create", "clidisk@c1"],
                            out=buf) == 0
        buf = io_mod.StringIO()
        assert rbd_cli.main(base + ["snap", "ls", "clidisk"],
                            out=buf) == 0
        assert "c1" in buf.getvalue()
        assert rbd_cli.main(base + ["snap", "rm", "clidisk@c1"],
                            out=buf) == 0
        buf = io_mod.StringIO()
        assert rbd_cli.main(base + ["--io-size", "4096", "--io-total",
                                    "64K", "bench", "clidisk"],
                            out=buf) == 0
        assert "bytes/sec" in buf.getvalue()
        assert rbd_cli.main(base + ["rm", "clidisk"], out=buf) == 0


class TestShrinkRegrow:
    def test_regrow_exposes_zeros(self, rbd, io):
        """Shrink must truncate the boundary object: regrowing reads
        zeros, not stale pre-shrink bytes (librbd semantics)."""
        rbd.create("disk5", 2 * MB, order=20)
        with Image(io, "disk5") as img:
            img.write(0, b"\xEE" * (2 * MB))
            img.resize(MB + 512 * 1024)       # partial boundary object
            img.resize(2 * MB)
            tail = img.read(MB + 512 * 1024, 512 * 1024)
            assert tail == b"\x00" * (512 * 1024)
            head = img.read(MB, 512 * 1024)
            assert head == b"\xEE" * (512 * 1024)
