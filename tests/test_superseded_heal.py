"""Out-of-order sub-op handling: ordered-apply parking + the
superseded-skip heal backstop.

The scenario (reference analog: out-of-order MOSDRepOp delivery after
a lost message + resend): replica misses op N (writefull), then op
N+1 (setxattr) arrives first.  Two defenses, both tested here:

  1. PARKING (primary defense): N+1 detects the prior-chain gap and
     parks until N's resend lands; both apply in order — no hole.
  2. HEAL (backstop, forced here via _PARK_CAP=0 — the cap-overflow /
     park-expired path): N+1 applies first, the resend of N is
     superseded, and the replica queues a heal — a pull of the
     primary's full copy (replicated) or a shard rebuild excluding
     the stale shard (EC, MPGInfo op=rebuild_me with version-gated
     source reads).
"""

import time
from types import SimpleNamespace

import pytest

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.messages import MOSDECSubOpWrite, MOSDRepOp
from ceph_tpu.osd.pg import HINFO_KEY, VER_KEY, shard_oid, stash_oid
from ceph_tpu.store.objectstore import Transaction
from ceph_tpu.utils import denc
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


def _settle_write(io, oid, data, timeout=30.0):
    from ceph_tpu.client import RadosError
    end = time.time() + timeout
    while True:
        try:
            io.write_full(oid, data)
            return
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)


def _conn_to(cluster, osd_id):
    addr = cluster.leader().osdmon.osdmap.get_addr(osd_id)
    return SimpleNamespace(peer_name=f"osd.{osd_id}",
                          peer_addr=tuple(addr))


class TestSupersededHeal:
    def test_replicated_superseded_pulls_primary_copy(self, cluster):
        rados = cluster.client()
        rados.create_pool("heal-rep", pg_num=1)
        io = rados.open_ioctx("heal-rep")
        _settle_write(io, "obj", b"base")
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "obj")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary, replica = acting[0], acting[1]
        ppg = cluster.osds[primary].get_pg(pgid)
        rpg = cluster.osds[replica].get_pg(pgid)
        base_ev = ppg.pglog.objects["obj"]
        ev_n = (base_ev[0], ppg.pglog.head[1] + 1)
        ev_n1 = (base_ev[0], ppg.pglog.head[1] + 2)
        payload = b"the-acked-N-payload"

        def rep_msg(ev, prior, ops, tid):
            cid = ppg.cid
            txn = Transaction()
            for op in ops:
                if op[0] == "writefull":
                    txn.truncate(cid, "obj", 0)
                    txn.write(cid, "obj", 0, op[1])
                elif op[0] == "setxattr":
                    txn.setattr(cid, "obj", "u." + op[1], op[2])
            txn.setattr(cid, "obj", VER_KEY, repr(ev).encode())
            entry = {"ev": ev, "oid": "obj", "op": "modify",
                     "prior": prior, "rollback": None, "shard": None}
            msg = MOSDRepOp(reqid=("client.heal", tid), pgid=str(pgid),
                            ops=txn.ops, log=entry,
                            epoch=m.epoch)
            msg.src = f"osd.{primary}"
            return msg

        n = rep_msg(ev_n, base_ev, [("writefull", payload)], 1)
        n1 = rep_msg(ev_n1, ev_n, [("setxattr", "k", b"v")], 2)
        conn = _conn_to(cluster, primary)
        # the primary itself applies both in order (it holds the truth)
        ppg.handle_rep_op(conn, rep_msg(ev_n, base_ev,
                                        [("writefull", payload)], 1))
        ppg.handle_rep_op(conn, rep_msg(ev_n1, ev_n,
                                        [("setxattr", "k", b"v")], 2))
        # force the HEAL path: parking disabled, so N+1 applies first
        # and the resend of N arrives superseded (models the park-cap
        # overflow / park-expired cases)
        rpg._PARK_CAP = 0
        # the replica sees them OUT OF ORDER: N+1 lands, then the
        # resend of N arrives and is superseded
        rpg.handle_rep_op(conn, n1)
        assert rpg.osd.store.read(rpg.cid, "obj") == b"base"  # hole!
        rpg.handle_rep_op(conn, n)
        # the superseded path must have queued a pull from the primary
        end = time.time() + 20
        while time.time() < end:
            try:
                if rpg.osd.store.read(rpg.cid, "obj") == payload:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        assert rpg.osd.store.read(rpg.cid, "obj") == payload
        assert rpg.osd.store.getattr(rpg.cid, "obj", "u.k") == b"v"

    def test_ec_superseded_requests_shard_rebuild(self, cluster):
        rados = cluster.client()
        rados.create_ec_pool("heal-ec", "k2m1h",
                             {"plugin": "tpu", "k": 2, "m": 1,
                              "technique": "reed_sol_van"}, pg_num=1)
        io = rados.open_ioctx("heal-ec")
        # payloads must exceed one stripe width so BOTH data shards
        # carry real (version-distinguishing) bytes — a sub-stripe
        # object leaves shard 1 all-padding in every version
        _settle_write(io, "obj", b"v1-bytes" * 1600)
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "obj")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary = next(o for o in acting if o >= 0)
        ppg = cluster.osds[primary].get_pg(pgid)
        codec = ppg._ec_codec()
        sinfo = ppg._ec_sinfo(codec)
        payload = b"v2-THE-ACKED-DATA" * 1000
        shards, stripe_crcs = ecutil.encode_object_ex(
            codec, sinfo, payload)
        crcs = ecutil.fold_shard_crcs(stripe_crcs, sinfo.chunk_size)
        pre_crcs = ecutil.fold_shard_crcs(
            stripe_crcs, sinfo.chunk_size,
            upto=len(payload) // sinfo.stripe_width)
        prior = ppg.pglog.objects["obj"]
        ev_n = (prior[0], ppg.pglog.head[1] + 1)
        ev_n1 = (prior[0], ppg.pglog.head[1] + 2)
        conn = _conn_to(cluster, primary)

        def sub_write(pg, shard, ev, pri, data_write, tid):
            cid = pg.cid
            soid = shard_oid("obj", shard)
            txn = Transaction()
            txn.try_clone(cid, soid, stash_oid(soid, pri))
            if data_write:
                hinfo = denc.dumps(
                    {"size": len(payload), "crc": crcs[shard],
                     "crc_prefix": pre_crcs[shard], "shard": shard,
                     "stripe_unit": sinfo.chunk_size})
                txn.truncate(cid, soid, 0)
                txn.write(cid, soid, 0, shards[shard])
                txn.setattr(cid, soid, HINFO_KEY, hinfo)
            else:
                txn.setattr(cid, soid, "u.meta", b"m")
            txn.setattr(cid, soid, VER_KEY, repr(ev).encode())
            entry = {"ev": ev, "oid": "obj", "op": "modify",
                     "prior": pri, "rollback": {"type": "stash"},
                     "shard": None}
            msg = MOSDECSubOpWrite(
                reqid=("client.heal", tid), pgid=str(pgid),
                shard=shard, ops=txn.ops, log=entry,
                roll_forward_to=pg.last_complete, epoch=m.epoch)
            msg.src = f"osd.{primary}"
            return msg

        stale_shard = next(s for s, o in enumerate(acting)
                           if o >= 0 and o != primary)
        for shard, osd_id in enumerate(acting):
            if osd_id < 0:
                continue
            pg = cluster.osds[osd_id].get_pg(pgid)
            if shard == stale_shard:
                # misses the data write N, applies meta-only N+1,
                # then the resend of N arrives superseded (parking
                # disabled to force the heal backstop)
                pg._PARK_CAP = 0
                pg.handle_ec_sub_write(
                    conn, sub_write(pg, shard, ev_n1, ev_n, False, 2))
                pg.handle_ec_sub_write(
                    conn, sub_write(pg, shard, ev_n, prior, True, 1))
            else:
                pg.handle_ec_sub_write(
                    conn, sub_write(pg, shard, ev_n, prior, True, 1))
                pg.handle_ec_sub_write(
                    conn, sub_write(pg, shard, ev_n1, ev_n, False, 2))
        # rebuild_me -> primary reconstructs (excluding the stale
        # shard) and pushes the correct v2 shard bytes back
        spg = cluster.osds[acting[stale_shard]].get_pg(pgid)
        soid = shard_oid("obj", stale_shard)
        want = shards[stale_shard]
        end = time.time() + 25
        while time.time() < end:
            try:
                hi = denc.loads(
                    spg.osd.store.getattr(spg.cid, soid, HINFO_KEY))
                if hi["size"] == len(payload) and \
                        spg.osd.store.read(spg.cid, soid) == want:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        assert spg.osd.store.read(spg.cid, soid) == want
        hinfo = denc.loads(
            spg.osd.store.getattr(spg.cid, soid, HINFO_KEY))
        assert hinfo["size"] == len(payload)
        # the whole object decodes to v2 from any k shards
        assert io.read("obj") == payload

    def test_replicated_out_of_order_parks_and_applies_in_order(
            self, cluster):
        """With parking enabled (the default), an out-of-order N+1
        parks until the resend of N lands, then BOTH apply in order —
        no hole, no heal round-trip needed."""
        rados = cluster.client()
        rados.create_pool("park-rep", pg_num=1)
        io = rados.open_ioctx("park-rep")
        _settle_write(io, "obj", b"base")
        m = cluster.leader().osdmon.osdmap
        pgid = m.object_to_pg(io.pool_id, "obj")
        _up, acting = m.pg_to_up_acting_osds(pgid)
        primary, replica = acting[0], acting[1]
        ppg = cluster.osds[primary].get_pg(pgid)
        rpg = cluster.osds[replica].get_pg(pgid)
        base_ev = ppg.pglog.objects["obj"]
        ev_n = (base_ev[0], ppg.pglog.head[1] + 1)
        ev_n1 = (base_ev[0], ppg.pglog.head[1] + 2)
        payload = b"parked-then-applied"

        def rep_msg(ev, prior, ops, tid):
            cid = rpg.cid
            txn = Transaction()
            for op in ops:
                if op[0] == "writefull":
                    txn.truncate(cid, "obj", 0)
                    txn.write(cid, "obj", 0, op[1])
                elif op[0] == "setxattr":
                    txn.setattr(cid, "obj", "u." + op[1], op[2])
            txn.setattr(cid, "obj", VER_KEY, repr(ev).encode())
            entry = {"ev": ev, "oid": "obj", "op": "modify",
                     "prior": prior, "rollback": None, "shard": None}
            msg = MOSDRepOp(reqid=("client.park", tid), pgid=str(pgid),
                            ops=txn.ops, log=entry, epoch=m.epoch)
            msg.src = f"osd.{primary}"
            return msg

        conn = _conn_to(cluster, primary)
        # out of order: N+1 first — must PARK (no state change yet)
        rpg.handle_rep_op(conn, rep_msg(ev_n1, ev_n,
                                        [("setxattr", "k", b"v")], 2))
        assert rpg.osd.store.read(rpg.cid, "obj") == b"base"
        assert rpg.pglog.objects["obj"] == base_ev
        assert ("obj", ev_n1) in rpg._parked
        # the resend of N arrives: applies, then the parked N+1
        # flushes immediately — full state, no heal wait
        rpg.handle_rep_op(conn, rep_msg(ev_n, base_ev,
                                        [("writefull", payload)], 1))
        assert rpg.osd.store.read(rpg.cid, "obj") == payload
        assert rpg.osd.store.getattr(rpg.cid, "obj", "u.k") == b"v"
        assert rpg.pglog.objects["obj"] == ev_n1
        assert not rpg._parked
        # log is in ev order
        evs = [e["ev"] for e in rpg.pglog.entries]
        assert evs == sorted(evs)
