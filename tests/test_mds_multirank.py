"""Multi-rank MDS: subtree export/import, migration under live I/O,
crash recovery mid-migration, balancer (mds/Migrator.h:52,
mds/MDBalancer.h:39 redesigned onto shared-RADOS authority handoff).
"""

import threading
import time

import pytest

from ceph_tpu.fs import CephFS, FsError
from ceph_tpu.fs.mds import _SimulatedCrash
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def ranks(cluster):
    mds0 = cluster.start_mds("r0", rank=0)
    mds1 = cluster.start_mds("r1", rank=1)
    return mds0, mds1


@pytest.fixture()
def fs(cluster, ranks):
    return CephFS(cluster.client()).mount()


def put(fs, path, data=b""):
    with fs.open(path, "w") as f:
        if data:
            f.write(data)


def get(fs, path):
    with fs.open(path, "r") as f:
        return f.read()


def wait_for(pred, timeout=15, interval=0.1):
    end = time.time() + timeout
    while time.time() < end:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestSubtreeExport:
    def test_two_ranks_serve_disjoint_subtrees(self, fs, ranks):
        mds0, mds1 = ranks
        fs.mkdir("/left")
        fs.mkdir("/right")
        put(fs, "/left/f")
        mds0.export_dir("/right", 1)
        # ops on /right now land on rank 1; /left stays on rank 0
        put(fs, "/right/g", b"on rank one")
        assert get(fs, "/right/g") == b"on rank one"
        assert fs.listdir("/right") == ["g"]
        assert fs.listdir("/left") == ["f"]
        # the request flowed through rank 1 (its load counter moved)
        assert mds1._req_count > 0 or mds1._dir_hits
        # rank 0 no longer serves /right: its table says rank 1
        assert mds0._auth_rank("/right") == 1
        assert mds0._auth_rank("/left") == 0
        assert mds1._auth_rank("/right") == 1

    def test_export_back_and_forth(self, fs, ranks):
        mds0, mds1 = ranks
        fs.mkdir("/pingpong")
        put(fs, "/pingpong/x")
        mds0.export_dir("/pingpong", 1)
        put(fs, "/pingpong/x", b"one")
        mds1.export_dir("/pingpong", 0)
        put(fs, "/pingpong/x", b"zero")
        assert get(fs, "/pingpong/x") == b"zero"
        assert mds0._auth_rank("/pingpong") == 0

    def test_migration_under_live_client_io(self, cluster, ranks):
        """Writers hammer the subtree while it migrates: no mutation
        may be lost (the freeze defers, never drops)."""
        mds0, mds1 = ranks
        fs = CephFS(cluster.client()).mount()
        fs.mkdir("/live")
        stop = threading.Event()
        written: list[str] = []
        errors: list = []

        def writer():
            wfs = CephFS(cluster.client()).mount()
            i = 0
            while not stop.is_set() and i < 400:
                name = f"/live/f{i:04d}"
                try:
                    put(wfs, name, str(i).encode())
                    written.append(name)
                    i += 1
                except FsError as e:
                    if e.errno not in (11, 110):
                        errors.append(e)
                        return
            stop.set()

        th = threading.Thread(target=writer)
        th.start()
        # pin the ordering the old wall-clock sleeps raced on (write
        # throughput varies with the background beacon/flush cadence):
        # each migration happens only after the writer demonstrably
        # progressed, and the stop only after >20 writes landed — so
        # "real concurrency happened" is guaranteed, not hoped for
        assert wait_for(lambda: len(written) >= 8, timeout=60), \
            f"writer stalled at {len(written)} writes"
        owner = mds0 if mds0._auth_rank("/live") == 0 else mds1
        owner.export_dir("/live", 1)
        assert wait_for(lambda: len(written) >= 16, timeout=60), \
            f"writer stalled at {len(written)} after export"
        mds1.export_dir("/live", 0)
        assert wait_for(lambda: len(written) >= 24, timeout=60), \
            f"writer stalled at {len(written)} after re-import"
        stop.set()
        th.join(timeout=120)
        assert not errors, errors[0]
        assert len(written) > 20          # real concurrency happened
        names = fs.listdir("/live")
        for name in written:
            base = name.rsplit("/", 1)[1]
            assert base in names, f"lost {name}"
            assert get(fs, name) is not None

    def test_crash_before_commit_keeps_exporter(self, cluster, ranks):
        """Dying before the table CAS leaves the exporter
        authoritative; a fresh client sees no migration."""
        mds0, mds1 = ranks
        fs = CephFS(cluster.client()).mount()
        fs.mkdir("/crash1")
        put(fs, "/crash1/a")
        with pytest.raises(_SimulatedCrash):
            mds0.export_dir("/crash1", 1, _crash_at="frozen")
        # frozen state rolled back with the exception; still rank 0
        assert mds0._auth_rank("/crash1") == 0
        put(fs, "/crash1/a", b"still here")
        assert get(fs, "/crash1/a") == b"still here"

    def test_crash_after_flush_recovers(self, cluster, ranks):
        """Dying after the flush but before the CAS: exporter remains
        auth (commit point not reached), journal already flushed —
        no replay hazard, subtree still fully usable."""
        mds0, mds1 = ranks
        fs = CephFS(cluster.client()).mount()
        fs.mkdir("/crash2")
        put(fs, "/crash2/b")
        with pytest.raises(_SimulatedCrash):
            mds0.export_dir("/crash2", 1, _crash_at="flushed")
        assert mds0._auth_rank("/crash2") == 0
        put(fs, "/crash2/b", b"ok")
        assert get(fs, "/crash2/b") == b"ok"

    def test_kill9_exporter_mid_migration_importer_side(
            self, cluster, ranks):
        """kill -9 of the exporter right AFTER the table CAS: the
        importer is authoritative, data served from RADOS, and a
        restarted exporter routes requests to the importer."""
        mds0, mds1 = ranks
        fs = CephFS(cluster.client()).mount()
        fs.mkdir("/crash3")
        put(fs, "/crash3/c", b"precious")
        mds0.export_dir("/crash3", 1)     # commit point passed
        mds0.kill()                       # exporter dies uncleanly
        # operator restarts rank 0 (fresh daemon, fresh journal replay)
        cluster.mdss.remove(mds0)
        new0 = cluster.start_mds("r0b", rank=0)
        assert wait_for(
            lambda: cluster.client().monc.osdmap.mds_ranks.get(
                0, ("", None))[0] == "r0b", timeout=20)
        # importer is authoritative; the restarted rank 0 routes by
        # the committed table and the subtree is fully usable
        fs2 = CephFS(cluster.client()).mount()
        assert get(fs2, "/crash3/c") == b"precious"
        put(fs2, "/crash3/d")
        assert sorted(fs2.listdir("/crash3")) == ["c", "d"]
        assert new0._auth_rank("/crash3") == 1


class TestBalancer:
    def test_balancer_exports_hot_subtree(self):
        """A 2x load imbalance moves the hottest top-level dir to the
        cooler rank (MDBalancer.h:39 reduced).  Own cluster: the
        module cluster's MDS daemons would fight these over the
        osdmap rank slots (last beacon wins) and misroute clients."""
        import ceph_tpu.fs.mds as mdsmod
        cluster = MiniCluster(num_mons=1, num_osds=3).start()
        self._cluster = cluster
        mds0 = cluster.start_mds("balA", metadata_pool="balmeta",
                                 data_pool="baldata", rank=0)
        mds1 = cluster.start_mds("balB", metadata_pool="balmeta",
                                 data_pool="baldata", rank=1)
        fs = CephFS(cluster.client(), data_pool="baldata",
                    metadata_pool="balmeta").mount()
        fs.mkdir("/hot")
        for i in range(40):
            put(fs, f"/hot/f{i}")
        # drive one balance pass with an explicit load sample (the
        # background beacon may reset the live counters at any time;
        # counter plumbing is covered by the auto-balancer drive)
        mds1._beacon_multirank()          # publish rank 1's (idle) load
        from ceph_tpu.utils import denc
        mds0.meta.set_omap(mdsmod.LOAD_OID,
                           {"1": denc.dumps({"load": 0})})
        mds0.maybe_balance(100, {"/hot": 100})
        assert mds0._auth_rank("/hot") == 1
        # and the namespace still works through the new owner
        fs2 = CephFS(cluster.client(), data_pool="baldata",
                     metadata_pool="balmeta").mount()
        assert len(fs2.listdir("/hot")) == 40
        put(fs2, "/hot/after")
        assert "after" in fs2.listdir("/hot")
        cluster.stop()
