"""Multi-chip dryrun: the driver's virtual 8-device mesh gate.

conftest.py forces JAX_PLATFORMS=cpu with 8 virtual host devices, so
this exercises the same sharded step the driver dry-run-compiles
(__graft_entry__.dryrun_multichip) — dp x shard mesh, fused encode+CRC,
host-oracle cross-check.  The clear_backends fallback (jax already
initialized with too few devices, the driver's single-TPU scenario) is
exercised in a subprocess so it cannot disturb this process's mesh.
"""

import os
import subprocess
import sys

import jax

import __graft_entry__ as graft


def test_dryrun_multichip_8():
    r = graft.dryrun_multichip(8)
    assert r["oracle"] and r["mode"] == "inproc"


def test_dryrun_multichip_2():
    r = graft.dryrun_multichip(2)
    assert r["oracle"] and r["mode"] == "inproc"


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_ensure_devices_enough():
    devs = graft._ensure_devices(8)
    assert len(devs) >= 8


def test_fallback_after_backend_init():
    """Driver scenario: jax initialized with 1 device, then dryrun(4).

    The fallback must BOTH complete and still run the host-oracle
    verification — dryrun_multichip reports that explicitly, so a
    fallback that skipped the check cannot pass."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("CEPH_TPU_MULTICHIP_CHILD", None)
    code = (
        "import jax\n"
        "assert len(jax.devices()) == 1\n"  # initialize with too few
        "import __graft_entry__ as g\n"
        "r = g.dryrun_multichip(4)\n"
        "assert r['oracle'] is True, r\n"
        "assert r['devices'] >= 4, r\n"
        "print('fallback-ok', r['mode'])\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "fallback-ok" in out.stdout


def test_full_batch_oracle_equality():
    """Every stripe's parity and every chunk CRC from the chunk-sharded
    mesh step must equal the host oracle (VERDICT r4 weak #6: no more
    parity[0]-only spot checks)."""
    data, parity, crcs, matrix = graft._run_sharded(8)
    assert data.shape[0] >= 2          # a real batch, not one stripe
    graft.verify_against_oracle(data, parity, crcs, matrix)
