"""Device kernels vs numpy ground truth (runs on the 8-device CPU backend)."""

import numpy as np
import pytest

from ceph_tpu.ops import crc32c as crc_mod
from ceph_tpu.ops import ec_kernels, gf


@pytest.mark.parametrize("compute", ["int8", "bf16"])
def test_encode_matches_numpy(compute):
    rng = np.random.default_rng(0)
    k, m, L = 8, 3, 1024
    coding = gf.reed_sol_van_matrix(k, m)
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    fn = ec_kernels.make_codec_fn(coding, compute=compute)
    parity = np.asarray(fn(data))
    assert np.array_equal(parity, gf.encode_np(coding, data))


def test_encode_batched():
    rng = np.random.default_rng(1)
    k, m, L, B = 4, 2, 256, 5
    coding = gf.isa_rs_matrix(k, m)
    data = rng.integers(0, 256, size=(B, k, L), dtype=np.uint8)
    fn = ec_kernels.make_codec_fn(coding)
    parity = np.asarray(fn(data))
    assert parity.shape == (B, m, L)
    for b in range(B):
        assert np.array_equal(parity[b], gf.encode_np(coding, data[b]))


def test_decode_roundtrip_on_device():
    rng = np.random.default_rng(2)
    k, m, L = 6, 3, 512
    coding = gf.cauchy_good_matrix(k, m)
    gen = gf.systematic_generator(coding, k)
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    parity = np.asarray(ec_kernels.make_codec_fn(coding)(data))
    chunks = np.concatenate([data, parity], axis=0)
    lost = {1, 4, 7}
    present = [i for i in range(k + m) if i not in lost][:k]
    dec = gf.decode_matrix(gen, k, present)
    rebuilt = np.asarray(ec_kernels.make_codec_fn(dec)(chunks[present]))
    assert np.array_equal(rebuilt, data)


def test_gf2_bitmatrix_direct():
    """w=1 path: a raw GF(2) matrix (e.g. cauchy bitmatrix) applied directly."""
    rng = np.random.default_rng(3)
    k, m = 3, 2
    bm = gf.expand_bitmatrix(gf.cauchy_orig_matrix(k, m), 8)
    data = rng.integers(0, 256, size=(k, 64), dtype=np.uint8)
    out_dev = np.asarray(ec_kernels.make_codec_fn(bm, w=1)(data))
    # bit-domain ground truth
    bits = np.unpackbits(data, axis=0, bitorder="little")
    bits = bits.reshape(k, 8, 64).reshape(k * 8, 64)
    expect_bits = (bm @ bits) % 2
    expect = np.zeros((m, 64), dtype=np.uint8)
    for i in range(m):
        for b in range(8):
            expect[i] |= (expect_bits[i * 8 + b] << b).astype(np.uint8)
    assert np.array_equal(out_dev, expect)


@pytest.mark.parametrize("L,block", [(256, 32), (1000, 0)])
def test_device_crc(L, block):
    rng = np.random.default_rng(4)
    chunks = rng.integers(0, 256, size=(3, L), dtype=np.uint8)
    fn = ec_kernels.make_crc_fn(L, block=block or ec_kernels.DEFAULT_CRC_BLOCK)
    got = np.asarray(fn(chunks))
    for i in range(3):
        assert int(got[i]) == crc_mod.crc32c_sw(0, chunks[i].tobytes())


def test_fused_encode_crc():
    rng = np.random.default_rng(5)
    k, m, L, B = 8, 3, 512, 2
    coding = gf.reed_sol_van_matrix(k, m)
    data = rng.integers(0, 256, size=(B, k, L), dtype=np.uint8)
    fn = ec_kernels.make_encode_crc_fn(coding, L)
    parity, crcs = fn(data)
    parity, crcs = np.asarray(parity), np.asarray(crcs)
    assert crcs.shape == (B, k + m)
    for b in range(B):
        expect_parity = gf.encode_np(coding, data[b])
        assert np.array_equal(parity[b], expect_parity)
        allc = np.concatenate([data[b], expect_parity], axis=0)
        for i in range(k + m):
            assert int(crcs[b, i]) == crc_mod.crc32c_sw(0, allc[i].tobytes())


def test_seed_chaining_via_combine():
    """Device CRCs (seed 0) chain into ceph-style seeded CRCs on host."""
    rng = np.random.default_rng(6)
    L = 128
    chunk = rng.integers(0, 256, size=L, dtype=np.uint8)
    dev = int(np.asarray(ec_kernels.make_crc_fn(L)(chunk[None]))[0])
    seed = 0xCAFEBABE
    assert crc_mod.crc32c_combine(seed, dev, L) == crc_mod.crc32c_sw(seed, chunk.tobytes())
