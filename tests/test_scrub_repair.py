"""Scrub repair: corrupt/missing copies heal back to clean.

Reference scenarios: test/osd/osd-scrub-repair.sh
(TEST_corrupt_and_repair_replicated, TEST_corrupt_and_repair_jerasure
at :201,221) and PGBackend::be_select_auth_object (PGBackend.cc:501) —
authoritative-copy selection then repair writes, driven by a
`ceph pg repair` command.
"""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.store.objectstore import StoreError, Transaction
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def rados(cluster):
    return cluster.client()


def _settle(rados, cluster, pool, **kw):
    ctx = rados.open_ioctx(pool)
    end = time.time() + 60
    while True:
        try:
            ctx.write_full("settle", b"s")
            return ctx
        except RadosError:
            if time.time() > end:
                raise
            cluster.tick(0.3)


def _primary_pg(cluster, pool_id, oid):
    m = cluster.osds[0].osdmap
    pgid = m.object_to_pg(pool_id, oid)
    primary = m.pg_primary(pgid)
    return pgid, cluster.osds[primary].pgs[pgid]


def _holders(cluster, pgid):
    m = cluster.osds[0].osdmap
    _up, acting = m.pg_to_up_acting_osds(pgid)
    return acting


class TestReplicatedRepair:
    def test_corrupt_replica_heals(self, cluster, rados):
        rados.create_pool("rep-fix", pg_num=4)
        io = _settle(rados, cluster, "rep-fix")
        io.write_full("victim", b"pristine-content")
        pgid, pg = _primary_pg(cluster, io.pool_id, "victim")
        acting = _holders(cluster, pgid)
        # corrupt a NON-primary replica on disk (silent bitrot)
        replica = cluster.osds[acting[1]]
        replica.store.apply_transaction(
            Transaction().write(f"pg_{pgid}", "victim", 2, b"\xbe\xef"))
        dirty = pg.scrub(deep=True)
        assert dirty["inconsistent"], "scrub missed the corruption"
        result = pg.scrub(deep=True, repair=True)
        assert result["repaired"] >= 1
        assert result["clean_after_repair"], result
        assert replica.store.read(f"pg_{pgid}", "victim") == \
            b"pristine-content"
        assert io.read("victim") == b"pristine-content"

    def test_corrupt_primary_copy_pulls_from_majority(self, cluster,
                                                      rados):
        rados.create_pool("rep-pri", pg_num=4)
        io = _settle(rados, cluster, "rep-pri")
        io.write_full("primary-bad", b"the-true-bytes")
        pgid, pg = _primary_pg(cluster, io.pool_id, "primary-bad")
        acting = _holders(cluster, pgid)
        primary = cluster.osds[acting[0]]
        primary.store.apply_transaction(
            Transaction().write(f"pg_{pgid}", "primary-bad", 0,
                                b"XXXX"))
        result = pg.scrub(deep=True, repair=True)
        assert result["repaired"] >= 1
        assert result["clean_after_repair"], result
        assert primary.store.read(f"pg_{pgid}", "primary-bad") == \
            b"the-true-bytes"

    def test_missing_replica_copy_is_pushed(self, cluster, rados):
        rados.create_pool("rep-miss", pg_num=4)
        io = _settle(rados, cluster, "rep-miss")
        io.write_full("lost", b"re-replicate-me")
        pgid, pg = _primary_pg(cluster, io.pool_id, "lost")
        acting = _holders(cluster, pgid)
        replica = cluster.osds[acting[2]]
        replica.store.apply_transaction(
            Transaction().remove(f"pg_{pgid}", "lost"))
        result = pg.scrub(deep=True, repair=True)
        assert result["repaired"] >= 1
        assert result["clean_after_repair"], result
        assert replica.store.read(f"pg_{pgid}", "lost") == \
            b"re-replicate-me"


class TestECRepair:
    @pytest.fixture(scope="class")
    def io(self, cluster, rados):
        rados.create_ec_pool("ec-fix", "fix_k2m1",
                             {"plugin": "tpu", "k": 2, "m": 1})
        return _settle(rados, cluster, "ec-fix")

    def test_corrupt_shard_rebuilds(self, cluster, rados, io):
        io.write_full("shardbad", bytes(range(256)) * 32)
        pgid, pg = _primary_pg(cluster, io.pool_id, "shardbad")
        acting = _holders(cluster, pgid)
        # corrupt shard 1 on its holder
        holder = cluster.osds[acting[1]]
        good = holder.store.read(f"pg_{pgid}", "shardbad.s1")
        holder.store.apply_transaction(
            Transaction().write(f"pg_{pgid}", "shardbad.s1", 7,
                                b"\x00\xff\x00"))
        result = pg.scrub(deep=True, repair=True)
        assert result["repaired"] >= 1
        assert result["clean_after_repair"], result
        assert holder.store.read(f"pg_{pgid}", "shardbad.s1") == good
        assert io.read("shardbad") == bytes(range(256)) * 32

    def test_missing_shard_file_rebuilds(self, cluster, rados, io):
        io.write_full("sharddel", b"Q" * 9000)
        pgid, pg = _primary_pg(cluster, io.pool_id, "sharddel")
        acting = _holders(cluster, pgid)
        holder = cluster.osds[acting[2]]
        holder.store.apply_transaction(
            Transaction().remove(f"pg_{pgid}", "sharddel.s2"))
        result = pg.scrub(deep=True, repair=True)
        assert result["repaired"] >= 1
        assert result["clean_after_repair"], result
        assert holder.store.exists(f"pg_{pgid}", "sharddel.s2")

    def test_corrupt_primary_shard_excluded_from_decode(self, cluster,
                                                        rados, io):
        payload = b"ABCD" * 4000
        io.write_full("pribad", payload)
        pgid, pg = _primary_pg(cluster, io.pool_id, "pribad")
        acting = _holders(cluster, pgid)
        primary = cluster.osds[acting[0]]
        primary.store.apply_transaction(
            Transaction().write(f"pg_{pgid}", "pribad.s0", 0,
                                b"garbage!"))
        result = pg.scrub(deep=True, repair=True)
        assert result["repaired"] >= 1
        assert result["clean_after_repair"], result
        assert io.read("pribad") == payload


class TestRepairCommand:
    def test_pg_repair_mon_command(self, cluster, rados):
        rados.create_pool("cmd-fix", pg_num=4)
        io = _settle(rados, cluster, "cmd-fix")
        io.write_full("cmdobj", b"command-driven-repair")
        pgid, pg = _primary_pg(cluster, io.pool_id, "cmdobj")
        acting = _holders(cluster, pgid)
        replica = cluster.osds[acting[1]]
        replica.store.apply_transaction(
            Transaction().write(f"pg_{pgid}", "cmdobj", 0, b"BAD"))
        rv, out, _ = rados.mon_command(
            {"prefix": "pg repair", "pgid": str(pgid)})
        assert rv == 0, out
        assert "repair" in out
        end = time.time() + 30
        while True:
            try:
                if replica.store.read(f"pg_{pgid}", "cmdobj") == \
                        b"command-driven-repair":
                    break
            except StoreError:
                pass
            if time.time() > end:
                raise AssertionError("pg repair command never healed")
            cluster.tick(0.3)
            time.sleep(0.05)

    def test_pg_scrub_command_bad_pgid(self, cluster, rados):
        rv, out, _ = rados.mon_command(
            {"prefix": "pg repair", "pgid": "nonsense"})
        assert rv == -22


class TestScheduledScrub:
    """Automatic interval-driven scrubs (OSD::sched_scrub,
    osd/OSD.cc:1054): corruption is caught — and with auto_repair,
    healed — without any `pg scrub` command."""

    @pytest.fixture(scope="class")
    def sched_cluster(self):
        conf = Config({
            "mon_tick_interval": 0.5,
            "osd_heartbeat_interval": 0.3,
            "osd_heartbeat_grace": 8.0,
            "mon_osd_min_down_reporters": 2,
            # aggressive schedule: shallow every 1s, deep every 2s
            "osd_scrub_min_interval": 1.0,
            "osd_deep_scrub_interval": 2.0,
            "osd_scrub_auto_repair": True,
        })
        c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
        yield c
        c.stop()

    def test_scheduled_deep_scrub_catches_corruption(
            self, sched_cluster):
        cluster = sched_cluster
        rados = cluster.client()
        rados.create_pool("auto-scrub", pg_num=4)
        io = _settle(rados, cluster, "auto-scrub")
        io.write_full("victim", b"bitrot-target-content")
        pgid, pg = _primary_pg(cluster, io.pool_id, "victim")
        acting = _holders(cluster, pgid)
        # silent bitrot on a replica — NO scrub command follows
        replica = cluster.osds[acting[1]]
        replica.store.apply_transaction(
            Transaction().write(f"pg_{pgid}", "victim", 3,
                                b"\xde\xad"))
        # the scheduler must detect AND (auto_repair) heal it
        end = time.time() + 30
        while time.time() < end:
            res = pg.last_scrub_result
            if res and (res.get("inconsistent")
                        or res.get("repaired")):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"scheduled scrub never saw the corruption: "
                f"{pg.last_scrub_result}")
        # healed on disk without any command
        end = time.time() + 30
        while time.time() < end:
            if replica.store.read(f"pg_{pgid}", "victim") == \
                    b"bitrot-target-content":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("auto repair never healed the copy")

    def test_stamps_advance_without_commands(self, sched_cluster):
        cluster = sched_cluster
        rados = cluster.client()
        rados.create_pool("auto-stamp", pg_num=4)
        io = _settle(rados, cluster, "auto-stamp")
        io.write_full("obj", b"x")
        pgid, pg = _primary_pg(cluster, io.pool_id, "obj")
        first = pg.last_scrub_stamp
        end = time.time() + 20
        while pg.last_scrub_stamp == first and time.time() < end:
            time.sleep(0.2)
        assert pg.last_scrub_stamp > first, \
            "scheduler never fired a scrub"
