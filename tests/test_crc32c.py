"""CRC32C: known vectors, seed chaining, GF(2) matrix formulation."""

import numpy as np

from ceph_tpu.ops import crc32c as c


def test_standard_vector():
    # canonical Castagnoli check value
    assert c.crc32c_std(b"123456789") == 0xE3069283


def test_raw_seed_semantics():
    # ceph-style chaining: crc(seed, a+b) == crc(crc(seed, a), b)
    seed = 0xDEADBEEF
    a, b = b"foo bar baz", b"the quick brown fox"
    assert c.crc32c_sw(c.crc32c_sw(seed, a), b) == c.crc32c_sw(seed, a + b)


def test_linear_formulation_matches():
    rng = np.random.default_rng(0)
    for n in (1, 7, 64, 100):
        data = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        for seed in (0, 1, 0xFFFFFFFF, 0x12345678):
            assert c.crc32c_linear(seed, data) == c.crc32c_sw(seed, data)


def test_combine():
    rng = np.random.default_rng(1)
    a = rng.integers(0, 256, size=37, dtype=np.uint8).tobytes()
    b = rng.integers(0, 256, size=101, dtype=np.uint8).tobytes()
    ca = c.crc32c_sw(0, a)
    cb = c.crc32c_sw(0, b)
    assert c.crc32c_combine(ca, cb, len(b)) == c.crc32c_sw(0, a + b)


def test_block_factorization():
    rng = np.random.default_rng(2)
    n, blk = 256, 32
    data = rng.integers(0, 256, size=n, dtype=np.uint8)
    fold, combine = c.block_crc_matrices(n, blk)
    bits = np.unpackbits(data, bitorder="little").reshape(n // blk, 8 * blk)
    r = (bits @ fold.T) % 2                       # (nblocks, 32)
    acc = np.zeros(32, dtype=np.uint8)
    for j in range(n // blk):
        acc ^= ((combine[j] @ r[j]) % 2).astype(np.uint8)
    assert c._bits_to_u32(acc) == c.crc32c_sw(0, data.tobytes())
