"""Foundation utils tests (config observers, throttle, counters, pools)."""

import threading
import time

import pytest

from ceph_tpu.utils.config import Config, OPTIONS
from ceph_tpu.utils.dout import DoutLogger, dump_recent, set_log_level
from ceph_tpu.utils.perf_counters import (PerfCountersBuilder,
                                          PerfCountersCollection)
from ceph_tpu.utils.throttle import Throttle
from ceph_tpu.utils.workqueue import (HeartbeatMap, ShardedThreadPool,
                                      ThreadPool)


class TestConfig:
    def test_defaults_and_typed_set(self):
        conf = Config()
        assert conf.osd_pool_default_size == 3
        conf.set_val("osd_pool_default_size", "5")
        conf.apply_changes()
        assert conf.get_val("osd_pool_default_size") == 5

    def test_unknown_option(self):
        conf = Config()
        with pytest.raises(KeyError):
            conf.set_val("no_such_option", 1)

    def test_observer_fires_on_apply(self):
        conf = Config()
        seen = []
        conf.add_observer(lambda c, keys: seen.append(sorted(keys)),
                          ["mon_lease", "mon_tick_interval"])
        conf.set_val("mon_lease", 7.5)
        conf.set_val("osd_heartbeat_grace", 30)  # not watched
        assert seen == []
        conf.apply_changes()
        assert seen == [["mon_lease"]]
        assert conf.mon_lease == 7.5

    def test_injectargs(self):
        conf = Config()
        conf.injectargs("--mon-lease 9 --osd-heartbeat-grace=25")
        assert conf.mon_lease == 9.0
        assert conf.osd_heartbeat_grace == 25.0

    def test_overrides_ctor(self):
        conf = Config({"osd_op_num_shards": 2})
        assert conf.osd_op_num_shards == 2

    def test_parse_file(self, tmp_path):
        path = tmp_path / "ceph.conf"
        path.write_text("[global]\nmon lease = 8\n"
                        "[osd]\nosd heartbeat grace = 40\n")
        conf = Config()
        conf.parse_file(str(path), section="osd")
        assert conf.mon_lease == 8.0
        assert conf.osd_heartbeat_grace == 40.0


class TestThrottle:
    def test_get_or_fail(self):
        t = Throttle("t", maximum=10)
        assert t.get_or_fail(8)
        assert not t.get_or_fail(5)
        t.put(8)
        assert t.get_or_fail(5)

    def test_blocking_get(self):
        t = Throttle("t", maximum=1)
        assert t.get(1)
        done = []

        def waiter():
            done.append(t.get(1, timeout=5))

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.05)
        assert not done
        t.put(1)
        th.join(timeout=5)
        assert done == [True]

    def test_timeout(self):
        t = Throttle("t", maximum=1)
        t.get(1)
        assert t.get(1, timeout=0.05) is False

    def test_unlimited(self):
        t = Throttle("t", maximum=0)
        assert t.get_or_fail(10 ** 9)


class TestPerfCounters:
    def test_counters(self):
        pc = (PerfCountersBuilder("osd")
              .add_u64_counter("op_w")
              .add_time_avg("op_w_latency")
              .add_histogram("op_latency_hist")
              .create_perf_counters())
        pc.inc("op_w")
        pc.inc("op_w", 4)
        pc.tinc("op_w_latency", 0.5)
        pc.tinc("op_w_latency", 1.5)
        pc.tinc("op_latency_hist", 0.005)
        d = pc.dump()
        assert d["op_w"] == 5
        assert d["op_w_latency"] == {"avgcount": 2, "sum": 2.0}
        assert sum(d["op_latency_hist"]["buckets"]) == 1
        assert pc.avg("op_w_latency") == 1.0

    def test_collection(self):
        coll = PerfCountersCollection()
        pc = PerfCountersBuilder("mon").add_u64("msgs").create_perf_counters()
        coll.add(pc)
        pc.inc("msgs")
        assert coll.dump() == {"mon": {"msgs": 1}}


class TestDout:
    def test_ring_and_levels(self, capsys):
        set_log_level("testsub", 1, gather=10)
        log = DoutLogger("testsub", "osd.0")
        log.dout(5, "gathered but not printed %d", 42)
        log.info("printed")
        import io
        buf = io.StringIO()
        dump_recent(buf, count=10)
        text = buf.getvalue()
        assert "gathered but not printed 42" in text


class TestPools:
    def test_threadpool_runs(self):
        tp = ThreadPool("t", 3)
        tp.start()
        results = []
        lock = threading.Lock()
        for i in range(20):
            tp.queue(lambda i=i: (lock.acquire(),
                                  results.append(i),
                                  lock.release()))
        tp.drain()
        tp.stop()
        assert sorted(results) == list(range(20))

    def test_sharded_ordering(self):
        pool = ShardedThreadPool("s", num_shards=4)
        pool.start()
        order: dict[str, list[int]] = {"a": [], "b": []}

        def work(key, i):
            time.sleep(0.001)
            order[key].append(i)

        for i in range(30):
            pool.queue("a", work, "a", i)
            pool.queue("b", work, "b", i)
        pool.drain()
        pool.stop()
        # per-key FIFO must hold even across shards
        assert order["a"] == list(range(30))
        assert order["b"] == list(range(30))

    def test_heartbeat_map(self):
        hb = HeartbeatMap()
        hb.reset_timeout("w1", grace=0.01)
        assert hb.is_healthy()
        time.sleep(0.03)
        assert not hb.is_healthy()
        hb.clear_timeout("w1")
        assert hb.is_healthy()


class TestManualClock:
    """Injectable time source (utils/clock.py): deterministic timers."""

    def test_now_advances_only_on_advance(self):
        from ceph_tpu.utils.clock import ManualClock
        c = ManualClock(start=100.0)
        assert c.now() == 100.0
        c.advance(2.5)
        assert c.now() == 102.5

    def test_timers_fire_in_due_order(self):
        from ceph_tpu.utils.clock import ManualClock
        c = ManualClock()
        fired = []
        c.timer(2.0, lambda: fired.append("b"))
        c.timer(1.0, lambda: fired.append("a"))
        c.timer(5.0, lambda: fired.append("never"))
        c.advance(3.0)
        assert fired == ["a", "b"]

    def test_cancelled_timer_does_not_fire(self):
        from ceph_tpu.utils.clock import ManualClock
        c = ManualClock()
        fired = []
        h = c.timer(1.0, lambda: fired.append("x"))
        h.cancel()
        c.advance(2.0)
        assert fired == []

    def test_rescheduling_callback_chains_within_window(self):
        from ceph_tpu.utils.clock import ManualClock
        c = ManualClock()
        fired = []

        def tick():
            fired.append(c.now())
            if len(fired) < 5:
                c.timer(1.0, tick)

        c.timer(1.0, tick)
        c.advance(10.0)
        assert len(fired) == 5
        assert fired == [1000001.0 + i for i in range(5)]

    def test_system_clock_timer_fires(self):
        from ceph_tpu.utils.clock import SystemClock
        ev = threading.Event()
        SystemClock().timer(0.01, ev.set)
        assert ev.wait(2.0)


class TestAsyncReserver:
    def test_bounded_grants_fifo_queue(self):
        from ceph_tpu.utils.reserver import AsyncReserver
        r = AsyncReserver(2)
        order = []
        releases = []
        for i in range(5):
            r.request(lambda rel, i=i: (order.append(i),
                                        releases.append(rel)))
        assert order == [0, 1]          # two slots granted
        assert r.queued == 3
        releases[0]()                   # frees -> grants 2
        assert order == [0, 1, 2]
        releases[1]()
        releases[2]()
        assert order == [0, 1, 2, 3, 4]
        # double release must not over-grant
        releases[0]()
        releases[0]()
        for rel in releases[3:]:
            rel()
        assert r.available == 2
        assert r.queued == 0

    def test_exception_in_grant_releases_slot(self):
        from ceph_tpu.utils.reserver import AsyncReserver
        r = AsyncReserver(1)
        with pytest.raises(RuntimeError):
            r.request(lambda rel: (_ for _ in ()).throw(
                RuntimeError("boom")))
        assert r.available == 1
        ran = []
        r.request(lambda rel: (ran.append(1), rel()))
        assert ran == [1]
