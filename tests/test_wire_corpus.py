"""Wire/disk format non-regression corpus (the reference's
ceph-object-corpus + test/encoding/readable.sh analog).

One representative instance of every registered message type and denc
struct is encoded; the CRC32C of each encoding is pinned in
tests/data/wire_corpus.json.  A refactor that changes any wire or disk
byte fails here BEFORE it can strand persisted state or break rolling
upgrades between builds.

Regenerate (deliberate format changes only — bump DENC_VERSION and add
an upgrade path when the change touches persisted structs):
    python tests/test_wire_corpus.py --create
"""

import json
import os
import sys

CORPUS_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "wire_corpus.json")


def build_samples() -> dict:
    """name -> bytes for every wire/disk format we promise stability."""
    from ceph_tpu.crush.map import CrushMap
    from ceph_tpu.mon import messages as monm
    from ceph_tpu.mon.monmap import MonMap
    from ceph_tpu.osd import messages as osdm
    from ceph_tpu.osd.osdmap import (OSDMap, OSDMapIncremental, OsdInfo,
                                     PgId, Pool)
    from ceph_tpu.fs import messages as fsm
    from ceph_tpu.utils import denc

    samples: dict[str, bytes] = {}

    def add(name: str, obj) -> None:
        samples[name] = denc.dumps(obj)

    # -- denc structs ------------------------------------------------------
    add("PgId", PgId(3, 7))
    add("Pool", Pool(2, "p", size=3, pg_num=16, snap_seq=5,
                     removed_snaps=[2, 3]))
    add("OsdInfo", OsdInfo(up=True, in_cluster=True, weight=0.5,
                           addr=("127.0.0.1", 6800)))
    inc = OSDMapIncremental(epoch=9)
    inc.new_up[1] = ("127.0.0.1", 6801)
    inc.new_down.append(2)
    inc.new_pool_snap_seq[0] = 4
    inc.new_mgr = ("x", ("127.0.0.1", 6900))
    add("OSDMapIncremental", inc)
    m = OSDMap()
    m.fsid = "corpus-fsid"
    m.apply_incremental(OSDMapIncremental(epoch=1))
    add("OSDMap", m)
    mm = MonMap(fsid="corpus-fsid")
    mm.add("a", ("127.0.0.1", 6789))
    add("MonMap", mm)
    add("CrushMap", CrushMap.build_flat(6, hosts=2))

    # -- messages (header + payload via Message.encode) --------------------
    def addmsg(msg) -> None:
        samples[type(msg).__name__] = msg.encode(seq=7)

    addmsg(monm.MMonElection(op="propose", epoch=3, rank=0, quorum=[]))
    addmsg(monm.MMonPaxos(op="begin", pn=101, version=5, value=b"v",
                          last_committed=4))
    addmsg(monm.MMonSubscribe(what={"osdmap": 0}))
    addmsg(monm.MMonCommand(tid=1, cmd={"prefix": "status"}))
    addmsg(monm.MMonCommandAck(tid=1, retval=0, out="ok", data=b""))
    addmsg(monm.MOSDBoot(osd_id=0, addr=("127.0.0.1", 6800)))
    addmsg(monm.MOSDFailure(target_osd=1, failed_for=12.5))
    addmsg(monm.MOSDMapMsg(full=None, incrementals=[b"i"], epoch=2))
    addmsg(monm.MMgrBeacon(name="x", addr=("127.0.0.1", 6900)))
    addmsg(monm.MMgrReport(entity="osd.0", counters={"osd": {"op": 1}},
                           epoch=2))
    addmsg(monm.MMDSBeacon(name="a", addr=("127.0.0.1", 6901)))
    addmsg(osdm.MOSDOp(tid=4, pgid="0.1", oid="o",
                       ops=[("writefull", b"x")], epoch=2, snapc=None,
                       snapid=None))
    addmsg(osdm.MOSDOpReply(tid=4, result=0, outdata=[], version=(1, 1),
                            epoch=2))
    addmsg(osdm.MOSDRepOp(reqid=("c", 4), pgid="0.1", ops=[],
                          log={"ev": (1, 1), "oid": "o", "op": "modify",
                               "prior": None, "rollback": None,
                               "shard": None}, epoch=2))
    addmsg(osdm.MOSDRepOpReply(reqid=("c", 4), pgid="0.1", result=0))
    addmsg(osdm.MOSDECSubOpWrite(reqid=("c", 5), pgid="0.1", shard=1,
                                 ops=[], log={"ev": (1, 2), "oid": "o",
                                              "op": "modify",
                                              "prior": None,
                                              "rollback": {"type":
                                                           "stash"},
                                              "shard": 1},
                                 roll_forward_to=(1, 1), epoch=2))
    addmsg(osdm.MOSDECSubOpWriteReply(reqid=("c", 5), pgid="0.1",
                                      shard=1, result=0))
    addmsg(osdm.MOSDECSubOpRead(reqid=None, pgid="0.1", shard=1,
                                oid="o", off=0, length=0))
    addmsg(osdm.MOSDECSubOpReadReply(reqid=None, pgid="0.1", shard=1,
                                     result=0, data=b"d", hinfo=None))
    addmsg(osdm.MOSDPing(op="ping", stamp=1.0, epoch=2, pgid="0.0"))
    addmsg(osdm.MWatchNotify(oid="o", pgid="0.1", notify_id=1, cookie=2,
                             payload=b"p"))
    addmsg(osdm.MWatchNotifyAck(oid="o", pgid="0.1", notify_id=1,
                                cookie=2, reply=b"r"))
    addmsg(fsm.MClientRequest(tid=1, op="mkdir", path="/d", size=None,
                              new_path=None))
    addmsg(fsm.MClientReply(tid=1, result=0, data={"ino": 2}))
    return samples


def build_corpus() -> dict:
    from ceph_tpu.ops import crc32c as crc_mod
    return {name: {"len": len(blob),
                   "crc": crc_mod.crc32c(0, blob)}
            for name, blob in sorted(build_samples().items())}


def test_wire_formats_stable():
    assert os.path.exists(CORPUS_PATH), \
        "corpus missing — run: python tests/test_wire_corpus.py --create"
    with open(CORPUS_PATH) as f:
        archived = json.load(f)
    current = build_corpus()
    missing = set(archived) - set(current)
    assert not missing, f"formats disappeared: {sorted(missing)}"
    for name in sorted(archived):
        assert current[name] == archived[name], \
            f"WIRE FORMAT CHANGED: {name} (archived {archived[name]} " \
            f"vs {current[name]}) — bump DENC_VERSION + upgrade path " \
            f"and regenerate deliberately"


def test_all_samples_roundtrip():
    """Every sample decodes back through the registry."""
    from ceph_tpu.msg.message import Message
    from ceph_tpu.utils import denc
    for name, blob in build_samples().items():
        if blob[:4] == b"CTM1":            # message frames
            type_id, plen, seq = Message.parse_header(
                blob[:Message.header_size()])
            msg = Message.decode(type_id, seq,
                                 blob[Message.header_size():])
            assert type(msg).__name__ == name
        else:
            denc.loads(blob)


if __name__ == "__main__":
    if "--create" in sys.argv:
        os.makedirs(os.path.dirname(CORPUS_PATH), exist_ok=True)
        with open(CORPUS_PATH, "w") as f:
            json.dump(build_corpus(), f, indent=1, sort_keys=True)
        print(f"wrote {CORPUS_PATH} ({len(build_corpus())} formats)")
    else:
        test_wire_formats_stable()
        print("wire corpus OK")
