"""Mgr plane, compressor framework, cls_kvstore."""

import time

import pytest

from ceph_tpu import compressor
from ceph_tpu.client import RadosError
from ceph_tpu.utils import denc
from ceph_tpu.vstart import MiniCluster


class TestCompressor:
    @pytest.mark.parametrize("alg", compressor.algorithms())
    def test_roundtrip(self, alg):
        c = compressor.create(alg)
        data = b"squeeze me " * 1000
        blob = c.compress(data)
        assert len(blob) < len(data)
        assert c.decompress(blob) == data
        assert compressor.decompress_any(blob) == data

    def test_wrong_algorithm_rejected(self):
        blob = compressor.create("zlib").compress(b"x")
        with pytest.raises(compressor.CompressorError):
            compressor.create("bz2").decompress(blob)

    def test_corrupt_blob_rejected(self):
        blob = bytearray(compressor.create("zlib").compress(b"payload"))
        blob[-1] ^= 0xFF
        with pytest.raises(compressor.CompressorError):
            compressor.decompress_any(bytes(blob))

    def test_unknown_name(self):
        with pytest.raises(compressor.CompressorError):
            compressor.create("snappy")

    def test_filestore_snapshot_compressed(self, tmp_path):
        from ceph_tpu.store import create as store_create
        from ceph_tpu.store.objectstore import Transaction
        path = str(tmp_path / "osd")
        st = store_create("filestore", path)
        st.mkfs()
        st.mount()
        st.apply_transaction(Transaction().create_collection("c")
                             .touch("c", "o").write("c", "o", 0,
                                                    b"z" * 10000))
        st._checkpoint()
        st.umount()
        raw = open(f"{path}/snapshot", "rb").read()
        # CSN2: magic + u32 crc32c(body) + compressed body — much
        # smaller than the 10k of raw object data it covers
        assert raw.startswith(b"CSN2")
        assert len(raw) < 10000
        from ceph_tpu.compressor import decompress_any
        from ceph_tpu.ops.crc32c import crc32c
        import struct
        (want,) = struct.unpack_from("<I", raw, 4)
        assert crc32c(0, raw[8:]) == want
        decompress_any(raw[8:])      # body is a valid compressed blob
        # remount replays the compressed snapshot
        st2 = store_create("filestore", path)
        st2.mount()
        assert st2.read("c", "o") == b"z" * 10000
        st2.umount()


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3).start()
    c.start_mgr("x")
    yield c
    c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    rados = cluster.client()
    rados.create_pool("mgrpool", pg_num=4)
    ctx = rados.open_ioctx("mgrpool")
    end = time.time() + 20
    while True:
        try:
            ctx.write_full("warm", b"w")
            break
        except RadosError:
            if time.time() > end:
                raise
            time.sleep(0.3)
    return ctx


class TestMgr:
    def test_mgr_address_in_map(self, cluster, io):
        end = time.time() + 20
        while time.time() < end:
            m = cluster.leader().osdmon.osdmap
            if getattr(m, "mgr_addr", None):
                break
            cluster.tick(0.25)
        assert m.mgr_name == "x" and m.mgr_addr

    def test_daemons_report_counters(self, cluster, io):
        for i in range(5):
            io.write_full(f"m{i}", b"metric")
        mgr = cluster.mgrs[0]
        end = time.time() + 30
        while time.time() < end and len(mgr.daemon_state) < 3:
            cluster.tick(0.5)
        assert len(mgr.daemon_state) >= 3
        state = mgr.dump()
        assert any(s["counters"].get("osd", {}).get("op", 0) > 0
                   for s in state.values())

    def test_module_aggregation(self, cluster, io):
        mgr = cluster.mgrs[0]
        end = time.time() + 20
        while time.time() < end and \
                mgr.run_module("io_totals")["op"] == 0:
            cluster.tick(0.5)
        totals = mgr.run_module("io_totals")
        assert totals["op"] > 0 and totals["reporters"] >= 3
        assert "error" in mgr.run_module("nope")

    def test_mgr_status_via_asok(self, cluster, io):
        mgr = cluster.mgrs[0]
        st = mgr.asok.execute("status")
        assert st["entity"] == "mgr.x"


class TestClsKvstore:
    def test_put_get_rm_cas(self, cluster, io):
        io.execute("kv", "kvstore", "put",
                   denc.dumps({"kv": {"a": b"1", "b": b"2"}}))
        got = denc.loads(io.execute("kv", "kvstore", "get",
                                    denc.dumps(["a", "b"])))
        assert got == {"a": b"1", "b": b"2"}
        with pytest.raises(RadosError) as ei:
            io.execute("kv", "kvstore", "put",
                       denc.dumps({"kv": {"a": b"X"},
                                   "if_absent": True}))
        assert ei.value.errno == 17
        io.execute("kv", "kvstore", "cas",
                   denc.dumps({"key": "a", "expect": b"1",
                               "value": b"10"}))
        with pytest.raises(RadosError) as ei:
            io.execute("kv", "kvstore", "cas",
                       denc.dumps({"key": "a", "expect": b"1",
                                   "value": b"20"}))
        assert ei.value.errno == 125
        io.execute("kv", "kvstore", "rm", denc.dumps(["b"]))
        got = denc.loads(io.execute("kv", "kvstore", "get",
                                    denc.dumps([])))
        assert got == {"a": b"10"}
