"""rbd-mirror daemon: continuous journal replay between pools
(tools/rbd_mirror/ data path over the journal library)."""

import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.rbd import RBD, Image
from ceph_tpu.rbd.mirror import RbdMirror
from ceph_tpu.utils.config import Config
from ceph_tpu.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    conf = Config({
        "mon_tick_interval": 0.5,
        "osd_heartbeat_interval": 0.5,
        "osd_heartbeat_grace": 8.0,
        "mon_osd_min_down_reporters": 2,
    })
    c = MiniCluster(num_mons=1, num_osds=3, conf=conf).start()
    yield c
    c.stop()


@pytest.fixture(scope="module")
def pools(cluster):
    rados = cluster.client()
    for pool in ("mir-src", "mir-dst"):
        rados.create_pool(pool, pg_num=4)
        io = rados.open_ioctx(pool)
        end = time.time() + 60
        while True:
            try:
                io.write_full("settle", b"s")
                break
            except RadosError:
                if time.time() > end:
                    raise
                cluster.tick(0.3)
    return rados.open_ioctx("mir-src"), rados.open_ioctx("mir-dst")


class TestRbdMirror:
    def test_continuous_replication(self, cluster, pools):
        src_io, dst_io = pools
        rados = cluster.client()
        RBD(src_io).create("vm", 1 << 20, order=16, journaling=True)
        with Image(src_io, "vm") as img:
            img.write(0, b"primary-image-bytes")
            img.write(70_000, b"spanning")
        mirror = RbdMirror(rados, rados, "mir-src", "mir-dst",
                           interval=0.2)
        applied = mirror.run_once()
        assert applied.get("vm", 0) >= 2
        with Image(dst_io, "vm") as twin:
            assert twin.read(0, 19) == b"primary-image-bytes"
            assert twin.read(70_000, 8) == b"spanning"
        # incremental: new writes flow on the next pass
        with Image(src_io, "vm") as img:
            img.write(500, b"delta-1")
            img.resize(1 << 21)
        assert mirror.run_once().get("vm") == 2
        with Image(dst_io, "vm") as twin:
            assert twin.read(500, 7) == b"delta-1"
            assert twin.size() == 1 << 21
        # idempotent when idle
        assert mirror.run_once().get("vm") == 0

    def test_daemon_loop_and_new_image_discovery(self, cluster, pools):
        src_io, dst_io = pools
        rados = cluster.client()
        mirror = RbdMirror(rados, rados, "mir-src", "mir-dst",
                           interval=0.1).start()
        try:
            RBD(src_io).create("late", 1 << 20, order=16,
                               journaling=True)
            with Image(src_io, "late") as img:
                img.write(0, b"discovered-late")
            end = time.time() + 30
            while True:
                try:
                    with Image(dst_io, "late") as twin:
                        if twin.read(0, 15) == b"discovered-late":
                            break
                except RadosError:
                    pass
                if time.time() > end:
                    raise AssertionError("mirror never replicated")
                time.sleep(0.2)
        finally:
            mirror.stop()

    def test_unjournaled_images_ignored(self, cluster, pools):
        src_io, dst_io = pools
        rados = cluster.client()
        RBD(src_io).create("plain", 1 << 20, order=16)
        with Image(src_io, "plain") as img:
            img.write(0, b"not-mirrored")
        mirror = RbdMirror(rados, rados, "mir-src", "mir-dst",
                           interval=0.2)
        out = mirror.run_once()
        assert "plain" not in out
        assert "plain" not in RBD(dst_io).list()

    def test_snapshots_replicate(self, cluster, pools):
        src_io, dst_io = pools
        rados = cluster.client()
        RBD(src_io).create("snapm", 1 << 20, order=16, journaling=True)
        with Image(src_io, "snapm") as img:
            img.write(0, b"before")
            img.snap_create("s1")
            img.write(0, b"after!")
        RbdMirror(rados, rados, "mir-src", "mir-dst",
                  interval=0.2).run_once()
        with Image(dst_io, "snapm") as twin:
            assert twin.read(0, 6) == b"after!"
        with Image(dst_io, "snapm", snapshot="s1") as snap:
            assert snap.read(0, 6) == b"before"

    def test_discard_beyond_twin_size_does_not_wedge(self, cluster,
                                                     pools):
        """A replayed discard past the twin's creation size must grow
        the twin first, not wedge replay forever on RbdError(22):
        source history = write, discard@2M, resize DOWN to 1M — the
        twin is created at the CURRENT (1M) size, so the discard
        event lands beyond it (rbd/mirror.py + replay_journal)."""
        src_io, dst_io = pools
        rados = cluster.client()
        RBD(src_io).create("disc", 4 << 20, order=16, journaling=True)
        with Image(src_io, "disc") as img:
            img.write(0, b"live-head-bytes")
            img.write((2 << 20) - 8, b"x" * 16)
            img.discard(2 << 20, 1 << 16)
            img.resize(1 << 20)
        mirror = RbdMirror(rados, rados, "mir-src", "mir-dst",
                           interval=0.2)
        applied = mirror.run_once()
        assert applied.get("disc", 0) >= 4
        with Image(dst_io, "disc") as twin:
            assert twin.size() == 1 << 20
            assert twin.read(0, 15) == b"live-head-bytes"
        # replay is clean on the next pass (nothing re-fails)
        assert mirror.run_once().get("disc") == 0

    def test_promote_demote_failover_and_back(self, cluster, pools):
        """Two-way failover (ImageReplayer promote/demote): demote at
        the source, drain, promote the twin, write there, replicate
        back with a reverse daemon, then fail back — data converges
        and a demoted image refuses client writes."""
        src_io, dst_io = pools
        rados = cluster.client()
        RBD(src_io).create("fo", 1 << 20, order=16, journaling=True)
        with Image(src_io, "fo") as img:
            img.write(0, b"written-at-A")
        fwd = RbdMirror(rados, rados, "mir-src", "mir-dst",
                        interval=0.2)
        fwd.run_once()
        # failover: demote A, drain, promote B
        with Image(src_io, "fo") as img:
            img.mirror_demote()
            assert not img.is_primary
        with Image(src_io, "fo") as img:
            with pytest.raises(Exception) as ei:
                img.write(0, b"refused")
            assert getattr(ei.value, "errno", None) == 30
        fwd.run_once()                   # drain (no-op here)
        with Image(dst_io, "fo") as twin:
            twin.mirror_promote()
            assert twin.is_primary
        with Image(dst_io, "fo") as twin:
            twin.write(0, b"written-at-B")
            twin.write(100, b"more-B")
        # reverse replication lands B's new events on the demoted A
        rev = RbdMirror(rados, rados, "mir-dst", "mir-src",
                        interval=0.2)
        applied = rev.run_once()
        assert applied.get("fo", 0) >= 2
        with Image(src_io, "fo", _mirror_replay=True) as a:
            assert a.read(0, 12) == b"written-at-B"
            assert a.read(100, 6) == b"more-B"
        # fail back: demote B, drain, promote A, write at A, forward
        # daemon replicates to B again
        with Image(dst_io, "fo") as twin:
            twin.mirror_demote()
        rev.run_once()                   # drain
        with Image(src_io, "fo") as a:
            a.mirror_promote()
        with Image(src_io, "fo") as a:
            a.write(200, b"back-home")
        fwd.run_once()
        with Image(dst_io, "fo", _mirror_replay=True) as twin:
            assert twin.read(200, 9) == b"back-home"
            assert twin.read(0, 12) == b"written-at-B"
