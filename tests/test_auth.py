"""cephx-lite auth: keyring, handshake accept/reject, signing, cluster.

The reference's model (auth/cephx/CephxProtocol.h challenge-response,
CephxSessionHandler per-message signing, KeyRing files) at the session
layer: possession of the keyring secret gates the messenger handshake
and every frame carries an HMAC signature.
"""

import threading
import time

import pytest

from ceph_tpu.auth import KeyRing, cephx, generate_key
from ceph_tpu.msg import Message, Messenger, Policy
from ceph_tpu.msg.message import register_message
from ceph_tpu.utils.config import Config


@register_message
class MAuthTest(Message):
    TYPE = 990


class Collector:
    def __init__(self):
        self.got = []
        self.event = threading.Event()

    def ms_dispatch(self, conn, msg):
        if isinstance(msg, MAuthTest):
            self.got.append(msg.payload)
            self.event.set()
            return True
        return False

    def ms_handle_reset(self, conn):
        pass


def mk_messenger(name, key=None, mode=None):
    conf = Config({"ms_connect_timeout": 2.0, "ms_max_backoff": 0.5})
    if mode:
        conf.set_val("auth_cluster_required", mode)
    if key:
        conf.set_val("key", key)
    conf.apply_changes()
    m = Messenger(name, conf=conf)
    m.bind(("127.0.0.1", 0))
    return m


class TestKeyRing:
    def test_roundtrip_and_wildcard(self, tmp_path):
        ring = KeyRing()
        k1, k2 = generate_key(), generate_key()
        ring.add("client.admin", k1)
        ring.add("*", k2)
        path = str(tmp_path / "keyring")
        ring.save(path)
        loaded = KeyRing.from_file(path)
        assert loaded.get("client.admin") == ring.get("client.admin")
        assert loaded.get("osd.7") == ring.get("*")   # wildcard fallback

    def test_sign_check(self):
        skey = b"s" * 32
        frame = b"header+payload"
        sig = cephx.sign(skey, frame)
        assert cephx.check(skey, frame, sig)
        assert not cephx.check(skey, frame + b"x", sig)
        assert not cephx.check(b"t" * 32, frame, sig)


class TestMessengerAuth:
    def _deliver(self, sender, receiver, payload=b"hi", timeout=5.0):
        col = Collector()
        receiver.add_dispatcher_tail(col)
        receiver.start()
        sender.start()
        try:
            sender.send_message(MAuthTest(payload=payload),
                                receiver.name, receiver.addr)
            return col.event.wait(timeout)
        finally:
            sender.shutdown()
            receiver.shutdown()

    def test_same_key_delivers(self):
        key = generate_key()
        a = mk_messenger("client.a", key, "cephx")
        b = mk_messenger("osd.0", key, "cephx")
        assert self._deliver(a, b)

    def test_unauthenticated_peer_rejected(self):
        key = generate_key()
        a = mk_messenger("client.rogue")            # auth=none
        b = mk_messenger("osd.0", key, "cephx")
        assert not self._deliver(a, b, timeout=2.0)

    def test_wrong_key_rejected(self):
        a = mk_messenger("client.a", generate_key(), "cephx")
        b = mk_messenger("osd.0", generate_key(), "cephx")
        assert not self._deliver(a, b, timeout=2.0)

    def test_missing_key_raises(self):
        with pytest.raises(ValueError, match="no key"):
            mk_messenger("osd.0", None, "cephx")


class TestClusterWithAuth:
    def test_cluster_io_with_cephx(self):
        from ceph_tpu.client import RadosError
        from ceph_tpu.vstart import MiniCluster
        key = generate_key()
        conf = Config({
            "mon_tick_interval": 0.5,
            "osd_heartbeat_interval": 0.5,
            "osd_heartbeat_grace": 8.0,
            "mon_osd_min_down_reporters": 2,
            "mon_osd_down_out_interval": 5.0,
            "auth_cluster_required": "cephx",
            "key": key,
        })
        c = MiniCluster(num_mons=3, num_osds=3, conf=conf).start()
        try:
            r = c.client()
            r.create_pool("authrep", pg_num=4)
            io = r.open_ioctx("authrep")
            end = time.time() + 20
            while True:
                try:
                    io.write_full("secure", b"signed payload")
                    break
                except RadosError:
                    if time.time() > end:
                        raise
                    time.sleep(0.3)
            assert io.read("secure") == b"signed payload"
        finally:
            c.stop()
