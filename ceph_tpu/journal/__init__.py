"""Client-side distributed journal (journal/ analog: Journaler,
JournalRecorder, JournalPlayer, ObjectRecorder/Player, trimmer).

The reference's journal library — the substrate under rbd-mirror —
records entries into a ring of RADOS objects ("splay" objects) with
commit positions tracked in a metadata object's omap, so a remote
player can tail the journal and a trimmer can drop fully-committed
object sets.  Reduced here to the load-bearing core:

  * metadata object <prefix>.meta: omap holds the static layout
    (splay_width, entries_per_object) and each client's commit
    position;
  * entry objects <prefix>.<objnum>: POSITION-TAGGED length-prefixed
    records — concurrent appenders may interleave arrival order
    within an object, so every record carries its position and the
    player indexes by it rather than by arrival order;
  * position allocation is a compare-and-swap through the kvstore
    object class (in-OSD serialization), so two recorders can never
    claim the same position;
  * Journaler.append / replay(from_pos) / commit(pos) / trim().

Entry objects are slot-bounded (entries_per_object records each), not
byte-bounded: trim granularity is a whole splay set.
"""

from __future__ import annotations

import struct

from ..client.rados import RadosError
from ..utils import denc

_REC = struct.Struct("<QI")     # position, payload length


class JournalError(RadosError):
    pass


def meta_oid(prefix: str) -> str:
    return f"{prefix}.meta"


def entry_oid(prefix: str, objnum: int) -> str:
    return f"{prefix}.{objnum:08x}"


class Journaler:
    """Recorder + player + trimmer over one journal (Journaler.cc)."""

    def __init__(self, ioctx, prefix: str, client_id: str = "main"):
        self.io = ioctx
        self.prefix = prefix
        self.client_id = client_id
        self.meta: dict | None = None

    # -- lifecycle ---------------------------------------------------------

    def create(self, splay_width: int = 4,
               entries_per_object: int = 256) -> None:
        if splay_width < 1 or entries_per_object < 1:
            raise JournalError(22, "bad layout")
        try:
            self.io.stat(meta_oid(self.prefix))
            raise JournalError(17, f"journal {self.prefix} exists")
        except RadosError as e:
            if e.errno != 2:
                raise
        self.io.set_omap(meta_oid(self.prefix), {
            "layout": denc.dumps({
                "splay_width": splay_width,
                "entries_per_object": entries_per_object}),
        })
        self.meta = {"splay_width": splay_width,
                     "entries_per_object": entries_per_object}

    def open(self) -> "Journaler":
        try:
            omap = self.io.get_omap(meta_oid(self.prefix))
        except RadosError as e:
            raise JournalError(e.errno,
                               f"no journal {self.prefix}") from e
        blob = omap.get("layout")
        if blob is None:
            raise JournalError(2, f"no journal {self.prefix}")
        self.meta = denc.loads(blob)
        return self

    def register_client(self, client_id: str) -> None:
        """Start tracking a consumer; a RE-registration is a no-op —
        resetting an existing commit position to 0 would stall trim
        and make the client replay past trimmed sets."""
        try:
            self.io.execute(meta_oid(self.prefix), "kvstore", "cas",
                            denc.dumps({"key": f"commit.{client_id}",
                                        "expect": None,
                                        "value": denc.dumps(0)}))
        except RadosError as e:
            if e.errno != 125:      # ECANCELED = already registered
                raise

    def remove(self) -> None:
        if self.meta is None:
            self.open()
        total = self._entry_count()
        width = self.meta["splay_width"]
        per_obj = self.meta["entries_per_object"]
        sets = total // (width * per_obj) + 2
        for objnum in range(sets * width):
            try:
                self.io.remove_object(entry_oid(self.prefix, objnum))
            except RadosError:
                pass
        self.io.remove_object(meta_oid(self.prefix))

    # -- positions ---------------------------------------------------------

    def _entry_count(self) -> int:
        omap = self.io.get_omap(meta_oid(self.prefix))
        blob = omap.get("entries")
        return denc.loads(blob) if blob else 0

    def _commit_positions(self) -> dict[str, int]:
        omap = self.io.get_omap(meta_oid(self.prefix))
        out = {}
        for key, blob in omap.items():
            if key.startswith("commit."):
                out[key[len("commit."):]] = denc.loads(blob)
        return out

    def commit(self, position: int) -> None:
        """Entries below `position` are consumed by THIS client."""
        self.io.set_omap(meta_oid(self.prefix),
                         {f"commit.{self.client_id}":
                          denc.dumps(int(position))})

    def _objnum_for(self, entry_no: int) -> int:
        width = self.meta["splay_width"]
        per_obj = self.meta["entries_per_object"]
        setno = entry_no // (width * per_obj)
        return setno * width + entry_no % width

    # -- recorder ----------------------------------------------------------

    def _alloc_position(self) -> int:
        """CAS the entries counter in-OSD: concurrent recorders never
        claim the same position (JournalMetadata allocation)."""
        while True:
            omap = self.io.get_omap(meta_oid(self.prefix))
            cur = omap.get("entries")
            n = denc.loads(cur) if cur else 0
            try:
                self.io.execute(
                    meta_oid(self.prefix), "kvstore", "cas",
                    denc.dumps({"key": "entries", "expect": cur,
                                "value": denc.dumps(n + 1)}))
                return n
            except RadosError as e:
                if e.errno != 125:      # ECANCELED = lost the race
                    raise

    def append(self, entry: bytes) -> int:
        """Record one entry; returns its position (entry number)."""
        if self.meta is None:
            self.open()
        entry = bytes(entry)
        n = self._alloc_position()
        objnum = self._objnum_for(n)
        self.io.append(entry_oid(self.prefix, objnum),
                       _REC.pack(n, len(entry)) + entry)
        return n

    # -- player ------------------------------------------------------------

    def replay(self, from_position: int = 0):
        """Yield (position, entry_bytes) from from_position onward.

        Entry objects are read per splay SET and evicted once the
        cursor leaves the set — memory is bounded by one set, not the
        journal (JournalPlayer's prefetch window).
        """
        if self.meta is None:
            self.open()
        total = self._entry_count()
        width = self.meta["splay_width"]
        per_obj = self.meta["entries_per_object"]
        cache: dict[int, dict[int, bytes]] = {}
        cur_set = None
        for n in range(from_position, total):
            setno = n // (width * per_obj)
            if setno != cur_set:
                cache.clear()              # evict the finished set
                cur_set = setno
            objnum = self._objnum_for(n)
            if objnum not in cache:
                cache[objnum] = self._read_entries(objnum)
            if n not in cache[objnum]:
                raise JournalError(5, f"journal truncated at {n}")
            yield n, cache[objnum][n]

    def _read_entries(self, objnum: int) -> dict[int, bytes]:
        try:
            blob = self.io.read(entry_oid(self.prefix, objnum))
        except RadosError as e:
            if e.errno == 2:
                return {}
            raise
        out: dict[int, bytes] = {}
        pos = 0
        while pos + _REC.size <= len(blob):
            position, ln = _REC.unpack_from(blob, pos)
            pos += _REC.size
            if pos + ln > len(blob):
                break                  # torn tail
            out[position] = blob[pos: pos + ln]
            pos += ln
        return out

    # -- trimmer -----------------------------------------------------------

    def trim(self) -> int:
        """Drop entry objects wholly below every client's commit
        position (JournalTrimmer); a persisted floor marker keeps each
        call O(newly dead sets), not O(history)."""
        if self.meta is None:
            self.open()
        positions = self._commit_positions()
        if not positions:
            return 0
        floor = min(positions.values())
        width = self.meta["splay_width"]
        per_obj = self.meta["entries_per_object"]
        dead_sets = floor // (width * per_obj)
        omap = self.io.get_omap(meta_oid(self.prefix))
        start = denc.loads(omap["trimmed_sets"]) \
            if "trimmed_sets" in omap else 0
        removed = 0
        for setno in range(start, dead_sets):
            for i in range(width):
                try:
                    self.io.remove_object(
                        entry_oid(self.prefix, setno * width + i))
                    removed += 1
                except RadosError:
                    pass
        if dead_sets > start:
            self.io.set_omap(meta_oid(self.prefix),
                             {"trimmed_sets": denc.dumps(dead_sets)})
        return removed
