"""Standalone daemon entry points (ceph_mon.cc / ceph_osd.cc analogs).

    python -m ceph_tpu.daemons mon --name a -c ceph.conf
    python -m ceph_tpu.daemons osd --id 0 -c ceph.conf

ceph.conf is the usual ini (utils/config.py parse_file) plus cluster
topology the binaries need to boot:

    [global]
    fsid = ...
    mon host = 127.0.0.1:6789,127.0.0.1:6790,127.0.0.1:6791
    objectstore = filestore
    osd data = /var/lib/ceph-tpu/osd-$id

Monitors are named a, b, c... in mon-host order (the reference derives
rank from the monmap the same way).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from .mon.monmap import MonMap
from .utils.config import Config


DEFAULT_MON_PORT = 6789


def parse_mon_host(spec: str) -> list[tuple[str, int]]:
    """host[:port] list; portless entries get the default mon port,
    [v6]:port bracket syntax supported."""
    addrs = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith("["):              # [v6addr]:port
            host, _, rest = part[1:].partition("]")
            port = rest.lstrip(":") or str(DEFAULT_MON_PORT)
        elif part.count(":") == 1:
            host, _, port = part.partition(":")
        else:                                  # portless, or bare v6
            host, port = part, str(DEFAULT_MON_PORT)
        try:
            addrs.append((host or "127.0.0.1", int(port)))
        except ValueError:
            raise SystemExit(f"bad mon_host entry {part!r}")
    return addrs


def load_conf(path: str | None, section: str | None = None) -> Config:
    conf = Config()
    if path:
        conf.parse_file(path, section)
    return conf


def monmap_from_conf(conf: Config) -> MonMap:
    spec = str(conf.mon_host)
    if not spec:
        raise SystemExit("conf has no mon_host")
    mm = MonMap(fsid=str(conf.fsid) or "00000000-0000-0000-0000-000000000000")
    for i, addr in enumerate(parse_mon_host(spec)):
        mm.add(chr(ord("a") + i), addr)
    return mm


def _run_forever(daemon) -> None:
    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    try:
        stop.wait()
    finally:
        daemon.shutdown()


def main_mon(args) -> None:
    conf = load_conf(args.conf, f"mon.{args.name}")
    monmap = monmap_from_conf(conf)
    from .mon.monitor import Monitor
    mon = Monitor(args.name, monmap, conf=conf,
                  store_path=args.store_path or "")
    mon.start()
    print(f"mon.{args.name} up at {monmap.addr_of(args.name)}",
          flush=True)
    _run_forever(mon)


def main_osd(args) -> None:
    conf = load_conf(args.conf, f"osd.{args.id}")
    monmap = monmap_from_conf(conf)
    from .osd.daemon import OSDDaemon
    store_kind = args.store or str(conf.objectstore)
    osd = OSDDaemon(int(args.id), monmap, conf=conf,
                    store_kind=store_kind,
                    store_path=args.store_path or "")
    osd.start()
    print(f"osd.{args.id} up at {osd.msgr.addr}", flush=True)
    _run_forever(osd)


def main_mgr(args) -> None:
    conf = load_conf(args.conf, f"mgr.{args.name}")
    monmap = monmap_from_conf(conf)
    from .mgr import MgrDaemon
    mgr = MgrDaemon(args.name, monmap, conf=conf)
    mgr.start()
    print(f"mgr.{args.name} up at {mgr.msgr.addr}", flush=True)
    _run_forever(mgr)


def main_mds(args) -> None:
    conf = load_conf(args.conf, f"mds.{args.name}")
    monmap = monmap_from_conf(conf)
    from .fs.mds import MDSDaemon
    mds = MDSDaemon(args.name, monmap, conf=conf)
    mds.start()
    print(f"mds.{args.name} up at {mds.msgr.addr}", flush=True)
    _run_forever(mds)


def main_rgw(args) -> None:
    conf = load_conf(args.conf, "client.rgw")
    monmap = monmap_from_conf(conf)
    from .client import Rados
    from .rgw import RGWDaemon
    r = Rados(monmap, "client.rgw", conf=conf)
    r.connect()
    rgw = RGWDaemon(r, port=args.port, access_key=args.access_key,
                    secret_key=args.secret_key)
    rgw.start()
    print(f"rgw up at http://127.0.0.1:{rgw.port}", flush=True)
    _run_forever(rgw)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="ceph-tpu-daemon")
    sub = parser.add_subparsers(dest="role", required=True)

    p_mon = sub.add_parser("mon")
    p_mon.add_argument("--name", required=True)
    p_mon.add_argument("-c", "--conf")
    p_mon.add_argument("--store-path", default="")

    p_osd = sub.add_parser("osd")
    p_osd.add_argument("--id", required=True, type=int)
    p_osd.add_argument("-c", "--conf")
    p_osd.add_argument("--store", default="")
    p_osd.add_argument("--store-path", default="")

    p_mgr = sub.add_parser("mgr")
    p_mgr.add_argument("--name", required=True)
    p_mgr.add_argument("-c", "--conf")

    p_mds = sub.add_parser("mds")
    p_mds.add_argument("--name", required=True)
    p_mds.add_argument("-c", "--conf")

    p_rgw = sub.add_parser("rgw")
    p_rgw.add_argument("--port", type=int, default=7480)
    p_rgw.add_argument("--access-key", default="")
    p_rgw.add_argument("--secret-key", default="")
    p_rgw.add_argument("-c", "--conf")

    args = parser.parse_args(argv)
    if args.role == "mon":
        main_mon(args)
    elif args.role == "mgr":
        main_mgr(args)
    elif args.role == "mds":
        main_mds(args)
    elif args.role == "rgw":
        main_rgw(args)
    else:
        main_osd(args)


if __name__ == "__main__":
    main(sys.argv[1:])
