"""AsyncReserver: bounded concurrent recovery grants
(common/AsyncReserver.h reduced to FIFO + a front-of-queue lane).

The reference gates recovery/backfill with reservation slots so
recovery can never starve client I/O (osd/OSD.h:918-971). Here the
grant callback receives a `release` function; releasing hands the
slot to the oldest waiter. release() is idempotent, so a safety
timer can double as the completion path without double-granting.

``request(fn, front=True)`` is the priority-promotion lane the
reference expresses with request priorities: a recovery pull that a
CLIENT OP is blocked on goes to the head of the wait queue, ahead of
every queued background push/backfill round, so serve-during-repair
latency is bounded by one in-flight grant, not the whole repair
backlog.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable


class AsyncReserver:
    def __init__(self, slots: int):
        self._slots = max(1, int(slots))
        self._queue: deque[Callable] = deque()
        self._lock = threading.Lock()

    @property
    def available(self) -> int:
        with self._lock:
            return self._slots

    @property
    def queued(self) -> int:
        with self._lock:
            return len(self._queue)

    def request(self, fn: Callable[[Callable[[], None]], None],
                front: bool = False) -> None:
        """fn(release) runs when a slot frees (immediately if one is
        available).  fn MUST eventually call release() exactly once
        (extra calls are ignored).  front=True queues ahead of every
        FIFO waiter (blocked-op pull promotion)."""
        with self._lock:
            if self._slots > 0:
                self._slots -= 1
                run = True
            else:
                if front:
                    self._queue.appendleft(fn)
                else:
                    self._queue.append(fn)
                run = False
        if run:
            self._fire(fn)

    def _fire(self, fn: Callable) -> None:
        released = [False]

        def release() -> None:
            with self._lock:
                if released[0]:
                    return
                released[0] = True
                nxt = self._queue.popleft() if self._queue else None
                if nxt is None:
                    self._slots += 1
            if nxt is not None:
                self._fire(nxt)

        try:
            fn(release)
        except Exception:
            release()
            raise
