"""Throttles: bounded counters gating admission (common/Throttle.h analog)."""

from __future__ import annotations

import threading


class Throttle:
    """Blocking counting throttle with dynamic max."""

    def __init__(self, name: str, maximum: int = 0):
        self.name = name
        self._max = maximum
        self._count = 0
        self._cond = threading.Condition()

    @property
    def current(self) -> int:
        return self._count

    @property
    def maximum(self) -> int:
        return self._max

    def reset_max(self, maximum: int) -> None:
        with self._cond:
            self._max = maximum
            self._cond.notify_all()

    def _should_wait(self, c: int) -> bool:
        # reference Throttle::_should_wait: an over-max request proceeds
        # once current <= max (no starvation under small-op traffic)
        if self._max <= 0:
            return False
        if c <= self._max:
            return self._count > 0 and self._count + c > self._max
        return self._count > self._max

    def get(self, count: int = 1, timeout: float | None = None) -> bool:
        """Block until `count` fits; returns False on timeout."""
        with self._cond:
            ok = self._cond.wait_for(lambda: not self._should_wait(count),
                                     timeout)
            if not ok:
                return False
            self._count += count
            return True

    def get_or_fail(self, count: int = 1) -> bool:
        with self._cond:
            if self._should_wait(count):
                return False
            self._count += count
            return True

    def take(self, count: int = 1) -> int:
        """Unconditional take (can overshoot), like Throttle::take."""
        with self._cond:
            self._count += count
            return self._count

    def put(self, count: int = 1) -> int:
        with self._cond:
            self._count = max(0, self._count - count)
            self._cond.notify_all()
            return self._count
