"""dout-style subsystem logging with an in-memory crash ring.

The analog of common/dout.h + log/Log.h:18 in the reference: per-
subsystem (level, gather) pairs, cheap when disabled, with a bounded
ring of recent entries (at a higher gather level) dumped on crash.
Backed by the stdlib logging module rather than a custom flusher thread
— Python's logging already serializes; the ring is the part worth
keeping.
"""

from __future__ import annotations

import collections
import logging
import sys
import threading
import time

_SUBSYS_LEVELS: dict[str, tuple[int, int]] = {}   # name -> (level, gather)
_DEFAULT = (1, 5)
_ring: collections.deque = collections.deque(maxlen=10000)
_ring_lock = threading.Lock()

_root = logging.getLogger("ceph_tpu")
if not _root.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(message)s", datefmt="%H:%M:%S"))
    _root.addHandler(_h)
    _root.setLevel(logging.DEBUG)
    _root.propagate = False


def set_log_level(subsys: str, level: int, gather: int | None = None) -> None:
    g = gather if gather is not None else max(level, _DEFAULT[1])
    _SUBSYS_LEVELS[subsys] = (level, g)


def get_log_level(subsys: str) -> tuple[int, int]:
    return _SUBSYS_LEVELS.get(subsys, _DEFAULT)


def dump_recent(out=sys.stderr, count: int = 1000) -> None:
    """Crash-dump the ring, like Log::dump_recent."""
    with _ring_lock:
        entries = list(_ring)[-count:]
    out.write(f"--- begin dump of recent events ({len(entries)}) ---\n")
    for ts, subsys, lvl, msg in entries:
        out.write(f"{ts:.6f} {subsys} {lvl} : {msg}\n")
    out.write("--- end dump of recent events ---\n")


class DoutLogger:
    """Per-component logger: self.log = DoutLogger('osd', whoami='osd.3')."""

    def __init__(self, subsys: str, who: str = ""):
        self.subsys = subsys
        self.who = who
        self._py = _root.getChild(subsys if not who else f"{subsys}.{who}")

    def dout(self, level: int, msg: str, *args) -> None:
        show, gather = get_log_level(self.subsys)
        if level > show and level > gather:
            return
        if args:
            msg = msg % args
        if level <= gather:
            with _ring_lock:
                _ring.append((time.time(), self.subsys, level,
                              f"{self.who} {msg}" if self.who else msg))
        if level <= show:
            self._py.debug("%2d %s", level, msg)

    # convenience tiers
    def error(self, msg: str, *args) -> None:
        self.dout(-1, "ERROR: " + msg, *args)

    def warn(self, msg: str, *args) -> None:
        self.dout(0, "WARN: " + msg, *args)

    def info(self, msg: str, *args) -> None:
        self.dout(1, msg, *args)

    def debug(self, msg: str, *args) -> None:
        self.dout(10, msg, *args)

    def trace(self, msg: str, *args) -> None:
        self.dout(20, msg, *args)
