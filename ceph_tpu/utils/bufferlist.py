"""BufferList: a zero-copy byte rope (the reference's bufferlist).

The analog of include/buffer.h's ``bufferlist``: an ordered list of
buffer views over memory someone else owns.  ``append`` and ``slice``
never copy — they add or narrow ``memoryview`` segments — so a payload
can traverse client -> striper -> objecter -> messenger -> OSD -> EC
fan-out -> store while its bytes are materialized at most once (the
encode staging buffer / the WAL append; see utils/copyaudit.py).

Accepted segment sources: ``bytes``, ``bytearray``, ``memoryview``,
C-contiguous uint8 ``numpy`` arrays, and other ``BufferList``s (their
segments are shared, not copied).  Views hold a reference to the
exporting object, so lifetime is safe; the flip side is the usual
bufferlist contract — callers must not mutate a buffer they handed in
while the rope (or anything it was sent to) is still in flight.

``crc32c(seed)`` folds segment-by-segment with the chained-seed model
(``bufferlist::crc32c``); ``iov()`` exposes the segments for
gather-write; ``to_bytes()`` is the explicit flatten (audited).
"""

from __future__ import annotations

from typing import Iterable, Iterator

from . import copyaudit

_BYTES_LIKE = (bytes, bytearray, memoryview)


def _as_view(data) -> memoryview:
    """A flat uint8 memoryview over `data`, without copying."""
    if isinstance(data, memoryview):
        mv = data
    else:
        # covers bytes/bytearray and any C-contiguous buffer exporter
        # (numpy uint8 arrays included)
        mv = memoryview(data)
    if mv.ndim != 1 or mv.format not in ("B", "b", "c"):
        mv = mv.cast("B")
    return mv


class BufferList:
    """Zero-copy rope of byte segments."""

    __slots__ = ("_segs", "_len")

    def __init__(self, data=None):
        self._segs: list[memoryview] = []
        self._len = 0
        if data is not None:
            self.append(data)

    # -- building ----------------------------------------------------------

    def append(self, data) -> "BufferList":
        """Add a segment (no copy).  Accepts bytes-likes, uint8 numpy
        arrays, and other BufferLists (segment lists are shared)."""
        if isinstance(data, BufferList):
            self._segs.extend(data._segs)
            self._len += data._len
            return self
        mv = _as_view(data)
        if len(mv):
            self._segs.append(mv)
            self._len += len(mv)
        return self

    # -- geometry ----------------------------------------------------------

    def __len__(self) -> int:
        return self._len

    @property
    def num_segments(self) -> int:
        return len(self._segs)

    def is_contiguous(self) -> bool:
        return len(self._segs) <= 1

    # -- slicing (zero-copy) ----------------------------------------------

    def slice(self, off: int, length: int | None = None) -> "BufferList":
        """A sub-rope of [off, off+length) as narrowed views."""
        if off < 0:
            raise ValueError("negative offset")
        if length is None:
            length = self._len - off
        length = max(0, min(length, self._len - off))
        out = BufferList()
        pos = 0
        need = length
        for seg in self._segs:
            if need <= 0:
                break
            seg_len = len(seg)
            if pos + seg_len <= off:
                pos += seg_len
                continue
            start = max(0, off - pos)
            take = min(seg_len - start, need)
            out._segs.append(seg[start:start + take])
            out._len += take
            need -= take
            pos += seg_len
        return out

    def __getitem__(self, key):
        if isinstance(key, slice):
            start, stop, step = key.indices(self._len)
            if step != 1:
                raise ValueError("BufferList slices must be contiguous")
            return self.slice(start, stop - start)
        if key < 0:
            key += self._len
        if not 0 <= key < self._len:
            raise IndexError("BufferList index out of range")
        pos = 0
        for seg in self._segs:
            if key < pos + len(seg):
                return seg[key - pos]
            pos += len(seg)
        raise IndexError("BufferList index out of range")

    # -- consuming ---------------------------------------------------------

    def iov(self) -> list[memoryview]:
        """The segments, for gather-write / per-segment staging."""
        return list(self._segs)

    def __iter__(self) -> Iterator[memoryview]:
        return iter(self._segs)

    def to_bytes(self) -> bytes:
        """Flatten to one bytes object — THE copy, audited."""
        if not self._segs:
            return b""
        if len(self._segs) == 1:
            # a single segment still materializes a new bytes object
            copyaudit.note("bufferlist.flatten", self._len)
            return bytes(self._segs[0])
        copyaudit.note("bufferlist.flatten", self._len)
        return b"".join(self._segs)

    def __bytes__(self) -> bytes:
        return self.to_bytes()

    def crc32c(self, seed: int = 0) -> int:
        """Chained per-segment CRC32C — no flatten (bufferlist::crc32c)."""
        from ..ops import crc32c as crc_mod
        crc = seed
        for seg in self._segs:
            crc = crc_mod.crc32c(crc, seg)
        return crc

    # -- comparison (no flatten) -------------------------------------------

    def __eq__(self, other) -> bool:
        if isinstance(other, BufferList):
            if other._len != self._len:
                return False
            other = other.iov()
        elif isinstance(other, _BYTES_LIKE):
            if len(other) != self._len:
                return False
            other = [_as_view(other)]
        else:
            return NotImplemented
        # walk both segment lists without materializing either side
        mine = self._segs
        i = j = oi = oj = 0
        while i < len(mine) and oi < len(other):
            a, b = mine[i], _as_view(other[oi])
            n = min(len(a) - j, len(b) - oj)
            if a[j:j + n] != b[oj:oj + n]:
                return False
            j += n
            oj += n
            if j == len(a):
                i, j = i + 1, 0
            if oj == len(b):
                oi, oj = oi + 1, 0
        return True

    def __hash__(self):
        raise TypeError("BufferList is unhashable (mutable rope)")

    def __repr__(self):
        return (f"BufferList(len={self._len}, "
                f"segments={len(self._segs)})")


# ---------------------------------------------------------------------------
# payload helpers shared by the data-path layers
# ---------------------------------------------------------------------------


def wrap_payload(data):
    """Normalize a user payload for zero-copy transport.

    ``bytes``/``memoryview``/``BufferList`` pass through untouched
    (immutable or caller-owned views).  A mutable ``bytearray`` is
    snapshotted — the old ``bytes(data)`` defense, now the only place
    it happens — so callers cannot mutate an in-flight op's payload.
    """
    if isinstance(data, bytearray):
        copyaudit.note("payload.snapshot", len(data))
        return bytes(data)
    if isinstance(data, (bytes, memoryview, BufferList)):
        return data
    # exotic buffer exporters (numpy etc.): wrap as a view
    return _as_view(data)


def iov_of(data) -> list:
    """The gather-write segments of any payload type (no copy)."""
    if isinstance(data, BufferList):
        return data.iov()
    if isinstance(data, _BYTES_LIKE):
        return [data] if len(data) else []
    return [_as_view(data)]


def as_buffer(data):
    """One contiguous buffer for store/denc consumers.

    Single-segment ropes and plain bytes-likes come back as-is (no
    copy); only a fragmented rope flattens (audited inside
    ``to_bytes``)."""
    if isinstance(data, BufferList):
        if data.num_segments == 1:
            return data.iov()[0]
        return data.to_bytes()
    return data


def concat(parts: Iterable) -> BufferList:
    """Rope concatenation: shares every part's segments."""
    out = BufferList()
    for p in parts:
        out.append(p)
    return out
