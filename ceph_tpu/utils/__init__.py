"""Common runtime: config, logging, counters, queues, throttles.

The analog of the reference's common/ tier (SURVEY.md §2.1 "common
runtime"): everything else in the framework types against these.
"""

from .config import Config, Option, OPTIONS
from .dout import DoutLogger, set_log_level
from .perf_counters import PerfCounters, PerfCountersBuilder, PerfCountersCollection
from .throttle import Throttle

__all__ = [
    "Config", "Option", "OPTIONS",
    "DoutLogger", "set_log_level",
    "PerfCounters", "PerfCountersBuilder", "PerfCountersCollection",
    "Throttle",
]
