"""Typed config with live mutation + observer pattern.

The md_config_t analog (/root/reference/src/common/config.h:168-212:
set_val + apply_changes calling handle_conf_change on registered
md_config_obs_t observers; options declared with typed defaults like
common/config_opts.h).  Fault-injection knobs live here from day one,
matching the reference's config-driven injection style (SURVEY.md §5.3).
"""

from __future__ import annotations

import configparser
import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Mapping


@dataclass(frozen=True)
class Option:
    name: str
    type: type          # int, float, bool, str
    default: Any
    desc: str = ""

    def parse(self, value):
        if self.type is bool:
            if isinstance(value, bool):
                return value
            return str(value).lower() in ("1", "true", "yes", "on")
        return self.type(value)


# The subset of the reference's 1159 options this framework uses so far;
# grows as components land.  Names keep the reference's spelling where
# the meaning is identical so operators can carry intuition over.
OPTIONS: dict[str, Option] = {}


def _opt(name: str, type_: type, default, desc: str = "") -> None:
    OPTIONS[name] = Option(name, type_, default, desc)


# -- global ----------------------------------------------------------------
_opt("name", str, "client.admin", "entity name")
_opt("fsid", str, "", "cluster id")
_opt("mon_host", str, "", "comma-separated mon addresses")
_opt("log_level", int, 1, "default per-subsystem log level")
_opt("log_ring_size", int, 10000, "recent log entries kept for crash dump")

# -- auth --------------------------------------------------------------------
_opt("auth_cluster_required", str, "none",
     "cephx | none: session auth + per-message signing on the messenger")
_opt("keyring", str, "", "path to the keyring file")
_opt("key", str, "", "base64 secret (overrides keyring lookup)")

# -- messenger -------------------------------------------------------------
_opt("ms_type", str, "blocking",
     "messenger stack: blocking (one loop thread per messenger) | "
     "async (shared epoll event-loop worker pool)")
_opt("ms_async_op_threads", int, 3,
     "event-loop workers in the shared async-messenger pool")
_opt("ms_tcp_nodelay", bool, True, "")
_opt("ms_initial_backoff", float, 0.2, "reconnect backoff start")
_opt("ms_max_backoff", float, 15.0, "reconnect backoff cap")
_opt("ms_connect_timeout", float, 10.0, "handshake reply timeout")
_opt("ms_inject_socket_failures", int, 0,
     "1-in-N chance to drop a connection (fault injection)")
_opt("ms_inject_delay_probability", float, 0.0, "")
_opt("ms_inject_delay_max", float, 1.0, "seconds")
_opt("ms_dispatch_throttle_bytes", int, 100 << 20, "")

# -- mon -------------------------------------------------------------------
_opt("mon_lease", float, 5.0, "paxos peon lease seconds")
_opt("mon_lease_renew_interval", float, 3.0, "")
_opt("mon_lease_ack_timeout", float, 10.0, "")
_opt("mon_election_timeout", float, 5.0, "")
_opt("mon_tick_interval", float, 5.0, "")
_opt("osd_scrub_min_interval", float, 86400.0,
     "seconds between automatic shallow scrubs per PG")
_opt("osd_deep_scrub_interval", float, 604800.0,
     "seconds between automatic deep scrubs per PG")
_opt("osd_max_scrubs", int, 1,
     "max scheduled scrubs kicked per heartbeat tick")
_opt("osd_scrub_load_threshold", int, 8,
     "skip scheduled scrubs while this many ops are in flight")
_opt("osd_scrub_auto_repair", bool, False,
     "scheduled scrubs repair what they find inconsistent")
_opt("mds_bal_auto", bool, False,
     "auto-export hot subtrees to cooler ranks on beacon ticks")
_opt("mds_bal_min", int, 20,
     "minimum per-tick load before the balancer considers moving")
_opt("mon_osd_down_out_interval", float, 600.0,
     "seconds before a down OSD is marked out")
_opt("mon_osd_min_down_reporters", int, 1, "")
_opt("mon_osd_report_timeout", float, 900.0, "")
_opt("paxos_propose_interval", float, 1.0, "")

# -- osd -------------------------------------------------------------------
_opt("osd_pool_default_size", int, 3, "replicas")
_opt("osd_pool_default_min_size", int, 0, "0 -> size - size/2")
_opt("osd_pool_default_pg_num", int, 8, "")
_opt("osd_pool_default_erasure_code_profile", str,
     "plugin=tpu technique=reed_sol_van k=2 m=1", "")
_opt("osd_heartbeat_interval", float, 6.0, "")
_opt("osd_heartbeat_grace", float, 20.0, "")
_opt("osd_max_write_size", int, 90 << 20, "")
_opt("osd_client_message_size_cap", int, 500 << 20, "")
_opt("osd_op_num_shards", int, 5, "sharded op queue shards")
_opt("osd_op_num_threads_per_shard", int, 2, "")
_opt("osd_recovery_max_active", int, 3, "")
_opt("osd_recovery_block_retry", float, 1.0,
     "re-promotion cadence for client ops parked on a missing "
     "object's recovery pull (the op blocks instead of serving stale "
     "store bytes; each retry re-promotes the pull to the front of "
     "the recovery queue)")
_opt("osd_recovery_block_max_retries", int, 30,
     "recovery-blocked ops are EAGAINed back to the client after "
     "this many re-promotion rounds (the objecter resend/timeout "
     "machinery then owns the op) so a pull that can never complete "
     "cannot wedge a client op forever")
_opt("osd_scrub_sleep", float, 0.0, "")
_opt("osd_deep_scrub_stripe_batch", int, 64,
     "stripes per TPU dispatch during deep scrub")
_opt("osd_ec_pipeline_depth", int, 2,
     "overlapped EC device dispatches kept in flight")
_opt("osd_ec_pipeline_coalesce_ms", float, 2.0,
     "wait granularity while coalescing EC stripe work behind a "
     "busy device")
_opt("osd_ec_pipeline_max_batch", int, 256,
     "max stripes fused into one EC pipeline dispatch")
_opt("osd_ec_device_shards", str, "all",
     "devices the EC pipeline spreads mega-batches over: 'all' (every "
     "visible chip) or a count capping the dispatch lanes")
_opt("osd_ec_pipeline_scrub_weight", float, 0.25,
     "scrub CRC channels' share of contended EC pipeline dispatch "
     "slots (client-write encodes take the rest); >= 1 disables the "
     "yield (strict cross-channel FIFO)")
_opt("osd_ec_hbm_cache_bytes", int, 64 << 20,
     "HBM budget for the device-resident EC stripe cache (encoded "
     "stripes stay on-chip so deep scrub / recovery of a cached "
     "object pay zero re-upload); 0 disables the cache")
_opt("osd_ec_mesh_min_bytes", int, 256 << 20,
     "a single dispatch lane's staging budget: a coalesced EC batch "
     "larger than this shard_maps its chunk-length axis across the "
     "device mesh (one pod-scale dispatch, donated staging arena) "
     "instead of riding one chip's HBM; 0 disables mesh dispatch")
_opt("osd_ec_device_mesh", str, "auto",
     "axis layout for EC mesh dispatch: 'auto' spans every active "
     "lane on the chunk-length axis, an integer caps the member "
     "count, 'AxB' lays out dp x ls (stripes x chunk-length) "
     "explicitly")
# -- per-pool QoS (dmClock-style service classes) ---------------------------
# Options named `osd_pool_qos_<pool>` are DYNAMIC (auto-registered on
# first set): the value is a `res:weight:lim` triple (utils/dmclock.
# parse_spec) giving pool <pool> a reserved IOPS floor, a proportional
# weight for the surplus, and an IOPS ceiling (0 = none/unlimited,
# e.g. "100:2:0").  They shape BOTH the OSD's sharded op queue and the
# EC pipeline's dispatch-lane picks.  `osd_pool_qos_default` applies
# to every pool without its own entry ('' = unconstrained FIFO).
QOS_OPT_PREFIX = "osd_pool_qos_"
_opt("osd_qos_recovery", str, "",
     "dmClock service class for recovery/backfill pushes "
     "('res:weight:lim'; '' = unconstrained).  With a class set, "
     "MPGPush payloads are tagged into it with bytes-weighted cost, "
     "so a backfill storm is throttleable instead of riding the "
     "unconstrained control plane")
_opt("osd_qos_cost_bytes_unit", int, 4096,
     "dmClock cost normalization: an op costs "
     "1 + payload_bytes/this (a 4 MiB write is not the same grant as "
     "a 4 KiB stat); 0 reverts to cost=1 per op")
_opt("osd_pool_qos_default", str, "",
     "res:weight:lim service class for pools without their own "
     "osd_pool_qos_<pool> entry ('' = unconstrained FIFO)")
_opt("osd_ec_cost_aware_placement", bool, True,
     "EC pipeline lane placement uses per-(shape, chip) measured "
     "service-time EMAs to override the least-loaded pick when a "
     "chip is measured faster (cost_diverged counts overrides); "
     "false restores pure least-loaded/round-robin")
_opt("osd_inject_failure_on_pg_removal", bool, False, "")
_opt("osd_debug_inject_dispatch_delay_probability", float, 0.0, "")
_opt("osd_debug_inject_dispatch_delay_duration", float, 0.1, "")
_opt("osd_op_complaint_time", float, 30.0,
     "ops in flight longer than this are reported as slow (one-shot "
     "log complaint + the level-triggered 'N slow ops' HEALTH_WARN "
     "flag on pg-stats reports)")
_opt("osd_op_history_size", int, 20, "historic ops kept for dump")
_opt("osd_op_history_duration", float, 600.0,
     "historic ops older than this are pruned from the ring even "
     "below the size bound (osd_op_history_duration analog)")
_opt("osd_enable_op_tracker", bool, True,
     "per-op tracing (TrackedOp spans + historic rings); off keeps "
     "only the latency counters — the bench tracer-overhead gate "
     "compares both modes")
_opt("flight_recorder_dir", str, "",
     "arm the op-tracing flight recorder: a fired CrashPoint or a "
     "DurabilityLedger verify failure snapshots every registered "
     "daemon's in-flight/historic ops + pg log summaries into this "
     "directory ('' = disarmed)")
_opt("flight_recorder_max", int, 16,
     "incident directories the flight recorder writes before going "
     "quiet (bounds a crash soak's disk use)")
_opt("paxos_max_versions", int, 500,
     "committed paxos versions kept before the leader proposes a trim")
_opt("paxos_trim_keep", int, 250,
     "versions retained by a trim; peers behind the trim point "
     "rejoin via full store sync")
_opt("auth_service_ticket_ttl", float, 60.0,
     "cephx service-ticket lifetime; clients renew at ~1/3 of it and "
     "services refresh rotating secrets on the same cadence")
_opt("osd_pg_log_max_entries", int, 2000,
     "bounded PG log length (osd_max_pg_log_entries analog): peering "
     "exchanges log deltas within this window; a peer whose "
     "last_update predates the trimmed tail must backfill")
_opt("osd_backfill_scan_batch", int, 64,
     "objects compared per backfill scan round (BackfillInterval "
     "window analog)")
_opt("osd_subop_resend_interval", float, 2.0,
     "write gathers older than this resend sub-ops to unacked shards "
     "(replicas dedup by log ev) and drop shards whose holder left "
     "the acting set — ECBackend check_op/on_change requeue analog")
_opt("admin_socket_dir", str, "",
     "directory for per-daemon admin sockets ('' disables the socket; "
     "the in-process hook registry always works)")

# -- objectstore -----------------------------------------------------------
_opt("objectstore", str, "memstore", "memstore | filestore")
_opt("objectstore_inject_eio_probability", float, 0.0,
     "1-in-N read EIO fault injection")
_opt("filestore_commit_interval", float, 0.2,
     "seconds between journal commits")

# -- erasure ---------------------------------------------------------------
_opt("erasure_code_plugins_preload", str, "tpu jerasure", "")

# -- client ----------------------------------------------------------------
_opt("client_mount_timeout", float, 300.0, "")
_opt("objecter_inflight_ops", int, 1024, "op budget")
_opt("objecter_inflight_op_bytes", int, 100 << 20, "")
_opt("objecter_timeout", float, 10.0, "resend/ping interval")
_opt("objecter_op_timeout", float, 30.0,
     "per-op deadline: an op not acked within this window fails with "
     "ETIMEDOUT (110) instead of hanging on a dead primary")
_opt("objecter_backoff_base", float, 0.5,
     "first resend interval for a silent op; doubles per silent try")
_opt("objecter_backoff_max", float, 5.0,
     "resend interval cap for the exponential backoff")
_opt("objecter_silent_kick", float, 6.0,
     "seconds of continuous silence on one primary's link before the "
     "connection is marked down and redialed; must exceed a slow-but-"
     "alive op's service time or the kick drops its in-flight reply")

# -- rgw -------------------------------------------------------------------
_opt("rgw_sync_retries", int, 3,
     "in-round retries per bucket before the sync agent quarantines "
     "it (the bucket sits out under exponential backoff instead of "
     "failing the whole round)")
_opt("rgw_sync_backoff_base", float, 0.5,
     "first backoff interval for a quarantined bucket (and for the "
     "round-level peer probe after a failed discovery); doubles per "
     "consecutive failure")
_opt("rgw_sync_backoff_max", float, 10.0,
     "backoff interval cap for the sync agent's exponential backoff "
     "(bounds time-to-recover after a long partition heals)")

# -- mds -------------------------------------------------------------------
_opt("mds_beacon_grace", float, 15.0,
     "mds ranks silent past this are dropped from the map so clients "
     "stop routing to dead addresses (0 disables pruning)")

# -- fault injection (FaultSet, ceph_tpu/utils/faults.py) -------------------
_opt("faultset_seed", int, 0,
     "seed for the FaultSet decision streams; same seed + same "
     "per-entity call order reproduces the fault schedule")
_opt("faultset_rules", str, "",
     "';'-separated FaultSet rules installed via injectargs, e.g. "
     "'partition osd.1 osd.2; eio osd.0 obj* 0.5; tpu_error 1.0' "
     "(replaces prior conf-sourced rules; '' clears them)")


class Config:
    """A live option map with observers (thread-safe)."""

    def __init__(self, overrides: Mapping[str, Any] | None = None):
        self._lock = threading.RLock()
        self._values: dict[str, Any] = {
            name: opt.default for name, opt in OPTIONS.items()}
        self._observers: list[tuple[Callable, tuple[str, ...]]] = []
        self._pending: set[str] = set()
        # bumped per apply_changes batch that changed anything: the
        # `perf dump` daemon block reports it as the conf epoch
        self.generation = 0
        if overrides:
            for key, val in overrides.items():
                self.set_val(key, val)
            self.apply_changes()

    def __getattr__(self, name: str):
        # config.osd_pool_default_size style access
        try:
            with self._lock:
                return self._values[name]
        except KeyError:
            raise AttributeError(name) from None

    def get_val(self, name: str):
        with self._lock:
            if name not in self._values:
                raise KeyError(f"unknown option {name!r}")
            return self._values[name]

    def set_val(self, name: str, value) -> None:
        opt = OPTIONS.get(name)
        if opt is None and name.startswith(QOS_OPT_PREFIX):
            # per-pool QoS entries are dynamic by nature (pools are
            # created at runtime): auto-register as a string option so
            # injectargs/conf files/observers all work unchanged
            opt = Option(name, str, "", "dynamic per-pool qos spec")
            OPTIONS[name] = opt
            with self._lock:
                self._values.setdefault(name, opt.default)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        parsed = opt.parse(value)
        with self._lock:
            # .get: a dynamic option may have been registered by a
            # DIFFERENT Config instance after this one was built
            if self._values.get(name, opt.default) != parsed or \
                    name not in self._values:
                self._values[name] = parsed
                self._pending.add(name)

    def add_observer(self, handler: Callable[[Config, set[str]], None],
                     keys: Iterable[str]) -> None:
        """handler(conf, changed_keys) fires on apply_changes."""
        self._observers.append((handler, tuple(keys)))

    def remove_observer(self, handler) -> None:
        self._observers = [(h, k) for h, k in self._observers
                           if h is not handler]

    def apply_changes(self) -> set[str]:
        with self._lock:
            changed = set(self._pending)
            self._pending.clear()
            if changed:
                self.generation += 1
        if changed:
            for handler, keys in list(self._observers):
                # a trailing '*' in an observer key is a prefix match
                # (dynamic options like osd_pool_qos_<pool>)
                hit = {c for c in changed
                       if any(c == k or (k.endswith("*")
                                         and c.startswith(k[:-1]))
                              for k in keys)}
                if hit:
                    handler(self, hit)
        return changed

    def injectargs(self, args: str) -> None:
        """'--osd-heartbeat-grace 30 --mon-lease 7' style live
        injection.  Values are shell-quoted, so multi-word values work:
        --faultset-rules 'partition osd.1 osd.2'."""
        import shlex
        toks = shlex.split(args)
        i = 0
        while i < len(toks):
            tok = toks[i]
            if not tok.startswith("--"):
                raise ValueError(f"expected --option, got {tok!r}")
            name = tok[2:]
            if "=" in name:
                name, val = name.split("=", 1)
                name = name.replace("-", "_")
            else:
                name = name.replace("-", "_")
                i += 1
                if i >= len(toks):
                    raise ValueError(f"missing value for {tok}")
                val = toks[i]
            self.set_val(name, val)
            i += 1
        self.apply_changes()

    def parse_file(self, path: str, section: str | None = None) -> None:
        """ini config file; [global] plus optional entity section."""
        parser = configparser.ConfigParser()
        parser.read(path)
        for sec in ("global", section):
            if sec and parser.has_section(sec):
                for key, val in parser.items(sec):
                    name = key.replace(" ", "_").replace("-", "_")
                    # dynamic options (osd_pool_qos_<pool>) register
                    # themselves inside set_val — a conf file must be
                    # able to carry them just like injectargs
                    if name in OPTIONS or \
                            name.startswith(QOS_OPT_PREFIX):
                        self.set_val(name, val)
        self.apply_changes()

    def dump(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._values)
