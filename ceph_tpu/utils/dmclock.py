"""dmClock-style QoS tag math: reservation / weight / limit per client.

The reference OSD keeps a noisy client from starving others with a
weighted op queue (osd/ mClockScheduler, after the dmClock paper:
Gulati et al., OSDI '10).  Each client class carries three knobs:

  * ``res``    — reserved service rate (grants/sec) it must receive
                 even under full contention (0 = no reservation);
  * ``weight`` — its share of the service left over after every
                 reservation is met (proportional phase);
  * ``lim``    — a hard ceiling on its service rate (0 = unlimited):
                 a limited client is NOT served above ``lim`` even
                 when the system is otherwise idle.

This module is the shared tag tracker both QoS surfaces use: the
OSD's sharded op queue (utils/workqueue.py ``ShardedThreadPool``) and
the EC pipeline's dispatch-lane picker (ops/pipeline.py).  Tags are
kept PER CLIENT, not per request (start-time fair queuing form): a
grant advances the client's reservation/proportional/limit tags by
``cost/rate``, and eligibility is tag <= now.  Sharing one
``DmClockState`` across all op shards makes the configured rates
cluster-honest no matter how a pool's pgs hash across shards.

Selection rule per service opportunity (``pick``):

  1. **reservation phase** — among clients whose reservation tag is
     due (r_tag <= now), serve the earliest tag.  Unconstrained
     clients (no spec: internal work, pools without QoS conf) are
     always reservation-eligible at their oldest queued arrival time,
     so plain FIFO behavior is preserved exactly when nothing is
     configured, and system work can never be starved by tenant QoS.
  2. **proportional phase** — otherwise, among clients under their
     limit (l_tag <= now), serve the smallest proportional tag
     (weighted fair sharing).
  3. **throttled** — every queued client is over its limit: serve
     nothing; the caller sleeps until ``next_wake`` (counted as a
     throttle stall).

Counters per client: ``res_grants`` / ``prop_grants`` (which phase
served it), ``deadline_misses`` (a reservation grant delivered more
than two periods late — the reservation was not actually honored at
that moment), plus global ``throttle_stalls``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

_INF = float("inf")


@dataclass(frozen=True)
class QosSpec:
    """One client class's reservation / weight / limit."""
    res: float = 0.0      # reserved grants/sec (0 = none)
    weight: float = 1.0   # proportional share (relative)
    lim: float = 0.0      # grant/sec ceiling (0 = unlimited)

    def __post_init__(self):
        if self.res < 0 or self.lim < 0 or self.weight <= 0:
            raise ValueError(f"invalid qos spec {self}")
        if self.lim and self.res > self.lim:
            raise ValueError(
                f"qos spec reservation {self.res} exceeds limit "
                f"{self.lim}")


def parse_spec(text: str) -> QosSpec:
    """``res:weight:lim`` (the conf grammar, e.g. ``100:2:500``).

    Missing trailing fields default (``"100"`` = res 100, weight 1,
    unlimited; ``"0:3"`` = pure weight 3)."""
    parts = [p.strip() for p in str(text).split(":")]
    if not 1 <= len(parts) <= 3:
        raise ValueError(f"qos spec {text!r}: want res[:weight[:lim]]")
    try:
        res = float(parts[0] or 0)
        weight = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
        lim = float(parts[2]) if len(parts) > 2 and parts[2] else 0.0
    except ValueError:
        raise ValueError(f"qos spec {text!r}: non-numeric field")
    return QosSpec(res=res, weight=weight, lim=lim)


class _Client:
    __slots__ = ("name", "spec", "r_tag", "p_tag", "l_tag",
                 "res_grants", "prop_grants", "deadline_misses",
                 "throttle_stalls")

    def __init__(self, name: str, spec: QosSpec | None):
        self.name = name
        self.spec = spec            # None = unconstrained (FIFO class)
        self.r_tag = 0.0
        self.p_tag = 0.0
        self.l_tag = 0.0
        self.res_grants = 0
        self.prop_grants = 0
        self.deadline_misses = 0
        # service opportunities this client sat out limit-throttled
        # while NOTHING else was servable (per-class attribution of
        # the global throttle_stalls — "how often did @recovery's lim
        # actually hold work back?")
        self.throttle_stalls = 0


# grant phases (returned by pick for accounting/tests)
RES = "res"
PROP = "prop"


class DmClockState:
    """Shared per-client tag state.  Thread-safe; one instance may
    back many queues (every op shard of a daemon, or the pipeline's
    channel picker) so the configured rates hold globally."""

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._clients: dict[str, _Client] = {}
        self.throttle_stalls = 0

    # -- configuration -----------------------------------------------------

    def set_spec(self, name: str, spec: QosSpec | None) -> None:
        with self._lock:
            c = self._clients.get(name)
            if c is None:
                self._clients[name] = _Client(name, spec)
            else:
                c.spec = spec

    def configure(self, specs: dict[str, QosSpec]) -> None:
        """Replace the spec set: named clients get their spec, every
        other known client drops back to unconstrained."""
        with self._lock:
            for name, c in self._clients.items():
                c.spec = specs.get(name)
            for name, spec in specs.items():
                if name not in self._clients:
                    self._clients[name] = _Client(name, spec)

    def spec_of(self, name: str) -> QosSpec | None:
        with self._lock:
            c = self._clients.get(name)
            return c.spec if c else None

    def has_specs(self) -> bool:
        with self._lock:
            return any(c.spec is not None
                       for c in self._clients.values())

    # -- the scheduling decision -------------------------------------------

    def pick(self, candidates: dict[str, float],
             now: float | None = None,
             cost: float = 1.0,
             costs: dict[str, float] | None = None
             ) -> tuple[str | None, str | None, float]:
        """One service opportunity over ``candidates``
        ({client_name: oldest queued arrival time}).

        Returns ``(client, phase, next_wake)``: the client to serve
        and which phase granted it, or ``(None, None, wake_time)``
        when every candidate is limit-throttled (the caller should
        sleep until ``wake_time`` or new work arrives — and count a
        throttle stall via :meth:`note_stall`).

        The grant ADVANCES the winner's tags by its cost/rate, so the
        caller must dequeue what it asked about.  ``costs`` carries a
        PER-CANDIDATE head cost (bytes-weighted scheduling: a 4 MiB
        write advances its client's tags ~1000x further than a 4 KiB
        stat, so configured rates meter BYTES, not op counts);
        ``cost`` is the scalar fallback for callers whose work is
        uniform.
        """
        if now is None:
            now = self._clock()
        if costs is None:
            costs = {}
        with self._lock:
            best_res = None        # (tag, name)
            best_prop = None       # (p_tag, arrival, name)
            next_wake = now + 0.1
            limited: list[str] = []
            for name, arrival in candidates.items():
                c = self._clients.get(name)
                if c is None:
                    c = self._clients[name] = _Client(name, None)
                spec = c.spec
                if spec is None:
                    # unconstrained: reservation-eligible at arrival
                    # order — FIFO among themselves and against
                    # reserved clients' due tags
                    if best_res is None or arrival < best_res[0]:
                        best_res = (arrival, name)
                    continue
                # an idle client's stale tags fast-forward to now
                # (no banked credit, no banked debt: dmClock's
                # max(now, tag) arrival rule)
                if spec.res > 0:
                    r_tag = max(c.r_tag, arrival)
                    if r_tag <= now and (best_res is None
                                         or r_tag < best_res[0]):
                        best_res = (r_tag, name)
                    elif r_tag > now:
                        next_wake = min(next_wake, r_tag)
                if spec.lim > 0 and max(c.l_tag, arrival) > now:
                    next_wake = min(next_wake,
                                    max(c.l_tag, arrival))
                    limited.append(name)
                    continue       # over limit: not prop-eligible
                p_tag = max(c.p_tag, arrival)
                key = (p_tag, arrival)
                if best_prop is None or key < best_prop[:2]:
                    best_prop = (p_tag, arrival, name)
            if best_res is not None:
                name = best_res[1]
                c = self._clients[name]
                wcost = float(costs.get(name, cost))
                if c.spec is not None and c.spec.res > 0:
                    due = max(c.r_tag, candidates[name])
                    if now - due > 2.0 * wcost / c.spec.res:
                        c.deadline_misses += 1
                    c.r_tag = max(due, now - wcost / c.spec.res) \
                        + wcost / c.spec.res
                    self._advance_aux(c, now, wcost)
                c.res_grants += 1
                return name, RES, next_wake
            if best_prop is not None:
                name = best_prop[2]
                c = self._clients[name]
                wcost = float(costs.get(name, cost))
                if c.spec is not None:
                    c.p_tag = max(c.p_tag, candidates[name], now) \
                        + wcost / c.spec.weight
                    self._advance_lim(c, now, wcost)
                c.prop_grants += 1
                return name, PROP, next_wake
            # nothing servable: every queued client is over its limit —
            # attribute the stall to each held-back class so perf dump
            # can say WHOSE lim is doing the throttling
            for name in limited:
                self._clients[name].throttle_stalls += 1
            return None, None, next_wake

    def _advance_aux(self, c: _Client, now: float, cost: float) -> None:
        """A reservation grant still consumes proportional share and
        counts toward the limit (dmClock serves each request once)."""
        c.p_tag = max(c.p_tag, now) + cost / c.spec.weight
        self._advance_lim(c, now, cost)

    @staticmethod
    def _advance_lim(c: _Client, now: float, cost: float) -> None:
        if c.spec.lim > 0:
            c.l_tag = max(c.l_tag, now) + cost / c.spec.lim

    def note_stall(self) -> None:
        with self._lock:
            self.throttle_stalls += 1

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The perf-dump ``qos`` block: per-client grants + misses."""
        with self._lock:
            clients = {}
            for name, c in self._clients.items():
                if c.spec is None and not c.res_grants \
                        and not c.prop_grants:
                    continue
                ent = {"res_grants": c.res_grants,
                       "prop_grants": c.prop_grants,
                       "deadline_misses": c.deadline_misses,
                       "throttle_stalls": c.throttle_stalls}
                if c.spec is not None:
                    ent["spec"] = (f"{c.spec.res:g}:{c.spec.weight:g}"
                                   f":{c.spec.lim:g}")
                clients[name] = ent
            return {"enabled": any(c.spec is not None
                                   for c in self._clients.values()),
                    "throttle_stalls": self.throttle_stalls,
                    "clients": clients}
