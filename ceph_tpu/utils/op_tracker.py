"""Op tracking: in-flight timelines + historic ops + slow-op warnings.

The TrackedOp/OpTracker analog (common/TrackedOp.{h,cc},
osd/OpRequest.cc): every client op gets an event timeline ("queued",
"reached_pg", "commit_sent"), in-flight ops are dumpable through the
admin socket (dump_ops_in_flight / dump_historic_ops), and ops older
than the complaint threshold are surfaced as slow-op warnings.
"""

from __future__ import annotations

import threading
from collections import deque


class TrackedOp:
    __slots__ = ("desc", "start", "events", "_tracker", "_id")

    def __init__(self, tracker: "OpTracker", desc: str, now: float):
        self._tracker = tracker
        self.desc = desc
        self.start = now
        self._id = 0
        self.events: list[tuple[float, str]] = [(now, "initiated")]

    def mark_event(self, event: str) -> None:
        self.events.append((self._tracker.clock.now(), event))

    def finish(self) -> None:
        self.mark_event("done")
        self._tracker._finish(self)

    def age(self, now: float) -> float:
        return now - self.start

    def dump(self) -> dict:
        return {"description": self.desc,
                "initiated_at": self.start,
                "age": self._tracker.clock.now() - self.start,
                "events": [{"time": t, "event": e}
                           for t, e in self.events]}


class OpTracker:
    """Per-daemon op registry (OpTracker + OpHistory)."""

    def __init__(self, clock, history_size: int = 20,
                 complaint_age: float = 30.0, logger=None):
        self.clock = clock
        self.complaint_age = complaint_age
        self.log = logger
        self._lock = threading.Lock()
        self._inflight: dict[int, TrackedOp] = {}
        self._seq = 0
        self._history: deque[dict] = deque(maxlen=history_size)
        self._complained: set[int] = set()

    def create(self, desc: str) -> TrackedOp:
        op = TrackedOp(self, desc, self.clock.now())
        with self._lock:
            self._seq += 1
            op._id = self._seq
            self._inflight[op._id] = op
        return op

    def _finish(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(op._id, None)
            self._complained.discard(op._id)
            self._history.append(op.dump())

    def check_slow_ops(self) -> list[dict]:
        """Ops past the complaint age (called from the daemon tick)."""
        now = self.clock.now()
        slow = []
        with self._lock:
            for op_id, op in self._inflight.items():
                if op.age(now) > self.complaint_age \
                        and op_id not in self._complained:
                    self._complained.add(op_id)
                    slow.append(op.dump())
        if slow and self.log is not None:
            for s in slow:
                self.log.warn("slow op (%.0fs): %s",
                              s["age"], s["description"])
        return slow

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            return {"num_ops": len(self._history),
                    "ops": list(self._history)}
