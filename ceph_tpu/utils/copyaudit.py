"""Host-copy audit: runtime accounting of payload-byte copies.

The zero-copy data path (utils/bufferlist.py rope payloads, CTM2
out-of-band message segments, shard-view EC fan-out, memoryview store
writes) leaves a small, known set of places where payload bytes are
still materialized on the host:

  * ``ec.stage``        — padding/reshaping a payload into the (S, k, L)
                          stripe batch the encode kernel consumes (the
                          H2D staging buffer; one copy per encode).
                          RETIRED on the mesh-dispatch path: a
                          mesh-sized payload stages into a pinned
                          arena whose upload is donated to the device
                          computation (ops/pipeline.py StagingArena),
                          so the staging copy IS the H2D transfer —
                          the site re-arms automatically when such a
                          batch degrades to a non-mesh serve;
  * ``journal.append``  — the WAL flatten: journaled stores serialize
                          the transaction batch once, by design the only
                          place the write path flattens shard bytes;
  * ``bufferlist.flatten`` — an explicit ``BufferList.to_bytes()`` (a
                          consumer that genuinely needs contiguous
                          bytes, e.g. a sub-threshold inline field);
  * ``msg.inline``      — a bytes field too small for an out-of-band
                          segment, denc-copied into the frame.

Every such site calls :func:`note` with the byte count; ``perf dump``
exposes the totals plus ``host_copies_per_write`` (copies amortized
over the daemon's write ops), and ``bench.py --smoke`` gates the
per-write copy count so a copy regression in the hot path fails CI
loudly instead of silently re-widening the kernel<->e2e gap.

Counters are process-wide (the write path spans client, messenger, OSD
and store layers in one process here), monotonic, and cheap: one lock,
two adds.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_copies = 0
_bytes = 0
_writes = 0
_reads = 0
_sites: dict[str, list[int]] = {}      # site -> [copies, bytes]

# sites that materialize payload on the READ path (the PR 9 read-side
# zero-copy scope): their copies amortize over read ops as
# host_copies_per_read.  The hot cache/intact read path contributes
# ZERO entries here — only degraded reads (chunk rebuild) and explicit
# flattens by read consumers pay.
READ_SITES = frozenset({
    "ec.decode_rebuild",       # degraded read: rebuilt chunks only
    "read.flatten",            # a read consumer flattening its rope
    "cache.mesh_unpad",        # cache-served read of a PADDED mesh
                               # entry: the pad-strip contiguous copy
})


def note(site: str, nbytes: int) -> None:
    """Record one host materialization of `nbytes` payload bytes."""
    global _copies, _bytes
    with _lock:
        _copies += 1
        _bytes += nbytes
        ent = _sites.get(site)
        if ent is None:
            _sites[site] = [1, nbytes]
        else:
            ent[0] += 1
            ent[1] += nbytes


def note_write() -> None:
    """Record one client write op reaching a primary — the PROCESS-WIDE
    denominator for host_copies_per_write.  Copies are counted
    process-wide (the path spans client/msg/osd/store in one process),
    so the write count must be too: dividing by one daemon's own op_w
    would over-report by the daemon count in a multi-OSD process."""
    global _writes
    with _lock:
        _writes += 1


def note_read() -> None:
    """One client read op served by a primary — the denominator for
    host_copies_per_read (same process-wide rationale as writes)."""
    global _reads
    with _lock:
        _reads += 1


def snapshot() -> dict:
    """Totals + per-site breakdown (the perf-dump ``data_path`` block)."""
    with _lock:
        read_copies = sum(c for s, (c, b) in _sites.items()
                          if s in READ_SITES)
        read_bytes = sum(b for s, (c, b) in _sites.items()
                         if s in READ_SITES)
        return {
            "host_copies": _copies,
            "ec_host_copy_bytes": _bytes,
            "writes": _writes,
            "reads": _reads,
            "read_copies": read_copies,
            "read_copy_bytes": read_bytes,
            "sites": {s: {"copies": c, "bytes": b}
                      for s, (c, b) in sorted(_sites.items())},
        }


def reset() -> None:
    """Zero all counters (bench phases measure deltas this way)."""
    global _copies, _bytes, _writes, _reads
    with _lock:
        _copies = 0
        _bytes = 0
        _writes = 0
        _reads = 0
        _sites.clear()
