"""Typed perf counters (common/perf_counters.h analog).

PerfCountersBuilder declares u64 / time / long-run-average / histogram
counters for a subsystem; PerfCountersCollection aggregates every
component's counters for `perf dump` (admin socket / mgr export).
"""

from __future__ import annotations

import threading
from typing import Any

U64 = "u64"
TIME = "time"
LONGRUNAVG = "longrunavg"
HISTOGRAM = "histogram"

_HIST_BUCKETS = [0.0001, 0.001, 0.01, 0.1, 1.0, 10.0, float("inf")]


class PerfCounters:
    def __init__(self, name: str, schema: dict[str, str]):
        self.name = name
        self._schema = schema
        self._lock = threading.Lock()
        self._vals: dict[str, Any] = {}
        for key, typ in schema.items():
            if typ in (U64, TIME):
                self._vals[key] = 0
            elif typ == LONGRUNAVG:
                self._vals[key] = [0, 0.0]          # count, sum
            elif typ == HISTOGRAM:
                self._vals[key] = [0] * len(_HIST_BUCKETS)

    def inc(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._vals[key] += amount

    def dec(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._vals[key] -= amount

    def set(self, key: str, value) -> None:
        with self._lock:
            self._vals[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        """Record a duration: LONGRUNAVG accumulates, HISTOGRAM buckets."""
        with self._lock:
            slot = self._vals[key]
            if self._schema[key] == LONGRUNAVG:
                slot[0] += 1
                slot[1] += seconds
            elif self._schema[key] == HISTOGRAM:
                for i, edge in enumerate(_HIST_BUCKETS):
                    if seconds <= edge:
                        slot[i] += 1
                        break
            else:
                self._vals[key] += seconds

    def value(self, key: str):
        with self._lock:
            v = self._vals[key]
            return list(v) if isinstance(v, list) else v

    def avg(self, key: str) -> float:
        with self._lock:
            count, total = self._vals[key]
            return total / count if count else 0.0

    def dump(self) -> dict[str, Any]:
        with self._lock:
            out = {}
            for key, typ in self._schema.items():
                v = self._vals[key]
                if typ == LONGRUNAVG:
                    out[key] = {"avgcount": v[0], "sum": v[1]}
                elif typ == HISTOGRAM:
                    out[key] = {"buckets": list(v),
                                "edges": list(_HIST_BUCKETS)}
                else:
                    out[key] = v
            return out


class PerfCountersBuilder:
    def __init__(self, name: str):
        self.name = name
        self._schema: dict[str, str] = {}

    def add_u64_counter(self, key: str, desc: str = ""):
        self._schema[key] = U64
        return self

    add_u64 = add_u64_counter

    def add_time(self, key: str, desc: str = ""):
        self._schema[key] = TIME
        return self

    def add_time_avg(self, key: str, desc: str = ""):
        self._schema[key] = LONGRUNAVG
        return self

    def add_histogram(self, key: str, desc: str = ""):
        self._schema[key] = HISTOGRAM
        return self

    def create_perf_counters(self) -> PerfCounters:
        return PerfCounters(self.name, dict(self._schema))


class PerfCountersCollection:
    def __init__(self):
        self._lock = threading.Lock()
        self._loggers: dict[str, PerfCounters] = {}

    def add(self, counters: PerfCounters) -> None:
        with self._lock:
            self._loggers[counters.name] = counters

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def dump(self) -> dict[str, dict]:
        with self._lock:
            loggers = list(self._loggers.values())
        return {c.name: c.dump() for c in loggers}
