"""Injectable time source for every daemon-side timer and timestamp.

The reference drives failure detection, paxos leases and down->out
aging off the wall clock (e.g. OSDMonitor grace math,
/root/reference/src/mon/OSDMonitor.cc:1752; lease stamps,
mon/Paxos.cc:623).  An in-process test cluster cannot use the wall
clock for those: a single first-shape jit compile can hold the GIL for
tens of seconds, which reads as "peer silent past grace" and flaps the
map (the round-1 flaky test).  Every daemon therefore takes a Clock;
production uses SystemClock, MiniCluster shares one ManualClock whose
time only moves when the test advances it — heartbeat grace, lease
expiry and down-out intervals become deterministic functions of the
test script, not of scheduler noise.

Only *cluster-logic* time goes through Clock (heartbeats, leases,
elections, failure aging, tick loops).  Transport-level waits (socket
timeouts, condvar waits for in-flight RPCs) stay on the real clock:
they bound real thread/network progress, not simulated time.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable


class TimerHandle:
    """Cancelable handle returned by Clock.timer()."""

    __slots__ = ("_cancel", "cancelled")

    def __init__(self, cancel: Callable[[], None]):
        self._cancel = cancel
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True
        self._cancel()


class SystemClock:
    """Real time: time.time() + threading.Timer."""

    def now(self) -> float:
        return time.time()

    def timer(self, delay: float, fn: Callable) -> TimerHandle:
        t = threading.Timer(delay, fn)
        t.daemon = True
        t.start()
        return TimerHandle(t.cancel)

    def sleep(self, secs: float) -> None:
        time.sleep(secs)


class ManualClock:
    """Virtual time that moves only under advance().

    Timers are kept in a heap; advance(dt) steps now() forward and runs
    every callback that came due, in due-time order, on the advancing
    thread (so a test's advance() call returns only after all cluster
    reactions to the elapsed time have at least been initiated).
    Callbacks may schedule new timers; those fire in the same advance()
    if they fall inside the window.
    """

    def __init__(self, start: float = 1_000_000.0):
        self._t = start
        self._lock = threading.Lock()
        self._timers: list = []          # (due, seq, fn, handle)
        self._seq = itertools.count()

    def now(self) -> float:
        with self._lock:
            return self._t

    def timer(self, delay: float, fn: Callable) -> TimerHandle:
        handle = TimerHandle(lambda: None)
        with self._lock:
            heapq.heappush(self._timers,
                           (self._t + delay, next(self._seq), fn, handle))
        return handle

    def sleep(self, secs: float) -> None:
        """Virtual sleep: returns once now() has advanced past the
        deadline (some other thread must be advancing)."""
        deadline = self.now() + secs
        while self.now() < deadline:
            time.sleep(0.001)

    def advance(self, dt: float) -> None:
        target = self.now() + dt
        while True:
            with self._lock:
                if self._timers and self._timers[0][0] <= target:
                    due, _seq, fn, handle = heapq.heappop(self._timers)
                    self._t = max(self._t, due)
                else:
                    self._t = target
                    return
            if not handle.cancelled:
                fn()
