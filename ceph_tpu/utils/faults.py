"""FaultSet: central, seed-deterministic fault-injection registry.

Every layer that used to hand-roll its own injection (the messenger's
``ms_inject_socket_failures`` 1-in-N roll, MemStore's
``inject_eio_probability``) now asks ONE process-wide registry instead.
That buys three properties the scattered hooks never had:

  * **targetable** — rules are scoped by entity glob ("osd.3",
    "osd.*", "client.*") and, for stores, by object-name glob, so a
    test can partition exactly two daemons or EIO exactly one shard
    instead of spraying randomness everywhere;
  * **deterministic** — all randomness flows through named streams
    derived from one seed (per-entity streams, so one daemon's
    decision sequence does not depend on another thread's
    interleaving); the same seed and the same per-entity call order
    reproduce the same fault schedule;
  * **runtime-operable** — rules install/clear through the daemons'
    admin sockets ("faults install/clear/dump") and through
    ``injectargs --faultset-rules ...`` (config observer), the same
    surface the reference exposes for its ms_inject_* knobs.

Rule types (the teuthology thrasher vocabulary, reduced):

  partition(a, b, symmetric=True)   no traffic a->b (and b->a)
  drop(dst, prob, src="*")          message loss on the send path
  delay(dst, secs, prob, src="*")   extra latency on the send path
  socket_kill(dst, one_in, src="*") kill 1-in-N sends' connections
  store_eio(osd, oid_glob, prob)    targeted EIO on store reads
  tpu_device_error(prob, device)    EC device dispatch fails; device
                                    "*" degrades the plugin to the
                                    host matrix-codec path + health
                                    WARN, a device-index glob
                                    quarantines just that chip's
                                    pipeline lane (redrain to the
                                    surviving chips)
  crash(site_glob, prob, owner)     simulated power loss at a named
                                    crash point threaded through the
                                    write path (journal.pre_fsync,
                                    journal.post_fsync,
                                    journal.mid_apply,
                                    snapshot.mid_write,
                                    snapshot.pre_rename, pglog.append,
                                    store.pre_apply, store.post_apply,
                                    the BlockStore deferred-write WAL
                                    sites wal.pre_kv_commit,
                                    wal.post_kv_commit, wal.mid_apply,
                                    wal.pre_trim, alloc.mid_cow, and
                                    the mon-store paxos sites
                                    paxos.pre_commit, paxos.mid_commit,
                                    paxos.post_accept_pre_ack): the
                                    store freezes (no further
                                    mutation reaches disk) and the
                                    owning daemon aborts without
                                    acking.  ONE-SHOT: the rule
                                    removes itself after firing, so a
                                    restart of the crashed daemon does
                                    not immediately re-crash.
  fsync_reorder(prob, owner)        arms the ALICE reordering model
                                    for crashes on matching owners:
                                    writes buffered BETWEEN fsync
                                    barriers may survive out of order
                                    (durable B, lost earlier A) — the
                                    crashing store keeps a seeded
                                    SUBSET of its un-fsync'd writes
                                    instead of a prefix.  Consumed
                                    together with the crash rule that
                                    fires (one-shot).

The module-level singleton (``faults.get()``) is what the wired layers
consult; tests that want isolation can swap it with ``set_global()``
or simply ``get().reset()`` between cases.
"""

from __future__ import annotations

import threading
import zlib
from fnmatch import fnmatchcase
from random import Random
from typing import Callable


def _match(pattern: str, entity: str) -> bool:
    return pattern == "*" or fnmatchcase(entity, pattern)


class CrashPoint(Exception):
    """Simulated power loss: a crash rule fired at a named crash site.

    Deliberately NOT a StoreError — the write paths' StoreError
    handlers reply to the client, and a crash must never ack or nack:
    the op simply dies with the daemon, exactly like a kill -9 between
    the disk write and the reply.  Propagates to the op worker, which
    drops it quietly (the daemon is already aborting)."""


class FaultRule:
    __slots__ = ("id", "kind", "params", "source", "hits")

    def __init__(self, rid: int, kind: str, params: dict,
                 source: str = "api"):
        self.id = rid
        self.kind = kind
        self.params = params
        self.source = source
        self.hits = 0

    def dump(self) -> dict:
        return {"id": self.id, "kind": self.kind, "source": self.source,
                "hits": self.hits, **self.params}

    def __repr__(self):
        return f"FaultRule({self.id}, {self.kind}, {self.params})"


class FaultSet:
    def __init__(self, seed: int = 0):
        self._lock = threading.RLock()
        self._seed = int(seed)
        self._rules: dict[int, FaultRule] = {}
        self._next_id = 1
        self._streams: dict[str, Random] = {}
        # per-kind fast-path flags: the messenger consults this on
        # EVERY frame, so "no rules installed" must cost one attribute
        # read, not a lock + scan
        self._have_net = False
        self._have_store = False
        self._have_tpu = False
        self._have_crash = False
        self._have_reorder = False
        # bounded trace of fired faults, for post-mortem + repro checks
        self._trace: list[tuple] = []
        self._trace_cap = 10000

    # -- seeding -----------------------------------------------------------

    @property
    def seed(self) -> int:
        return self._seed

    def reseed(self, seed: int) -> None:
        """Reset all decision streams to a fresh seed (rules stay)."""
        with self._lock:
            self._seed = int(seed)
            self._streams.clear()
            self._trace.clear()

    def reset(self, seed: int | None = None) -> None:
        """Clear every rule and decision stream (test isolation)."""
        with self._lock:
            self._rules.clear()
            self._streams.clear()
            self._trace.clear()
            if seed is not None:
                self._seed = int(seed)
            self._refresh_flags()

    def _stream(self, name: str) -> Random:
        rng = self._streams.get(name)
        if rng is None:
            rng = self._streams[name] = Random(
                (self._seed << 32) ^ zlib.crc32(name.encode()))
        return rng

    def _note(self, *event) -> None:
        if len(self._trace) < self._trace_cap:
            self._trace.append(event)

    def trace(self) -> list[tuple]:
        with self._lock:
            return list(self._trace)

    # -- rule installation -------------------------------------------------

    def _add(self, kind: str, params: dict, source: str = "api") -> int:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._rules[rid] = FaultRule(rid, kind, params, source)
            self._refresh_flags()
            return rid

    def _refresh_flags(self) -> None:
        kinds = {r.kind for r in self._rules.values()}
        self._have_net = bool(kinds & {"partition", "drop", "delay",
                                       "socket_kill"})
        self._have_store = "store_eio" in kinds
        self._have_tpu = "tpu_device_error" in kinds
        self._have_crash = "crash" in kinds
        self._have_reorder = "fsync_reorder" in kinds

    def partition(self, a: str, b: str, symmetric: bool = True,
                  source: str = "api") -> int:
        """Block all traffic a->b (and b->a when symmetric)."""
        return self._add("partition", {"a": a, "b": b,
                                       "symmetric": bool(symmetric)},
                         source)

    def drop(self, dst: str, prob: float, src: str = "*",
             source: str = "api") -> int:
        """Silently lose sent messages src->dst with probability."""
        return self._add("drop", {"src": src, "dst": dst,
                                  "prob": float(prob)}, source)

    def delay(self, dst: str, secs: float, prob: float = 1.0,
              src: str = "*", source: str = "api") -> int:
        """Add latency to sends src->dst."""
        return self._add("delay", {"src": src, "dst": dst,
                                   "secs": float(secs),
                                   "prob": float(prob)}, source)

    def socket_kill(self, dst: str, one_in: int, src: str = "*",
                    source: str = "api") -> int:
        """Kill 1-in-N sends' connections (the ms_inject_socket_failures
        semantics, but targetable)."""
        return self._add("socket_kill", {"src": src, "dst": dst,
                                         "one_in": int(one_in)}, source)

    def store_eio(self, osd: str, oid_glob: str = "*",
                  prob: float = 1.0, source: str = "api") -> int:
        """EIO on store reads of matching objects on matching daemons."""
        return self._add("store_eio", {"osd": osd, "oid": oid_glob,
                                       "prob": float(prob)}, source)

    def tpu_device_error(self, prob: float = 1.0, device: str = "*",
                         source: str = "api") -> int:
        """Fail EC device dispatch; untargeted (device="*") the tpu
        plugin must degrade to the host matrix-codec path, not error
        the op.  `device` may glob a device INDEX (e.g. "3"): the EC
        pipeline then quarantines only that chip's dispatch lane and
        redrains its work onto the surviving chips."""
        return self._add("tpu_device_error",
                         {"prob": float(prob), "device": str(device)},
                         source)

    def crash(self, site: str = "*", prob: float = 1.0,
              owner: str = "osd.*", source: str = "api") -> int:
        """Simulated power loss at crash points matching `site` on
        daemons matching `owner`.  The firing store freezes (nothing
        after the site's disk state reaches disk) and the daemon
        aborts without acking.  One-shot: the rule removes itself
        after firing."""
        return self._add("crash", {"site": str(site),
                                   "prob": float(prob),
                                   "owner": str(owner)}, source)

    def fsync_reorder(self, prob: float = 1.0, owner: str = "*",
                      source: str = "api") -> int:
        """Arm the fsync-reordering model for crashes on `owner`: the
        next crash keeps a seeded SUBSET of the writes buffered since
        the last fsync barrier instead of a contiguous prefix (ALICE's
        reordering vulnerability window: durable B, lost earlier A).
        One-shot: consumed together with the crash that uses it."""
        return self._add("fsync_reorder", {"prob": float(prob),
                                           "owner": str(owner)}, source)

    def clear(self, rule_id: int | None = None,
              source: str | None = None) -> int:
        """Remove one rule by id, all rules from a source, or all."""
        with self._lock:
            if rule_id is not None:
                removed = 1 if self._rules.pop(int(rule_id), None) else 0
            elif source is not None:
                victims = [r for r, rule in self._rules.items()
                           if rule.source == source]
                for r in victims:
                    del self._rules[r]
                removed = len(victims)
            else:
                removed = len(self._rules)
                self._rules.clear()
            self._refresh_flags()
            return removed

    def rules(self) -> list[FaultRule]:
        with self._lock:
            return list(self._rules.values())

    def dump(self) -> dict:
        with self._lock:
            return {"seed": self._seed,
                    "rules": [r.dump() for r in self._rules.values()],
                    "fired": len(self._trace)}

    # -- spec parsing (injectargs / admin-socket surface) ------------------
    #
    # A spec is ';'-separated rules:
    #   partition osd.1 osd.2 [oneway]
    #   drop <dst-glob> <prob> [src-glob]
    #   delay <dst-glob> <secs> [prob] [src-glob]
    #   kill <dst-glob> <one_in> [src-glob]
    #   eio <osd-glob> <oid-glob> [prob]
    #   tpu_error <prob> [device-index-glob]
    #   crash <prob> <site-glob> [owner-glob]
    # install_from_spec REPLACES all rules previously installed from the
    # same source, so re-applying a config value is idempotent.

    def install_from_spec(self, spec: str, source: str = "conf"
                          ) -> list[int]:
        rules: list[tuple] = []
        for part in (spec or "").split(";"):
            toks = part.split()
            if not toks:
                continue
            kind, args = toks[0], toks[1:]
            if kind == "partition" and len(args) >= 2:
                rules.append(("partition",
                              dict(a=args[0], b=args[1],
                                   symmetric="oneway" not in args[2:])))
            elif kind == "drop" and len(args) >= 2:
                rules.append(("drop", dict(
                    dst=args[0], prob=float(args[1]),
                    src=args[2] if len(args) > 2 else "*")))
            elif kind == "delay" and len(args) >= 2:
                rules.append(("delay", dict(
                    dst=args[0], secs=float(args[1]),
                    prob=float(args[2]) if len(args) > 2 else 1.0,
                    src=args[3] if len(args) > 3 else "*")))
            elif kind == "kill" and len(args) >= 2:
                rules.append(("socket_kill", dict(
                    dst=args[0], one_in=int(args[1]),
                    src=args[2] if len(args) > 2 else "*")))
            elif kind == "eio" and len(args) >= 2:
                rules.append(("store_eio", dict(
                    osd=args[0], oid_glob=args[1],
                    prob=float(args[2]) if len(args) > 2 else 1.0)))
            elif kind == "tpu_error" and len(args) >= 1:
                rules.append(("tpu_device_error", dict(
                    prob=float(args[0]),
                    device=args[1] if len(args) > 1 else "*")))
            elif kind == "crash" and len(args) >= 2:
                rules.append(("crash", dict(
                    prob=float(args[0]), site=args[1],
                    owner=args[2] if len(args) > 2 else "osd.*")))
            elif kind == "reorder" and len(args) >= 1:
                rules.append(("fsync_reorder", dict(
                    prob=float(args[0]),
                    owner=args[1] if len(args) > 1 else "*")))
            else:
                raise ValueError(f"bad fault rule {part.strip()!r}")
        with self._lock:
            self.clear(source=source)
            return [getattr(self, kind)(source=source, **kw)
                    for kind, kw in rules]

    # -- decision hooks (the wired layers call these) ----------------------

    def partitioned(self, src: str, dst: str) -> bool:
        if not self._have_net:
            return False
        with self._lock:
            for rule in self._rules.values():
                if rule.kind != "partition":
                    continue
                p = rule.params
                if (_match(p["a"], src) and _match(p["b"], dst)) or (
                        p["symmetric"] and _match(p["a"], dst)
                        and _match(p["b"], src)):
                    rule.hits += 1
                    return True
        return False

    def should_drop(self, src: str, dst: str) -> bool:
        if not self._have_net:
            return False
        with self._lock:
            for rule in self._rules.values():
                if rule.kind != "drop":
                    continue
                p = rule.params
                if _match(p["src"], src) and _match(p["dst"], dst) and \
                        self._stream(f"net:{src}").random() < p["prob"]:
                    rule.hits += 1
                    self._note("drop", src, dst)
                    return True
        return False

    def send_delay(self, src: str, dst: str) -> float:
        if not self._have_net:
            return 0.0
        total = 0.0
        with self._lock:
            for rule in self._rules.values():
                if rule.kind != "delay":
                    continue
                p = rule.params
                if _match(p["src"], src) and _match(p["dst"], dst) and \
                        self._stream(f"net:{src}").random() < p["prob"]:
                    rule.hits += 1
                    total += p["secs"]
            if total:
                self._note("delay", src, dst, total)
        return total

    def should_kill_socket(self, src: str, dst: str,
                           conf_one_in: int = 0) -> bool:
        """Combines the legacy ms_inject_socket_failures config knob
        (the caller passes its value) with targeted socket_kill rules;
        all randomness comes from this registry's seeded streams."""
        if not self._have_net and not conf_one_in:
            return False
        with self._lock:
            rng = self._stream(f"net:{src}")
            if conf_one_in and rng.randrange(int(conf_one_in)) == 0:
                self._note("socket_kill", src, dst, "conf")
                return True
            for rule in self._rules.values():
                if rule.kind != "socket_kill":
                    continue
                p = rule.params
                if _match(p["src"], src) and _match(p["dst"], dst) and \
                        p["one_in"] > 0 and \
                        rng.randrange(p["one_in"]) == 0:
                    rule.hits += 1
                    self._note("socket_kill", src, dst, rule.id)
                    return True
        return False

    def recv_delay(self, src: str, dst: str, conf_prob: float,
                   conf_max: float) -> float:
        """Legacy ms_inject_delay_* knobs, seeded centrally."""
        if not conf_prob:
            return 0.0
        with self._lock:
            rng = self._stream(f"net:{dst}")
            if rng.random() < conf_prob:
                return rng.random() * conf_max
        return 0.0

    def should_store_eio(self, owner: str, oid: str,
                         conf_prob: float = 0.0) -> bool:
        if not self._have_store and not conf_prob:
            return False
        with self._lock:
            rng = self._stream(f"store:{owner or '?'}")
            if conf_prob and rng.random() < conf_prob:
                self._note("store_eio", owner, oid, "conf")
                return True
            for rule in self._rules.values():
                if rule.kind != "store_eio":
                    continue
                p = rule.params
                if _match(p["osd"], owner) and _match(p["oid"], oid) \
                        and rng.random() < p["prob"]:
                    rule.hits += 1
                    self._note("store_eio", owner, oid, rule.id)
                    return True
        return False

    def tpu_error(self, device=None) -> bool:
        """Roll the TPU device-error rules.

        device=None is the untargeted query (plugin route guard, the
        whole-device degrade): only device="*" rules match it.  A
        device INDEX (the pipeline asks per dispatch lane) matches
        both "*" rules and rules targeting that index — a targeted
        rule never fires outside its chip, so one bad chip of eight
        quarantines one lane instead of degrading the codec."""
        if not self._have_tpu:
            return False
        with self._lock:
            for rule in self._rules.values():
                if rule.kind != "tpu_device_error":
                    continue
                pat = rule.params.get("device", "*")
                if device is None:
                    if pat != "*":
                        continue
                elif not _match(pat, str(device)):
                    continue
                if self._stream("tpu").random() < rule.params["prob"]:
                    rule.hits += 1
                    self._note("tpu_device_error", rule.id, device)
                    return True
        return False

    def should_crash(self, owner: str, site: str) -> bool:
        """Roll the crash rules for a named crash point on `owner`.

        A firing rule is ONE-SHOT — it removes itself — so the crashed
        daemon can be restarted against the same FaultSet without
        instantly crashing again (the Jepsen kill-restart cycle needs
        exactly one kill per installed rule)."""
        if not self._have_crash:
            return False
        with self._lock:
            fired = None
            for rule in self._rules.values():
                if rule.kind != "crash":
                    continue
                p = rule.params
                if _match(p["site"], site) and \
                        _match(p["owner"], owner or "?") and \
                        self._stream(f"crash:{owner or '?'}").random() \
                        < p["prob"]:
                    rule.hits += 1
                    self._note("crash", owner, site, rule.id)
                    fired = rule.id
                    break
            if fired is not None:
                del self._rules[fired]
                self._refresh_flags()
                return True
        return False

    def torn_keep_fraction(self, owner: str) -> float:
        """Seeded fraction of an un-fsynced write that survives a
        crash (the ALICE torn-write model): the store truncates the
        tail to this fraction before freezing, so the same seed
        reproduces the same torn record byte-for-byte."""
        with self._lock:
            return self._stream(f"crash:{owner or '?'}").random()

    def crash_tracking_armed(self, owner: str) -> bool:
        """Should `owner`'s store pay for crash bookkeeping (pre-image
        capture for the reordering model)?  True only when a crash or
        fsync_reorder rule could actually fire on this owner — a
        mon-only rule must not tax every OSD store's write path."""
        if not self._have_crash and not self._have_reorder:
            return False
        with self._lock:
            for rule in self._rules.values():
                if rule.kind == "crash" and \
                        _match(rule.params["owner"], owner or "?"):
                    return True
                if rule.kind == "fsync_reorder" and \
                        _match(rule.params["owner"], owner or "?"):
                    return True
        return False

    def torn_ops(self, owner: str, ops: list) -> tuple[list, bool]:
        """The ALICE torn-write model applied to a transaction's op
        list: returns (surviving ops, reorder_used).  With an
        fsync_reorder rule armed (consumed here, one-shot) a seeded
        SUBSET survives — out-of-order durability; otherwise a seeded
        prefix.  Shared by every store that tears KV commits."""
        if self.reorder_armed(owner):
            mask = self.torn_survivors(owner, len(ops))
            return [op for op, keep in zip(ops, mask) if keep], True
        keep = int(self.torn_keep_fraction(owner) * len(ops))
        return list(ops[:keep]), False

    def reorder_armed(self, owner: str) -> bool:
        """Consume an fsync_reorder rule for `owner`, if one matches:
        the crash firing right now should keep a seeded SUBSET of the
        un-fsync'd writes (out-of-order survival) instead of a prefix.
        One-shot, like the crash rule it rides with."""
        with self._lock:
            fired = None
            for rule in self._rules.values():
                if rule.kind != "fsync_reorder":
                    continue
                p = rule.params
                if _match(p["owner"], owner or "?") and \
                        self._stream(f"crash:{owner or '?'}").random() \
                        < p["prob"]:
                    rule.hits += 1
                    self._note("fsync_reorder", owner, rule.id)
                    fired = rule.id
                    break
            if fired is not None:
                del self._rules[fired]
                self._refresh_flags()
                return True
        return False

    def torn_survivors(self, owner: str, n: int) -> list[bool]:
        """Seeded per-write survival mask for the reordering model: of
        `n` writes buffered since the last fsync barrier, which landed
        on disk before power was lost.  Independent coin flips, so
        "durable B, lost earlier A" windows occur; deterministic per
        seed + owner call order."""
        with self._lock:
            rng = self._stream(f"crash:{owner or '?'}")
            return [rng.random() < 0.5 for _ in range(n)]

    # -- admin-socket glue -------------------------------------------------

    def register_asok(self, asok) -> None:
        """Hook the faults surface onto a daemon's AdminSocket."""
        asok.register("faults dump", lambda c: self.dump())
        asok.register(
            "faults install",
            lambda c: {"installed": self.install_from_spec(
                c.get("rules", ""), source=c.get("source", "asok"))})
        asok.register(
            "faults clear",
            lambda c: {"removed": self.clear(
                rule_id=c.get("id"), source=c.get("source"))})
        asok.register(
            "faults reseed",
            lambda c: (self.reseed(int(c.get("seed", 0))),
                       {"seed": self.seed})[1])


_global = FaultSet()


def get() -> FaultSet:
    return _global


def set_global(fs: FaultSet) -> FaultSet:
    global _global
    prev, _global = _global, fs
    return prev


def conf_observer() -> Callable:
    """A Config observer applying faultset_seed/faultset_rules; daemons
    register it so `injectargs --faultset-rules '...'` takes effect."""
    def handler(conf, changed: set[str]) -> None:
        if "faultset_seed" in changed:
            get().reseed(int(conf.faultset_seed))
        if "faultset_rules" in changed:
            get().install_from_spec(str(conf.faultset_rules),
                                    source="conf")
    return handler
