"""denc — data-only, versioned binary encoding for wire and disk.

The analog of the reference's encode/decode discipline
(/root/reference/src/include/encoding.h and the per-struct
``encode(..., bufferlist&)`` + ``DECODE_START(v, bl)`` idiom): every
frame is explicit, versioned, and decoding hostile bytes can only ever
produce plain data or a registered struct type — never code execution
(unlike pickle, which this replaces).

Model:
  * primitives: None, bool, int (zigzag varint), float, bytes, str,
    list, tuple, dict, set, numpy ndarray (dtype+shape+raw bytes);
  * struct types opt in via ``@denc_type`` and are encoded as
    (type name, version, field dict). Decode looks the name up in the
    registry — unknown names and bad tags raise ``DencError``;
  * versioning: a class bumps ``DENC_VERSION`` when its fields change;
    decode of a *newer* version than the running code raises (same
    contract as DECODE_START's compat check); decode of an *older*
    version calls ``_denc_upgrade(fields, version)`` — which must be a
    ``@staticmethod`` (or classmethod): it runs before any instance
    exists.

Corrupt or truncated input raises ``DencError`` — never an arbitrary
exception from deep inside, and never attribute access on untrusted
objects.
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np


class DencError(ValueError):
    pass


# one-byte tags
T_NONE = 0x00
T_TRUE = 0x01
T_FALSE = 0x02
T_INT = 0x03
T_FLOAT = 0x04
T_BYTES = 0x05
T_STR = 0x06
T_LIST = 0x07
T_TUPLE = 0x08
T_DICT = 0x09
T_SET = 0x0A
T_NDARRAY = 0x0B
T_OBJ = 0x0C

_F64 = struct.Struct("<d")

_registry: dict[str, type] = {}


def denc_type(klass: type) -> type:
    """Class decorator: make a struct type encodable/decodable.

    Encodes the instance ``__dict__`` (minus keys starting with "_").
    Override points: ``DENC_VERSION`` (int, default 1),
    ``_denc_fields()`` -> dict, ``_denc_upgrade(fields, version)``.
    """
    name = klass.__name__
    existing = _registry.get(name)
    if existing is not None and existing is not klass:
        raise ValueError(f"denc type name collision: {name}")
    _registry[name] = klass
    return klass


def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _big(n: int) -> int:
    # arbitrary-precision zigzag: non-negatives even, negatives odd
    return (n << 1) if n >= 0 else ((-n) << 1) - 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _encode(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(T_NONE)
    elif obj is True:
        out.append(T_TRUE)
    elif obj is False:
        out.append(T_FALSE)
    elif type(obj) is int:
        out.append(T_INT)
        out += _uvarint(_big(obj))
    elif type(obj) is float:
        out.append(T_FLOAT)
        out += _F64.pack(obj)
    elif type(obj) is bytes or type(obj) is bytearray or \
            type(obj) is memoryview:
        b = bytes(obj)
        out.append(T_BYTES)
        out += _uvarint(len(b))
        out += b
    elif type(obj) is str:
        b = obj.encode("utf-8")
        out.append(T_STR)
        out += _uvarint(len(b))
        out += b
    elif type(obj) is list:
        out.append(T_LIST)
        out += _uvarint(len(obj))
        for v in obj:
            _encode(v, out)
    elif type(obj) is tuple:
        out.append(T_TUPLE)
        out += _uvarint(len(obj))
        for v in obj:
            _encode(v, out)
    elif type(obj) is dict:
        out.append(T_DICT)
        out += _uvarint(len(obj))
        for k, v in obj.items():
            _encode(k, out)
            _encode(v, out)
    elif type(obj) is set or type(obj) is frozenset:
        out.append(T_SET)
        out += _uvarint(len(obj))
        for v in obj:
            _encode(v, out)
    elif isinstance(obj, np.integer):
        out.append(T_INT)
        out += _uvarint(_big(int(obj)))
    elif isinstance(obj, np.floating):
        out.append(T_FLOAT)
        out += _F64.pack(float(obj))
    elif isinstance(obj, np.ndarray):
        dt = obj.dtype.str.encode()
        raw = np.ascontiguousarray(obj).tobytes()
        out.append(T_NDARRAY)
        out += _uvarint(len(dt))
        out += dt
        out += _uvarint(obj.ndim)
        for d in obj.shape:
            out += _uvarint(d)
        out += _uvarint(len(raw))
        out += raw
    else:
        klass = type(obj)
        if _registry.get(klass.__name__) is not klass:
            raise DencError(
                f"type {klass.__name__} is not denc-encodable "
                f"(register with @denc_type)")
        if hasattr(obj, "_denc_fields"):
            fields = obj._denc_fields()
        elif isinstance(obj, tuple) and hasattr(klass, "_fields"):
            fields = dict(zip(klass._fields, obj))   # NamedTuple
        else:
            fields = {k: v for k, v in obj.__dict__.items()
                      if not k.startswith("_")}
        name = klass.__name__.encode()
        out.append(T_OBJ)
        out += _uvarint(len(name))
        out += name
        out += _uvarint(getattr(klass, "DENC_VERSION", 1))
        _encode(fields, out)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise DencError("truncated input")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def byte(self) -> int:
        if self.pos >= len(self.buf):
            raise DencError("truncated input")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def uvarint(self) -> int:
        shift = 0
        n = 0
        while True:
            b = self.byte()
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
            if shift > 600:
                raise DencError("varint too long")


def _decode(r: _Reader, depth: int = 0) -> Any:
    if depth > 100:
        raise DencError("nesting too deep")
    tag = r.byte()
    if tag == T_NONE:
        return None
    if tag == T_TRUE:
        return True
    if tag == T_FALSE:
        return False
    if tag == T_INT:
        return _unzigzag(r.uvarint())
    if tag == T_FLOAT:
        return _F64.unpack(r.take(8))[0]
    if tag == T_BYTES:
        return r.take(r.uvarint())
    if tag == T_STR:
        try:
            return r.take(r.uvarint()).decode("utf-8")
        except UnicodeDecodeError as e:
            raise DencError(f"bad utf-8: {e}") from None
    if tag == T_LIST:
        return [_decode(r, depth + 1) for _ in range(r.uvarint())]
    if tag == T_TUPLE:
        return tuple(_decode(r, depth + 1) for _ in range(r.uvarint()))
    if tag == T_DICT:
        n = r.uvarint()
        d = {}
        for _ in range(n):
            k = _decode(r, depth + 1)
            try:
                d[k] = _decode(r, depth + 1)
            except TypeError as e:
                raise DencError(f"unhashable dict key: {e}") from None
        return d
    if tag == T_SET:
        try:
            return {_decode(r, depth + 1) for _ in range(r.uvarint())}
        except TypeError as e:
            raise DencError(f"unhashable set member: {e}") from None
    if tag == T_NDARRAY:
        dt = r.take(r.uvarint()).decode("ascii", "replace")
        try:
            dtype = np.dtype(dt)
        except TypeError as e:
            raise DencError(f"bad dtype {dt!r}: {e}") from None
        if dtype.hasobject:
            raise DencError("object dtypes are not decodable")
        ndim = r.uvarint()
        if ndim > 32:
            raise DencError("too many dimensions")
        shape = tuple(r.uvarint() for _ in range(ndim))
        raw = r.take(r.uvarint())
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if dtype.itemsize * count != len(raw):
            raise DencError("ndarray payload size mismatch")
        return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
    if tag == T_OBJ:
        name = r.take(r.uvarint()).decode("utf-8", "replace")
        version = r.uvarint()
        klass = _registry.get(name)
        if klass is None:
            raise DencError(f"unknown denc type {name!r}")
        fields = _decode(r, depth + 1)
        if not isinstance(fields, dict):
            raise DencError(f"bad field container for {name}")
        code_version = getattr(klass, "DENC_VERSION", 1)
        if version > code_version:
            raise DencError(
                f"{name} v{version} is newer than supported v{code_version}")
        if version < code_version:
            upgrade = getattr(klass, "_denc_upgrade", None)
            if upgrade is None:
                raise DencError(
                    f"{name} v{version} has no upgrade path to "
                    f"v{code_version}")
            try:
                fields = upgrade(fields, version)
            except TypeError as e:
                raise DencError(
                    f"{name}._denc_upgrade must be a "
                    f"staticmethod/classmethod taking (fields, version): "
                    f"{e}") from None
            if not isinstance(fields, dict):
                raise DencError(f"{name}._denc_upgrade returned non-dict")
        if isinstance(klass, type) and issubclass(klass, tuple) and \
                hasattr(klass, "_fields"):
            try:
                return klass(**fields)               # NamedTuple
            except TypeError as e:
                raise DencError(f"bad fields for {name}: {e}") from None
        obj = klass.__new__(klass)
        obj.__dict__.update(fields)
        if hasattr(obj, "_denc_finish"):
            obj._denc_finish()
        return obj
    raise DencError(f"bad tag 0x{tag:02x}")


def dumps(obj: Any) -> bytes:
    out = bytearray()
    _encode(obj, out)
    return bytes(out)


def loads(buf: bytes) -> Any:
    r = _Reader(bytes(buf))
    obj = _decode(r)
    if r.pos != len(r.buf):
        raise DencError(f"{len(r.buf) - r.pos} trailing bytes")
    return obj
