"""Thread pools with per-shard ordering + stuck-thread watchdog.

Analogs of common/WorkQueue.h (ThreadPool, ShardedThreadPool: the OSD's
op execution uses N shards, each single-threaded per ordering domain so
ops for one PG never reorder) and common/HeartbeatMap.h (each worker
carries a grace/suicide deadline; an expired grace flags unhealthy, an
expired suicide aborts the process — crash-to-recover).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable

from .faults import CrashPoint


class HeartbeatMap:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles: dict[str, tuple[float, float, float]] = {}
        # name -> (deadline, grace, suicide_deadline)

    def reset_timeout(self, name: str, grace: float,
                      suicide_grace: float = 0.0) -> None:
        now = time.monotonic()
        with self._lock:
            self._handles[name] = (
                now + grace, grace,
                now + suicide_grace if suicide_grace else 0.0)

    def clear_timeout(self, name: str) -> None:
        with self._lock:
            self._handles.pop(name, None)

    def is_healthy(self) -> bool:
        now = time.monotonic()
        healthy = True
        with self._lock:
            for name, (deadline, grace, suicide) in self._handles.items():
                if deadline and now > deadline:
                    healthy = False
                if suicide and now > suicide:
                    # crash-to-recover, like HeartbeatMap suicide_grace
                    os._exit(1)
        return healthy


class QosQueue:
    """A queue.Queue-surface (put/get/task_done/join) whose dequeue
    order is dmClock-scheduled across client classes.

    Items enqueue FIFO per client; each ``get`` runs one dmClock
    service opportunity over the queued clients' heads (see
    utils/dmclock.py): reserved clients are served on their due tags,
    unconstrained work (no spec — internal ops, pools without QoS
    conf) keeps exact FIFO order, and a limit-throttled queue makes
    the worker SLEEP rather than serve above the cap.  The
    ``DmClockState`` is shared across every shard's QosQueue so the
    configured rates are per-daemon truths, not per-shard fractions.
    """

    def __init__(self, state):
        self._state = state
        self._cv = threading.Condition()
        self._qs: dict[str | None, "queue.deque"] = {}
        self._unfinished = 0

    def put(self, item, client: str | None = None,
            cost: float = 1.0) -> None:
        from collections import deque
        import time as _time
        with self._cv:
            q = self._qs.get(client)
            if q is None:
                q = self._qs[client] = deque()
            q.append((item, _time.monotonic(), float(cost)))
            self._unfinished += 1
            self._cv.notify()

    def get(self, timeout: float | None = None):
        import time as _time
        deadline = (_time.monotonic() + timeout) if timeout else None
        with self._cv:
            while True:
                cands = {c: q[0][1] for c, q in self._qs.items() if q}
                now = _time.monotonic()
                wait = None
                if cands:
                    def _key(c):
                        return c if c is not None else "_system"
                    client, _phase, wake = self._state.pick(
                        {_key(c): t for c, t in cands.items()}, now,
                        # bytes-weighted: each candidate's HEAD cost
                        # advances its tags on a grant
                        costs={_key(c): self._qs[c][0][2]
                               for c in cands})
                    if client is not None:
                        key = None if client == "_system" \
                            and None in cands else client
                        item, _t, _cost = self._qs[key].popleft()
                        return item
                    # every queued client over its limit: hold off
                    self._state.note_stall()
                    wait = max(0.001, wake - now)
                if deadline is not None:
                    remain = deadline - now
                    if remain <= 0:
                        raise queue.Empty
                    wait = remain if wait is None else min(wait, remain)
                self._cv.wait(wait)

    def task_done(self) -> None:
        with self._cv:
            self._unfinished -= 1
            if self._unfinished <= 0:
                self._cv.notify_all()

    def join(self) -> None:
        with self._cv:
            while self._unfinished > 0:
                self._cv.wait()

    def qsize(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._qs.values())


class ThreadPool:
    """Simple FIFO pool; work items are callables.  With a
    ``qos_state`` (utils/dmclock.DmClockState) the internal queue is
    a :class:`QosQueue` and ``queue`` accepts a ``qos=`` client tag."""

    def __init__(self, name: str, num_threads: int = 2,
                 hbmap: HeartbeatMap | None = None, grace: float = 60.0,
                 qos_state=None):
        self.name = name
        self._q = QosQueue(qos_state) if qos_state is not None \
            else queue.Queue()
        self._qos = qos_state is not None
        self._stop = False
        self.hbmap = hbmap
        self.grace = grace
        self._threads = [
            threading.Thread(target=self._worker, name=f"{name}-{i}",
                             daemon=True)
            for i in range(num_threads)]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def queue(self, fn: Callable, *args, qos: str | None = None,
              qos_cost: float = 1.0) -> None:
        if self._qos:
            self._q.put((fn, args), client=qos, cost=qos_cost)
        else:
            self._q.put((fn, args))

    def _worker(self) -> None:
        me = threading.current_thread().name
        while not self._stop:
            try:
                fn, args = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if self.hbmap:
                self.hbmap.reset_timeout(me, self.grace)
            try:
                fn(*args)
            except Exception as e:  # a work item must never kill its worker
                # a fired crash point unwinds through here by design:
                # the daemon is aborting, the op must die silently
                # (never ack) — not spam a traceback per in-flight op
                if not isinstance(e, CrashPoint):
                    import traceback
                    traceback.print_exc()
            finally:
                if self.hbmap:
                    self.hbmap.clear_timeout(me)
                self._q.task_done()

    def drain(self) -> None:
        self._q.join()

    def stop(self) -> None:
        self._stop = True
        # wake idle workers NOW: they poll the queue at 0.2s, and a
        # daemon stops its pools sequentially, so without a nudge a
        # teardown costs O(pools x poll interval) of pure waiting.
        # The no-op rides the normal item path (executed, task_done),
        # so pending-work semantics at stop are unchanged.
        for t in self._threads:
            if t.is_alive():
                self.queue(_stop_nudge)
        for t in self._threads:
            t.join(timeout=2)


def _stop_nudge() -> None:
    pass


class ShardedThreadPool:
    """N independent single-thread shards; same-key work never reorders.

    The ShardedOpWQ pattern (osd/OSD.cc:8802): work is enqueued by an
    ordering key (e.g. pg id); key -> shard by hash.
    """

    def __init__(self, name: str, num_shards: int = 5,
                 hbmap: HeartbeatMap | None = None, grace: float = 60.0,
                 qos_state=None):
        self.name = name
        self.num_shards = num_shards
        # ONE DmClockState across every shard (when given): rates are
        # daemon-global regardless of how pgids hash across shards
        self.qos_state = qos_state
        self._shards = [ThreadPool(f"{name}-s{i}", 1, hbmap, grace,
                                   qos_state=qos_state)
                        for i in range(num_shards)]

    def start(self) -> None:
        for s in self._shards:
            s.start()

    def queue(self, key, fn: Callable, *args,
              qos: str | None = None, qos_cost: float = 1.0) -> None:
        self._shards[hash(key) % self.num_shards].queue(
            fn, *args, qos=qos, qos_cost=qos_cost)

    def drain(self) -> None:
        for s in self._shards:
            s.drain()

    def stop(self) -> None:
        for s in self._shards:
            s.stop()
