"""Thread pools with per-shard ordering + stuck-thread watchdog.

Analogs of common/WorkQueue.h (ThreadPool, ShardedThreadPool: the OSD's
op execution uses N shards, each single-threaded per ordering domain so
ops for one PG never reorder) and common/HeartbeatMap.h (each worker
carries a grace/suicide deadline; an expired grace flags unhealthy, an
expired suicide aborts the process — crash-to-recover).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable

from .faults import CrashPoint


class HeartbeatMap:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles: dict[str, tuple[float, float, float]] = {}
        # name -> (deadline, grace, suicide_deadline)

    def reset_timeout(self, name: str, grace: float,
                      suicide_grace: float = 0.0) -> None:
        now = time.monotonic()
        with self._lock:
            self._handles[name] = (
                now + grace, grace,
                now + suicide_grace if suicide_grace else 0.0)

    def clear_timeout(self, name: str) -> None:
        with self._lock:
            self._handles.pop(name, None)

    def is_healthy(self) -> bool:
        now = time.monotonic()
        healthy = True
        with self._lock:
            for name, (deadline, grace, suicide) in self._handles.items():
                if deadline and now > deadline:
                    healthy = False
                if suicide and now > suicide:
                    # crash-to-recover, like HeartbeatMap suicide_grace
                    os._exit(1)
        return healthy


class ThreadPool:
    """Simple FIFO pool; work items are callables."""

    def __init__(self, name: str, num_threads: int = 2,
                 hbmap: HeartbeatMap | None = None, grace: float = 60.0):
        self.name = name
        self._q: queue.Queue = queue.Queue()
        self._stop = False
        self.hbmap = hbmap
        self.grace = grace
        self._threads = [
            threading.Thread(target=self._worker, name=f"{name}-{i}",
                             daemon=True)
            for i in range(num_threads)]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def queue(self, fn: Callable, *args) -> None:
        self._q.put((fn, args))

    def _worker(self) -> None:
        me = threading.current_thread().name
        while not self._stop:
            try:
                fn, args = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if self.hbmap:
                self.hbmap.reset_timeout(me, self.grace)
            try:
                fn(*args)
            except Exception as e:  # a work item must never kill its worker
                # a fired crash point unwinds through here by design:
                # the daemon is aborting, the op must die silently
                # (never ack) — not spam a traceback per in-flight op
                if not isinstance(e, CrashPoint):
                    import traceback
                    traceback.print_exc()
            finally:
                if self.hbmap:
                    self.hbmap.clear_timeout(me)
                self._q.task_done()

    def drain(self) -> None:
        self._q.join()

    def stop(self) -> None:
        self._stop = True
        for t in self._threads:
            t.join(timeout=2)


class ShardedThreadPool:
    """N independent single-thread shards; same-key work never reorders.

    The ShardedOpWQ pattern (osd/OSD.cc:8802): work is enqueued by an
    ordering key (e.g. pg id); key -> shard by hash.
    """

    def __init__(self, name: str, num_shards: int = 5,
                 hbmap: HeartbeatMap | None = None, grace: float = 60.0):
        self.name = name
        self.num_shards = num_shards
        self._shards = [ThreadPool(f"{name}-s{i}", 1, hbmap, grace)
                        for i in range(num_shards)]

    def start(self) -> None:
        for s in self._shards:
            s.start()

    def queue(self, key, fn: Callable, *args) -> None:
        self._shards[hash(key) % self.num_shards].queue(fn, *args)

    def drain(self) -> None:
        for s in self._shards:
            s.drain()

    def stop(self) -> None:
        for s in self._shards:
            s.stop()
