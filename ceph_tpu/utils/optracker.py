"""Op tracing plane: TrackedOp spans, in-flight/historic dumps,
slow-op accounting, and the crash-scoped flight recorder.

The TrackedOp/OpTracker analog (common/TrackedOp.{h,cc},
osd/OpRequest.cc) grown from an event timeline into a span tracer:

  * every client op carries a **trace id** (``"<client>:<tid>"``) and a
    list of named **spans** — [t0, t1) intervals on the process-wide
    monotonic clock — stamped by every layer the op crosses: messenger
    receive -> op-shard queue wait (dmClock stalls included, tagged
    with the pool service class), execution, EC pipeline phases
    (coalesce wait, H2D staging, device compute, D2H fetch — or the
    host drain), journal/WAL append+fsync, and the replica sub-op
    round trip.  Sub-ops and recovery pushes carry the SAME trace id
    over the wire (a plain CTM2 frame field), so per-daemon dumps
    correlate into one cross-daemon timeline
    (tools/trace_dump.py -> chrome://tracing / Perfetto).
  * two clocks on purpose: ``start``/``age`` ride the daemon's
    injectable Clock (slow-op complaint math stays deterministic under
    the test ManualClock), while span endpoints ride
    ``time.monotonic()`` (real latency attribution; one process-wide
    timebase means per-daemon dumps merge without offset fixups).
  * each tracker keeps a bounded in-flight table, a historic ring
    (``osd_op_history_size`` / ``osd_op_history_duration``) and a
    separate slow-op ring (ops that crossed ``osd_op_complaint_time``),
    behind ``dump_ops_in_flight`` / ``dump_historic_ops`` /
    ``dump_historic_slow_ops``.
  * deep layers attach spans WITHOUT parameter threading: the op shard
    publishes its op via :func:`set_current`, and e.g. the filestore
    journal calls ``with optracker.span("journal"): ...`` — a no-op
    when no op is current (internal work, untracked paths).

The **flight recorder** turns "rerun and hope" into a captured
timeline: daemons register dump callables; when armed (conf
``flight_recorder_dir``) a fired CrashPoint or a DurabilityLedger
verify failure snapshots EVERY registered daemon's in-flight +
historic ops (plus its pg log summaries) into a per-incident
directory, ready for ``tools/trace_dump.py``.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from contextlib import contextmanager

# ---------------------------------------------------------------------------
# thread-local current op: how deep layers (stores, ecutil) attach
# spans to whatever op their thread is executing
# ---------------------------------------------------------------------------

_tls = threading.local()


def set_current(op: "TrackedOp | None") -> None:
    _tls.op = op


def current() -> "TrackedOp | None":
    return getattr(_tls, "op", None)


@contextmanager
def op_context(op: "TrackedOp | None"):
    """Publish `op` as the thread's current op for the block (nested
    publishes restore the outer op on exit)."""
    prev = current()
    set_current(op)
    try:
        yield op
    finally:
        set_current(prev)


@contextmanager
def span(name: str, **args):
    """Stamp a span onto the thread's current op around the block; a
    plain passthrough when nothing is being traced."""
    op = current()
    if op is None:
        yield None
        return
    op.span_begin(name, **args)
    try:
        yield op
    finally:
        op.span_end(name)


def add_span(name: str, t0: float, t1: float, **args) -> None:
    """Attach an externally measured [t0, t1) monotonic interval to
    the current op (pipeline phases measured on other threads)."""
    op = current()
    if op is not None:
        op.add_span(name, t0, t1, **args)


def note_pipeline_phases(ph: dict | None) -> None:
    """Translate one EC pipeline submission's phase stamps (the
    ``trace_phases`` dict the pipeline attaches to its futures) into
    spans on the current op: coalesce wait, H2D staging, device
    compute, D2H fetch — or the host drain — plus a degrade marker
    when the batch was requeued off a quarantined/failed lane."""
    op = current()
    if op is None or not ph:
        return
    sub, picked = ph.get("submit"), ph.get("picked")
    if sub is not None and picked is not None and picked > sub:
        op.add_span("ec.coalesce", sub, picked)
    s0, s1 = ph.get("stage0"), ph.get("stage1")
    if s0 is not None and s1 is not None and s1 > s0:
        op.add_span("ec.stage_h2d", s0, s1)
    c0, c1 = ph.get("collect0"), ph.get("done")
    issue = ph.get("issue")
    if issue is not None and c0 is not None and c0 > issue:
        op.add_span("ec.device_compute", issue, c0)
    if c0 is not None and c1 is not None and c1 > c0:
        op.add_span("ec.d2h", c0, c1)
    h0, h1 = ph.get("host0"), ph.get("host1")
    if h0 is not None and h1 is not None and h1 > h0:
        op.add_span("ec.host_encode", h0, h1)
    if ph.get("requeues"):
        op.mark_event(f"ec_degraded_requeues:{ph['requeues']}")


# ---------------------------------------------------------------------------
# TrackedOp
# ---------------------------------------------------------------------------


class TrackedOp:
    __slots__ = ("desc", "trace_id", "kind", "start", "mstart", "mend",
                 "events", "spans", "_open", "_tracker", "_id", "_done",
                 "_slock")

    def __init__(self, tracker: "OpTracker", desc: str, now: float,
                 trace_id: str = "", kind: str = "client"):
        self._tracker = tracker
        # span/event state is touched from more than one thread (the
        # op shard's execute spans vs a timer/messenger continuation
        # finishing the op, e.g. a notify timeout) — serialize it
        self._slock = threading.Lock()
        self.desc = desc
        self.trace_id = trace_id
        self.kind = kind
        self.start = now                 # tracker clock (age math)
        self.mstart = time.monotonic()   # span timebase
        self.mend: float | None = None
        self._id = 0
        self._done = False
        self.events: list[tuple[float, float, str]] = [
            (now, self.mstart, "initiated")]
        # closed spans: [name, t0, t1, args-or-None] (monotonic)
        self.spans: list[list] = []
        self._open: list[list] = []      # LIFO of open [name, t0, args]

    # -- events ------------------------------------------------------------

    def mark_event(self, event: str) -> None:
        stamp = (self._tracker.clock.now(), time.monotonic(), event)
        with self._slock:
            if self._done:
                return
            self.events.append(stamp)

    # -- spans -------------------------------------------------------------

    def span_begin(self, name: str, _t0: float | None = None,
                   **args) -> None:
        """Open a span; `_t0` backdates its start (the queue span is
        anchored to the op's initiation instant so span coverage has
        no pre-queue bookkeeping hole on sub-millisecond ops)."""
        with self._slock:
            if self._done:
                return
            self._open.append([name, time.monotonic() if _t0 is None
                               else _t0, args or None])

    def span_end(self, name: str | None = None) -> float | None:
        """Close the most recent open span (matching `name` when
        given); a no-op when nothing matches — layers may race the
        op's finish and must never raise.  Returns the close stamp so
        an adjacent span can begin at exactly the same instant."""
        with self._slock:
            if not self._open:
                return None
            idx = len(self._open) - 1
            if name is not None:
                while idx >= 0 and self._open[idx][0] != name:
                    idx -= 1
                if idx < 0:
                    return None
            nm, t0, args = self._open.pop(idx)
            t1 = time.monotonic()
            self.spans.append([nm, t0, t1, args])
            return t1

    def add_span(self, name: str, t0: float, t1: float, **args) -> None:
        with self._slock:
            if self._done:
                return
            self.spans.append([name, float(t0), float(t1),
                               args or None])

    # -- lifecycle ---------------------------------------------------------

    def finish(self) -> None:
        now_m = time.monotonic()
        now_c = self._tracker.clock.now()
        with self._slock:
            if self._done:
                return
            while self._open:                # auto-close (replica_wait
                nm, t0, args = self._open.pop()   # ends at reply)
                self.spans.append([nm, t0, now_m, args])
            self.mend = now_m
            self.events.append((now_c, now_m, "done"))
            self._done = True
        self._tracker._finish(self)

    def age(self, now: float) -> float:
        return now - self.start

    @property
    def duration(self) -> float:
        """Monotonic wall time (so far, for in-flight ops)."""
        return (self.mend if self.mend is not None
                else time.monotonic()) - self.mstart

    def dump(self) -> dict:
        with self._slock:
            events = list(self.events)
            spans = list(self.spans)
        return {"description": self.desc,
                "trace_id": self.trace_id,
                "kind": self.kind,
                "daemon": self._tracker.daemon,
                "initiated_at": self.start,
                "age": self._tracker.clock.now() - self.start,
                "mstart": self.mstart,
                "duration": round(self.duration, 6),
                "events": [{"time": t, "mtime": mt, "event": e}
                           for t, mt, e in events],
                "spans": [{"name": nm, "t0": t0, "t1": t1,
                           **({"args": args} if args else {})}
                          for nm, t0, t1, args in spans]}


class _NullOp:
    """Tracker-disabled stand-in: carries just enough (start/age) for
    the op_latency counter; every tracing call is a no-op."""

    __slots__ = ("start", "trace_id")

    def __init__(self, now: float, trace_id: str = ""):
        self.start = now
        self.trace_id = trace_id

    def age(self, now: float) -> float:
        return now - self.start

    def mark_event(self, event: str) -> None:
        pass

    def span_begin(self, name: str, _t0: float | None = None,
                   **args) -> None:
        pass

    def span_end(self, name: str | None = None) -> float | None:
        return None

    def add_span(self, name: str, t0: float, t1: float, **args) -> None:
        pass

    def finish(self) -> None:
        pass


# ---------------------------------------------------------------------------
# OpTracker
# ---------------------------------------------------------------------------


class OpTracker:
    """Per-daemon op registry (OpTracker + OpHistory): a bounded
    in-flight table, the historic ring (size- AND age-bounded), the
    slow-op ring, and the slow-op complaint/summary machinery."""

    def __init__(self, clock, history_size: int = 20,
                 complaint_age: float = 30.0, logger=None,
                 history_duration: float = 600.0, enabled: bool = True,
                 daemon: str = ""):
        self.clock = clock
        self.complaint_age = complaint_age
        self.history_size = history_size
        self.history_duration = history_duration
        self.enabled = enabled
        self.daemon = daemon
        self.log = logger
        self._lock = threading.Lock()
        self._inflight: dict[int, TrackedOp] = {}
        self._seq = 0
        # (finished_mono, dump) rings: age pruning needs the stamp
        self._history: deque[tuple[float, dict]] = deque(
            maxlen=max(1, history_size))
        self._slow_history: deque[tuple[float, dict]] = deque(
            maxlen=max(1, history_size))
        self._complained: set[int] = set()

    def create(self, desc: str, trace_id: str = "",
               kind: str = "client"):
        if not self.enabled:
            return _NullOp(self.clock.now(), trace_id)
        op = TrackedOp(self, desc, self.clock.now(), trace_id=trace_id,
                       kind=kind)
        with self._lock:
            self._seq += 1
            op._id = self._seq
            self._inflight[op._id] = op
        return op

    def _finish(self, op: TrackedOp) -> None:
        doc = op.dump()
        now_m = time.monotonic()
        with self._lock:
            was_slow = op._id in self._complained
            self._inflight.pop(op._id, None)
            self._complained.discard(op._id)
            self._history.append((now_m, doc))
            if was_slow or doc["age"] > self.complaint_age:
                self._slow_history.append((now_m, doc))

    def _pruned_locked(self, ring: deque) -> list[dict]:
        """Ring contents minus entries older than the history
        duration (osd_op_history_duration), pruned in place."""
        floor = time.monotonic() - self.history_duration
        while ring and ring[0][0] < floor:
            ring.popleft()
        return [doc for _t, doc in ring]

    # -- slow ops ----------------------------------------------------------

    def check_slow_ops(self) -> list[dict]:
        """Ops newly past the complaint age (called from the daemon
        tick); each op is complained about once."""
        now = self.clock.now()
        slow = []
        with self._lock:
            for op_id, op in self._inflight.items():
                if op.age(now) > self.complaint_age \
                        and op_id not in self._complained:
                    self._complained.add(op_id)
                    slow.append(op.dump())
        if slow and self.log is not None:
            for s in slow:
                self.log.warn("slow op (%.0fs): %s",
                              s["age"], s["description"])
        return slow

    def slow_ops_summary(self) -> tuple[int, float]:
        """(count, oldest_age) over CURRENTLY in-flight ops older than
        the complaint threshold — the level-triggered feed behind the
        'N slow ops, oldest blocked for Xs' health flag (clears by
        itself once the ops complete)."""
        now = self.clock.now()
        count, oldest = 0, 0.0
        with self._lock:
            for op in self._inflight.values():
                age = op.age(now)
                if age > self.complaint_age:
                    count += 1
                    oldest = max(oldest, age)
        return count, oldest

    # -- dumps -------------------------------------------------------------

    def num_inflight(self) -> int:
        """O(1) in-flight count (perf dump runs every heartbeat; it
        must not serialize every op's spans just to count them)."""
        with self._lock:
            return len(self._inflight)

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = self._pruned_locked(self._history)
        return {"num_ops": len(ops), "size": self.history_size,
                "duration": self.history_duration, "ops": ops}

    def dump_historic_slow_ops(self) -> dict:
        with self._lock:
            ops = self._pruned_locked(self._slow_history)
        return {"num_ops": len(ops),
                "complaint_time": self.complaint_age, "ops": ops}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Process-wide incident snapshotter.  Daemons register a dump
    callable; :meth:`record` (fired by a CrashPoint or a ledger verify
    failure) writes every registered daemon's document — in-flight +
    historic + slow ops, pg log summaries — as JSON files under a
    fresh ``<dir>/<seq>_<reason>/`` directory.  Disarmed (the default)
    it costs one flag check; the record count is bounded so a crash
    soak cannot fill the disk."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: dict[str, object] = {}     # name -> callable
        self.dir = ""
        self.max_records = 16
        self._seq = 0
        self.records: list[str] = []              # written incident dirs

    # -- registration ------------------------------------------------------

    def register(self, name: str, dump_fn) -> None:
        with self._lock:
            self._sources[name] = dump_fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    # -- arming ------------------------------------------------------------

    def arm(self, directory: str, max_records: int = 16) -> None:
        with self._lock:
            d = str(directory or "")
            if d != self.dir:
                # a fresh DIRECTORY is a fresh incident budget (an
                # exhausted soak must not leave the next arming
                # unable to record) — but a re-arm of the SAME dir
                # (every restarted daemon arms from conf) keeps the
                # sequence, so incident 001 is never overwritten
                self._seq = 0
            self.dir = d
            self.max_records = max(1, int(max_records))

    def disarm(self) -> None:
        with self._lock:
            self.dir = ""

    @property
    def armed(self) -> bool:
        return bool(self.dir)

    # -- recording ---------------------------------------------------------

    def record(self, reason: str, extra: dict | None = None) -> str | None:
        """Snapshot every registered source.  Returns the incident
        directory, or None when disarmed / over the record cap.  Never
        raises: the recorder runs inside crash/verify paths whose own
        error must stay the headline."""
        with self._lock:
            if not self.dir or self._seq >= self.max_records:
                return None
            self._seq += 1
            seq = self._seq
            sources = dict(self._sources)
            base = self.dir
        slug = re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:80] or "incident"
        path = os.path.join(base, f"{seq:03d}_{slug}")
        try:
            os.makedirs(path, exist_ok=True)
            manifest = {"reason": reason, "recorded_at": time.time(),
                        "monotonic": time.monotonic(),
                        "daemons": sorted(sources)}
            for name, fn in sorted(sources.items()):
                try:
                    doc = fn()
                except Exception as e:   # a wedged daemon still dumps
                    doc = {"error": f"{type(e).__name__}: {e}"}
                with open(os.path.join(path, f"{name}.json"), "w",
                          encoding="utf-8") as f:
                    json.dump(doc, f, indent=1, default=str)
            if extra:
                with open(os.path.join(path, "extra.json"), "w",
                          encoding="utf-8") as f:
                    json.dump(extra, f, indent=1, default=str)
            with open(os.path.join(path, "manifest.json"), "w",
                      encoding="utf-8") as f:
                json.dump(manifest, f, indent=1)
        except OSError:
            return None
        with self._lock:
            self.records.append(path)
        return path


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def flight_record(reason: str, extra: dict | None = None) -> str | None:
    """Convenience trigger: snapshot now if the recorder is armed."""
    return _recorder.record(reason, extra)
