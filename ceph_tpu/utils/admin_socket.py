"""Admin socket: per-daemon introspection endpoint.

The common/admin_socket.{h,cc} analog: components register command
hooks ("perf dump", "dump_ops_in_flight", "config show", ...); the
daemon answers JSON over a unix domain socket (the `ceph daemon
<name> <cmd>` path) and the same registry is callable in-process.

Wire protocol (like the reference's admin socket): the client sends one
JSON line {"prefix": "perf dump", ...}\n and receives one JSON document
back, connection closed after each command.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Callable


class AdminSocket:
    def __init__(self, name: str, path: str = ""):
        self.name = name
        self.path = path
        self._hooks: dict[str, Callable[[dict], object]] = {}
        self._server: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stopped = False
        self.register("help", lambda cmd: sorted(self._hooks))

    def register(self, prefix: str,
                 hook: Callable[[dict], object]) -> None:
        self._hooks[prefix] = hook

    def execute(self, cmd: dict | str) -> object:
        if isinstance(cmd, str):
            cmd = {"prefix": cmd}
        hook = self._hooks.get(cmd.get("prefix", ""))
        if hook is None:
            return {"error": f"unknown command {cmd.get('prefix')!r}; "
                             f"try 'help'"}
        return hook(cmd)

    # -- unix socket front-end ---------------------------------------------

    def start(self) -> None:
        if not self.path:
            return
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(self.path)
        self._server.listen(8)
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name=f"asok-{self.name}")
        self._thread.start()

    def _serve(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            # thread-per-connection + recv timeout: one stalled client
            # must not wedge introspection for the daemon's lifetime
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            buf = b""
            while not buf.endswith(b"\n") and len(buf) < 1 << 20:
                chunk = conn.recv(4096)
                if not chunk:
                    break
                buf += chunk
            cmd = json.loads(buf.decode() or "{}")
            out = self.execute(cmd)
            conn.sendall(json.dumps(out, default=str).encode())
        except Exception as e:
            try:
                conn.sendall(json.dumps({"error": str(e)}).encode())
            except OSError:
                pass
        finally:
            conn.close()

    def shutdown(self) -> None:
        self._stopped = True
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        if self.path:
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass


def admin_command(path: str, cmd: dict | str) -> object:
    """Client side: the `ceph daemon <sock> <cmd>` analog."""
    if isinstance(cmd, str):
        cmd = {"prefix": cmd}
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.connect(path)
        s.sendall(json.dumps(cmd).encode() + b"\n")
        buf = b""
        while True:
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            buf += chunk
        return json.loads(buf.decode())
    finally:
        s.close()
