"""CephFS client: POSIX-ish file API over MDS metadata + striped data
(client/Client.{h,cc} + libcephfs.cc reduced).

Metadata ops go to the active MDS (discovered from the osdmap, where
the FSMap is folded in); file DATA goes straight to the data pool,
striped by inode number — the same client/MDS split as the reference
(Client::make_request for metadata, Objecter/Filer for data).
"""

from __future__ import annotations

import itertools
import threading
import time

from ..client.rados import RadosError
from ..client.striper import Layout, file_to_extents
from ..msg import Dispatcher
from .messages import (MClientCaps, MClientCapsAck, MClientReply,
                       MClientRequest)


class FsError(RadosError):
    pass


def data_oid(ino: int, object_no: int) -> str:
    return f"{ino:x}.{object_no:08x}"


SUBTREES_OID = "mds_subtrees"


def subtree_rank(table: dict, norm: str) -> int:
    """Longest-prefix authority lookup — the ONE definition shared by
    client routing and MDS authority checks (divergence here would
    make them disagree on who owns a path)."""
    best, bestlen = 0, -1
    for p, r in table.items():
        if (p == "/" or norm == p
                or norm.startswith(p + "/")) and len(p) > bestlen:
            best, bestlen = r, len(p)
    return best


MUTATES_PARENT = frozenset(
    {"mkdir", "create", "unlink", "rmdir", "setattr", "rename"})


def route_path(op: str, norm: str) -> str:
    """The path whose subtree authority serves this op: ops that
    mutate the parent directory's omap route by the parent; snapshot
    ops route by the snapped dir.  Shared by client and MDS so both
    sides always agree."""
    parts = [p for p in norm.strip("/").split("/") if p]
    if ".snap" in parts:
        i = parts.index(".snap")
        return "/" + "/".join(parts[:i]) if i else "/"
    if op in MUTATES_PARENT:
        return norm.rsplit("/", 1)[0] or "/"
    return norm


def load_subtree_table(io) -> dict | None:
    """Read the authoritative subtree table from the metadata pool;
    None when unreadable (caller keeps its cache)."""
    from ..utils import denc
    try:
        raw = io.get_omap(SUBTREES_OID)
    except Exception:
        return None
    return {p: denc.loads(v) for p, v in raw.items()} if raw else None


class CephFS(Dispatcher):
    """Mounted filesystem handle (libcephfs ceph_mount analog)."""

    _tid_seq = itertools.count(1)     # shared across mounts
    # messenger id -> weakrefs of live mounts on it: a caps revoke
    # must reach EVERY sibling mount sharing the messenger, not just
    # whichever dispatcher sits first (see ms_dispatch).  Weakrefs:
    # a mount dropped without unmount() must not leak forever
    _mounts: dict[int, list] = {}

    def __init__(self, rados, data_pool: str = "cephfs_data",
                 metadata_pool: str = "cephfs_metadata"):
        self.rados = rados
        self.data_pool_name = data_pool
        self.metadata_pool_name = metadata_pool
        self.data = None
        # subtree-root path -> auth rank (multi-rank routing table,
        # cached from the SUBTREES_OID omap; refreshed on ESTALE)
        self._subtrees: dict[str, int] = {"/": 0}
        # tids are PROCESS-global: several CephFS mounts can share one
        # rados handle (one messenger), and per-instance counters
        # starting at 1 would collide — the wrong mount would claim
        # the reply
        self._tid = CephFS._tid_seq
        self._pending: dict[int, dict] = {}
        self._lock = threading.Lock()
        self.mounted = False
        # capability-backed caches (client/Client.h:251 inode/dentry
        # cache model): entries exist exactly while we hold the cap —
        # an MDS revoke drops them
        self._attr_cache: dict[str, dict] = {}
        self._dir_cache: dict[str, dict] = {}
        self._write_caps: set[str] = set()
        self._dirty_size: dict[str, int] = {}   # buffered attr state
        self.rpcs = 0        # MDS round trips (cache-hit observability)
        rados.msgr.add_dispatcher_tail(self)
        import weakref
        CephFS._mounts.setdefault(id(rados.msgr), []).append(
            weakref.ref(self))

    # -- mds rpc -----------------------------------------------------------

    def _subtree_rank(self, path: str) -> int:
        return subtree_rank(self._subtrees, self._norm(path))

    def _refresh_subtrees(self) -> None:
        try:
            io = self.rados.open_ioctx(self.metadata_pool_name)
        except Exception:
            return
        table = load_subtree_table(io)
        if table:
            self._subtrees = table

    def _mds_addr(self, path: str = "/"):
        m = self.rados.monc.osdmap
        ranks = getattr(m, "mds_ranks", None) or {}
        ent = ranks.get(self._subtree_rank(path))
        if ent is not None:
            return f"mds.{ent[0]}", tuple(ent[1])
        if not getattr(m, "mds_addr", None):
            raise FsError(107, "no active mds")     # ENOTCONN
        return f"mds.{m.mds_name}", tuple(m.mds_addr)

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MClientReply):
            with self._lock:
                slot = self._pending.get(msg.tid)
                if slot is not None:
                    slot["reply"] = msg
                    slot["event"].set()
            # not ours -> let a sibling mount on this messenger see it
            return slot is not None
        if isinstance(msg, MClientCaps):
            # fan out to EVERY sibling mount on this messenger (they
            # all cache under the same client entity) and answer with
            # ONE ack carrying the merged buffered-size flushes
            flushes: dict[str, int] = {}
            refs = CephFS._mounts.get(id(self.rados.msgr), [])
            mounts = [m for r in refs if (m := r()) is not None]
            refs[:] = [r for r in refs if r() is not None]
            for mount in (mounts or [self]):
                flushes.update(mount._collect_revoke(msg))
            self.rados.msgr.send_message(
                MClientCapsAck(ack_id=msg.ack_id, flushes=flushes),
                conn.peer_name, conn.peer_addr)
            return True
        return False

    def _collect_revoke(self, msg) -> dict[str, int]:
        """MDS pulled our caps: drop the caches beneath each path and
        surface buffered sizes for the ack (the MDS applies them
        before the conflicting op runs)."""
        flushes: dict[str, int] = {}
        with self._lock:
            for path in msg.paths:
                for cache in (self._attr_cache, self._dir_cache):
                    for key in [k for k in cache
                                if k == path
                                or k.startswith(path + "/")]:
                        del cache[key]
                for key in [k for k in self._write_caps
                            if k == path or k.startswith(path + "/")]:
                    self._write_caps.discard(key)
                    if key in self._dirty_size:
                        flushes[key] = self._dirty_size.pop(key)
        return flushes

    @staticmethod
    def _norm(path: str) -> str:
        return "/" + "/".join(p for p in path.strip("/").split("/")
                              if p)

    def _invalidate_local(self, path: str, prefix: bool = False) -> None:
        """Our own mutation: drop our stale cache entries (the MDS
        only revokes OTHER clients)."""
        p = self._norm(path)
        parent = p.rsplit("/", 1)[0] or "/"
        for cache in (self._attr_cache, self._dir_cache):
            cache.pop(p, None)
            cache.pop(parent, None)
            if prefix:
                for key in [k for k in cache
                            if k.startswith(p + "/")]:
                    del cache[key]

    def _request(self, op: str, path: str, timeout: float = 30.0,
                 **kw):
        """One metadata op, multi-rank aware: ESTALE re-targets via a
        refreshed subtree table (the MDS names the right rank in the
        reply), EAGAIN waits out an in-flight subtree export."""
        deadline = time.time() + timeout
        while True:
            reply = self._request_once(op, path, timeout, kw)
            if reply.result == -116:      # wrong rank: re-target
                hint = (reply.data or {}).get("rank") \
                    if isinstance(reply.data, dict) else None
                self._refresh_subtrees()
                if hint is not None:
                    # key the pin by the ROUTE path — that is what the
                    # retry's longest-prefix lookup consults
                    self._subtrees[route_path(op, self._norm(path))] \
                        = int(hint)
                time.sleep(0.1)   # hinted rank may be mid-(re)beacon:
                # without backoff this spins at wire RTT for the whole
                # deadline when the target rank is down
            elif reply.result == -11:     # frozen: export in flight
                time.sleep(0.1)
            else:
                break
            if time.time() > deadline:
                raise FsError(110, f"{op} {path}: retries timed out")
        if reply.result < 0:
            raise FsError(-reply.result, f"{op} {path}: errno "
                                         f"{-reply.result}")
        return self._absorb_reply(op, reply)

    def _request_once(self, op: str, path: str, timeout: float,
                      kw: dict):
        tid = next(self._tid)
        slot = {"event": threading.Event(), "reply": None}
        with self._lock:
            self._pending[tid] = slot
        self.rpcs += 1
        try:
            entity, addr = self._mds_addr(route_path(op, self._norm(path)))
            req = MClientRequest(tid=tid, op=op, path=path,
                                 size=kw.get("size"),
                                 new_path=kw.get("new_path"))
            self.rados.msgr.send_message(req, entity, addr)
            if not slot["event"].wait(timeout):
                raise FsError(110, f"mds op {op} timed out")
            return slot["reply"]
        finally:
            with self._lock:
                self._pending.pop(tid, None)

    def _absorb_reply(self, op: str, reply):
        # adopt the data pool's snap context (SnapClient model): our
        # writes after a snapshot must carry the new snapc so the
        # OSDs copy-on-write the pre-snapshot data
        snapc = getattr(reply, "snapc", None)
        if snapc is not None and self.data is not None:
            want = (snapc[0],
                    sorted((int(x) for x in snapc[1]), reverse=True))
            if want != (self.data.snap_seq, self.data.snaps):
                # covers removal too: rmdir .snap/x shrinks the snap
                # list without bumping seq — keeping the stale context
                # would COW-clone to deleted snaps forever
                self.data.set_snap_context(*snapc)
        # granted caps let us cache what this reply carries
        for grant in getattr(reply, "grants", None) or []:
            p = grant["path"]
            with self._lock:
                if op in ("getattr", "lookup", "create", "setattr") \
                        and isinstance(reply.data, dict) \
                        and "ino" in reply.data:
                    self._attr_cache[p] = dict(reply.data)
                elif op == "readdir":
                    self._dir_cache[p] = dict(reply.data)
                if "w" in grant["caps"]:
                    self._write_caps.add(p)
        return reply.data

    # -- mount -------------------------------------------------------------

    def mount(self, timeout: float = 30.0) -> "CephFS":
        end = time.time() + timeout
        while time.time() < end:
            try:
                self._request("getattr", "/", timeout=5.0)
                break
            except FsError:
                time.sleep(0.5)
        else:
            raise FsError(110, "mount timed out (no mds?)")
        self.data = self.rados.open_ioctx(self.data_pool_name)
        self.mounted = True
        return self

    def unmount(self) -> None:
        self.mounted = False
        peers = CephFS._mounts.get(id(self.rados.msgr))
        if peers:
            peers[:] = [r for r in peers
                        if r() is not None and r() is not self]
            if not peers:
                CephFS._mounts.pop(id(self.rados.msgr), None)

    # -- namespace ops -----------------------------------------------------

    def mkdir(self, path: str) -> None:
        self._request("mkdir", path)
        with self._lock:
            self._invalidate_local(path)

    def mkdirs(self, path: str) -> None:
        parts = [p for p in path.strip("/").split("/") if p]
        cur = ""
        for part in parts:
            cur = f"{cur}/{part}"
            try:
                self.mkdir(cur)     # NOT _request: the local cache
            except FsError as e:    # invalidation must ride along
                if e.errno != 17:
                    raise

    def listdir(self, path: str) -> list[str]:
        p = self._norm(path)
        with self._lock:
            cached = self._dir_cache.get(p)
            if cached is not None:
                return sorted(cached)   # cap held: no MDS round trip
        return sorted(self._request("readdir", path))

    def stat(self, path: str) -> dict:
        p = self._norm(path)
        with self._lock:
            cached = self._attr_cache.get(p)
            if cached is not None:
                out = dict(cached)      # cap held: no MDS round trip
                if p in self._dirty_size:
                    out["size"] = max(out["size"],
                                      self._dirty_size[p])
                return out
        return self._request("getattr", path)

    def unlink(self, path: str) -> None:
        self._flush_dirty(path)
        inode = self._request("unlink", path)
        with self._lock:
            self._invalidate_local(path)
        self._purge_data(inode)

    def rmdir(self, path: str) -> None:
        self._request("rmdir", path)
        with self._lock:
            self._invalidate_local(path, prefix=True)

    def rename(self, src: str, dst: str) -> None:
        self._flush_dirty(src)
        result = self._request("rename", src, new_path=dst)
        with self._lock:
            self._invalidate_local(src, prefix=True)
            self._invalidate_local(dst, prefix=True)
        replaced = (result or {}).get("replaced")
        if replaced:
            self._purge_data(replaced)   # atomically-replaced file

    def _flush_dirty(self, path: str) -> None:
        """Push a buffered size update to the MDS (cap flush)."""
        p = self._norm(path)
        with self._lock:
            size = self._dirty_size.pop(p, None)
        if size is not None:
            self._request("setattr", path, size=size)

    def _purge_data(self, inode: dict) -> None:
        lo = Layout(**inode["layout"])
        objects = (inode["size"] + lo.object_size - 1) // lo.object_size
        comps = [self.data.aio_remove(data_oid(inode["ino"], i))
                 for i in range(objects)]
        for c in comps:
            c.wait_for_complete()

    # -- file I/O ----------------------------------------------------------

    def open(self, path: str, mode: str = "r") -> "File":
        if ".snap" in path.split("/") and (
                "w" in mode or "a" in mode or "+" in mode):
            raise FsError(30, "snapshots are read-only")     # EROFS
        if "w" in mode or "a" in mode or "+" in mode:
            inode = self._request("create", path)
            with self._lock:
                # our own create: drop our cached parent listing (the
                # MDS only revokes OTHER clients' caps)
                parent = self._norm(path).rsplit("/", 1)[0] or "/"
                self._dir_cache.pop(parent, None)
            if "w" in mode and inode["size"]:
                self._purge_data(inode)
                inode = self._request("setattr", path, size=0)
        else:
            inode = self._request("getattr", path)
            if inode["type"] != "file":
                raise FsError(21, f"{path} is a directory")
        return File(self, path, inode, mode)


class File:
    """An open file (Fh analog): pread/pwrite through the striper."""

    def __init__(self, fs: CephFS, path: str, inode: dict, mode: str):
        self.fs = fs
        self.path = path
        self.inode = inode
        self.mode = mode
        self.layout = Layout(**inode["layout"])
        self._pos = inode["size"] if "a" in mode else 0

    @property
    def ino(self) -> int:
        return self.inode["ino"]

    def size(self) -> int:
        return self.inode["size"]

    def write(self, data: bytes, offset: int | None = None) -> int:
        if self.inode.get("snapid") is not None:
            raise FsError(30, "snapshots are read-only")    # EROFS
        if not any(m in self.mode for m in "wa+"):
            raise FsError(9, "file not open for writing")   # EBADF
        data = bytes(data)
        off = self._pos if offset is None else offset
        comps = []
        for ext in file_to_extents(self.layout, off, len(data)):
            chunk = data[ext.logical_offset - off:
                         ext.logical_offset - off + ext.length]
            comps.append(self.fs.data.aio_write(
                data_oid(self.ino, ext.object_no), chunk,
                offset=ext.offset))
        for c in comps:
            c.wait_for_complete()
        for c in comps:
            c.result()
        end = off + len(data)
        if offset is None:
            self._pos = end
        if end > self.inode["size"]:
            p = self.fs._norm(self.path)
            with self.fs._lock:
                buffered = p in self.fs._write_caps
                if buffered:
                    # write-buffering cap (Fw): the size update stays
                    # client-side until close or a cap revoke flushes
                    # it — no MDS round trip per write
                    self.inode = dict(self.inode, size=end)
                    self.fs._dirty_size[p] = end
                    if p in self.fs._attr_cache:
                        self.fs._attr_cache[p]["size"] = end
            if not buffered:
                self.inode = self.fs._request("setattr", self.path,
                                              size=end)
        return len(data)

    def read(self, length: int = -1, offset: int | None = None) -> bytes:
        off = self._pos if offset is None else offset
        size = self.inode["size"]
        if length < 0 or off + length > size:
            length = max(0, size - off)
        if length == 0:
            return b""
        snapid = self.inode.get("snapid")
        comps = []
        for ext in file_to_extents(self.layout, off, length):
            if snapid is not None:
                # snapshot read: the pool resolves the clone (or the
                # unchanged head) covering this snapid
                comps.append((ext, self.fs.data.rados.aio_submit(
                    self.fs.data.snap_read,
                    data_oid(self.ino, ext.object_no), snapid,
                    ext.length, ext.offset)))
                continue
            comps.append((ext, self.fs.data.aio_read(
                data_oid(self.ino, ext.object_no), length=ext.length,
                offset=ext.offset)))
        buf = bytearray(length)
        for ext, c in comps:
            c.wait_for_complete()
            try:
                piece = c.result()
            except RadosError as e:
                if e.errno != 2:
                    raise
                piece = b""
            lo = ext.logical_offset - off
            buf[lo: lo + len(piece)] = piece
        if offset is None:
            self._pos = off + length
        return bytes(buf)

    def seek(self, pos: int) -> None:
        self._pos = pos

    def close(self) -> None:
        self.fs._flush_dirty(self.path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
