"""CephFS client: POSIX-ish file API over MDS metadata + striped data
(client/Client.{h,cc} + libcephfs.cc reduced).

Metadata ops go to the active MDS (discovered from the osdmap, where
the FSMap is folded in); file DATA goes straight to the data pool,
striped by inode number — the same client/MDS split as the reference
(Client::make_request for metadata, Objecter/Filer for data).
"""

from __future__ import annotations

import itertools
import threading
import time

from ..client.rados import RadosError
from ..client.striper import Layout, file_to_extents
from ..msg import Dispatcher
from .messages import MClientReply, MClientRequest


class FsError(RadosError):
    pass


def data_oid(ino: int, object_no: int) -> str:
    return f"{ino:x}.{object_no:08x}"


class CephFS(Dispatcher):
    """Mounted filesystem handle (libcephfs ceph_mount analog)."""

    def __init__(self, rados, data_pool: str = "cephfs_data"):
        self.rados = rados
        self.data_pool_name = data_pool
        self.data = None
        self._tid = itertools.count(1)
        self._pending: dict[int, dict] = {}
        self._lock = threading.Lock()
        self.mounted = False
        rados.msgr.add_dispatcher_tail(self)

    # -- mds rpc -----------------------------------------------------------

    def _mds_addr(self):
        m = self.rados.monc.osdmap
        if not getattr(m, "mds_addr", None):
            raise FsError(107, "no active mds")     # ENOTCONN
        return f"mds.{m.mds_name}", tuple(m.mds_addr)

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MClientReply):
            with self._lock:
                slot = self._pending.get(msg.tid)
                if slot is not None:
                    slot["reply"] = msg
                    slot["event"].set()
            return True
        return False

    def _request(self, op: str, path: str, timeout: float = 30.0,
                 **kw):
        tid = next(self._tid)
        slot = {"event": threading.Event(), "reply": None}
        with self._lock:
            self._pending[tid] = slot
        try:
            entity, addr = self._mds_addr()
            req = MClientRequest(tid=tid, op=op, path=path,
                                 size=kw.get("size"),
                                 new_path=kw.get("new_path"))
            self.rados.msgr.send_message(req, entity, addr)
            if not slot["event"].wait(timeout):
                raise FsError(110, f"mds op {op} timed out")
            reply = slot["reply"]
        finally:
            with self._lock:
                self._pending.pop(tid, None)
        if reply.result < 0:
            raise FsError(-reply.result, f"{op} {path}: errno "
                                         f"{-reply.result}")
        return reply.data

    # -- mount -------------------------------------------------------------

    def mount(self, timeout: float = 30.0) -> "CephFS":
        end = time.time() + timeout
        while time.time() < end:
            try:
                self._request("getattr", "/", timeout=5.0)
                break
            except FsError:
                time.sleep(0.5)
        else:
            raise FsError(110, "mount timed out (no mds?)")
        self.data = self.rados.open_ioctx(self.data_pool_name)
        self.mounted = True
        return self

    def unmount(self) -> None:
        self.mounted = False

    # -- namespace ops -----------------------------------------------------

    def mkdir(self, path: str) -> None:
        self._request("mkdir", path)

    def mkdirs(self, path: str) -> None:
        parts = [p for p in path.strip("/").split("/") if p]
        cur = ""
        for part in parts:
            cur = f"{cur}/{part}"
            try:
                self._request("mkdir", cur)
            except FsError as e:
                if e.errno != 17:
                    raise

    def listdir(self, path: str) -> list[str]:
        return sorted(self._request("readdir", path))

    def stat(self, path: str) -> dict:
        return self._request("getattr", path)

    def unlink(self, path: str) -> None:
        inode = self._request("unlink", path)
        self._purge_data(inode)

    def rmdir(self, path: str) -> None:
        self._request("rmdir", path)

    def rename(self, src: str, dst: str) -> None:
        result = self._request("rename", src, new_path=dst)
        replaced = (result or {}).get("replaced")
        if replaced:
            self._purge_data(replaced)   # atomically-replaced file

    def _purge_data(self, inode: dict) -> None:
        lo = Layout(**inode["layout"])
        objects = (inode["size"] + lo.object_size - 1) // lo.object_size
        comps = [self.data.aio_remove(data_oid(inode["ino"], i))
                 for i in range(objects)]
        for c in comps:
            c.wait_for_complete()

    # -- file I/O ----------------------------------------------------------

    def open(self, path: str, mode: str = "r") -> "File":
        if "w" in mode or "a" in mode or "+" in mode:
            inode = self._request("create", path)
            if "w" in mode and inode["size"]:
                self._purge_data(inode)
                inode = self._request("setattr", path, size=0)
        else:
            inode = self._request("getattr", path)
            if inode["type"] != "file":
                raise FsError(21, f"{path} is a directory")
        return File(self, path, inode, mode)


class File:
    """An open file (Fh analog): pread/pwrite through the striper."""

    def __init__(self, fs: CephFS, path: str, inode: dict, mode: str):
        self.fs = fs
        self.path = path
        self.inode = inode
        self.mode = mode
        self.layout = Layout(**inode["layout"])
        self._pos = inode["size"] if "a" in mode else 0

    @property
    def ino(self) -> int:
        return self.inode["ino"]

    def size(self) -> int:
        return self.inode["size"]

    def write(self, data: bytes, offset: int | None = None) -> int:
        if not any(m in self.mode for m in "wa+"):
            raise FsError(9, "file not open for writing")   # EBADF
        data = bytes(data)
        off = self._pos if offset is None else offset
        comps = []
        for ext in file_to_extents(self.layout, off, len(data)):
            chunk = data[ext.logical_offset - off:
                         ext.logical_offset - off + ext.length]
            comps.append(self.fs.data.aio_write(
                data_oid(self.ino, ext.object_no), chunk,
                offset=ext.offset))
        for c in comps:
            c.wait_for_complete()
        for c in comps:
            c.result()
        end = off + len(data)
        if offset is None:
            self._pos = end
        if end > self.inode["size"]:
            self.inode = self.fs._request("setattr", self.path,
                                          size=end)
        return len(data)

    def read(self, length: int = -1, offset: int | None = None) -> bytes:
        off = self._pos if offset is None else offset
        size = self.inode["size"]
        if length < 0 or off + length > size:
            length = max(0, size - off)
        if length == 0:
            return b""
        comps = []
        for ext in file_to_extents(self.layout, off, length):
            comps.append((ext, self.fs.data.aio_read(
                data_oid(self.ino, ext.object_no), length=ext.length,
                offset=ext.offset)))
        buf = bytearray(length)
        for ext, c in comps:
            c.wait_for_complete()
            try:
                piece = c.result()
            except RadosError as e:
                if e.errno != 2:
                    raise
                piece = b""
            lo = ext.logical_offset - off
            buf[lo: lo + len(piece)] = piece
        if offset is None:
            self._pos = off + length
        return bytes(buf)

    def seek(self, pos: int) -> None:
        self._pos = pos

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
