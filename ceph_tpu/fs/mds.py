"""MDS: the CephFS metadata server (mds/MDSRank.cc, Server.cc,
MDCache.cc reduced to a single active rank).

All metadata lives IN RADOS, mirroring the reference's on-disk model:

  * each directory is one omap object ``dir.<ino>`` in the metadata
    pool; a dentry key maps to the child's full inode record (the
    reference embeds inodes in dentries the same way);
  * the inode-number allocator is an omap counter (InoTable analog);
  * file data never touches the MDS — clients stripe it into the data
    pool addressed by ino (mds/client data path split).

DIVERGENCE: the reference journals metadata events (MDLog) and applies
lazily for latency; here every mutation applies write-through to the
metadata pool before the reply, so an MDS restart needs no replay —
the durability point is identical, the latency model simpler.  Multi-
rank subtree migration/balancing is out of scope (single active MDS).
"""

from __future__ import annotations

import itertools
import threading
import time

from ..client.rados import Rados, RadosError
from ..mon.client import MonClient
from ..mon.messages import MMDSBeacon
from ..mon.monmap import MonMap
from ..msg import Dispatcher, Messenger, Policy
from ..utils import denc
from ..utils.clock import SystemClock
from ..utils.config import Config
from ..utils.dout import DoutLogger
from .messages import (MClientCaps, MClientCapsAck, MClientReply,
                       MClientRequest)

ROOT_INO = 1
INOTABLE = "mds_inotable"
DEFAULT_LAYOUT = {"stripe_unit": 1 << 22, "stripe_count": 1,
                  "object_size": 1 << 22}


def dir_oid(ino: int) -> str:
    return f"dir.{ino:x}"


def new_inode(ino: int, typ: str, layout=None) -> dict:
    now = time.time()
    return {"ino": ino, "type": typ, "size": 0, "mtime": now,
            "ctime": now, "layout": layout or dict(DEFAULT_LAYOUT)}


class MDSDaemon(Dispatcher):
    def __init__(self, name: str, monmap: MonMap,
                 conf: Config | None = None,
                 metadata_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data", clock=None):
        self.name = name
        self.entity = f"mds.{name}"
        self.conf = conf or Config()
        self.clock = clock or SystemClock()
        self.log = DoutLogger("mds", self.entity)
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool

        self.msgr = Messenger(self.entity, conf=self.conf)
        self.msgr.bind(("127.0.0.1", 0))
        self.msgr.set_policy("mon", Policy.lossless_peer())
        self.msgr.set_policy("client", Policy.stateless_server())
        self.msgr.add_dispatcher_tail(self)
        self.monc = MonClient(self.msgr, monmap)

        # own RADOS client for the metadata pool (Objecter-backed)
        self._rados = Rados(monmap, f"client.{self.entity}",
                            conf=self.conf)
        self.meta = None
        self._lock = threading.Lock()    # single-rank serialization
        self._beacon_timer = None
        self._stopped = False
        # dentry cache (MDCache reduced): dir ino -> {name: inode}.
        # Single active rank writes ALL metadata, so the cache is
        # trivially coherent; bounded by eviction below.
        self._dcache: dict[int, dict[str, dict]] = {}
        self._dcache_max = 1024
        # capabilities (Locker.cc reduced): path -> {client: caps},
        # plus client sessions (entity -> reply addr) and pending
        # revoke gathers (ack_id -> state)
        self._caps: dict[str, dict[str, str]] = {}
        self._sessions: dict[str, tuple] = {}
        self._revokes: dict[int, dict] = {}
        self._ack_id = itertools.count(1)
        # client -> consecutive revoke-ack timeouts (laggy tracking);
        # strikes are rate-limited so a slow-but-alive client whose
        # acks land just past the window is not rapid-fired to 3
        self._laggy: dict[str, int] = {}
        self._laggy_last: dict[str, float] = {}   # last strike time

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.msgr.start()
        self._rados.connect()
        try:
            self._rados.create_pool(self.metadata_pool)
        except RadosError:
            pass
        try:
            self._rados.create_pool(self.data_pool)
        except RadosError:
            pass
        self.meta = self._rados.open_ioctx(self.metadata_pool)
        self._ensure_root()
        self._beacon()

    def shutdown(self) -> None:
        self._stopped = True
        if self._beacon_timer:
            self._beacon_timer.cancel()
        self._rados.shutdown()
        self.msgr.shutdown()

    def _beacon(self) -> None:
        if self._stopped:
            return
        self.monc.send(MMDSBeacon(name=self.name, addr=self.msgr.addr))
        self._beacon_timer = self.clock.timer(
            float(self.conf.mon_tick_interval) * 2, self._beacon)

    def _ensure_root(self) -> None:
        try:
            self.meta.stat(dir_oid(ROOT_INO))
        except RadosError:
            self.meta.write_full(dir_oid(ROOT_INO), b"")
            self.meta.set_omap(INOTABLE, {"next": b"2"})

    # -- inode table -------------------------------------------------------

    def _alloc_ino(self) -> int:
        omap = self.meta.get_omap(INOTABLE)
        ino = int(omap.get("next", b"2"))
        self.meta.set_omap(INOTABLE, {"next": str(ino + 1).encode()})
        return ino

    # -- path resolution ---------------------------------------------------

    @staticmethod
    def _split(path: str) -> list[str]:
        return [p for p in path.strip("/").split("/") if p]

    def _dentries(self, dir_ino: int) -> dict[str, dict]:
        cached = self._dcache.get(dir_ino)
        if cached is not None:
            return cached
        try:
            omap = self.meta.get_omap(dir_oid(dir_ino))
        except RadosError:
            return {}
        out = {k: denc.loads(v) for k, v in omap.items()}
        if len(self._dcache) >= self._dcache_max:
            self._dcache.pop(next(iter(self._dcache)))
        self._dcache[dir_ino] = out
        return out

    def _resolve(self, path: str) -> dict:
        """Path -> inode record; raises RadosError(ENOENT/ENOTDIR)."""
        cur = {"ino": ROOT_INO, "type": "dir"}
        for part in self._split(path):
            if cur["type"] != "dir":
                raise RadosError(20, f"{part}: not a directory")
            ent = self._dentries(cur["ino"]).get(part)
            if ent is None:
                raise RadosError(2, f"no such entry {part}")
            cur = ent
        return cur

    def _resolve_parent(self, path: str) -> tuple[dict, str]:
        parts = self._split(path)
        if not parts:
            raise RadosError(22, "bad path")
        parent = self._resolve("/".join(parts[:-1]))
        if parent["type"] != "dir":
            raise RadosError(20, "parent not a directory")
        return parent, parts[-1]

    def _set_dentry(self, dir_ino: int, name: str, inode: dict) -> None:
        self.meta.set_omap(dir_oid(dir_ino), {name: denc.dumps(inode)})
        if dir_ino in self._dcache:
            self._dcache[dir_ino][name] = inode

    def _rm_dentry(self, dir_ino: int, name: str) -> None:
        self.meta.rm_omap_keys(dir_oid(dir_ino), [name])
        if dir_ino in self._dcache:
            self._dcache[dir_ino].pop(name, None)

    # -- request handling --------------------------------------------------

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MClientRequest):
            threading.Thread(target=self._handle, args=(conn, msg),
                             daemon=True).start()
            return True
        if isinstance(msg, MClientCapsAck):
            # inline: the revoking op thread is WAITING on this while
            # holding the rank lock — acks must not need it
            state = self._revokes.get(msg.ack_id)
            self._laggy.pop(conn.peer_name, None)   # alive after all
            self._laggy_last.pop(conn.peer_name, None)
            if state is not None:
                with state["lock"]:
                    state["flushes"].update(msg.flushes or {})
                    state["acked"].add(conn.peer_name)
                    state["waiting"].discard(conn.peer_name)
                if not state["waiting"]:
                    state["event"].set()
            return True
        return False

    def _handle(self, conn, msg) -> None:
        with self._lock:
            self._sessions[msg.src] = conn.peer_addr
            try:
                affected = self._affected_paths(msg)
                if affected:
                    # Locker semantics: conflicting client caps are
                    # revoked (and their buffered attrs flushed)
                    # BEFORE the mutation executes
                    flushes = self._revoke_caps(msg.src, affected)
                    self._apply_cap_flushes(flushes)
                else:
                    # reads conflict only with WRITE-buffering caps:
                    # another client's unflushed size must land before
                    # we answer (reader-revokes-writer, Locker model)
                    conflicts = self._read_conflicts(msg)
                    if conflicts:
                        flushes = self._revoke_caps(
                            msg.src, conflicts, write_only=True)
                        self._apply_cap_flushes(flushes)
                data = self._execute(msg)
                grants = self._grant_caps(msg)
                reply = MClientReply(tid=msg.tid, result=0, data=data,
                                     grants=grants)
            except RadosError as e:
                reply = MClientReply(tid=msg.tid, result=-e.errno,
                                     data=None)
            except Exception as e:
                self.log.error("request %s failed: %s", msg.op, e)
                reply = MClientReply(tid=msg.tid, result=-5, data=None)
        self.msgr.send_message(reply, conn.peer_name, conn.peer_addr)

    # -- capabilities (Locker.cc reduced) ----------------------------------

    def _norm(self, path: str) -> str:
        return "/" + "/".join(self._split(path))

    def _parent_of(self, norm: str) -> str:
        return norm.rsplit("/", 1)[0] or "/"

    def _affected_paths(self, msg) -> list[tuple[str, bool]]:
        """Paths a mutation invalidates: (path, prefix?) pairs."""
        op = msg.op
        if op in ("getattr", "lookup", "readdir"):
            return []
        p = self._norm(msg.path)
        parent = self._parent_of(p)
        if op in ("mkdir", "create", "setattr", "unlink"):
            return [(parent, False), (p, False)]
        if op == "rmdir":
            return [(parent, False), (p, True)]
        if op == "rename":
            d = self._norm(msg.new_path)
            return [(parent, False), (self._parent_of(d), False),
                    (p, True), (d, True)]
        return []

    def _read_conflicts(self, msg) -> list[tuple[str, bool]]:
        p = self._norm(msg.path)
        if msg.op in ("getattr", "lookup"):
            return [(p, False)]
        if msg.op == "readdir":
            return [(p, True)]     # listings embed child sizes
        return []

    def _revoke_caps(self, requester: str, affected: list,
                     write_only: bool = False) -> dict:
        """Pull matching caps from every OTHER client; wait (bounded)
        for their acks, which carry buffered-attr flushes."""
        per_client: dict[str, list[str]] = {}
        for cap_path in list(self._caps):
            for apath, prefix in affected:
                hit = cap_path == apath or (
                    prefix and cap_path.startswith(apath + "/"))
                if not hit:
                    continue
                holders = self._caps[cap_path]
                for client in list(holders):
                    if client == requester:
                        continue
                    if write_only and "w" not in holders[client]:
                        continue
                    per_client.setdefault(client, []).append(cap_path)
                    del holders[client]
                if not holders:
                    del self._caps[cap_path]
                break
        targets = {c: ps for c, ps in per_client.items()
                   if c in self._sessions}
        if not targets:
            return {}
        ack_id = next(self._ack_id)
        # a client that already blew a revoke window is LAGGY: send
        # the revoke but do not wait on it again — one dead client
        # must not serialize every conflicting op behind 1s stalls
        waited = {c for c in targets if not self._laggy.get(c)}
        state = {"waiting": set(waited), "flushes": {}, "acked": set(),
                 "event": threading.Event(),
                 "lock": threading.Lock()}
        self._revokes[ack_id] = state
        for client, paths in targets.items():
            self.msgr.send_message(
                MClientCaps(ack_id=ack_id, paths=sorted(set(paths))),
                client, self._sessions[client])
        # bounded REAL-time wait: acks arrive on the messenger thread
        # (no rank lock needed); a dead client costs one window
        if state["waiting"]:
            state["event"].wait(1.0)
        self._revokes.pop(ack_id, None)
        # strike every target that did not ack — including laggy ones
        # we no longer wait on (a LATE ack clears the counter via the
        # ack handler, so only a truly dead client accumulates).
        # Copies under state["lock"]: the messenger thread may still
        # be mutating these sets for an ack in flight.
        with state["lock"]:
            acked = set(state["acked"])
            flushes = dict(state["flushes"])
        now = time.time()
        for client in set(targets) - acked:
            # at most one strike per real revoke window: laggy clients
            # get a zero-length window, so without this cooldown a
            # burst of ops would rapid-fire a 1.2s-RTT client straight
            # to 3 strikes before any in-flight ack could land
            if now - self._laggy_last.get(client, 0.0) < 1.0:
                continue
            self._laggy_last[client] = now
            fails = self._laggy.get(client, 0) + 1
            self._laggy[client] = fails
            if fails >= 3:
                # Session::close semantics: a persistently dead
                # client loses its session (and with it, its caps)
                self._laggy.pop(client, None)
                self._laggy_last.pop(client, None)
                self._sessions.pop(client, None)
                for holders in self._caps.values():
                    holders.pop(client, None)
        return flushes

    def _apply_cap_flushes(self, flushes: dict) -> None:
        """A revoked writer's buffered size lands before the op."""
        for path, size in flushes.items():
            try:
                parent, name = self._resolve_parent(path)
                ent = self._dentries(parent["ino"]).get(name)
                if ent is not None and ent["type"] == "file":
                    ent["size"] = max(int(ent["size"]), int(size))
                    ent["mtime"] = time.time()
                    self._set_dentry(parent["ino"], name, ent)
            except RadosError:
                continue

    def _grant_caps(self, msg) -> list:
        """Read caps on resolved paths; read+buffer caps on files the
        client created/opened (Fw analog)."""
        op = msg.op
        p = self._norm(msg.path)
        if op in ("getattr", "lookup", "readdir"):
            caps = "r"
        elif op in ("create", "setattr"):
            caps = "rw"
        else:
            return []
        self._caps.setdefault(p, {})[msg.src] = caps
        return [{"path": p, "caps": caps}]

    def _execute(self, msg):
        op, path = msg.op, msg.path
        if op == "getattr":
            return self._resolve(path)
        if op == "lookup":
            return self._resolve(path)
        if op == "readdir":
            node = self._resolve(path)
            if node["type"] != "dir":
                raise RadosError(20, "not a directory")
            return {name: ent for name, ent in
                    self._dentries(node["ino"]).items()}
        if op == "mkdir":
            parent, name = self._resolve_parent(path)
            if name in self._dentries(parent["ino"]):
                raise RadosError(17, "exists")
            ino = self._alloc_ino()
            inode = new_inode(ino, "dir")
            self.meta.write_full(dir_oid(ino), b"")
            self._set_dentry(parent["ino"], name, inode)
            return inode
        if op == "create":
            parent, name = self._resolve_parent(path)
            existing = self._dentries(parent["ino"]).get(name)
            if existing is not None:
                if existing["type"] != "file":
                    raise RadosError(21, "is a directory")
                return existing
            inode = new_inode(self._alloc_ino(), "file")
            self._set_dentry(parent["ino"], name, inode)
            return inode
        if op == "setattr":
            parent, name = self._resolve_parent(path)
            ent = self._dentries(parent["ino"]).get(name)
            if ent is None:
                raise RadosError(2, "no such entry")
            if msg.size is not None:
                ent["size"] = int(msg.size)
            ent["mtime"] = time.time()
            self._set_dentry(parent["ino"], name, ent)
            return ent
        if op == "unlink":
            parent, name = self._resolve_parent(path)
            ent = self._dentries(parent["ino"]).get(name)
            if ent is None:
                raise RadosError(2, "no such entry")
            if ent["type"] == "dir":
                raise RadosError(21, "is a directory")
            self._rm_dentry(parent["ino"], name)
            return ent          # client deletes the data objects
        if op == "rmdir":
            parent, name = self._resolve_parent(path)
            ent = self._dentries(parent["ino"]).get(name)
            if ent is None:
                raise RadosError(2, "no such entry")
            if ent["type"] != "dir":
                raise RadosError(20, "not a directory")
            if self._dentries(ent["ino"]):
                raise RadosError(39, "directory not empty")
            self._rm_dentry(parent["ino"], name)
            self._dcache.pop(ent["ino"], None)
            try:
                self.meta.remove_object(dir_oid(ent["ino"]))
            except RadosError:
                pass
            return None
        if op == "rename":
            # renaming a directory into its own subtree would detach
            # it into an unreachable cycle (POSIX EINVAL)
            src_norm = "/" + "/".join(self._split(path))
            dst_norm = "/" + "/".join(self._split(msg.new_path))
            if dst_norm == src_norm or \
                    dst_norm.startswith(src_norm + "/"):
                raise RadosError(22, "destination inside source")
            src_parent, src_name = self._resolve_parent(path)
            ent = self._dentries(src_parent["ino"]).get(src_name)
            if ent is None:
                raise RadosError(2, "no such entry")
            dst_parent, dst_name = self._resolve_parent(msg.new_path)
            dst = self._dentries(dst_parent["ino"]).get(dst_name)
            replaced = None
            if dst is not None:
                # POSIX atomic replace for files (write-tmp + rename);
                # DIVERGENCE: replacing a directory destination is
                # EEXIST here (no dir-over-empty-dir)
                if dst["type"] != "file" or ent["type"] != "file":
                    raise RadosError(17, "destination exists")
                replaced = dst
            self._set_dentry(dst_parent["ino"], dst_name, ent)
            self._rm_dentry(src_parent["ino"], src_name)
            return {"entry": ent, "replaced": replaced}
        raise RadosError(95, f"unknown mds op {op!r}")
