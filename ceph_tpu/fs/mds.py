"""MDS: the CephFS metadata server (mds/MDSRank.cc, Server.cc,
MDCache.cc reduced to a single active rank).

All metadata lives IN RADOS, mirroring the reference's on-disk model:

  * each directory is one omap object ``dir.<ino>`` in the metadata
    pool; a dentry key maps to the child's full inode record (the
    reference embeds inodes in dentries the same way);
  * the inode-number allocator is an omap counter (InoTable analog);
  * file data never touches the MDS — clients stripe it into the data
    pool addressed by ino (mds/client data path split).

Metadata mutations are JOURNALED (mds/MDLog.cc model): each request
appends one event — a list of idempotent steps — to an MDLog journal
in the metadata pool (the shared Journaler library, the reference's
osdc/Journaler), applies to the dentry cache, and replies; dirty
directory omaps flush lazily on the beacon tick, after which the
journal commit position advances and old segments trim.  An MDS that
dies mid-burst replays the journal from its commit position on
restart and converges (journal replay, mds/journal.cc).

Snapshots (.snap, SnapServer/snaprealm reduced): `mkdir d/.snap/name`
allocates a self-managed snapid on the DATA pool (so client writes
carrying the updated snap context make the OSDs COW file data) and
eagerly freezes the metadata subtree under d into one snapshot object;
`d/.snap/name/...` paths resolve inside the frozen tree, with file
reads served from the data pool at that snapid.  DIVERGENCE: the
reference's snaprealms are lazy COW over the live tree; the eager
metadata freeze trades O(subtree) capture cost for the same read
semantics.

Multi-rank (mds/Migrator.h:52, mds/MDBalancer.h:39 redesigned):
ranks shard the namespace by SUBTREE, with the authoritative table in
a RADOS omap (SUBTREES_OID).  Because all metadata already lives in
shared RADOS dir omaps, migration collapses to an authority handoff:
freeze subtree -> revoke caps -> flush journal -> CAS the table —
no cache state ships, the importer faults everything in.  Clients
route by longest-prefix over the same table and re-target on ESTALE
hints.  The balancer publishes per-rank load samples to LOAD_OID and
exports the hottest top-level subtree when 2x-imbalanced.
"""

from __future__ import annotations

import itertools
import threading
import time

from ..client.rados import Rados, RadosError
from ..mon.client import MonClient
from ..mon.messages import MMDSBeacon
from ..mon.monmap import MonMap
from ..msg import Dispatcher, Policy, create_messenger
from ..utils import denc
from ..utils.clock import SystemClock
from ..utils.config import Config
from ..utils.dout import DoutLogger
from .messages import (MClientCaps, MClientCapsAck, MClientReply,
                       MClientRequest)

ROOT_INO = 1
INOTABLE = "mds_inotable"
SUBTREES_OID = "mds_subtrees"     # omap: subtree root path -> auth rank
LOAD_OID = "mds_load"             # omap: rank -> {"load": reqs/tick}
DEFAULT_LAYOUT = {"stripe_unit": 1 << 22, "stripe_count": 1,
                  "object_size": 1 << 22}


class _SimulatedCrash(Exception):
    """Test hook: dies at a chosen point inside export_dir."""


def dir_oid(ino: int) -> str:
    return f"dir.{ino:x}"


def new_inode(ino: int, typ: str, layout=None) -> dict:
    now = time.time()
    return {"ino": ino, "type": typ, "size": 0, "mtime": now,
            "ctime": now, "layout": layout or dict(DEFAULT_LAYOUT)}


class MDSDaemon(Dispatcher):
    def __init__(self, name: str, monmap: MonMap,
                 conf: Config | None = None,
                 metadata_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data", clock=None,
                 rank: int = 0):
        self.name = name
        self.entity = f"mds.{name}"
        self.rank = rank
        self.conf = conf or Config()
        self.clock = clock or SystemClock()
        self.log = DoutLogger("mds", self.entity)
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool

        self.msgr = create_messenger(self.entity, conf=self.conf)
        self.msgr.bind(("127.0.0.1", 0))
        self.msgr.set_policy("mon", Policy.lossless_peer())
        self.msgr.set_policy("client", Policy.stateless_server())
        self.msgr.add_dispatcher_tail(self)
        self.monc = MonClient(self.msgr, monmap)

        # own RADOS client for the metadata pool (Objecter-backed)
        self._rados = Rados(monmap, f"client.{self.entity}",
                            conf=self.conf)
        self.meta = None
        self._lock = threading.RLock()   # rank-wide serialization
        self._beacon_timer = None
        self._stopped = False
        # dentry cache (MDCache reduced): dir ino -> {name: inode}.
        # Single active rank writes ALL metadata, so the cache is
        # trivially coherent; bounded by eviction below.
        self._dcache: dict[int, dict[str, dict]] = {}
        self._dcache_max = 1024
        # capabilities (Locker.cc reduced): path -> {client: caps},
        # plus client sessions (entity -> reply addr) and pending
        # revoke gathers (ack_id -> state)
        self._caps: dict[str, dict[str, str]] = {}
        self._sessions: dict[str, tuple] = {}
        self._revokes: dict[int, dict] = {}
        self._ack_id = itertools.count(1)
        # client -> consecutive revoke-ack timeouts (laggy tracking);
        # strikes are rate-limited so a slow-but-alive client whose
        # acks land just past the window is not rapid-fired to 3
        self._laggy: dict[str, int] = {}
        self._laggy_last: dict[str, float] = {}   # last strike time
        # MDLog state: journaled-but-unflushed omap deltas per dir
        # (dir ino -> {name: serialized inode | None=removed}),
        # created/removed dir objects, and the journal head position
        self.mdlog = None
        self._mdlog_head = 0
        self._pending_flush: dict[int, dict[str, bytes | None]] = {}
        self._created_dirs: set[int] = set()
        self._removed_dirs: set[int] = set()
        self._skip_flush = False         # kill(): crash simulation
        # snapshots: "ino:name" -> {"snapid": n, "oid": frozen-tree}
        self.data_io = None
        self._snaps: dict[str, dict] = {}
        self._frozen_cache: dict[str, dict] = {}
        # multi-rank state (Migrator/MDBalancer reduced): the subtree
        # table maps subtree-root paths to their authoritative rank;
        # the RADOS omap SUBTREES_OID is the source of truth and this
        # is a cache refreshed on beacon ticks and authority misses
        self._subtrees: dict[str, int] = {"/": 0}
        self._frozen_subtrees: set[str] = set()   # exports in flight
        self._req_count = 0                # load since last beacon
        self._dir_hits: dict[str, int] = {}   # top-level dir -> hits

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.msgr.start()
        if self.msgr.auth_mode == "cephx":
            self.monc.enable_service_auth(
                [self.msgr], own_service="mds",
                ticket_services=[], clock=self.clock)
        self._rados.connect()
        try:
            self._rados.create_pool(self.metadata_pool)
        except RadosError:
            pass
        try:
            self._rados.create_pool(self.data_pool)
        except RadosError:
            pass
        self.meta = self._rados.open_ioctx(self.metadata_pool)
        self.data_io = self._rados.open_ioctx(self.data_pool)
        self._ensure_root()
        self._load_snaps()
        self._mdlog_open()
        self.monc.subscribe({"monmap": 0})   # membership changes
        self._beacon()

    def shutdown(self) -> None:
        self._stopped = True
        self.monc.shutdown()
        if self._beacon_timer:
            self._beacon_timer.cancel()
        if not self._skip_flush:
            try:
                with self._lock:
                    self._flush_mdlog()
            except Exception:
                pass
        self._rados.shutdown()
        self.msgr.shutdown()

    def kill(self) -> None:
        """kill -9 analog: die with journaled-but-unflushed events
        still in the MDLog — the restart replay test's entry point."""
        self._skip_flush = True
        self.shutdown()

    def _beacon(self) -> None:
        if self._stopped:
            return
        self.monc.send(MMDSBeacon(name=self.name, addr=self.msgr.addr,
                                  rank=self.rank))
        try:
            self._beacon_multirank()
        except Exception:
            pass    # metadata pool may not exist yet
        try:
            with self._lock:
                self._flush_mdlog()
        except Exception:
            self.log.warn("mdlog flush failed; retrying next beacon")
        self._beacon_timer = self.clock.timer(
            float(self.conf.mon_tick_interval) * 2, self._beacon)

    # -- MDLog (mds/MDLog.cc + journal replay, reduced) --------------------

    def _mdlog_open(self) -> None:
        from ..journal import Journaler
        j = Journaler(self.meta, "mdlog", client_id="mds")
        try:
            j.open()
        except RadosError:
            j.create()
            j.open()
        j.register_client("mds")
        self.mdlog = j
        start = j._commit_positions().get("mds", 0)
        self._mdlog_head = start
        replayed = 0
        for pos, blob in j.replay(start):
            try:
                self._apply_steps(denc.loads(blob))
            except Exception as e:
                self.log.error("mdlog replay failed at %d: %s", pos, e)
            self._mdlog_head = pos + 1
            replayed += 1
        if replayed:
            self.log.info("mdlog: replayed %d events", replayed)
            self._flush_mdlog()

    def _mutate(self, steps: list) -> None:
        """Journal one event (durably, in the metadata pool) then
        apply it to the cache; the omap flush is lazy.  Caller holds
        self._lock."""
        pos = self.mdlog.append(denc.dumps(steps))
        self._mdlog_head = pos + 1
        self._apply_steps(steps)
        if sum(len(p) for p in self._pending_flush.values()) >= 512:
            self._flush_mdlog()       # bound journal segment growth

    def _apply_steps(self, steps: list) -> None:
        """Apply idempotent event steps to the dentry cache + pending
        flush set (replay-safe: steps carry absolute state)."""
        for st in steps:
            kind = st[0]
            if kind == "set":
                _, dino, name, inode = st
                ents = self._dentries(dino)
                ents[name] = dict(inode)
                self._dcache[dino] = ents
                self._pending_flush.setdefault(dino, {})[name] = \
                    denc.dumps(inode)
            elif kind == "rm":
                _, dino, name = st
                ents = self._dentries(dino)
                ents.pop(name, None)
                self._dcache[dino] = ents
                self._pending_flush.setdefault(dino, {})[name] = None
            elif kind == "mkdirobj":
                ino = st[1]
                self._created_dirs.add(ino)
                self._removed_dirs.discard(ino)
                if len(self._dcache) >= self._dcache_max:
                    self._dcache.pop(next(iter(self._dcache)))
                self._dcache[ino] = {}
            elif kind == "rmdirobj":
                ino = st[1]
                self._removed_dirs.add(ino)
                self._created_dirs.discard(ino)
                self._pending_flush.pop(ino, None)
                self._dcache.pop(ino, None)

    def _flush_mdlog(self) -> None:
        """Land journaled deltas in the directory omaps, then advance
        the journal commit position and trim expired segments (the
        reference's segment expiry).  Caller holds self._lock.  A
        partial flush is safe: steps are idempotent, so a crash here
        just replays them."""
        if self.mdlog is None or (
                not self._pending_flush and not self._created_dirs
                and not self._removed_dirs):
            return
        head = self._mdlog_head
        for ino in sorted(self._created_dirs):
            self.meta.write_full(dir_oid(ino), b"")
        for dino, names in sorted(self._pending_flush.items()):
            if dino in self._removed_dirs:
                continue
            sets = {n: blob for n, blob in names.items()
                    if blob is not None}
            rms = [n for n, blob in names.items() if blob is None]
            if sets:
                self.meta.set_omap(dir_oid(dino), sets)
            if rms:
                self.meta.rm_omap_keys(dir_oid(dino), rms)
        for ino in sorted(self._removed_dirs):
            try:
                self.meta.remove_object(dir_oid(ino))
            except RadosError:
                pass
        self._pending_flush.clear()
        self._created_dirs.clear()
        self._removed_dirs.clear()
        self.mdlog.commit(head)
        try:
            self.mdlog.trim()
        except RadosError:
            pass

    def _ensure_root(self) -> None:
        try:
            self.meta.stat(dir_oid(ROOT_INO))
        except RadosError:
            self.meta.write_full(dir_oid(ROOT_INO), b"")
            self.meta.set_omap(INOTABLE, {"next": b"2"})
        try:
            self.meta.execute(SUBTREES_OID, "kvstore", "put",
                              denc.dumps({"kv": {"/": denc.dumps(0)},
                                          "if_absent": True}))
        except RadosError:
            pass                          # root entry already present
        self._load_subtrees()

    # -- multi-rank: subtree authority (mds/Migrator.h:52 reduced) ---------

    def _load_subtrees(self) -> None:
        from . import load_subtree_table
        table = load_subtree_table(self.meta)
        if table and table != self._subtrees:
            # authority moved: anything we cached under a regained
            # subtree may predate the other rank's mutations
            self._subtrees = table
            self._dcache.clear()

    def _auth_rank(self, norm: str) -> int:
        from . import subtree_rank
        return subtree_rank(self._subtrees, norm)

    def _is_frozen(self, norm: str) -> bool:
        return any(norm == f or norm.startswith(f + "/")
                   for f in self._frozen_subtrees)

    def _note_load(self, norm: str) -> None:
        self._req_count += 1
        parts = self._split(norm)
        if parts:
            top = "/" + parts[0]
            self._dir_hits[top] = self._dir_hits.get(top, 0) + 1

    def _beacon_multirank(self) -> None:
        """Per-beacon multi-rank upkeep: refresh the subtree cache,
        publish our load sample, and (when enabled) run one balancer
        pass (mds/MDBalancer.h:39 reduced to a shared load table)."""
        if self.meta is None:
            return
        self._load_subtrees()
        load, self._req_count = self._req_count, 0
        hits, self._dir_hits = dict(self._dir_hits), {}
        try:
            self.meta.set_omap(LOAD_OID, {str(self.rank): denc.dumps(
                {"load": load, "hits": hits})})
        except RadosError:
            return
        if bool(getattr(self.conf, "mds_bal_auto", False)):
            try:
                self.maybe_balance(load, hits)
            except Exception as e:
                self.log.warn("balance pass failed: %s", e)

    def maybe_balance(self, load: int, hits: dict) -> None:
        """Export our hottest owned top-level subtree to the least-
        loaded rank when our load is at least 2x theirs."""
        min_load = int(getattr(self.conf, "mds_bal_min", 20) or 20)
        if load < min_load:
            return
        try:
            table = {int(r): denc.loads(v) for r, v in
                     self.meta.get_omap(LOAD_OID).items()}
        except RadosError:
            return
        peers = {r: e.get("load", 0) for r, e in table.items()
                 if r != self.rank}
        if not peers:
            return
        target = min(peers, key=peers.get)
        if peers[target] * 2 > load:
            return
        for top, _n in sorted(hits.items(), key=lambda t: -t[1]):
            if self._auth_rank(top) == self.rank and top != "/":
                self.log.info("balancer: exporting %s to rank %d "
                              "(load %d vs %d)", top, target, load,
                              peers[target])
                self.export_dir(top, target)
                return

    def export_dir(self, path: str, target_rank: int,
                   _crash_at: str | None = None) -> None:
        """Migrate authority over a subtree to another rank (the
        Migrator export state machine collapsed onto shared RADOS
        metadata: freeze -> revoke caps -> flush journal -> CAS the
        subtree table).  All metadata already lives in RADOS dir
        omaps, so no cache state ships — the importer faults it in.

        Crash safety: the table CAS is the single commit point.  Dying
        before it leaves the exporter authoritative (freeze state is
        in-memory); dying after it leaves the importer authoritative
        with a fully-flushed journal either way."""
        norm = self._norm(path)
        if norm == "/":
            raise RadosError(22, "cannot export the root")
        with self._lock:
            self._load_subtrees()
            if self._auth_rank(norm) != self.rank:
                raise RadosError(116, f"{norm} not ours to export")
            self._frozen_subtrees.add(norm)
        try:
            if _crash_at == "frozen":
                raise _SimulatedCrash("frozen")
            with self._lock:
                # every client's caps under the subtree must come home
                # (their buffered attrs flush) before authority moves
                flushes = self._revoke_caps("", [(norm, True)])
                self._apply_cap_flushes(flushes)
                self._flush_mdlog()
                self._dcache.clear()
                if _crash_at == "flushed":
                    raise _SimulatedCrash("flushed")
                cur = self._subtrees.get(norm)
                expect = denc.dumps(cur) if cur is not None else None
                self.meta.execute(SUBTREES_OID, "kvstore", "cas",
                                  denc.dumps({
                                      "key": norm, "expect": expect,
                                      "value": denc.dumps(
                                          int(target_rank))}))
                self._subtrees[norm] = int(target_rank)
                self.log.info("exported %s to rank %d", norm,
                              target_rank)
        finally:
            self._frozen_subtrees.discard(norm)

    # -- inode table -------------------------------------------------------

    def _alloc_ino(self) -> int:
        omap = self.meta.get_omap(INOTABLE)
        ino = int(omap.get("next", b"2"))
        self.meta.set_omap(INOTABLE, {"next": str(ino + 1).encode()})
        return ino

    # -- path resolution ---------------------------------------------------

    @staticmethod
    def _split(path: str) -> list[str]:
        return [p for p in path.strip("/").split("/") if p]

    def _dentries(self, dir_ino: int,
                  cacheable: bool = True) -> dict[str, dict]:
        cached = self._dcache.get(dir_ino)
        if cached is not None:
            return cached
        try:
            omap = self.meta.get_omap(dir_oid(dir_ino))
        except RadosError:
            omap = {}
        out = {k: denc.loads(v) for k, v in omap.items()}
        # overlay journaled-but-unflushed deltas: a cache eviction
        # must never resurrect the pre-journal omap state
        for name, blob in self._pending_flush.get(dir_ino, {}).items():
            if blob is None:
                out.pop(name, None)
            else:
                out[name] = denc.loads(blob)
        if cacheable:
            # dirs OUTSIDE our subtree authority are never cached:
            # another rank mutates them and nothing would invalidate
            # our copy (the reference replicates such dirs with
            # explicit cache coherence; we read through instead)
            if len(self._dcache) >= self._dcache_max:
                self._dcache.pop(next(iter(self._dcache)))
            self._dcache[dir_ino] = out
        return out

    def _resolve(self, path: str) -> dict:
        """Path -> inode record; raises RadosError(ENOENT/ENOTDIR)."""
        cur = {"ino": ROOT_INO, "type": "dir"}
        cur_path = ""
        for part in self._split(path):
            if cur["type"] != "dir":
                raise RadosError(20, f"{part}: not a directory")
            ours = self._auth_rank(cur_path or "/") == self.rank
            ent = self._dentries(cur["ino"], cacheable=ours).get(part)
            if ent is None:
                raise RadosError(2, f"no such entry {part}")
            cur = ent
            cur_path = f"{cur_path}/{part}"
        return cur

    def _resolve_parent(self, path: str) -> tuple[dict, str]:
        parts = self._split(path)
        if not parts:
            raise RadosError(22, "bad path")
        parent = self._resolve("/".join(parts[:-1]))
        if parent["type"] != "dir":
            raise RadosError(20, "parent not a directory")
        return parent, parts[-1]

    # -- request handling --------------------------------------------------

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MClientRequest):
            threading.Thread(target=self._handle, args=(conn, msg),
                             daemon=True).start()
            return True
        if isinstance(msg, MClientCapsAck):
            # inline: the revoking op thread is WAITING on this while
            # holding the rank lock — acks must not need it
            state = self._revokes.get(msg.ack_id)
            self._laggy.pop(conn.peer_name, None)   # alive after all
            self._laggy_last.pop(conn.peer_name, None)
            if state is not None:
                with state["lock"]:
                    state["flushes"].update(msg.flushes or {})
                    state["acked"].add(conn.peer_name)
                    state["waiting"].discard(conn.peer_name)
                if not state["waiting"]:
                    state["event"].set()
            return True
        return False

    def _route_norm(self, op: str, norm: str) -> str:
        # ops that mutate the PARENT directory's omap (the dentry
        # lives there) route by the parent — otherwise mutating a
        # subtree ROOT's dentry from the subtree owner would silently
        # stale the parent owner's cache.  Shared rule with the client
        # (fs.route_path) so both sides agree.
        from . import route_path
        return route_path(op, norm)

    def _authority_gate(self, msg) -> "MClientReply | None":
        """Multi-rank routing: a frozen subtree answers EAGAIN (the
        export is mid-flight; retry lands post-CAS), a path whose
        authority is another rank answers ESTALE with the rank hint
        (the client refreshes its table and re-targets), and a
        cross-rank rename is EXDEV (matching the reference's
        cross-mds rename limits).  Structural ops on a subtree root
        owned by a DIFFERENT rank than its parent are EBUSY — the
        subtree must be imported back first (a reduced stand-in for
        the reference's cross-rank dirfrag locking)."""
        path = getattr(msg, "path", None)
        if path is None:
            return None
        norm = self._norm(path)
        route = self._route_norm(msg.op, norm)
        if self._is_frozen(norm) or self._is_frozen(route):
            return MClientReply(tid=msg.tid, result=-11, data=None)
        r = self._auth_rank(route)
        if r != self.rank:
            self._load_subtrees()     # maybe we just imported it
            r = self._auth_rank(route)
        if r != self.rank:
            return MClientReply(tid=msg.tid, result=-116,
                                data={"rank": r})
        if msg.op in ("rmdir", "unlink", "rename") and norm != "/":
            owner = self._subtrees.get(norm)
            if owner is not None and owner != self.rank:
                return MClientReply(tid=msg.tid, result=-16,
                                    data=None)    # EBUSY
        newp = getattr(msg, "new_path", None)
        if newp:
            nnorm = self._norm(newp)
            nroute = self._route_norm(msg.op, nnorm)
            if self._is_frozen(nnorm) or self._is_frozen(nroute):
                return MClientReply(tid=msg.tid, result=-11, data=None)
            nowner = self._subtrees.get(nnorm)
            if self._auth_rank(nroute) != self.rank or (
                    nowner is not None and nowner != self.rank):
                return MClientReply(tid=msg.tid, result=-18,
                                    data=None)
        self._note_load(norm)
        return None

    def _handle(self, conn, msg) -> None:
        with self._lock:
            self._sessions[msg.src] = conn.peer_addr
            gate = self._authority_gate(msg)
            if gate is not None:
                self.msgr.send_message(gate, conn.peer_name,
                                       conn.peer_addr)
                return
            try:
                affected = self._affected_paths(msg)
                if affected:
                    # Locker semantics: conflicting client caps are
                    # revoked (and their buffered attrs flushed)
                    # BEFORE the mutation executes
                    flushes = self._revoke_caps(msg.src, affected)
                    self._apply_cap_flushes(flushes)
                else:
                    # reads conflict only with WRITE-buffering caps:
                    # another client's unflushed size must land before
                    # we answer (reader-revokes-writer, Locker model)
                    conflicts = self._read_conflicts(msg)
                    if conflicts:
                        flushes = self._revoke_caps(
                            msg.src, conflicts, write_only=True)
                        self._apply_cap_flushes(flushes)
                data = self._execute(msg)
                grants = self._grant_caps(msg)
                reply = MClientReply(tid=msg.tid, result=0, data=data,
                                     grants=grants,
                                     snapc=self._snapc())
            except RadosError as e:
                reply = MClientReply(tid=msg.tid, result=-e.errno,
                                     data=None)
            except Exception as e:
                self.log.error("request %s failed: %s", msg.op, e)
                reply = MClientReply(tid=msg.tid, result=-5, data=None)
        self.msgr.send_message(reply, conn.peer_name, conn.peer_addr)

    # -- capabilities (Locker.cc reduced) ----------------------------------

    def _norm(self, path: str) -> str:
        return "/" + "/".join(self._split(path))

    def _parent_of(self, norm: str) -> str:
        return norm.rsplit("/", 1)[0] or "/"

    def _affected_paths(self, msg) -> list[tuple[str, bool]]:
        """Paths a mutation invalidates: (path, prefix?) pairs."""
        op = msg.op
        if op in ("getattr", "lookup", "readdir"):
            return []
        parts = self._split(msg.path)
        if ".snap" in parts:
            if op == "mkdir":
                # snapshot create: every buffered attr under the
                # snapped dir must land before the freeze, or the
                # frozen tree captures stale sizes
                dpath = "/" + "/".join(parts[:parts.index(".snap")])
                return [(dpath, True)]
            return []           # other snap ops are read-only/EROFS
        p = self._norm(msg.path)
        parent = self._parent_of(p)
        if op in ("mkdir", "create", "setattr", "unlink"):
            return [(parent, False), (p, False)]
        if op == "rmdir":
            return [(parent, False), (p, True)]
        if op == "rename":
            d = self._norm(msg.new_path)
            return [(parent, False), (self._parent_of(d), False),
                    (p, True), (d, True)]
        return []

    def _read_conflicts(self, msg) -> list[tuple[str, bool]]:
        p = self._norm(msg.path)
        if msg.op in ("getattr", "lookup"):
            return [(p, False)]
        if msg.op == "readdir":
            return [(p, True)]     # listings embed child sizes
        return []

    def _revoke_caps(self, requester: str, affected: list,
                     write_only: bool = False) -> dict:
        """Pull matching caps from every OTHER client; wait (bounded)
        for their acks, which carry buffered-attr flushes."""
        per_client: dict[str, list[str]] = {}
        for cap_path in list(self._caps):
            for apath, prefix in affected:
                hit = cap_path == apath or (
                    prefix and cap_path.startswith(apath + "/"))
                if not hit:
                    continue
                holders = self._caps[cap_path]
                for client in list(holders):
                    if client == requester:
                        continue
                    if write_only and "w" not in holders[client]:
                        continue
                    per_client.setdefault(client, []).append(cap_path)
                    del holders[client]
                if not holders:
                    del self._caps[cap_path]
                break
        targets = {c: ps for c, ps in per_client.items()
                   if c in self._sessions}
        if not targets:
            return {}
        ack_id = next(self._ack_id)
        # a client that already blew a revoke window is LAGGY: send
        # the revoke but do not wait on it again — one dead client
        # must not serialize every conflicting op behind 1s stalls
        waited = {c for c in targets if not self._laggy.get(c)}
        state = {"waiting": set(waited), "flushes": {}, "acked": set(),
                 "event": threading.Event(),
                 "lock": threading.Lock()}
        self._revokes[ack_id] = state
        for client, paths in targets.items():
            self.msgr.send_message(
                MClientCaps(ack_id=ack_id, paths=sorted(set(paths))),
                client, self._sessions[client])
        # bounded REAL-time wait: acks arrive on the messenger thread
        # (no rank lock needed); a dead client costs one window
        if state["waiting"]:
            state["event"].wait(1.0)
        self._revokes.pop(ack_id, None)
        # strike every target that did not ack — including laggy ones
        # we no longer wait on (a LATE ack clears the counter via the
        # ack handler, so only a truly dead client accumulates).
        # Copies under state["lock"]: the messenger thread may still
        # be mutating these sets for an ack in flight.
        with state["lock"]:
            acked = set(state["acked"])
            flushes = dict(state["flushes"])
        now = time.time()
        for client in set(targets) - acked:
            # at most one strike per real revoke window: laggy clients
            # get a zero-length window, so without this cooldown a
            # burst of ops would rapid-fire a 1.2s-RTT client straight
            # to 3 strikes before any in-flight ack could land
            if now - self._laggy_last.get(client, 0.0) < 1.0:
                continue
            self._laggy_last[client] = now
            fails = self._laggy.get(client, 0) + 1
            self._laggy[client] = fails
            if fails >= 3:
                # Session::close semantics: a persistently dead
                # client loses its session (and with it, its caps)
                self._laggy.pop(client, None)
                self._laggy_last.pop(client, None)
                self._sessions.pop(client, None)
                for holders in self._caps.values():
                    holders.pop(client, None)
        return flushes

    def _apply_cap_flushes(self, flushes: dict) -> None:
        """A revoked writer's buffered size lands before the op."""
        for path, size in flushes.items():
            try:
                parent, name = self._resolve_parent(path)
                ent = self._dentries(parent["ino"]).get(name)
                if ent is not None and ent["type"] == "file":
                    ent["size"] = max(int(ent["size"]), int(size))
                    ent["mtime"] = time.time()
                    self._mutate([("set", parent["ino"], name,
                                   ent)])
            except RadosError:
                continue

    def _grant_caps(self, msg) -> list:
        """Read caps on resolved paths; read+buffer caps on files the
        client created/opened (Fw analog)."""
        op = msg.op
        p = self._norm(msg.path)
        if op in ("getattr", "lookup", "readdir"):
            caps = "r"
        elif op in ("create", "setattr"):
            caps = "rw"
        else:
            return []
        self._caps.setdefault(p, {})[msg.src] = caps
        return [{"path": p, "caps": caps}]

    def _execute(self, msg):
        op, path = msg.op, msg.path
        if ".snap" in self._split(path) or (
                op == "rename" and
                ".snap" in self._split(msg.new_path)):
            return self._execute_snap(msg)
        if op == "getattr":
            return self._resolve(path)
        if op == "lookup":
            return self._resolve(path)
        if op == "readdir":
            node = self._resolve(path)
            if node["type"] != "dir":
                raise RadosError(20, "not a directory")
            return {name: ent for name, ent in
                    self._dentries(node["ino"]).items()}
        if op == "mkdir":
            parent, name = self._resolve_parent(path)
            if name in self._dentries(parent["ino"]):
                raise RadosError(17, "exists")
            ino = self._alloc_ino()
            inode = new_inode(ino, "dir")
            self._mutate([("mkdirobj", ino),
                          ("set", parent["ino"], name, inode)])
            return inode
        if op == "create":
            parent, name = self._resolve_parent(path)
            existing = self._dentries(parent["ino"]).get(name)
            if existing is not None:
                if existing["type"] != "file":
                    raise RadosError(21, "is a directory")
                return existing
            inode = new_inode(self._alloc_ino(), "file")
            self._mutate([("set", parent["ino"], name, inode)])
            return inode
        if op == "setattr":
            parent, name = self._resolve_parent(path)
            ent = self._dentries(parent["ino"]).get(name)
            if ent is None:
                raise RadosError(2, "no such entry")
            if msg.size is not None:
                ent["size"] = int(msg.size)
            ent["mtime"] = time.time()
            self._mutate([("set", parent["ino"], name, ent)])
            return ent
        if op == "unlink":
            parent, name = self._resolve_parent(path)
            ent = self._dentries(parent["ino"]).get(name)
            if ent is None:
                raise RadosError(2, "no such entry")
            if ent["type"] == "dir":
                raise RadosError(21, "is a directory")
            self._mutate([("rm", parent["ino"], name)])
            return ent          # client deletes the data objects
        if op == "rmdir":
            parent, name = self._resolve_parent(path)
            ent = self._dentries(parent["ino"]).get(name)
            if ent is None:
                raise RadosError(2, "no such entry")
            if ent["type"] != "dir":
                raise RadosError(20, "not a directory")
            if self._dentries(ent["ino"]):
                raise RadosError(39, "directory not empty")
            self._mutate([("rm", parent["ino"], name),
                          ("rmdirobj", ent["ino"])])
            return None
        if op == "rename":
            # renaming a directory into its own subtree would detach
            # it into an unreachable cycle (POSIX EINVAL)
            src_norm = "/" + "/".join(self._split(path))
            dst_norm = "/" + "/".join(self._split(msg.new_path))
            if dst_norm == src_norm or \
                    dst_norm.startswith(src_norm + "/"):
                raise RadosError(22, "destination inside source")
            src_parent, src_name = self._resolve_parent(path)
            ent = self._dentries(src_parent["ino"]).get(src_name)
            if ent is None:
                raise RadosError(2, "no such entry")
            dst_parent, dst_name = self._resolve_parent(msg.new_path)
            dst = self._dentries(dst_parent["ino"]).get(dst_name)
            replaced = None
            if dst is not None:
                # POSIX atomic replace for files (write-tmp + rename);
                # DIVERGENCE: replacing a directory destination is
                # EEXIST here (no dir-over-empty-dir)
                if dst["type"] != "file" or ent["type"] != "file":
                    raise RadosError(17, "destination exists")
                replaced = dst
            # ONE journal event: the rename replays atomically
            self._mutate([
                ("set", dst_parent["ino"], dst_name, ent),
                ("rm", src_parent["ino"], src_name)])
            return {"entry": ent, "replaced": replaced}
        raise RadosError(95, f"unknown mds op {op!r}")

    # -- snapshots (.snap, SnapServer/snaprealm reduced) -------------------

    def _load_snaps(self) -> None:
        try:
            omap = self.meta.get_omap("mds_snaps")
        except RadosError:
            return
        snapc = omap.pop("_snapc", None)
        self._snaps = {k: denc.loads(v) for k, v in omap.items()}
        if snapc is not None:
            seq, snaps = denc.loads(snapc)
            self.data_io.set_snap_context(seq, snaps)

    def _snapc(self) -> tuple:
        return (self.data_io.snap_seq, list(self.data_io.snaps))

    def _split_snap_path(self, path: str):
        """'a/b/.snap/name/rest...' -> ('a/b', 'name'|None, [rest])."""
        parts = self._split(path)
        i = parts.index(".snap")
        return ("/".join(parts[:i]),
                parts[i + 1] if len(parts) > i + 1 else None,
                parts[i + 2:])

    def _execute_snap(self, msg):
        op = msg.op
        if ".snap" not in self._split(msg.path):
            # rename whose DESTINATION is under .snap
            raise RadosError(30, "snapshots are read-only")
        dpath, name, rest = self._split_snap_path(msg.path)
        dnode = self._resolve(dpath)
        if dnode["type"] != "dir":
            raise RadosError(20, "not a directory")
        key = f"{dnode['ino']:x}:{name}" if name else None
        if op == "mkdir" and name and not rest:
            return self._snap_create(dnode, key, name)
        if op == "rmdir" and name and not rest:
            return self._snap_remove(key)
        if op in ("getattr", "lookup", "readdir"):
            return self._snap_read(op, dnode, name, rest)
        raise RadosError(30, "snapshots are read-only")   # EROFS

    def _snap_create(self, dnode, key, name):
        if key in self._snaps:
            raise RadosError(17, "snapshot exists")
        # make the frozen tree reflect every acked mutation
        self._flush_mdlog()
        # allocate the data-pool snapid: clients that learn the new
        # snap context (carried on every reply) make the OSDs COW
        # file data written from now on
        snapid = self.data_io.create_selfmanaged_snap()
        tree: dict[str, dict] = {}

        def freeze(ino: int, rel: str) -> None:
            ents = dict(self._dentries(ino))
            tree[rel] = ents
            for nm, ent in ents.items():
                if ent["type"] == "dir":
                    freeze(ent["ino"], f"{rel}/{nm}" if rel else nm)

        freeze(dnode["ino"], "")
        oid = f"snap.{dnode['ino']:x}.{snapid:x}"
        self.meta.write_full(oid, denc.dumps(tree))
        rec = {"snapid": snapid, "oid": oid,
               "created": time.time()}
        self._snaps[key] = rec
        self.meta.set_omap("mds_snaps", {
            key: denc.dumps(rec),
            "_snapc": denc.dumps(self._snapc())})
        self.log.info("snapshot %s of dir %x -> snapid %d",
                      key, dnode["ino"], snapid)
        return {"ino": dnode["ino"], "type": "dir",
                "snapid": snapid, "size": 0,
                "mtime": rec["created"], "ctime": rec["created"],
                "layout": dict(DEFAULT_LAYOUT)}

    def _snap_remove(self, key):
        rec = self._snaps.pop(key, None)
        if rec is None:
            raise RadosError(2, "no such snapshot")
        self._frozen_cache.pop(rec["oid"], None)
        try:
            self.meta.remove_object(rec["oid"])
        except RadosError:
            pass
        try:
            self.data_io.remove_selfmanaged_snap(rec["snapid"])
        except RadosError:
            pass
        self.meta.rm_omap_keys("mds_snaps", [key])
        self.meta.set_omap("mds_snaps",
                           {"_snapc": denc.dumps(self._snapc())})
        return None

    def _frozen(self, rec: dict) -> dict:
        tree = self._frozen_cache.get(rec["oid"])
        if tree is None:
            tree = denc.loads(self.meta.read(rec["oid"]))
            if len(self._frozen_cache) > 16:
                self._frozen_cache.pop(next(iter(self._frozen_cache)))
            self._frozen_cache[rec["oid"]] = tree
        return tree

    def _snap_read(self, op, dnode, name, rest):
        ino = dnode["ino"]
        if name is None:
            # '<dir>/.snap' itself: list this dir's snapshot names
            prefix = f"{ino:x}:"
            names = {k[len(prefix):]: {"ino": ino, "type": "dir",
                                       "size": 0, "mtime": v["created"],
                                       "ctime": v["created"],
                                       "layout": dict(DEFAULT_LAYOUT)}
                     for k, v in self._snaps.items()
                     if k.startswith(prefix)}
            if op == "readdir":
                return names
            return {"ino": ino, "type": "dir", "size": 0,
                    "mtime": 0.0, "ctime": 0.0,
                    "layout": dict(DEFAULT_LAYOUT)}
        rec = self._snaps.get(f"{ino:x}:{name}")
        if rec is None:
            raise RadosError(2, "no such snapshot")
        tree = self._frozen(rec)
        snapid = rec["snapid"]

        def anno(ent: dict) -> dict:
            return (dict(ent, snapid=snapid)
                    if ent.get("type") == "file" else dict(ent))

        # resolve `rest` inside the frozen tree
        rel = ""
        cur = {"ino": ino, "type": "dir", "size": 0, "mtime": 0.0,
               "ctime": 0.0, "layout": dict(DEFAULT_LAYOUT)}
        for i, part in enumerate(rest):
            ents = tree.get(rel, {})
            ent = ents.get(part)
            if ent is None:
                raise RadosError(2, f"no such entry {part}")
            cur = ent
            if ent["type"] == "dir":
                rel = f"{rel}/{part}" if rel else part
            elif i != len(rest) - 1:
                raise RadosError(20, f"{part}: not a directory")
        if op == "readdir":
            if cur["type"] != "dir":
                raise RadosError(20, "not a directory")
            return {nm: anno(e)
                    for nm, e in tree.get(rel, {}).items()}
        return anno(cur)
