"""CephFS wire messages (messages/MClientRequest.h / MClientReply.h)."""

from __future__ import annotations

from ..msg import Message, register_message


@register_message
class MClientRequest(Message):
    """client -> mds metadata op.

    fields: tid, op (str), path (str), and op-specific args:
      mkdir/create: mode-ish ignored; rename: new_path;
      setattr: size/mtime; readdir/lookup/getattr: just path.
    """
    TYPE = 220


@register_message
class MClientReply(Message):
    TYPE = 221
    # fields: tid, result (0 or -errno), data (op-specific)


@register_message
class MClientCaps(Message):
    """mds -> client capability revoke (messages/MClientCaps.h,
    Locker.cc revocation reduced): the client must drop its cached
    dentries/attrs under each path (prefix semantics) and ack,
    flushing any buffered attr state in the ack."""
    TYPE = 222
    # fields: ack_id, paths (list[str])


@register_message
class MClientCapsAck(Message):
    TYPE = 223
    # fields: ack_id, flushes ({path: buffered size})
