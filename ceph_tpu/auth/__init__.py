"""Auth: cephx-lite session authentication + keyring (auth/ analog)."""

from . import cephx
from .keyring import KeyRing, generate_key

__all__ = ["cephx", "KeyRing", "generate_key"]
