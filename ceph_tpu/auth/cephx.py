"""cephx-lite: shared-secret session auth + per-message signing.

Semantics follow auth/cephx/CephxProtocol.h (challenge/response proofs
over a shared secret; CephxSessionHandler's per-message signatures,
CephxSessionHandler.cc sign_message/check_message_signature) reduced to
the session layer: both ends prove knowledge of the entity's keyring
secret via HMAC challenges and derive a per-connection session key that
signs every frame.  The ticket-granting (AUTH_SESSION_KEY ->
service-ticket) indirection is deliberately not reproduced — one
keyring secret authenticates the session directly.  auth=none disables
the whole layer (config auth_cluster_required, like the reference's
auth supported knobs).
"""

from __future__ import annotations

import hashlib
import hmac
import os

NONCE_LEN = 16
PROOF_LEN = 32
SIG_LEN = 8


def make_nonce() -> bytes:
    return os.urandom(NONCE_LEN)


def proof(key: bytes, client_nonce: bytes, server_nonce: bytes,
          who: bytes) -> bytes:
    """Challenge-response proof: knowledge of `key` bound to both
    nonces and the prover's role (so a proof cannot be reflected)."""
    return hmac.new(key, b"cephx-proof" + client_nonce + server_nonce
                    + who, hashlib.sha256).digest()


def session_key(key: bytes, client_nonce: bytes,
                server_nonce: bytes) -> bytes:
    return hmac.new(key, b"cephx-session" + client_nonce + server_nonce,
                    hashlib.sha256).digest()


def sign(skey: bytes, frame: bytes) -> bytes:
    """Per-message signature (CephxSessionHandler::sign_message)."""
    return hmac.new(skey, frame, hashlib.sha256).digest()[:SIG_LEN]


def sign_iov(skey: bytes, parts) -> bytes:
    """Signature over a gather-write frame: the HMAC folds each buffer
    in place (label, header, seg table, payload, segments) — same
    digest as sign() over the joined bytes, zero joins."""
    h = hmac.new(skey, digestmod=hashlib.sha256)
    for p in parts:
        h.update(p)
    return h.digest()[:SIG_LEN]


def check(skey: bytes, frame: bytes, sig: bytes) -> bool:
    return hmac.compare_digest(sign(skey, frame), sig)


def check_iov(skey: bytes, parts, sig: bytes) -> bool:
    return hmac.compare_digest(sign_iov(skey, parts), sig)


# ---------------------------------------------------------------------------
# Ticket blobs + rotating service secrets (CephxProtocol.h:143
# CephXTicketBlob / CephXServiceTicketInfo, reduced).
#
# The TGS indirection: a client authenticates to the MON with its own
# keyring secret and asks for a SERVICE ticket — an opaque blob sealed
# under the service class's ROTATING secret (which only the service
# daemons fetch from the mon), carrying the client's identity, an
# expiry stamp and a fresh connection secret.  The service unseals the
# blob with its current (or previous — rotation keeps one back) secret
# and both sides derive per-connection session keys from the carried
# secret, so the service never needs the client's keyring entry and
# rotating the service secret invalidates outstanding tickets on the
# reference's schedule, not on daemon restarts.
#
# Sealing is XOR with a SHA256-CTR keystream + HMAC tag — integrity
# first, matching the framework's frame-signing (not encrypting)
# threat model.
# ---------------------------------------------------------------------------

SECRET_LEN = 32


def make_secret() -> bytes:
    return os.urandom(SECRET_LEN)


def _keystream(secret: bytes, nonce: bytes, n: int) -> bytes:
    out = bytearray()
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(
            secret + nonce + ctr.to_bytes(4, "big")).digest()
        ctr += 1
    return bytes(out[:n])


def seal(secret: bytes, payload: bytes) -> bytes:
    nonce = os.urandom(NONCE_LEN)
    body = bytes(a ^ b for a, b in
                 zip(payload, _keystream(secret, nonce, len(payload))))
    tag = hmac.new(secret, b"cephx-seal" + nonce + body,
                   hashlib.sha256).digest()
    return nonce + tag + body


def unseal(secret: bytes, blob: bytes) -> bytes | None:
    if len(blob) < NONCE_LEN + 32:
        return None
    nonce, tag, body = (blob[:NONCE_LEN], blob[NONCE_LEN:NONCE_LEN + 32],
                        blob[NONCE_LEN + 32:])
    want = hmac.new(secret, b"cephx-seal" + nonce + body,
                    hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        return None
    return bytes(a ^ b for a, b in
                 zip(body, _keystream(secret, nonce, len(body))))
